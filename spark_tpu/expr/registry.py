"""Function registry: SQL/DataFrame function names → expression builders.

Role of the reference's FunctionRegistry (sqlcat/analysis/FunctionRegistry.scala)."""

from __future__ import annotations

from typing import Callable, Sequence

from ..errors import AnalysisException
from . import expressions as E

Builder = Callable[..., E.Expression]


def _lit_str(e: E.Expression) -> str:
    if isinstance(e, E.Literal) and isinstance(e.value, str):
        return e.value
    raise AnalysisException("expected a string literal argument")


_REGISTRY: dict[str, Builder] = {}


def register(name: str, builder: Builder) -> None:
    _REGISTRY[name.lower()] = builder


def lookup(name: str) -> Builder | None:
    return _REGISTRY.get(name.lower())


def build_function(name: str, args: Sequence[E.Expression],
                   distinct: bool = False) -> E.Expression:
    n = name.lower()
    if n == "count":
        if len(args) == 0 or isinstance(args[0], E.UnresolvedStar):
            return E.Count(None, distinct=False)
        return E.Count(args[0], distinct=distinct)
    if n in ("sum",) and distinct:
        raise AnalysisException("sum(distinct) not yet supported")
    b = lookup(n)
    if b is None:
        raise AnalysisException(f"Undefined function: {name}",
                                error_class="UNRESOLVED_ROUTINE")
    return b(*args)


def _reg_all() -> None:
    r = register
    # aggregates
    r("sum", lambda c: E.Sum(c))
    r("min", lambda c: E.Min(c))
    r("max", lambda c: E.Max(c))
    r("avg", lambda c: E.Average(c))
    r("mean", lambda c: E.Average(c))
    r("first", lambda c, *a: E.First(c))
    r("first_value", lambda c, *a: E.First(c))
    r("any_value", lambda c, *a: E.AnyValue(c))
    r("stddev", lambda c: E.StddevSamp(c))
    r("stddev_samp", lambda c: E.StddevSamp(c))
    r("stddev_pop", lambda c: E.StddevPop(c))
    r("variance", lambda c: E.VarianceSamp(c))
    r("var_samp", lambda c: E.VarianceSamp(c))
    r("var_pop", lambda c: E.VariancePop(c))
    r("collect_set", lambda c: E.CollectSet(c))
    # math
    r("abs", lambda c: E.Abs(c))
    r("sqrt", lambda c: E.Sqrt(c))
    r("exp", lambda c: E.Exp(c))
    r("ln", lambda c: E.Log(c))
    r("log", lambda c: E.Log(c))
    r("log10", lambda c: E.Log10(c))
    r("floor", lambda c: E.Floor(c))
    r("ceil", lambda c: E.Ceil(c))
    r("ceiling", lambda c: E.Ceil(c))
    r("round", lambda c, s=None: E.Round(c, s))
    r("power", lambda a, b: E.Pow(a, b))
    r("pow", lambda a, b: E.Pow(a, b))
    r("mod", lambda a, b: E.Remainder(a, b))
    r("negative", lambda c: E.UnaryMinus(c))
    # conditionals
    r("if", lambda p, a, b: E.If(p, a, b))
    r("coalesce", lambda *a: E.Coalesce(list(a)))
    r("nullif", lambda a, b: E.NullIf(a, b))
    r("nvl", lambda a, b: E.Coalesce([a, b]))
    r("ifnull", lambda a, b: E.Coalesce([a, b]))
    r("greatest", lambda *a: E.Greatest(list(a)))
    r("least", lambda *a: E.Least(list(a)))
    r("isnull", lambda c: E.IsNull(c))
    r("isnotnull", lambda c: E.IsNotNull(c))
    r("isnan", lambda c: E.IsNaN(c))
    # strings
    r("upper", lambda c: E.Upper(c))
    r("ucase", lambda c: E.Upper(c))
    r("lower", lambda c: E.Lower(c))
    r("lcase", lambda c: E.Lower(c))
    r("trim", lambda c: E.Trim(c))
    r("ltrim", lambda c: E.LTrim(c))
    r("rtrim", lambda c: E.RTrim(c))
    r("length", lambda c: E.Length(c))
    r("char_length", lambda c: E.Length(c))
    r("substring", lambda c, p, l=None: E.Substring(c, p, l))
    r("substr", lambda c, p, l=None: E.Substring(c, p, l))
    r("concat", lambda *a: E.Concat(list(a)))
    r("replace", lambda c, s, rep: E.StringReplace(c, s, rep))
    r("lpad", lambda c, l, p=None: E.Lpad(c, l, p if p is not None else E.Literal(" "))),
    r("rpad", lambda c, l, p=None: E.Rpad(c, l, p if p is not None else E.Literal(" "))),
    r("startswith", lambda c, p: E.StartsWith(c, _lit_str(p)))
    r("endswith", lambda c, p: E.EndsWith(c, _lit_str(p)))
    r("contains", lambda c, p: E.Contains(c, _lit_str(p)))
    r("like", lambda c, p: E.Like(c, _lit_str(p)))
    r("rlike", lambda c, p: E.RLike(c, _lit_str(p)))
    r("regexp", lambda c, p: E.RLike(c, _lit_str(p)))
    # datetime
    r("year", lambda c: E.Year(c))
    r("month", lambda c: E.Month(c))
    r("day", lambda c: E.DayOfMonth(c))
    r("dayofmonth", lambda c: E.DayOfMonth(c))
    r("quarter", lambda c: E.Quarter(c))
    r("dayofweek", lambda c: E.DayOfWeek(c))
    r("dayofyear", lambda c: E.DayOfYear(c))
    r("weekofyear", lambda c: E.WeekOfYear(c))
    r("date_add", lambda d, n: E.DateAdd(d, n))
    r("date_sub", lambda d, n: E.DateSub(d, n))
    r("datediff", lambda a, b: E.DateDiff(a, b))
    r("trunc", lambda c, f: E.TruncDate(c, _lit_str(f)))
    r("date_trunc", lambda f, c: E.TruncDate(c, _lit_str(f)))
    r("make_date", lambda y, m, d: E.MakeDate(y, m, d))
    r("to_date", lambda c, fmt=None: E.Cast(c, __import__(
        "spark_tpu.types", fromlist=["date"]).date))
    # window / ranking
    from .window import (
        CumeDist, DenseRank, Lag, Lead, NTile, PercentRank, Rank, RowNumber,
    )

    r("row_number", lambda: RowNumber())
    r("rank", lambda: Rank())
    r("dense_rank", lambda: DenseRank())
    r("percent_rank", lambda: PercentRank())
    r("cume_dist", lambda: CumeDist())
    r("ntile", lambda n: NTile(n))
    r("lag", lambda c, off=None, d=None: Lag(
        c, off if off is not None else E.Literal(1), d))
    r("lead", lambda c, off=None, d=None: Lead(
        c, off if off is not None else E.Literal(1), d))


_reg_all()
