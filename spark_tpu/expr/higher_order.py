"""Higher-order functions over arrays and maps.

Role of the reference's higherOrderFunctions.scala (ArrayTransform,
ArrayFilter, ArrayAggregate, ArrayExists, ArrayForAll, ZipWith,
TransformKeys, TransformValues, MapFilter, MapZipWith) and its lambda
binding (LambdaFunction, NamedLambdaVariable,
ResolveLambdaVariables in sqlcat/analysis/higherOrderFunctions.scala).

TPU mapping: collection columns are dictionary-encoded host values, so
a lambda runs on the HOST over one collection value at a time via the
scalar interpreter (expr/scalar.py) — the device carries only the
dictionary codes. Each HOF lowers to the in-process Python-eval path
(expr/pyudf.py → physical/python_eval.py): its inputs are the
collection argument(s) plus any OUTER columns the lambda captures, so
captures get full reference semantics instead of being rejected.
"""

from __future__ import annotations

import itertools
from typing import Sequence

from ..errors import AnalysisException
from ..types import (
    ArrayType, BooleanType, DataType, IntegerType, MapType, NullType,
    boolean, int32, null_type,
)
from . import expressions as E

_lambda_ids = itertools.count(1)


class UnresolvedNamedLambdaVariable(E.Expression):
    """A lambda parameter reference inside an unbound lambda body. The
    PARSER creates these (lexical scoping: it knows the param names), so
    attribute resolution can never capture a lambda name as a column."""

    child_fields = ()

    def __init__(self, name: str):
        self.name = name

    @property
    def resolved(self):
        return False

    @property
    def dtype(self):
        raise AnalysisException(
            f"lambda variable {self.name} not bound yet")

    def _data_args(self):
        return (("name", self.name),)

    def simple_string(self):
        return self.name


class NamedLambdaVariable(E.Expression):
    """A bound, typed lambda parameter (higherOrderFunctions.scala
    NamedLambdaVariable). Evaluated only by the scalar interpreter."""

    child_fields = ()

    def __init__(self, name: str, dtype: DataType,
                 expr_id: int | None = None):
        self.name = name
        self._dtype = dtype
        self.expr_id = expr_id if expr_id is not None \
            else next(_lambda_ids) | (1 << 40)   # disjoint from attr ids

    @property
    def resolved(self):
        return True

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return True

    def _data_args(self):
        return (("name", self.name), ("expr_id", self.expr_id))

    def eval(self, ctx):
        raise AnalysisException(
            f"lambda variable {self.name} outside a lambda body")

    def simple_string(self):
        return self.name


class LambdaFunction(E.Expression):
    """`x -> body` / `(x, y) -> body`. Ready for binding once its body
    has no unresolved attributes/functions left (lambda variables are
    bound by the enclosing higher-order function at build time)."""

    child_fields = ("body",)

    def __init__(self, params: Sequence[str], body: E.Expression):
        self.params = list(params)
        self.body = body

    @property
    def resolved(self):
        # ready for binding: outer-column references all resolved AND no
        # FREE lambda variables (a lambda referencing an ENCLOSING
        # lambda's parameter must wait for the outer binder — building
        # it standalone would bind against the wrong scope). Nested
        # lambdas bind their own params, so freeness is scope-aware.
        if any(isinstance(n, E.UnresolvedAttribute)
               for n in self.body.iter_nodes()):
            return False
        return not _free_lambda_vars(
            self.body, frozenset(p.lower() for p in self.params))

    @property
    def dtype(self):
        return self.body.dtype

    def _data_args(self):
        return (("params", tuple(self.params)),)

    def bind(self, types: Sequence[DataType]) -> tuple[list, E.Expression]:
        """params → typed NamedLambdaVariables substituted into the body,
        then resolve functions that were waiting on the lambda types —
        including nested higher-order functions, which stay as HOF nodes
        for the scalar interpreter (ResolveLambdaVariables +
        ResolveFunctions ordering in higherOrderFunctions.scala)."""
        if len(self.params) > len(types):
            raise AnalysisException(
                f"lambda has {len(self.params)} parameters but at most "
                f"{len(types)} are available")
        vars_ = [NamedLambdaVariable(p, t)
                 for p, t in zip(self.params, types)]
        top = {p.lower(): v for p, v in zip(self.params, vars_)}

        def sub(node, byname):
            if isinstance(node, LambdaFunction):
                # an inner lambda's params SHADOW ours inside its body
                inner = {p.lower() for p in node.params}
                reduced = {k: v for k, v in byname.items()
                           if k not in inner}
                return node.copy(body=sub(node.body, reduced))
            if isinstance(node, UnresolvedNamedLambdaVariable):
                v = byname.get(node.name.lower())
                return v if v is not None else node  # inner binder's job
            node = node.map_children(lambda c: sub(c, byname))
            if isinstance(node, E.UnresolvedFunction) and \
                    all(c.resolved for c in node.args):
                return build_inner_function(node.fname, node.args,
                                            node.distinct)
            return node

        return vars_, sub(self.body, top)

    def simple_string(self):
        ps = ", ".join(self.params)
        return f"lambda ({ps}) -> {self.body.simple_string()}"


def _free_lambda_vars(e: E.Expression, bound: frozenset) -> set:
    """Lambda variable names referenced under `e` that no enclosing
    lambda (within `e`) binds."""
    if isinstance(e, UnresolvedNamedLambdaVariable):
        return set() if e.name.lower() in bound else {e.name.lower()}
    if isinstance(e, LambdaFunction):
        return _free_lambda_vars(
            e.body, bound | {p.lower() for p in e.params})
    out: set = set()
    for c in e.children:
        out |= _free_lambda_vars(c, bound)
    return out


def mark_lambda_params(body: E.Expression,
                       params: Sequence[str]) -> E.Expression:
    """Parser helper: rewrite single-part UnresolvedAttributes matching a
    param name into UnresolvedNamedLambdaVariable (lexical scoping)."""
    names = {p.lower() for p in params}

    def sub(node):
        if isinstance(node, E.UnresolvedAttribute) and \
                len(node.name_parts) == 1 and \
                node.name_parts[0].lower() in names:
            return UnresolvedNamedLambdaVariable(node.name_parts[0])
        return node.map_children(sub)

    return sub(body)


# ---------------------------------------------------------------------------
# HOF expressions
# ---------------------------------------------------------------------------

def _elem_type(dt: DataType) -> DataType:
    return dt.element_type if isinstance(dt, ArrayType) else null_type


class HigherOrderFunction(E.Expression):
    """Base: one or two collection args + one (or two) lambdas. Lowers
    itself through the Python-eval host path; `scalar_apply` computes
    the result for ONE collection value (also used when a HOF appears
    nested inside another lambda)."""

    child_fields = ("args", "function")
    fname = "hof"

    def __init__(self, args: Sequence[E.Expression],
                 function: LambdaFunction):
        self.args = list(args)
        self.function = function
        self._bound = None      # (vars, body) after bind

    # -- binding --------------------------------------------------------
    def lambda_types(self) -> list[DataType]:
        raise NotImplementedError

    def bound(self):
        if self._bound is None:
            if isinstance(self.function, LambdaFunction):
                self._bound = self.function.bind(self.lambda_types())
            else:
                raise AnalysisException(
                    f"{self.fname} expects a lambda argument")
        return self._bound

    def collection_args(self) -> list[E.Expression]:
        return self.args

    def capture_exprs(self) -> list[E.Expression]:
        """Expressions whose free column references the lowered UDF must
        receive as extra inputs (lambda bodies; aggregate's zero too)."""
        return [self.bound()[1]]

    @property
    def resolved(self):
        return all(a.resolved for a in self.args) and \
            self.function.resolved

    @property
    def nullable(self):
        return True

    def scalar_apply(self, values: list, env: dict):
        raise NotImplementedError

    def eval(self, ctx):
        from ..errors import ExecutionError

        raise ExecutionError(
            f"{self.fname} must lower through the Python-eval path")

    def simple_string(self):
        a = ", ".join(x.simple_string() for x in self.args)
        return f"{self.fname}({a}, {self.function.simple_string()})"


def _pyval(v):
    """numpy → pure-Python values: lambda semantics (`is True` checks,
    Kleene logic) depend on Python singletons, and np.True_ is not
    True."""
    import numpy as np

    if isinstance(v, np.ndarray):
        return [_pyval(x) for x in v.tolist()]
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, (list, tuple)):
        return [_pyval(x) for x in v]
    if isinstance(v, dict):
        return {_pyval(k): _pyval(x) for k, x in v.items()}
    return v


def lower_hof(hof: "HigherOrderFunction"):
    """HOF → PythonUDF over (collection args + captured outer columns):
    the planner's ExtractPythonUDFs path then evaluates it host-side
    per row with full capture semantics."""
    from .pyudf import PythonUDF
    from .scalar import free_attributes

    hof.bound()     # force binding errors to surface at analysis time
    captured, seen = [], set()
    for e in hof.capture_exprs():
        for a in free_attributes(e):
            if a.expr_id not in seen:
                seen.add(a.expr_id)
                captured.append(a)
    coll = hof.collection_args()

    def fn(*vals):
        vals = [_pyval(v) for v in vals]
        env = {a.expr_id: v
               for a, v in zip(captured, vals[len(coll):])}
        return hof.scalar_apply(list(vals[:len(coll)]), env)

    return PythonUDF(fn, coll + captured, hof.dtype, name=hof.fname,
                     vectorized=False)


class ArrayTransform(HigherOrderFunction):
    """transform(arr, x -> ...) / transform(arr, (x, i) -> ...)."""

    fname = "transform"

    def lambda_types(self):
        return [_elem_type(self.args[0].dtype), int32]

    @property
    def dtype(self):
        return ArrayType(self.bound()[1].dtype)

    def scalar_apply(self, values, env):
        arr = values[0]
        if arr is None:
            return None
        from .scalar import scalar_eval

        vars_, body = self.bound()
        out = []
        for i, el in enumerate(arr):
            e2 = dict(env)
            e2[vars_[0].expr_id] = el
            if len(vars_) > 1:
                e2[vars_[1].expr_id] = i
            out.append(scalar_eval(body, e2))
        return out


class ArrayFilter(HigherOrderFunction):
    fname = "filter"

    def lambda_types(self):
        return [_elem_type(self.args[0].dtype), int32]

    @property
    def dtype(self):
        return self.args[0].dtype

    def scalar_apply(self, values, env):
        arr = values[0]
        if arr is None:
            return None
        from .scalar import scalar_eval

        vars_, body = self.bound()
        out = []
        for i, el in enumerate(arr):
            e2 = dict(env)
            e2[vars_[0].expr_id] = el
            if len(vars_) > 1:
                e2[vars_[1].expr_id] = i
            if scalar_eval(body, e2) is True:
                out.append(el)
        return out


class ArrayExists(HigherOrderFunction):
    """exists(arr, pred) with SQL three-valued logic: TRUE if any
    element satisfies, else NULL if any predicate was NULL, else
    FALSE (ArrayExists.followThreeValuedLogic)."""

    fname = "exists"

    def lambda_types(self):
        return [_elem_type(self.args[0].dtype)]

    @property
    def dtype(self):
        return boolean

    def scalar_apply(self, values, env):
        arr = values[0]
        if arr is None:
            return None
        from .scalar import scalar_eval

        vars_, body = self.bound()
        saw_null = False
        for el in arr:
            r = scalar_eval(body, {**env, vars_[0].expr_id: el})
            if r is True:
                return True
            if r is None:
                saw_null = True
        return None if saw_null else False


class ArrayForAll(HigherOrderFunction):
    fname = "forall"

    def lambda_types(self):
        return [_elem_type(self.args[0].dtype)]

    @property
    def dtype(self):
        return boolean

    def scalar_apply(self, values, env):
        arr = values[0]
        if arr is None:
            return None
        from .scalar import scalar_eval

        vars_, body = self.bound()
        saw_null = False
        for el in arr:
            r = scalar_eval(body, {**env, vars_[0].expr_id: el})
            if r is False:
                return False
            if r is None:
                saw_null = True
        return None if saw_null else True


class ArrayAggregate(HigherOrderFunction):
    """aggregate(arr, zero, (acc, x) -> ..., [acc -> finish])."""

    fname = "aggregate"

    def __init__(self, args, merge: LambdaFunction,
                 finish: LambdaFunction | None = None):
        super().__init__(args, merge)
        self.finish = finish
        self._finish_bound = None

    # finish participates in tree traversal
    child_fields = ("args", "function", "finish")

    def lambda_types(self):
        zero_t = self.args[1].dtype
        return [zero_t, _elem_type(self.args[0].dtype)]

    def finish_bound(self):
        if self.finish is None:
            return None
        if self._finish_bound is None:
            self._finish_bound = self.finish.bind([self.args[1].dtype])
        return self._finish_bound

    @property
    def resolved(self):
        base = super().resolved
        if self.finish is not None:
            base = base and self.finish.resolved
        return base

    @property
    def dtype(self):
        if self.finish is not None:
            return self.finish_bound()[1].dtype
        return self.bound()[1].dtype

    def collection_args(self):
        return [self.args[0]]

    def capture_exprs(self):
        out = [self.bound()[1], self.args[1]]
        fb = self.finish_bound()
        if fb is not None:
            out.append(fb[1])
        return out

    def scalar_apply(self, values, env):
        arr = values[0]
        if arr is None:
            return None
        from .scalar import scalar_eval

        acc = scalar_eval(self.args[1], env)    # zero expr (env-bound)
        vars_, body = self.bound()
        for el in arr:
            acc = scalar_eval(
                body, {**env, vars_[0].expr_id: acc,
                       vars_[1].expr_id: el})
        fb = self.finish_bound()
        if fb is not None:
            fvars, fbody = fb
            acc = scalar_eval(fbody, {**env, fvars[0].expr_id: acc})
        return acc


class ZipWith(HigherOrderFunction):
    """zip_with(a, b, (x, y) -> ...) — pads the shorter side with
    NULLs (reference ZipWith semantics)."""

    fname = "zip_with"

    def lambda_types(self):
        return [_elem_type(self.args[0].dtype),
                _elem_type(self.args[1].dtype)]

    @property
    def dtype(self):
        return ArrayType(self.bound()[1].dtype)

    def scalar_apply(self, values, env):
        a, b = values[0], values[1]
        if a is None or b is None:
            return None
        from .scalar import scalar_eval

        vars_, body = self.bound()
        n = max(len(a), len(b))
        out = []
        for i in range(n):
            out.append(scalar_eval(body, {
                **env,
                vars_[0].expr_id: a[i] if i < len(a) else None,
                vars_[1].expr_id: b[i] if i < len(b) else None}))
        return out


class ArraySortLambda(HigherOrderFunction):
    """array_sort(arr, (a, b) -> cmp) — comparator returns -1/0/1;
    NULLs placed last like the reference's default comparator."""

    fname = "array_sort"

    def lambda_types(self):
        et = _elem_type(self.args[0].dtype)
        return [et, et]

    @property
    def dtype(self):
        return self.args[0].dtype

    def scalar_apply(self, values, env):
        arr = values[0]
        if arr is None:
            return None
        import functools

        from .scalar import scalar_eval

        vars_, body = self.bound()

        def cmp(x, y):
            r = scalar_eval(body, {**env, vars_[0].expr_id: x,
                                   vars_[1].expr_id: y})
            return 0 if r is None else int(r)

        return sorted(arr, key=functools.cmp_to_key(cmp))


class TransformKeys(HigherOrderFunction):
    fname = "transform_keys"

    def lambda_types(self):
        dt = self.args[0].dtype
        if isinstance(dt, MapType):
            return [dt.key_type, dt.value_type]
        return [null_type, null_type]

    @property
    def dtype(self):
        dt = self.args[0].dtype
        vt = dt.value_type if isinstance(dt, MapType) else null_type
        return MapType(self.bound()[1].dtype, vt)

    def scalar_apply(self, values, env):
        m = values[0]
        if m is None:
            return None
        from .scalar import scalar_eval

        vars_, body = self.bound()
        out = {}
        for k, v in m.items():
            nk = scalar_eval(body, {**env, vars_[0].expr_id: k,
                                    vars_[1].expr_id: v})
            if nk is None:
                raise AnalysisException(
                    "transform_keys: a lambda produced a NULL key")
            out[nk] = v
        return out


class TransformValues(TransformKeys):
    fname = "transform_values"

    @property
    def dtype(self):
        dt = self.args[0].dtype
        kt = dt.key_type if isinstance(dt, MapType) else null_type
        return MapType(kt, self.bound()[1].dtype)

    def scalar_apply(self, values, env):
        m = values[0]
        if m is None:
            return None
        from .scalar import scalar_eval

        vars_, body = self.bound()
        return {k: scalar_eval(body, {**env, vars_[0].expr_id: k,
                                      vars_[1].expr_id: v})
                for k, v in m.items()}


class MapFilter(TransformKeys):
    fname = "map_filter"

    @property
    def dtype(self):
        return self.args[0].dtype

    def scalar_apply(self, values, env):
        m = values[0]
        if m is None:
            return None
        from .scalar import scalar_eval

        vars_, body = self.bound()
        return {k: v for k, v in m.items()
                if scalar_eval(body, {**env, vars_[0].expr_id: k,
                                      vars_[1].expr_id: v}) is True}


class MapZipWith(HigherOrderFunction):
    """map_zip_with(m1, m2, (k, v1, v2) -> ...) over the key union."""

    fname = "map_zip_with"

    def lambda_types(self):
        d1, d2 = self.args[0].dtype, self.args[1].dtype
        kt = d1.key_type if isinstance(d1, MapType) else null_type
        v1 = d1.value_type if isinstance(d1, MapType) else null_type
        v2 = d2.value_type if isinstance(d2, MapType) else null_type
        return [kt, v1, v2]

    @property
    def dtype(self):
        d1 = self.args[0].dtype
        kt = d1.key_type if isinstance(d1, MapType) else null_type
        return MapType(kt, self.bound()[1].dtype)

    def scalar_apply(self, values, env):
        m1, m2 = values[0], values[1]
        if m1 is None or m2 is None:
            return None
        from .scalar import scalar_eval

        vars_, body = self.bound()
        keys = list(m1) + [k for k in m2 if k not in m1]
        return {k: scalar_eval(body, {
            **env, vars_[0].expr_id: k,
            vars_[1].expr_id: m1.get(k),
            vars_[2].expr_id: m2.get(k)}) for k in keys}


# ---------------------------------------------------------------------------
# builders (registry entries)
# ---------------------------------------------------------------------------

_INNER_HOFS = {
    "transform": lambda a, f: ArrayTransform([a], f),
    "filter": lambda a, f: ArrayFilter([a], f),
    "exists": lambda a, f: ArrayExists([a], f),
    "forall": lambda a, f: ArrayForAll([a], f),
    "aggregate": lambda a, z, m, fin=None: ArrayAggregate([a, z], m, fin),
    "reduce": lambda a, z, m, fin=None: ArrayAggregate([a, z], m, fin),
    "zip_with": lambda a, b, f: ZipWith([a, b], f),
    "transform_keys": lambda m, f: TransformKeys([m], f),
    "transform_values": lambda m, f: TransformValues([m], f),
    "map_filter": lambda m, f: MapFilter([m], f),
    "map_zip_with": lambda a, b, f: MapZipWith([a, b], f),
    "array_sort": lambda a, f: ArraySortLambda([a], f),
}


def build_inner_function(name: str, args, distinct: bool) -> E.Expression:
    """Function resolution INSIDE a lambda body: nested HOFs stay as HOF
    nodes (the scalar interpreter applies them); everything else goes
    through the normal registry."""
    from .registry import build_function

    b = _INNER_HOFS.get(name.lower())
    if b is not None and any(isinstance(a, LambdaFunction) for a in args):
        return b(*args)
    return build_function(name, list(args), distinct)

def _need_lambda(args, n, name):
    lams = [a for a in args if isinstance(a, LambdaFunction)]
    if len(lams) < n:
        raise AnalysisException(f"{name} expects a lambda argument")
    return lams


def build_transform(arr, f):
    _need_lambda([f], 1, "transform")
    return lower_hof(ArrayTransform([arr], f))


def build_filter(arr, f):
    _need_lambda([f], 1, "filter")
    return lower_hof(ArrayFilter([arr], f))


def build_exists(arr, f):
    _need_lambda([f], 1, "exists")
    return lower_hof(ArrayExists([arr], f))


def build_forall(arr, f):
    _need_lambda([f], 1, "forall")
    return lower_hof(ArrayForAll([arr], f))


def build_aggregate(arr, zero, merge, finish=None):
    _need_lambda([merge], 1, "aggregate")
    return lower_hof(ArrayAggregate([arr, zero], merge, finish))


def build_zip_with(a, b, f):
    _need_lambda([f], 1, "zip_with")
    return lower_hof(ZipWith([a, b], f))


def build_transform_keys(m, f):
    _need_lambda([f], 1, "transform_keys")
    return lower_hof(TransformKeys([m], f))


def build_transform_values(m, f):
    _need_lambda([f], 1, "transform_values")
    return lower_hof(TransformValues([m], f))


def build_map_filter(m, f):
    _need_lambda([f], 1, "map_filter")
    return lower_hof(MapFilter([m], f))


def build_map_zip_with(m1, m2, f):
    _need_lambda([f], 1, "map_zip_with")
    return lower_hof(MapZipWith([m1, m2], f))
