"""Scalar (per-value) expression interpreter.

Role of the reference's interpreted expression eval
(sqlcat/expressions/Expression.scala `eval(InternalRow)`) for the ONE
place the TPU engine needs per-value host evaluation: lambda bodies of
higher-order functions (expr/higher_order.py). Batch expressions run
through the dual host/trace eval in expr/eval.py; lambdas run over the
elements of one collection value, so they evaluate here against an
environment binding lambda variables (and captured outer columns) to
Python values.

Three-valued logic follows SQL: any-null-in → null out for strict
operators; Kleene AND/OR; comparisons on null → null.
"""

from __future__ import annotations

import datetime
import math
from typing import Any, Callable

from ..errors import UnsupportedOperationError
from ..types import (
    ArrayType, BooleanType, DataType, DateType, DecimalType, DoubleType,
    FloatType, IntegerType, LongType, MapType, StringType, TimestampType,
)
from . import expressions as E

__all__ = ["scalar_eval", "free_attributes"]


def free_attributes(e: E.Expression) -> list:
    """Resolved outer-column references inside a lambda body (captured
    variables — the reference allows them; they become extra host
    inputs of the enclosing higher-order function)."""
    out, seen = [], set()
    for n in e.iter_nodes():
        if isinstance(n, E.AttributeReference) and n.expr_id not in seen:
            seen.add(n.expr_id)
            out.append(n)
    return out


def _cast_scalar(v, to: DataType):
    if v is None:
        return None
    try:
        if isinstance(to, StringType):
            if isinstance(v, bool):
                return "true" if v else "false"
            if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
                return f"{v:.1f}"
            return str(v)
        if isinstance(to, (IntegerType, LongType)):
            if isinstance(v, str):
                v = v.strip()
                return int(float(v)) if "." in v or "e" in v.lower() \
                    else int(v)
            return int(v)
        if isinstance(to, (DoubleType, FloatType)):
            return float(v)
        if isinstance(to, BooleanType):
            if isinstance(v, str):
                s = v.strip().lower()
                return True if s in ("true", "t", "1", "yes", "y") else \
                    False if s in ("false", "f", "0", "no", "n") else None
            return bool(v)
        if isinstance(to, DecimalType):
            return round(float(v), to.scale)
        if isinstance(to, (DateType, TimestampType)):
            return v       # already epoch-based ints in this engine
    except (ValueError, TypeError):
        return None
    return v


def _arith(fn: Callable[[Any, Any], Any]):
    def h(e, env):
        a = scalar_eval(e.left, env)
        b = scalar_eval(e.right, env)
        if a is None or b is None:
            return None
        try:
            return fn(a, b)
        except (ZeroDivisionError, ValueError, OverflowError):
            return None
    return h


def _cmp(fn: Callable[[Any, Any], bool]):
    def h(e, env):
        a = scalar_eval(e.left, env)
        b = scalar_eval(e.right, env)
        if a is None or b is None:
            return None
        return bool(fn(a, b))   # numpy bools are not the True singleton
    return h


def _h_and(e, env):
    a = scalar_eval(e.left, env)
    if a is False:
        return False
    b = scalar_eval(e.right, env)
    if b is False:
        return False
    return None if a is None or b is None else True


def _h_or(e, env):
    a = scalar_eval(e.left, env)
    if a is True:
        return True
    b = scalar_eval(e.right, env)
    if b is True:
        return True
    return None if a is None or b is None else False


def _h_case(e, env):
    for cond, val in e.branches:
        if scalar_eval(cond, env) is True:
            return scalar_eval(val, env)
    return scalar_eval(e.else_expr, env)


def _h_if(e, env):
    return scalar_eval(e.then if scalar_eval(e.pred, env) is True
                       else e.otherwise, env)


def _h_in(e, env):
    v = scalar_eval(e.child, env)
    if v is None:
        return None
    saw_null = False
    for item in e.items:
        x = scalar_eval(item, env)
        if x is None:
            saw_null = True
        elif x == v:
            return True
    return None if saw_null else False


def _h_coalesce(e, env):
    for c in e.args:
        v = scalar_eval(c, env)
        if v is not None:
            return v
    return None


def _h_extreme(pick):
    def h(e, env):
        vals = [scalar_eval(c, env) for c in e.args]
        vals = [v for v in vals if v is not None]
        return pick(vals) if vals else None
    return h


def _int_div(a, b):
    if b == 0:
        return None
    return int(a // b)


_DISPATCH: dict[type, Callable] = {
    E.Add: _arith(lambda a, b: a + b),
    E.Subtract: _arith(lambda a, b: a - b),
    E.Multiply: _arith(lambda a, b: a * b),
    E.Divide: _arith(lambda a, b: a / b if b else None),
    # SQL % follows the dividend's sign (fmod), unlike Python's %
    E.Remainder: _arith(lambda a, b: None if not b else (
        math.fmod(a, b) if isinstance(a, float) or isinstance(b, float)
        else int(math.fmod(a, b)))),
    E.Pow: _arith(lambda a, b: float(a) ** float(b)),
    E.EqualTo: _cmp(lambda a, b: a == b),
    E.NotEqualTo: _cmp(lambda a, b: a != b),
    E.LessThan: _cmp(lambda a, b: a < b),
    E.LessThanOrEqual: _cmp(lambda a, b: a <= b),
    E.GreaterThan: _cmp(lambda a, b: a > b),
    E.GreaterThanOrEqual: _cmp(lambda a, b: a >= b),
    E.And: _h_and,
    E.Or: _h_or,
    E.CaseWhen: _h_case,
    E.If: _h_if,
    E.In: _h_in,
    E.Coalesce: _h_coalesce,
    E.Greatest: _h_extreme(max),
    E.Least: _h_extreme(min),
}


def _strict_unary(fn):
    def h(v):
        return None if v is None else fn(v)
    return h


_UNARY: dict[type, Callable] = {
    E.UnaryMinus: _strict_unary(lambda v: -v),
    E.Abs: _strict_unary(abs),
    E.Not: _strict_unary(lambda v: not v),
    E.Floor: _strict_unary(lambda v: int(math.floor(v))),
    E.Ceil: _strict_unary(lambda v: int(math.ceil(v))),
    E.Sqrt: _strict_unary(lambda v: math.sqrt(v) if v >= 0 else None),
    E.Exp: _strict_unary(math.exp),
}


def scalar_eval(e: E.Expression, env: dict) -> Any:
    """Evaluate `e` to one Python value. `env` maps expr_id → value for
    NamedLambdaVariable and captured AttributeReference leaves."""
    from .higher_order import HigherOrderFunction, NamedLambdaVariable

    t = type(e)
    if t is E.Literal:
        return e.value
    if isinstance(e, NamedLambdaVariable):
        return env[e.expr_id]
    if isinstance(e, E.AttributeReference):
        if e.expr_id in env:
            return env[e.expr_id]
        raise UnsupportedOperationError(
            f"unbound column {e.name} inside lambda")
    if t is E.Alias:
        return scalar_eval(e.child, env)
    if t is E.Cast:
        return _cast_scalar(scalar_eval(e.child, env), e.to)
    if t is E.IsNull:
        return scalar_eval(e.child, env) is None
    if t is E.IsNotNull:
        return scalar_eval(e.child, env) is not None
    if t is E.EqualNullSafe:
        a, b = scalar_eval(e.left, env), scalar_eval(e.right, env)
        return a == b if (a is None) == (b is None) else False
    if t is E.NullIf:
        a, b = scalar_eval(e.left, env), scalar_eval(e.right, env)
        return None if a == b else a
    h = _DISPATCH.get(t)
    if h is not None:
        return h(e, env)
    u = _UNARY.get(t)
    if u is not None:
        return u(scalar_eval(e.child, env))
    if isinstance(e, HigherOrderFunction):
        return e.scalar_apply(
            [scalar_eval(c, env) for c in e.collection_args()], env)
    # generic bridges onto the batch-expression micro-kernels: any
    # value_of/transform/int_of expression evaluates one value directly
    if isinstance(e, E._ArrayLut):
        v = scalar_eval(e.child, env)
        if v is None:
            return None
        out, ok = e.value_of(v)
        return out if ok else None
    if isinstance(e, E._StringIntLut):
        v = scalar_eval(e.child, env)
        return None if v is None else e.int_of(v)
    if isinstance(e, E._DictTransform):
        v = scalar_eval(e.child, env)
        return None if v is None else e.transform(v)
    if isinstance(e, E.Concat):
        parts = [scalar_eval(c, env) for c in e.args]
        if any(p is None for p in parts):
            return None
        return "".join(str(p) for p in parts)
    from .pyudf import PythonUDF

    if isinstance(e, PythonUDF):
        # e.g. an array()/map() constructor nested in a lambda body
        return e.fn(*[scalar_eval(a, env) for a in e.args])
    raise UnsupportedOperationError(
        f"expression {type(e).__name__} not supported inside a lambda")
