"""Expression tree.

Role of the reference's ~700 expression classes (sqlcat/expressions/
Expression.scala, Cast.scala, aggregate/interfaces.scala, conditionalExpressions,
stringExpressions, datetimeExpressions...). Each expression here implements a
single `eval(ctx)` that serves both the host metadata pass and the jit trace
pass (see expr/eval.py) — the TPU analog of the reference's dual
interpreted-eval/doGenCode contract.

SQL three-valued logic is carried by optional validity masks; string
computations ride dictionary lookup tables registered through the aux channel.
"""

from __future__ import annotations

import datetime
import math
import re
from typing import Any, Optional, Sequence

import numpy as np

from ..errors import AnalysisException, TypeCheckError, UnsupportedOperationError
from ..plan.tree import TreeNode, next_id
from ..columnar.batch import StringDict, _hash_str
from ..types import (
    ArrayType, BooleanType, ByteType, DataType, DateType, DecimalType,
    DoubleType, FloatType, FractionalType, IntegerType, IntegralType, LongType,
    MapType, NullType, NumericType, ShortType, StringType, StructField,
    StructType, TimestampType,
    boolean, common_type, date, dict_encoded, float32, float64, infer_type,
    int8, int16, int32, int64, null_type, string, timestamp,
)


def _dict_empty(dt):
    """Placeholder dictionary entry for an absent nested value."""
    if isinstance(dt, ArrayType):
        return []
    if isinstance(dt, (MapType, StructType)):
        return {}
    return ""


def _to_device_value(dt, v):
    """Convert a host python value (as arrow to_pylist yields it) to the
    type's device representation — nested dictionaries hold date/
    timestamp/Decimal objects that numeric LUTs must re-encode."""
    if isinstance(v, bool) or v is None:
        return v
    if isinstance(dt, DateType) and isinstance(v, datetime.date):
        return (v - datetime.date(1970, 1, 1)).days
    if isinstance(dt, TimestampType) and isinstance(v, datetime.datetime):
        epoch = datetime.datetime(1970, 1, 1, tzinfo=v.tzinfo)
        return int((v - epoch).total_seconds() * 1_000_000)
    if isinstance(dt, DecimalType):
        import decimal as _d

        if isinstance(v, _d.Decimal):
            return int(v.scaleb(dt.scale).to_integral_value())
    return v
from .eval import EvalCtx, Val

__all__ = [
    "Expression", "Literal", "AttributeReference", "UnresolvedAttribute",
    "UnresolvedStar", "UnresolvedFunction", "Alias", "SortOrder",
    "Add", "Subtract", "Multiply", "Divide", "Remainder", "UnaryMinus",
    "Abs", "Pow", "Sqrt", "Exp", "Log", "Log10", "Floor", "Ceil", "Round",
    "EqualTo", "EqualNullSafe", "NotEqualTo", "LessThan", "LessThanOrEqual",
    "GreaterThan", "GreaterThanOrEqual", "And", "Or", "Not",
    "IsNull", "IsNotNull", "IsNaN", "In", "Like", "RLike", "StartsWith",
    "EndsWith", "Contains", "CaseWhen", "If", "Coalesce", "Cast", "NullIf",
    "Greatest", "Least",
    "Upper", "Lower", "Substring", "Length", "Trim", "LTrim", "RTrim",
    "Concat", "StringReplace", "Lpad", "Rpad",
    "Year", "Month", "DayOfMonth", "Quarter", "DayOfWeek", "DayOfYear",
    "WeekOfYear", "DateAdd", "DateSub", "DateDiff", "TruncDate", "MakeDate",
    "AggregateFunction", "Sum", "Count", "Min", "Max", "Average", "First",
    "AnyValue", "StddevSamp", "StddevPop", "VarianceSamp", "VariancePop",
    "CollectSet",
]


def _jnp():
    import jax.numpy as jnp

    return jnp


# ---------------------------------------------------------------------------
# Base
# ---------------------------------------------------------------------------

class Expression(TreeNode):
    @property
    def dtype(self) -> DataType:
        raise NotImplementedError(type(self).__name__)

    @property
    def nullable(self) -> bool:
        return True

    @property
    def resolved(self) -> bool:
        return all(c.resolved for c in self.children)

    @property
    def foldable(self) -> bool:
        return all(getattr(c, "foldable", False) for c in self.children) \
            and bool(self.children)

    def references(self) -> set[int]:
        out: set[int] = set()
        for n in self.iter_nodes():
            if isinstance(n, AttributeReference):
                out.add(n.expr_id)
        return out

    def eval(self, ctx: EvalCtx) -> Val:
        raise NotImplementedError(type(self).__name__)

    # helpers for DSL composition (api/column wraps these)
    def sql_name(self) -> str:
        return type(self).__name__.lower()


# ---------------------------------------------------------------------------
# Leaves & named expressions
# ---------------------------------------------------------------------------

class Literal(Expression):
    child_fields = ()

    def __init__(self, value: Any, dtype: DataType | None = None):
        if isinstance(value, float) and math.isnan(value):
            pass
        self.value = value
        self._dtype = dtype if dtype is not None else infer_type(value)
        if isinstance(value, datetime.datetime):
            epoch = datetime.datetime(1970, 1, 1, tzinfo=value.tzinfo)
            self.value = int((value - epoch).total_seconds() * 1_000_000)
        elif isinstance(value, datetime.date):
            self.value = (value - datetime.date(1970, 1, 1)).days
        else:
            import decimal as _d

            if isinstance(value, _d.Decimal):
                dt = self._dtype
                assert isinstance(dt, DecimalType)
                self.value = int(value.scaleb(dt.scale).to_integral_value())

    @property
    def dtype(self) -> DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self.value is None

    @property
    def resolved(self) -> bool:
        return True

    @property
    def foldable(self) -> bool:
        return True

    def _data_args(self) -> tuple:
        return (("value", self.value), ("dtype", str(self._dtype)))

    def eval(self, ctx: EvalCtx) -> Val:
        jnp = _jnp()
        dt = self._dtype
        if self.value is None:
            if not ctx.is_trace:
                return Val(dt, None, True,
                           StringDict([_dict_empty(dt)])
                           if dict_encoded(dt) else None)
            z = jnp.zeros((), dtype=dt.device_dtype)
            return Val(dt, z, jnp.zeros((), dtype=bool), None)
        if dict_encoded(dt):
            # string/array/map/struct literal: a one-entry dictionary,
            # all rows code 0
            if not ctx.is_trace:
                return Val(dt, None, None, StringDict([self.value]))
            return Val(dt, jnp.zeros((), dtype=jnp.int32), None, None)
        if not ctx.is_trace:
            return Val(dt, None, None, None)
        v = self.value
        return Val(dt, jnp.asarray(v, dtype=dt.device_dtype), None, None)

    def simple_string(self) -> str:
        return f"lit({self.value!r})"


class AttributeReference(Expression):
    """A resolved column (reference: sqlcat/expressions/namedExpressions.scala
    AttributeReference with exprId for self-join disambiguation)."""

    child_fields = ()

    def __init__(self, name: str, dtype: DataType, nullable: bool = True,
                 expr_id: int | None = None, qualifier: tuple[str, ...] = ()):
        self.name = name
        self._dtype = dtype
        self._nullable = nullable
        self.expr_id = next_id() if expr_id is None else expr_id
        self.qualifier = tuple(qualifier)

    @property
    def dtype(self) -> DataType:
        return self._dtype

    @property
    def nullable(self) -> bool:
        return self._nullable

    @property
    def resolved(self) -> bool:
        return True

    @property
    def foldable(self) -> bool:
        return False

    def with_nullability(self, nullable: bool) -> "AttributeReference":
        return AttributeReference(self.name, self._dtype, nullable,
                                  self.expr_id, self.qualifier)

    def renamed(self, name: str) -> "AttributeReference":
        return AttributeReference(name, self._dtype, self._nullable,
                                  self.expr_id, self.qualifier)

    def new_instance(self) -> "AttributeReference":
        return AttributeReference(self.name, self._dtype, self._nullable,
                                  None, self.qualifier)

    def eval(self, ctx: EvalCtx) -> Val:
        return ctx.attribute(self.expr_id)

    def _data_args(self) -> tuple:
        return (("expr_id", self.expr_id),)

    def simple_string(self) -> str:
        return f"{self.name}#{self.expr_id}"


class UnresolvedAttribute(Expression):
    child_fields = ()

    def __init__(self, name_parts: Sequence[str]):
        self.name_parts = tuple(name_parts)

    @property
    def name(self) -> str:
        return ".".join(self.name_parts)

    @property
    def resolved(self) -> bool:
        return False

    @property
    def foldable(self) -> bool:
        return False

    def simple_string(self) -> str:
        return f"'{self.name}"


class UnresolvedStar(Expression):
    child_fields = ()

    def __init__(self, target: Optional[str] = None):
        self.target = target

    @property
    def resolved(self) -> bool:
        return False


class UnresolvedFunction(Expression):
    child_fields = ("args",)

    def __init__(self, name: str, args: Sequence[Expression],
                 distinct: bool = False):
        self.fname = name
        self.args = list(args)
        self.distinct = distinct

    @property
    def resolved(self) -> bool:
        return False


class Alias(Expression):
    child_fields = ("child",)

    def __init__(self, child: Expression, name: str, expr_id: int | None = None):
        self.child = child
        self.name = name
        self.expr_id = next_id() if expr_id is None else expr_id

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    @property
    def nullable(self) -> bool:
        return self.child.nullable

    def to_attribute(self) -> AttributeReference:
        dt = self.child.dtype if self.child.resolved else null_type
        return AttributeReference(self.name, dt, self.child.nullable,
                                  self.expr_id)

    def eval(self, ctx: EvalCtx) -> Val:
        return ctx.eval(self.child)

    def _data_args(self) -> tuple:
        return (("name", self.name), ("expr_id", self.expr_id))

    def simple_string(self) -> str:
        return f"{self.child.simple_string()} AS {self.name}#{self.expr_id}"


class SortOrder(Expression):
    """Sort direction wrapper (reference: sqlcat/expressions/SortOrder.scala)."""

    child_fields = ("child",)

    def __init__(self, child: Expression, ascending: bool = True,
                 nulls_first: bool | None = None):
        self.child = child
        self.ascending = ascending
        self.nulls_first = nulls_first

    @property
    def dtype(self) -> DataType:
        return self.child.dtype

    def eval(self, ctx: EvalCtx) -> Val:
        return ctx.eval(self.child)


# ---------------------------------------------------------------------------
# Cast
# ---------------------------------------------------------------------------

_TRUE_STRINGS = {"t", "true", "y", "yes", "1"}
_FALSE_STRINGS = {"f", "false", "n", "no", "0"}


def _parse_date(s: str) -> int | None:
    s = s.strip()
    try:
        return (datetime.date.fromisoformat(s[:10]) - datetime.date(1970, 1, 1)).days
    except ValueError:
        return None


def _parse_ts(s: str) -> int | None:
    s = s.strip().replace("T", " ")
    for fmt in ("%Y-%m-%d %H:%M:%S.%f", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d"):
        try:
            d = datetime.datetime.strptime(s, fmt)
            return int((d - datetime.datetime(1970, 1, 1)).total_seconds() * 1e6)
        except ValueError:
            continue
    return None


class Cast(Expression):
    child_fields = ("child",)

    def __init__(self, child: Expression, to: DataType, ansi: bool = False):
        self.child = child
        self.to = to
        self.ansi = ansi

    @property
    def dtype(self) -> DataType:
        return self.to

    @property
    def nullable(self) -> bool:
        frm = self.child.dtype if self.child.resolved else null_type
        if isinstance(frm, StringType) and not isinstance(self.to, StringType):
            return True  # parse failures produce null
        return self.child.nullable

    def eval(self, ctx: EvalCtx) -> Val:
        c = ctx.eval(self.child)
        return cast_val(ctx, c, self.to)

    def simple_string(self) -> str:
        return f"cast({self.child.simple_string()} as {self.to.simple_string()})"


def cast_val(ctx: EvalCtx, c: Val, to: DataType) -> Val:
    jnp = _jnp()
    frm = c.dtype
    if type(frm) is type(to) and frm == to:
        return c
    if isinstance(frm, NullType):
        if not ctx.is_trace:
            return Val(to, None, True,
                       StringDict([""]) if isinstance(to, StringType) else None)
        z = jnp.zeros((), dtype=to.device_dtype)
        return Val(to, z, jnp.zeros((), dtype=bool), None)

    # ---- string source: parse the dictionary host-side --------------------
    if isinstance(frm, StringType) and not isinstance(to, StringType):
        def parse_arrays():
            vals = c.sdict.values if c.sdict else [""]
            out = np.zeros(max(len(vals), 1), dtype=to.device_dtype)
            ok = np.zeros(max(len(vals), 1), dtype=bool)
            for i, s in enumerate(vals):
                p = _parse_str(s, to)
                if p is not None:
                    out[i] = p
                    ok[i] = True
            return out, ok

        if not ctx.is_trace:
            data_lut = ctx.aux(lambda: parse_arrays()[0])
            ok_lut = ctx.aux(lambda: parse_arrays()[1])
            return Val(to, None, True, None)
        data_lut = ctx.aux(None)
        ok_lut = ctx.aux(None)
        codes = jnp.clip(c.data, 0, data_lut.shape[0] - 1)
        data = jnp.take(data_lut, codes)
        ok = jnp.take(ok_lut, codes)
        v = ok if c.validity is None else (ok & c.validity)
        return Val(to, data, v, None)

    # ---- to string: only foldable/dictionary sources supported ------------
    if isinstance(to, StringType):
        raise UnsupportedOperationError(
            f"cast({frm.simple_string()} as string) requires host "
            "materialization (not yet supported on device)")

    if not ctx.is_trace:
        return Val(to, None, c.validity, None)

    data = c.data
    v = c.validity
    # decimal handling
    if isinstance(frm, DecimalType) and isinstance(to, DecimalType):
        delta = to.scale - frm.scale
        if delta >= 0:
            data = data * (10 ** delta)
        else:
            f = 10 ** (-delta)
            half = f // 2
            data = jnp.where(data >= 0, (data + half) // f, -((-data + half) // f))
        return Val(to, data, v, None)
    if isinstance(frm, DecimalType):
        scaled = data.astype(jnp.float64) / (10.0 ** frm.scale)
        return cast_val(ctx, Val(float64, scaled, v, None), to)
    if isinstance(to, DecimalType):
        if jnp.issubdtype(data.dtype, jnp.integer) or data.dtype == jnp.bool_:
            d = data.astype(jnp.int64) * (10 ** to.scale)
        else:
            d = jnp.rint(data.astype(jnp.float64) * (10.0 ** to.scale)).astype(jnp.int64)
        return Val(to, d, v, None)
    # date/timestamp
    if isinstance(frm, DateType) and isinstance(to, TimestampType):
        return Val(to, data.astype(jnp.int64) * 86_400_000_000, v, None)
    if isinstance(frm, TimestampType) and isinstance(to, DateType):
        return Val(to, jnp.floor_divide(data, 86_400_000_000).astype(jnp.int32), v, None)
    if isinstance(frm, (DateType, TimestampType)) and isinstance(to, NumericType):
        return Val(to, data.astype(to.device_dtype), v, None)
    # bool
    if isinstance(to, BooleanType):
        return Val(to, data != 0, v, None)
    if isinstance(frm, BooleanType):
        return Val(to, data.astype(to.device_dtype), v, None)
    # float -> int truncates toward zero
    if isinstance(frm, FractionalType) and isinstance(to, IntegralType):
        t = jnp.nan_to_num(jnp.trunc(data), nan=0.0, posinf=0.0, neginf=0.0)
        return Val(to, t.astype(to.device_dtype), v, None)
    return Val(to, data.astype(to.device_dtype), v, None)


def _parse_str(s: str, to: DataType):
    s = s.strip()
    try:
        if isinstance(to, BooleanType):
            ls = s.lower()
            if ls in _TRUE_STRINGS:
                return True
            if ls in _FALSE_STRINGS:
                return False
            return None
        if isinstance(to, IntegralType):
            return int(float(s)) if ("." in s or "e" in s.lower()) else int(s)
        if isinstance(to, DecimalType):
            import decimal as _d

            return int(_d.Decimal(s).scaleb(to.scale).to_integral_value(
                rounding=_d.ROUND_HALF_UP))
        if isinstance(to, FractionalType):
            return float(s)
        if isinstance(to, DateType):
            return _parse_date(s)
        if isinstance(to, TimestampType):
            return _parse_ts(s)
    except (ValueError, ArithmeticError):
        return None
    return None


# ---------------------------------------------------------------------------
# Arithmetic
# ---------------------------------------------------------------------------

class BinaryExpression(Expression):
    child_fields = ("left", "right")
    symbol = "?"

    def __init__(self, left: Expression, right: Expression):
        self.left = left
        self.right = right

    def simple_string(self) -> str:
        return (f"({self.left.simple_string()} {self.symbol} "
                f"{self.right.simple_string()})")


class BinaryArithmetic(BinaryExpression):
    @property
    def dtype(self) -> DataType:
        lt, rt = self.left.dtype, self.right.dtype
        ct = common_type(lt, rt)
        if ct is None or not isinstance(ct, (NumericType,)):
            if isinstance(lt, (DateType,)) or isinstance(rt, (DateType,)):
                return self._date_result(lt, rt)
            raise TypeCheckError(
                f"{type(self).__name__} needs numeric operands, got "
                f"{lt.simple_string()}, {rt.simple_string()}")
        return self._result_type(ct)

    def _date_result(self, lt, rt) -> DataType:
        raise TypeCheckError(f"cannot apply {self.symbol} to dates")

    def _result_type(self, ct: DataType) -> DataType:
        return ct

    def eval(self, ctx: EvalCtx) -> Val:
        l = ctx.eval(self.left)
        r = ctx.eval(self.right)
        v = ctx.and_valid(l, r)
        out = self.dtype
        if not ctx.is_trace:
            return Val(out, None, v, None)
        jnp = _jnp()
        ld, rd = self._align(ctx, l, r, out)
        data, extra_null = self._op(ld, rd)
        if extra_null is not None:
            v = extra_null if v is None else (v & extra_null)
        return Val(out, data, v, None)

    def _align(self, ctx, l: Val, r: Val, out: DataType):
        jnp = _jnp()
        if isinstance(out, DecimalType):
            lc = cast_val(ctx, l, out) if not isinstance(l.dtype, DecimalType) else l
            rc = cast_val(ctx, r, out) if not isinstance(r.dtype, DecimalType) else r
            return lc.data, rc.data
        dd = out.device_dtype
        return l.data.astype(dd), r.data.astype(dd)

    def _op(self, l, r):
        raise NotImplementedError


class Add(BinaryArithmetic):
    symbol = "+"

    @property
    def dtype(self):
        if isinstance(self.right, IntervalLiteral):
            return self.left.dtype
        if isinstance(self.left, IntervalLiteral):
            return self.right.dtype
        return super().dtype

    def _date_result(self, lt, rt):
        if isinstance(lt, DateType) and isinstance(rt, IntegralType):
            return date
        if isinstance(rt, DateType) and isinstance(lt, IntegralType):
            return date
        raise TypeCheckError("date + non-int")

    def _result_type(self, ct):
        if isinstance(ct, DecimalType):
            return DecimalType(min(ct.precision + 1, DecimalType.MAX_PRECISION),
                               ct.scale)
        return ct

    def eval(self, ctx):
        if isinstance(self.right, IntervalLiteral) or \
                isinstance(self.left, IntervalLiteral):
            iv = self.right if isinstance(self.right, IntervalLiteral) \
                else self.left
            other = self.left if iv is self.right else self.right
            side = ctx.eval(other)
            if not ctx.is_trace:
                out_dt = side.dtype
                return Val(out_dt, None, side.validity, None)
            return _apply_interval(ctx, side, iv)
        lt = self.left.dtype if self.left.resolved else null_type
        rt = self.right.dtype if self.right.resolved else null_type
        if isinstance(lt, DateType) or isinstance(rt, DateType):
            l, r = ctx.eval(self.left), ctx.eval(self.right)
            v = ctx.and_valid(l, r)
            if not ctx.is_trace:
                return Val(date, None, v, None)
            jnp = _jnp()
            if isinstance(lt, DateType):
                return Val(date, l.data + r.data.astype(jnp.int32), v, None)
            return Val(date, r.data + l.data.astype(jnp.int32), v, None)
        return super().eval(ctx)

    def _op(self, l, r):
        return l + r, None


class Subtract(BinaryArithmetic):
    symbol = "-"

    @property
    def dtype(self):
        if isinstance(self.right, IntervalLiteral):
            return self.left.dtype
        return super().dtype

    def _date_result(self, lt, rt):
        if isinstance(lt, DateType) and isinstance(rt, DateType):
            return int32
        if isinstance(lt, DateType) and isinstance(rt, IntegralType):
            return date
        raise TypeCheckError("unsupported date subtraction")

    def _result_type(self, ct):
        if isinstance(ct, DecimalType):
            return DecimalType(min(ct.precision + 1, DecimalType.MAX_PRECISION),
                               ct.scale)
        return ct

    def eval(self, ctx):
        if isinstance(self.right, IntervalLiteral):
            side = ctx.eval(self.left)
            if not ctx.is_trace:
                return Val(side.dtype, None, side.validity, None)
            return _apply_interval(ctx, side, self.right.negated())
        lt = self.left.dtype if self.left.resolved else null_type
        rt = self.right.dtype if self.right.resolved else null_type
        if isinstance(lt, DateType):
            l, r = ctx.eval(self.left), ctx.eval(self.right)
            v = ctx.and_valid(l, r)
            out = self._date_result(lt, rt)
            if not ctx.is_trace:
                return Val(out, None, v, None)
            jnp = _jnp()
            return Val(out, (l.data - r.data).astype(jnp.int32), v, None)
        return super().eval(ctx)

    def _op(self, l, r):
        return l - r, None


class Multiply(BinaryArithmetic):
    symbol = "*"

    @staticmethod
    def _decimal_types(lt, rt):
        def as_dec(t):
            if isinstance(t, DecimalType):
                return t
            if isinstance(t, IntegralType):
                p = {1: 3, 2: 5, 4: 10, 8: 19}[t.device_dtype.itemsize]
                return DecimalType(p, 0)
            return None

        ld, rd = as_dec(lt), as_dec(rt)
        if ld is not None and rd is not None and (
                isinstance(lt, DecimalType) or isinstance(rt, DecimalType)):
            return ld, rd
        return None

    def _result_type(self, ct):
        if isinstance(ct, DecimalType):
            lt = self.left.dtype
            rt = self.right.dtype
            dd = self._decimal_types(lt, rt)
            if dd is not None:
                p = dd[0].precision + dd[1].precision
                s = dd[0].scale + dd[1].scale
                if p <= DecimalType.MAX_PRECISION:
                    return DecimalType(p, s)  # exact scaled-int64 product
            # precision exceeds int64 → float64 (documented deviation)
            return float64
        return ct

    def _align(self, ctx, l, r, out):
        if isinstance(out, DecimalType):
            # exact path: raw scaled int64 product, scales add
            ld = l.data if isinstance(l.dtype, DecimalType) \
                else l.data.astype(_jnp().int64)
            rd = r.data if isinstance(r.dtype, DecimalType) \
                else r.data.astype(_jnp().int64)
            return ld, rd
        if isinstance(out, FractionalType) and (
                isinstance(l.dtype, DecimalType) or isinstance(r.dtype, DecimalType)):
            lc = cast_val(ctx, l, float64)
            rc = cast_val(ctx, r, float64)
            return lc.data, rc.data
        return super()._align(ctx, l, r, out)

    def _op(self, l, r):
        return l * r, None


class TryAdd(Add):
    """try_add: NULL on integral overflow instead of wrapping (reference:
    Add with EvalMode.TRY, sqlcat/expressions/arithmetic.scala)."""

    def _op(self, l, r):
        data, _ = super()._op(l, r)
        jnp = _jnp()
        if not jnp.issubdtype(data.dtype, jnp.signedinteger):
            return data, None
        # signed add overflows iff operands share a sign the result lost
        ok = ~(((l >= 0) == (r >= 0)) & ((data >= 0) != (l >= 0)))
        return data, ok


class TrySubtract(Subtract):
    """try_subtract: NULL on integral overflow instead of wrapping."""

    def _op(self, l, r):
        data, _ = super()._op(l, r)
        jnp = _jnp()
        if not jnp.issubdtype(data.dtype, jnp.signedinteger):
            return data, None
        ok = ~(((l >= 0) != (r >= 0)) & ((data >= 0) != (l >= 0)))
        return data, ok


class TryMultiply(Multiply):
    """try_multiply: NULL on integral overflow instead of wrapping."""

    def _op(self, l, r):
        data, _ = super()._op(l, r)
        jnp = _jnp()
        if not jnp.issubdtype(data.dtype, jnp.signedinteger):
            return data, None
        info = jnp.iinfo(data.dtype)
        if info.bits < 64:
            wide = l.astype(jnp.int64) * r.astype(jnp.int64)
            return data, (wide >= info.min) & (wide <= info.max)
        # int64: division check is exact — wrapped result res = l*r - k*2^64
        # with floor(res/l) == r forces k == 0; only the (-1, INT64_MIN)
        # pair needs special-casing (its quotient itself wraps)
        nz = jnp.where(l == 0, jnp.ones_like(l), l)
        ok = (l == 0) | (jnp.floor_divide(data, nz) == r)
        ok = ok & ~((l == -1) & (r == info.min))
        return data, ok


class Divide(BinaryArithmetic):
    symbol = "/"

    def _result_type(self, ct):
        return float64

    def _align(self, ctx, l, r, out):
        return (cast_val(ctx, l, float64).data, cast_val(ctx, r, float64).data)

    def _op(self, l, r):
        jnp = _jnp()
        zero = r == 0
        safe = jnp.where(zero, _jnp().ones_like(r), r)
        return l / safe, ~zero  # x/0 => NULL (non-ANSI Spark semantics)


class Remainder(BinaryArithmetic):
    symbol = "%"

    def _op(self, l, r):
        jnp = _jnp()
        zero = r == 0
        safe = jnp.where(zero, jnp.ones_like(r), r)
        # Spark % keeps the sign of the dividend (like Java), numpy keeps divisor's
        if jnp.issubdtype(l.dtype, jnp.floating):
            m = l - jnp.trunc(l / safe) * safe
        else:
            m = l - jnp.sign(l) * (jnp.abs(l) // jnp.abs(safe)) * jnp.abs(safe)
        return m, ~zero


class UnaryExpression(Expression):
    child_fields = ("child",)

    def __init__(self, child: Expression):
        self.child = child

    def simple_string(self) -> str:
        return f"{self.sql_name()}({self.child.simple_string()})"


class UnaryMinus(UnaryExpression):
    @property
    def dtype(self):
        return self.child.dtype

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if not ctx.is_trace:
            return Val(self.dtype, None, c.validity, None)
        return Val(self.dtype, -c.data, c.validity, None)


class Abs(UnaryExpression):
    @property
    def dtype(self):
        return self.child.dtype

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if not ctx.is_trace:
            return Val(self.dtype, None, c.validity, None)
        return Val(self.dtype, _jnp().abs(c.data), c.validity, None)


class _MathUnary(UnaryExpression):
    fn = None
    domain_check = None  # optional lambda returning ok-mask

    @property
    def dtype(self):
        return float64

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if not ctx.is_trace:
            return Val(float64, None, True if (self.domain_check or c.has_validity) else None, None)
        jnp = _jnp()
        x = cast_val(ctx, c, float64).data
        v = c.validity
        if self.domain_check is not None:
            ok = self.domain_check(x)
            x = jnp.where(ok, x, jnp.ones_like(x))
            v = ok if v is None else (v & ok)
        data = self.fn(x)
        return Val(float64, data, v, None)


class Sqrt(_MathUnary):
    fn = staticmethod(lambda x: _jnp().sqrt(x))
    domain_check = staticmethod(lambda x: x >= 0)


class Exp(_MathUnary):
    fn = staticmethod(lambda x: _jnp().exp(x))


class Log(_MathUnary):
    fn = staticmethod(lambda x: _jnp().log(x))
    domain_check = staticmethod(lambda x: x > 0)


class Log10(_MathUnary):
    fn = staticmethod(lambda x: _jnp().log10(x))
    domain_check = staticmethod(lambda x: x > 0)


class Sin(_MathUnary):
    fn = staticmethod(lambda x: _jnp().sin(x))


class Cos(_MathUnary):
    fn = staticmethod(lambda x: _jnp().cos(x))


class Tan(_MathUnary):
    fn = staticmethod(lambda x: _jnp().tan(x))


class Asin(_MathUnary):
    fn = staticmethod(lambda x: _jnp().arcsin(x))
    domain_check = staticmethod(lambda x: _jnp().abs(x) <= 1)


class Acos(_MathUnary):
    fn = staticmethod(lambda x: _jnp().arccos(x))
    domain_check = staticmethod(lambda x: _jnp().abs(x) <= 1)


class Atan(_MathUnary):
    fn = staticmethod(lambda x: _jnp().arctan(x))


class Sinh(_MathUnary):
    fn = staticmethod(lambda x: _jnp().sinh(x))


class Cosh(_MathUnary):
    fn = staticmethod(lambda x: _jnp().cosh(x))


class Tanh(_MathUnary):
    fn = staticmethod(lambda x: _jnp().tanh(x))


class Log2(_MathUnary):
    fn = staticmethod(lambda x: _jnp().log2(x))
    domain_check = staticmethod(lambda x: x > 0)


class Log1p(_MathUnary):
    fn = staticmethod(lambda x: _jnp().log1p(x))
    domain_check = staticmethod(lambda x: x > -1)


class Expm1(_MathUnary):
    fn = staticmethod(lambda x: _jnp().expm1(x))


class Degrees(_MathUnary):
    fn = staticmethod(lambda x: _jnp().degrees(x))


class Radians(_MathUnary):
    fn = staticmethod(lambda x: _jnp().radians(x))


class Cbrt(_MathUnary):
    fn = staticmethod(lambda x: _jnp().cbrt(x))


class Atan2(BinaryArithmetic):
    symbol = "atan2"

    def _result_type(self, ct):
        return float64

    def _align(self, ctx, l, r, out):
        return (cast_val(ctx, l, float64).data, cast_val(ctx, r, float64).data)

    def _op(self, l, r):
        return _jnp().arctan2(l, r), None


class Signum(UnaryExpression):
    @property
    def dtype(self):
        return float64

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if not ctx.is_trace:
            return Val(float64, None, c.validity, None)
        jnp = _jnp()
        return Val(float64, jnp.sign(c.data.astype(jnp.float64)),
                   c.validity, None)


class Floor(UnaryExpression):
    @property
    def dtype(self):
        ct = self.child.dtype
        return ct if isinstance(ct, (IntegralType, DecimalType)) else int64

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if isinstance(c.dtype, (IntegralType,)):
            return c
        if not ctx.is_trace:
            return Val(self.dtype, None, c.validity, None)
        jnp = _jnp()
        if isinstance(c.dtype, DecimalType):
            f = 10 ** c.dtype.scale
            d = jnp.where(c.data >= 0, c.data // f, -((-c.data + f - 1) // f)) * f
            return Val(c.dtype, d, c.validity, None)
        return Val(int64, jnp.floor(c.data).astype(jnp.int64), c.validity, None)


class Ceil(UnaryExpression):
    @property
    def dtype(self):
        ct = self.child.dtype
        return ct if isinstance(ct, (IntegralType, DecimalType)) else int64

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if isinstance(c.dtype, (IntegralType,)):
            return c
        if not ctx.is_trace:
            return Val(self.dtype, None, c.validity, None)
        jnp = _jnp()
        if isinstance(c.dtype, DecimalType):
            f = 10 ** c.dtype.scale
            d = jnp.where(c.data >= 0, (c.data + f - 1) // f, -((-c.data) // f)) * f
            return Val(c.dtype, d, c.validity, None)
        return Val(int64, jnp.ceil(c.data).astype(jnp.int64), c.validity, None)


class NanVl(Expression):
    """nanvl(a, b): b where a is NaN (mathExpressions.scala NaNvl)."""

    child_fields = ("left", "right")

    def __init__(self, left: Expression, right: Expression):
        self.left = left
        self.right = right

    @property
    def dtype(self):
        return float64

    def eval(self, ctx):
        a = ctx.eval(cast_if(self.left, float64))
        b = ctx.eval(cast_if(self.right, float64))
        if not ctx.is_trace:
            return Val(float64, None,
                       True if a.has_validity or b.has_validity else None,
                       None)
        jnp = _jnp()
        nan = jnp.isnan(a.data)
        data = jnp.where(nan, jnp.broadcast_to(b.data, jnp.shape(
            jnp.broadcast_to(a.data, (ctx.capacity,)))), a.data)
        valid = None
        if a.validity is not None or b.validity is not None:
            av = a.validity if a.validity is not None else jnp.ones((), bool)
            bv = b.validity if b.validity is not None else jnp.ones((), bool)
            # a NULL left operand stays NULL even if its masked payload
            # is NaN (Spark: the null check precedes the NaN check)
            valid = jnp.broadcast_to(jnp.where(nan, av & bv, av),
                                     (ctx.capacity,))
        return Val(float64, data, valid, None)


class Round(Expression):
    child_fields = ("child", "scale_expr")

    def __init__(self, child: Expression, scale_expr: Expression | None = None):
        self.child = child
        self.scale_expr = scale_expr if scale_expr is not None else Literal(0)

    @property
    def dtype(self):
        ct = self.child.dtype
        return ct if isinstance(ct, (IntegralType, DecimalType)) else float64

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if not isinstance(self.scale_expr, Literal):
            raise UnsupportedOperationError("round() scale must be a literal")
        s = int(self.scale_expr.value or 0)
        if not ctx.is_trace:
            return Val(self.dtype, None, c.validity, None)
        jnp = _jnp()
        if isinstance(c.dtype, DecimalType):
            delta = c.dtype.scale - s
            if delta <= 0:
                return c
            f = 10 ** delta
            half = f // 2
            d = jnp.where(c.data >= 0, (c.data + half) // f, -((-c.data + half) // f)) * f
            return Val(c.dtype, d, c.validity, None)
        if isinstance(c.dtype, IntegralType):
            return c
        x = cast_val(ctx, c, float64).data
        f = 10.0 ** s
        # HALF_UP like Spark (not banker's rounding)
        d = jnp.trunc(x * f + jnp.where(x >= 0, 0.5, -0.5)) / f
        return Val(float64, d, c.validity, None)


class BRound(Round):
    """bround: HALF_EVEN (banker's) rounding — Spark's bround vs round
    split (mathExpressions.scala BRound)."""

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if not isinstance(self.scale_expr, Literal):
            raise UnsupportedOperationError(
                "bround() scale must be a literal")
        s = int(self.scale_expr.value or 0)
        if not ctx.is_trace:
            return Val(self.dtype, None, c.validity, None)
        jnp = _jnp()
        if isinstance(c.dtype, DecimalType):
            delta = c.dtype.scale - s
            if delta <= 0:
                return c
            f = 10 ** delta
            half = f // 2
            sign = jnp.where(c.data >= 0, 1, -1)
            a = jnp.abs(c.data)
            q = a // f
            r = a - q * f
            up = (r > half) | ((r == half) & (q % 2 == 1))  # half-to-even
            d = sign * (q + up.astype(q.dtype)) * f
            return Val(c.dtype, d, c.validity, None)
        if isinstance(c.dtype, IntegralType):
            return c
        x = cast_val(ctx, c, float64).data
        f = 10.0 ** s
        d = jnp.rint(x * f) / f  # rint = round-half-to-even
        return Val(float64, d, c.validity, None)


class BitwiseAnd(BinaryArithmetic):
    symbol = "&"

    def _op(self, l, r):
        return l & r, None


class BitwiseOr(BinaryArithmetic):
    symbol = "|"

    def _op(self, l, r):
        return l | r, None


class BitwiseXor(BinaryArithmetic):
    symbol = "^"

    def _op(self, l, r):
        return l ^ r, None


class BitwiseNot(UnaryExpression):
    @property
    def dtype(self):
        return self.child.dtype

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if not ctx.is_trace:
            return Val(self.dtype, None, c.validity, None)
        return Val(self.dtype, ~c.data, c.validity, None)


class ShiftLeft(BinaryArithmetic):
    symbol = "<<"

    def _op(self, l, r):
        return l << r, None


class ShiftRight(BinaryArithmetic):
    symbol = ">>"

    def _op(self, l, r):
        return l >> r, None


class Pow(BinaryArithmetic):
    symbol = "^"

    def _result_type(self, ct):
        return float64

    def _align(self, ctx, l, r, out):
        return (cast_val(ctx, l, float64).data, cast_val(ctx, r, float64).data)

    def _op(self, l, r):
        return l ** r, None


# ---------------------------------------------------------------------------
# Comparisons (string-aware)
# ---------------------------------------------------------------------------

def _string_eq_domain(ctx: EvalCtx, v: Val):
    """Map a string Val's codes to 64-bit value hashes via an aux lut."""
    jnp = _jnp()
    if not ctx.is_trace:
        lut = ctx.aux(lambda: (v.sdict.hashes if v.sdict and len(v.sdict)
                               else np.zeros(1, np.int64)))
        return None
    lut = ctx.aux(None)
    codes = jnp.clip(v.data, 0, lut.shape[0] - 1)
    return jnp.take(lut, codes)


def _string_rank_domain(ctx: EvalCtx, l: Val, r: Val):
    """Map two string Vals into a common ordering domain (merged-dict ranks)."""
    jnp = _jnp()

    def make_luts():
        a = l.sdict or StringDict([""])
        b = r.sdict or StringDict([""])
        allv = sorted(set(a.values) | set(b.values))
        pos = {v: i for i, v in enumerate(allv)}
        la = np.array([pos[v] for v in a.values] or [0], dtype=np.int64)
        lb = np.array([pos[v] for v in b.values] or [0], dtype=np.int64)
        return la, lb

    if not ctx.is_trace:
        ctx.aux(lambda: make_luts()[0])
        ctx.aux(lambda: make_luts()[1])
        return None, None
    la = ctx.aux(None)
    lb = ctx.aux(None)
    ld = jnp.take(la, jnp.clip(l.data, 0, la.shape[0] - 1))
    rd = jnp.take(lb, jnp.clip(r.data, 0, lb.shape[0] - 1))
    return ld, rd


class BinaryComparison(BinaryExpression):
    @property
    def dtype(self):
        return boolean

    def eval(self, ctx):
        l = ctx.eval(self.left)
        r = ctx.eval(self.right)
        lt, rt = l.dtype, r.dtype
        is_string = isinstance(lt, StringType) and isinstance(rt, StringType)
        if is_string:
            v = ctx.and_valid(l, r)
            if type(self) in (EqualTo, NotEqualTo, EqualNullSafe):
                ld = _string_eq_domain(ctx, l)
                rd = _string_eq_domain(ctx, r)
            else:
                ld, rd = _string_rank_domain(ctx, l, r)
            if not ctx.is_trace:
                return Val(boolean, None, v, None)
        else:
            # casts run in BOTH modes: string→X casts register dictionary
            # parse tables through the aux channel (host/trace symmetry)
            ct = common_type(lt, rt) or lt
            lc = cast_val(ctx, l, ct)
            rc = cast_val(ctx, r, ct)
            v = ctx.and_valid(lc, rc)
            if not ctx.is_trace:
                return Val(boolean, None, v, None)
            ld, rd = lc.data, rc.data
        return Val(boolean, self._cmp(ld, rd), v, None)

    def _cmp(self, l, r):
        raise NotImplementedError


class EqualTo(BinaryComparison):
    symbol = "="

    def _cmp(self, l, r):
        return l == r


class NotEqualTo(BinaryComparison):
    symbol = "!="

    def _cmp(self, l, r):
        return l != r


class EqualNullSafe(BinaryComparison):
    symbol = "<=>"

    def eval(self, ctx):
        l = ctx.eval(self.left)
        r = ctx.eval(self.right)
        is_string = isinstance(l.dtype, StringType) and isinstance(r.dtype, StringType)
        if is_string:
            ld = _string_eq_domain(ctx, l)
            rd = _string_eq_domain(ctx, r)
            lc, rc = l, r
        else:
            ct = common_type(l.dtype, r.dtype) or l.dtype
            lc = cast_val(ctx, l, ct)
            rc = cast_val(ctx, r, ct)
        if not ctx.is_trace:
            return Val(boolean, None, None, None)
        jnp = _jnp()
        if not is_string:
            ld, rd = lc.data, rc.data
        eq = ld == rd
        lv = lc.validity if lc.validity is not None else jnp.ones((), bool)
        rv = rc.validity if rc.validity is not None else jnp.ones((), bool)
        both_null = (~lv) & (~rv)
        data = jnp.where(lv & rv, eq, both_null)
        return Val(boolean, data, None, None)

    @property
    def nullable(self):
        return False


class LessThan(BinaryComparison):
    symbol = "<"

    def _cmp(self, l, r):
        return l < r


class LessThanOrEqual(BinaryComparison):
    symbol = "<="

    def _cmp(self, l, r):
        return l <= r


class GreaterThan(BinaryComparison):
    symbol = ">"

    def _cmp(self, l, r):
        return l > r


class GreaterThanOrEqual(BinaryComparison):
    symbol = ">="

    def _cmp(self, l, r):
        return l >= r


# ---------------------------------------------------------------------------
# Boolean logic — Kleene three-valued
# ---------------------------------------------------------------------------

class And(BinaryExpression):
    symbol = "AND"

    @property
    def dtype(self):
        return boolean

    def eval(self, ctx):
        l = ctx.eval(self.left)
        r = ctx.eval(self.right)
        if not ctx.is_trace:
            v = ctx.and_valid(l, r)
            return Val(boolean, None, v, None)
        jnp = _jnp()
        lv, rv = l.validity, r.validity
        ld, rd = l.data, r.data
        if lv is None and rv is None:
            return Val(boolean, ld & rd, None, None)
        lvv = lv if lv is not None else jnp.ones((), bool)
        rvv = rv if rv is not None else jnp.ones((), bool)
        # Kleene AND: FALSE wins over NULL; result known iff both known or
        # either side is a known FALSE
        known = (lvv & rvv) | (lvv & ~ld) | (rvv & ~rd)
        t_l = jnp.where(lvv, ld, False)
        t_r = jnp.where(rvv, rd, False)
        return Val(boolean, t_l & t_r, known, None)


class Or(BinaryExpression):
    symbol = "OR"

    @property
    def dtype(self):
        return boolean

    def eval(self, ctx):
        l = ctx.eval(self.left)
        r = ctx.eval(self.right)
        if not ctx.is_trace:
            v = ctx.and_valid(l, r)
            return Val(boolean, None, v, None)
        jnp = _jnp()
        lv, rv = l.validity, r.validity
        ld, rd = l.data, r.data
        if lv is None and rv is None:
            return Val(boolean, ld | rd, None, None)
        lvv = lv if lv is not None else jnp.ones((), bool)
        rvv = rv if rv is not None else jnp.ones((), bool)
        known = (lvv & rvv) | (lvv & ld) | (rvv & rd)
        t_l = jnp.where(lvv, ld, False)
        t_r = jnp.where(rvv, rd, False)
        return Val(boolean, t_l | t_r, known, None)


class Not(UnaryExpression):
    @property
    def dtype(self):
        return boolean

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if not ctx.is_trace:
            return Val(boolean, None, c.validity, None)
        return Val(boolean, ~c.data, c.validity, None)


# ---------------------------------------------------------------------------
# Null predicates / conditionals
# ---------------------------------------------------------------------------

class IsNull(UnaryExpression):
    @property
    def dtype(self):
        return boolean

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if not ctx.is_trace:
            return Val(boolean, None, None, None)
        jnp = _jnp()
        if c.validity is None:
            return Val(boolean, jnp.zeros((), bool), None, None)
        return Val(boolean, ~c.validity, None, None)


class IsNotNull(UnaryExpression):
    @property
    def dtype(self):
        return boolean

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if not ctx.is_trace:
            return Val(boolean, None, None, None)
        jnp = _jnp()
        if c.validity is None:
            return Val(boolean, jnp.ones((), bool), None, None)
        return Val(boolean, c.validity, None, None)


class IsNaN(UnaryExpression):
    @property
    def dtype(self):
        return boolean

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if not ctx.is_trace:
            return Val(boolean, None, c.validity, None)
        jnp = _jnp()
        if jnp.issubdtype(c.data.dtype, jnp.floating):
            return Val(boolean, jnp.isnan(c.data), c.validity, None)
        return Val(boolean, jnp.zeros((), bool), c.validity, None)


class If(Expression):
    child_fields = ("pred", "then", "otherwise")

    def __init__(self, pred, then, otherwise):
        self.pred = pred
        self.then = then
        self.otherwise = otherwise

    @property
    def dtype(self):
        return common_type(self.then.dtype, self.otherwise.dtype) or self.then.dtype

    def eval(self, ctx):
        return CaseWhen([(self.pred, self.then)], self.otherwise).eval(ctx)


class CaseWhen(Expression):
    child_fields = ("branch_exprs", "else_expr")
    equality_excluded_fields = ("branches",)  # same nodes as branch_exprs

    def __init__(self, branches: Sequence[tuple[Expression, Expression]],
                 else_expr: Expression | None = None):
        self.branches = [(p, v) for p, v in branches]
        self.branch_exprs = [e for pv in self.branches for e in pv]
        self.else_expr = else_expr if else_expr is not None else Literal(None)

    def copy(self, **overrides):
        if "branch_exprs" in overrides:
            be = overrides.pop("branch_exprs")
            overrides["branches"] = [(be[i], be[i + 1]) for i in range(0, len(be), 2)]
            new = object.__new__(type(self))
            new.__dict__.update(self.__dict__)
            new.__dict__.update(overrides)
            new.__dict__["branch_exprs"] = list(be)
            new.__dict__.pop("_hash", None)
            new.__dict__.pop("_dtype_memo", None)  # branches changed
            return new
        return super().copy(**overrides)

    @property
    def dtype(self):
        # memoized: a chain of nested CASEs (greatest/least expansion)
        # revisits each level's dtype from every ancestor — uncached
        # recursion is exponential in chain depth
        memo = self.__dict__.get("_dtype_memo")
        if memo is not None:
            return memo
        dt: DataType = null_type
        for _, v in self.branches:
            dt = common_type(dt, v.dtype) or v.dtype
        dt = common_type(dt, self.else_expr.dtype) or dt
        self.__dict__["_dtype_memo"] = dt
        return dt

    def eval(self, ctx):
        out = self.dtype
        jnp = _jnp()
        if isinstance(out, StringType):
            return self._eval_string(ctx)
        vals = [(ctx.eval(p), ctx.eval(cast_if(v, out))) for p, v in
                [(p, v) for p, v in self.branches]]
        ev = ctx.eval(cast_if(self.else_expr, out))
        if not ctx.is_trace:
            anynull = any(v.has_validity for _, v in vals) or ev.has_validity or \
                any(p.has_validity for p, _ in vals)
            return Val(out, None, True if anynull else None, None)
        data = jnp.broadcast_to(ev.data, (ctx.capacity,)) if ev.data.ndim == 0 else ev.data
        valid = ev.validity if ev.validity is not None else jnp.ones((), bool)
        valid = jnp.broadcast_to(valid, (ctx.capacity,))
        data = jnp.broadcast_to(data, (ctx.capacity,))
        decided = jnp.zeros((ctx.capacity,), bool)
        # evaluate branches first-match-wins
        for p, v in vals:
            pd = p.data
            if p.validity is not None:
                pd = pd & p.validity
            hit = jnp.broadcast_to(pd, (ctx.capacity,)) & ~decided
            vd = jnp.broadcast_to(v.data, (ctx.capacity,))
            vv = v.validity if v.validity is not None else jnp.ones((), bool)
            vv = jnp.broadcast_to(vv, (ctx.capacity,))
            data = jnp.where(hit, vd, data)
            valid = jnp.where(hit, vv, valid)
            decided = decided | hit
        has_any_null = (ev.validity is not None) or \
            any(v.validity is not None for _, v in vals)
        return Val(out, data, valid if has_any_null else None, None)

    def _eval_string(self, ctx):
        """String CASE: merge branch dictionaries into one output dict."""
        jnp = _jnp()
        branch_vals = [(ctx.eval(p), ctx.eval(v)) for p, v in self.branches]
        ev = ctx.eval(self.else_expr)
        all_strs = branch_vals + [(None, ev)]

        def merged_dict():
            md: list[str] = []
            idx: dict[str, int] = {}
            luts = []
            for _, v in all_strs:
                sd = v.sdict or StringDict([""])
                lut = np.zeros(max(len(sd), 1), np.int32)
                for i, s in enumerate(sd.values or [""]):
                    j = idx.get(s)
                    if j is None:
                        j = len(md)
                        md.append(s)
                        idx[s] = j
                    lut[i] = j
                luts.append(lut)
            return StringDict(md or [""]), luts

        if not ctx.is_trace:
            sd, luts = merged_dict()
            for lut in luts:
                ctx.aux(lambda l=lut: l)
            anynull = any(v.has_validity for _, v in all_strs) or \
                any(p.has_validity for p, _ in branch_vals)
            return Val(string, None, True if anynull else None, sd)
        luts = [ctx.aux(None) for _ in all_strs]
        elut = luts[-1]
        data = jnp.take(elut, jnp.clip(jnp.broadcast_to(ev.data, (ctx.capacity,)),
                                       0, elut.shape[0] - 1))
        valid = ev.validity if ev.validity is not None else jnp.ones((), bool)
        valid = jnp.broadcast_to(valid, (ctx.capacity,))
        decided = jnp.zeros((ctx.capacity,), bool)
        for (p, v), lut in zip(branch_vals, luts[:-1]):
            pd = p.data
            if p.validity is not None:
                pd = pd & p.validity
            hit = jnp.broadcast_to(pd, (ctx.capacity,)) & ~decided
            vd = jnp.take(lut, jnp.clip(jnp.broadcast_to(v.data, (ctx.capacity,)),
                                        0, lut.shape[0] - 1))
            vv = v.validity if v.validity is not None else jnp.ones((), bool)
            data = jnp.where(hit, vd, data)
            valid = jnp.where(hit, jnp.broadcast_to(vv, (ctx.capacity,)), valid)
            decided = decided | hit
        has_any_null = any(v.validity is not None for _, v in all_strs)
        return Val(string, data, valid if has_any_null else None, None)


def cast_if(e: Expression, to: DataType) -> Expression:
    if e.resolved and e.dtype == to:
        return e
    c = getattr(e, "_cast_cache", None)
    if c is not None and c.to == to:
        return c
    c = Cast(e, to)
    try:
        e._cast_cache = c
    except Exception:
        pass
    return c


class Coalesce(Expression):
    child_fields = ("args",)

    def __init__(self, args: Sequence[Expression]):
        self.args = list(args)

    @property
    def dtype(self):
        dt: DataType = null_type
        for a in self.args:
            dt = common_type(dt, a.dtype) or a.dtype
        return dt

    @property
    def nullable(self):
        return all(a.nullable for a in self.args)

    def eval(self, ctx):
        # rewrite as CASE WHEN a IS NOT NULL THEN a ... for uniform handling
        branches = [(IsNotNull(a), a) for a in self.args[:-1]]
        return CaseWhen(branches, self.args[-1]).eval(ctx)


class NullIf(BinaryExpression):
    @property
    def dtype(self):
        return self.left.dtype

    def eval(self, ctx):
        return CaseWhen([(EqualTo(self.left, self.right), Literal(None, self.left.dtype))],
                        self.left).eval(ctx)


class Greatest(Expression):
    child_fields = ("args",)
    _reduce = "maximum"

    def __init__(self, args: Sequence[Expression]):
        self.args = list(args)

    @property
    def dtype(self):
        dt = self.args[0].dtype
        for a in self.args[1:]:
            dt = common_type(dt, a.dtype) or dt
        return dt

    def eval(self, ctx):
        out = self.dtype
        if isinstance(out, StringType):
            # dictionary-encoded strings can't reduce by code arithmetic
            # (codes from different dictionaries aren't ordered) — expand
            # into the null-skipping CASE chain, which rides the existing
            # dictionary comparison machinery
            cmp_cls = GreaterThan if self._reduce == "maximum" else LessThan
            acc = self.args[0]
            for a in self.args[1:]:
                acc = CaseWhen([(IsNull(a), acc), (IsNull(acc), a),
                                (cmp_cls(acc, a), acc)], a)
            return ctx.eval(acc)
        vals = [ctx.eval(cast_if(a, out)) for a in self.args]
        v = ctx.and_valid(*vals)  # Spark: null only if ALL null; simplify: any-null→null? Spark Greatest skips nulls
        if not ctx.is_trace:
            return Val(out, None, True if any(x.has_validity for x in vals) else None, None)
        jnp = _jnp()
        fn = getattr(jnp, self._reduce)
        ident = None
        data = None
        valid = None
        for x in vals:
            xv = x.validity if x.validity is not None else jnp.ones((), bool)
            if data is None:
                data = x.data
                valid = jnp.broadcast_to(xv, jnp.shape(jnp.broadcast_to(x.data, (ctx.capacity,))))
                data = jnp.broadcast_to(data, (ctx.capacity,))
            else:
                xd = jnp.broadcast_to(x.data, (ctx.capacity,))
                xvv = jnp.broadcast_to(xv, (ctx.capacity,))
                both = valid & xvv
                data = jnp.where(both, fn(data, xd), jnp.where(xvv, xd, data))
                valid = valid | xvv
        has_null = any(x.validity is not None for x in vals)
        return Val(out, data, valid if has_null else None, None)


class Least(Greatest):
    _reduce = "minimum"


# ---------------------------------------------------------------------------
# IN / LIKE / string predicates
# ---------------------------------------------------------------------------

class In(Expression):
    child_fields = ("child", "items")

    def __init__(self, child: Expression, items: Sequence[Expression]):
        self.child = child
        self.items = list(items)

    @property
    def dtype(self):
        return boolean

    def eval(self, ctx):
        # SQL three-valued IN: TRUE on a match; else NULL when the list
        # holds a NULL (or the probe is NULL); else FALSE (reference:
        # predicates.scala In.eval null handling)
        c = ctx.eval(self.child)
        jnp = _jnp()
        if isinstance(c.dtype, StringType):
            targets = []
            has_null_item = False
            for it in self.items:
                if not isinstance(it, Literal):
                    raise UnsupportedOperationError("IN over strings needs literals")
                if it.value is None:
                    has_null_item = True
                else:
                    targets.append(it.value)

            def make_lut():
                sd = c.sdict or StringDict([""])
                tset = set(targets)
                return np.array([v in tset for v in (sd.values or [""])], bool)

            if not ctx.is_trace:
                ctx.aux(make_lut)
                valid = c.validity
                if has_null_item:
                    valid = True  # validity becomes data-dependent
                return Val(boolean, None, valid, None)
            lut = ctx.aux(None)
            data = jnp.take(lut, jnp.clip(c.data, 0, lut.shape[0] - 1))
            valid = c.validity
            if has_null_item:
                # unmatched rows are UNKNOWN, not false
                valid = data if valid is None else (valid & data)
            return Val(boolean, data, valid, None)
        vals = [ctx.eval(cast_if(i, c.dtype)) for i in self.items]
        if not ctx.is_trace:
            may_null_item = any(
                x.validity is not None or
                (isinstance(i, Literal) and i.value is None)
                for x, i in zip(vals, self.items))
            valid = c.validity
            if may_null_item:
                valid = True
            return Val(boolean, None, valid, None)
        matched = jnp.zeros((), bool)
        null_any = jnp.zeros((), bool)
        for x in vals:
            if x.validity is None:
                xv = jnp.ones((), bool)
            else:
                xv = x.validity
            matched = matched | ((c.data == x.data) & xv)
            null_any = null_any | ~xv
        valid = matched | ~null_any     # unmatched + null item → NULL
        if c.validity is not None:
            valid = valid & c.validity
        return Val(boolean, matched, valid, None)


def _like_to_regex(pattern: str, escape: str = "\\") -> str:
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return "^" + "".join(out) + "$"


class _StringPredicate(Expression):
    child_fields = ("child",)

    def __init__(self, child: Expression, pattern: str):
        self.child = child
        self.pattern = pattern

    @property
    def dtype(self):
        return boolean

    def matcher(self):
        raise NotImplementedError

    def eval(self, ctx):
        c = ctx.eval(self.child)
        jnp = _jnp()

        def make_lut():
            sd = c.sdict or StringDict([""])
            m = self.matcher()
            return np.array([bool(m(v)) for v in (sd.values or [""])], bool)

        if not ctx.is_trace:
            ctx.aux(make_lut)
            return Val(boolean, None, c.validity, None)
        lut = ctx.aux(None)
        data = jnp.take(lut, jnp.clip(c.data, 0, lut.shape[0] - 1))
        return Val(boolean, data, c.validity, None)


class Like(_StringPredicate):
    def matcher(self):
        rx = re.compile(_like_to_regex(self.pattern), re.DOTALL)
        return lambda s: rx.match(s) is not None


class RLike(_StringPredicate):
    def matcher(self):
        rx = re.compile(self.pattern)
        return lambda s: rx.search(s) is not None


class StartsWith(_StringPredicate):
    def matcher(self):
        p = self.pattern
        return lambda s: s.startswith(p)


class EndsWith(_StringPredicate):
    def matcher(self):
        p = self.pattern
        return lambda s: s.endswith(p)


class Contains(_StringPredicate):
    def matcher(self):
        p = self.pattern
        return lambda s: p in s


# ---------------------------------------------------------------------------
# String functions — dictionary transforms
# ---------------------------------------------------------------------------

class _DictTransform(Expression):
    """String→string function applied to dictionary values host-side;
    device codes pass through unchanged."""

    child_fields = ("child",)

    def __init__(self, child: Expression):
        self.child = child

    @property
    def dtype(self):
        return string

    def transform(self, s: str) -> str:
        raise NotImplementedError

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if not ctx.is_trace:
            sd = (c.sdict or StringDict([""])).map_values(self.transform)
            return Val(string, None, c.validity, sd)
        return Val(string, c.data, c.validity, None)


class Upper(_DictTransform):
    def transform(self, s):
        return s.upper()


class Lower(_DictTransform):
    def transform(self, s):
        return s.lower()


class Trim(_DictTransform):
    def transform(self, s):
        return s.strip()


class LTrim(_DictTransform):
    def transform(self, s):
        return s.lstrip()


class RTrim(_DictTransform):
    def transform(self, s):
        return s.rstrip()


class Substring(_DictTransform):
    def __init__(self, child: Expression, pos: Expression, length: Expression | None = None):
        super().__init__(child)
        if not isinstance(pos, Literal) or (length is not None and not isinstance(length, Literal)):
            raise UnsupportedOperationError("substring pos/len must be literals")
        self.pos = int(pos.value)
        self.length = None if length is None else int(length.value)

    def transform(self, s):
        # SQL 1-based; pos 0 treated as 1
        p = self.pos
        start = max(p - 1, 0) if p > 0 else max(len(s) + p, 0)
        if self.length is None:
            return s[start:]
        return s[start:start + max(self.length, 0)]


class StringReplace(_DictTransform):
    def __init__(self, child: Expression, search: Expression, replace: Expression):
        super().__init__(child)
        if not isinstance(search, Literal) or not isinstance(replace, Literal):
            raise UnsupportedOperationError("replace args must be literals")
        self.search = str(search.value)
        self.replace = str(replace.value)

    def transform(self, s):
        return s.replace(self.search, self.replace)


class Lpad(_DictTransform):
    def __init__(self, child, length: Expression, pad: Expression):
        super().__init__(child)
        self.length = int(length.value)
        self.pad = str(pad.value)

    def transform(self, s):
        if len(s) >= self.length:
            return s[: self.length]
        need = self.length - len(s)
        p = (self.pad * need)[:need]
        return p + s


class Rpad(Lpad):
    def transform(self, s):
        if len(s) >= self.length:
            return s[: self.length]
        need = self.length - len(s)
        p = (self.pad * need)[:need]
        return s + p


class Concat(Expression):
    """Concat where at most ONE argument is a non-literal string column (dict
    transform); general column||column needs dictionary products (later)."""

    child_fields = ("args",)

    def __init__(self, args: Sequence[Expression]):
        self.args = list(args)

    @property
    def dtype(self):
        return string

    def eval(self, ctx):
        # SQL concat is null-intolerant: any NULL argument nulls the result
        if any(isinstance(a, Literal) and a.value is None for a in self.args):
            return Literal(None, string).eval(ctx)
        col_idx = [i for i, a in enumerate(self.args) if not isinstance(a, Literal)]
        if len(col_idx) == 0:
            s = "".join(str(a.value) for a in self.args)
            return Literal(s).eval(ctx)
        if len(col_idx) > 1:
            raise UnsupportedOperationError(
                "concat of multiple string columns not yet supported")
        i = col_idx[0]
        prefix = "".join(str(a.value) for a in self.args[:i])
        suffix = "".join(str(a.value) for a in self.args[i + 1:])

        class _C(_DictTransform):
            def transform(self, s, _p=prefix, _s=suffix):
                return _p + s + _s

        return _C(self.args[i]).eval(ctx)


class RegexpExtract(_DictTransform):
    def __init__(self, child, pattern: Expression, group: Expression):
        super().__init__(child)
        self.pattern = str(pattern.value)
        self.group = int(group.value)
        self._rx = re.compile(self.pattern)

    def _data_args(self):
        return (("pattern", self.pattern), ("group", self.group))

    def transform(self, s):
        m = self._rx.search(s)
        if m is None:
            return ""
        try:
            return m.group(self.group) or ""
        except IndexError:
            return ""


class DateFormat(Expression):
    """date_format(d, fmt): Java-style pattern subset mapped to strftime,
    evaluated per-row host-side (value universe unknown) via the UDF
    fallback at planning time — this node only resolves the type."""

    child_fields = ("child",)

    _JAVA_TO_STRF = [("yyyy", "%Y"), ("MM", "%m"), ("dd", "%d"),
                     ("HH", "%H"), ("mm", "%M"), ("ss", "%S"),
                     ("EEEE", "%A"), ("E", "%a"), ("yy", "%y")]

    def __init__(self, child: Expression, fmt: Expression):
        self.child = child
        self.fmt = str(fmt.value)

    @property
    def dtype(self):
        return string

    @classmethod
    def to_strftime(cls, fmt: str) -> str:
        for a, b in cls._JAVA_TO_STRF:
            fmt = fmt.replace(a, b)
        return fmt

    def eval(self, ctx):
        raise UnsupportedOperationError(
            "date_format must be rewritten to a host UDF (optimizer rule "
            "RewriteHostOnlyExpressions)")


class Split(_DictTransform):
    """string → array<string> by a regex delimiter: one regex run per
    DICTIONARY value, producing a list-valued dictionary (see ArrayType).
    Under explode(), GenerateExec uses split_lists directly."""

    def __init__(self, child: Expression, delim: Expression):
        super().__init__(child)
        self.delim = str(delim.value)
        self._rx = re.compile(self.delim)

    @property
    def dtype(self):
        return ArrayType(string)

    def split_lists(self, values: list[str]) -> list[list[str]]:
        return [[p for p in self._rx.split(v)] for v in values]

    def transform(self, s):
        return self._rx.split(s)


class Grouping(UnaryExpression):
    """grouping(col) over GROUPING SETS/ROLLUP/CUBE (reference:
    sqlcat/expressions/grouping.scala Grouping) — folded to a 0/1 literal
    per branch when ExpandGroupingSets expands the sets."""

    @property
    def dtype(self):
        return int32

    def eval(self, ctx):
        raise AnalysisException(
            "grouping() is only valid with GROUPING SETS/ROLLUP/CUBE",
            error_class="UNSUPPORTED_GROUPING_EXPRESSION")


class GroupingID(Expression):
    """grouping_id(...) — bitmask of non-grouped keys, most-significant bit
    first (reference: grouping.scala GroupingID). Empty args = all keys."""

    child_fields = ("args",)

    def __init__(self, args: list[Expression]):
        self.args = list(args)

    @property
    def dtype(self):
        return int64

    def simple_string(self) -> str:
        return f"grouping_id({', '.join(a.simple_string() for a in self.args)})"

    def eval(self, ctx):
        raise AnalysisException(
            "grouping_id() is only valid with GROUPING SETS/ROLLUP/CUBE",
            error_class="UNSUPPORTED_GROUPING_EXPRESSION")


class Explode(Expression):
    """Generator marker (reference: sqlcat/expressions/generators.scala
    Explode) — extracted into a Generate operator by the analyzer."""

    child_fields = ("child",)

    def __init__(self, child: Expression):
        self.child = child

    @property
    def dtype(self):
        ct = self.child.dtype
        return ct.element_type if isinstance(ct, ArrayType) else ct

    def eval(self, ctx):
        raise UnsupportedOperationError(
            "explode() must be planned as a Generate operator")


class Length(UnaryExpression):
    @property
    def dtype(self):
        return int32

    def eval(self, ctx):
        c = ctx.eval(self.child)
        jnp = _jnp()
        if not isinstance(c.dtype, StringType):
            raise TypeCheckError("length() needs a string")

        def make_lut():
            sd = c.sdict or StringDict([""])
            return np.array([len(v) for v in (sd.values or [""])], np.int32)

        if not ctx.is_trace:
            ctx.aux(make_lut)
            return Val(int32, None, c.validity, None)
        lut = ctx.aux(None)
        return Val(int32, jnp.take(lut, jnp.clip(c.data, 0, lut.shape[0] - 1)),
                   c.validity, None)


class Initcap(_DictTransform):
    def transform(self, s):
        return " ".join(w[:1].upper() + w[1:].lower() if w else w
                        for w in s.split(" "))


class Reverse(_DictTransform):
    def transform(self, s):
        return s[::-1]


class Repeat(_DictTransform):
    def __init__(self, child, n: Expression):
        super().__init__(child)
        self.n = int(n.value)

    def transform(self, s):
        return s * self.n


class SubstringIndex(_DictTransform):
    def __init__(self, child, delim: Expression, count: Expression):
        super().__init__(child)
        self.delim = str(delim.value)
        self.count = int(count.value)

    def transform(self, s):
        parts = s.split(self.delim)
        if self.count > 0:
            return self.delim.join(parts[: self.count])
        if self.count < 0:
            return self.delim.join(parts[self.count:])
        return ""


class RegexpExtract(_DictTransform):
    """regexp_extract(col, pattern, idx) (reference:
    sqlcat/expressions/regexpExpressions.scala RegExpExtract) — one regex
    match per DICTIONARY value, codes pass through."""

    def __init__(self, child, pattern: Expression, idx: Expression = None):
        super().__init__(child)
        self.pattern = str(pattern.value)
        self.idx = 1 if idx is None else int(idx.value)
        self._rx = re.compile(self.pattern)

    def transform(self, s):
        m = self._rx.search(s)
        if m is None:
            return ""
        g = m.group(self.idx)
        return "" if g is None else g


class RegexpReplace(_DictTransform):
    """regexp_replace(col, pattern, replacement) (reference:
    regexpExpressions.scala RegExpReplace)."""

    def __init__(self, child, pattern: Expression, repl: Expression):
        super().__init__(child)
        self.pattern = str(pattern.value)
        self.repl = str(repl.value)
        self._rx = re.compile(self.pattern)

    def transform(self, s):
        # Spark/Java replacement uses $1 group refs; python wants \1
        return self._rx.sub(re.sub(r"\$(\d)", r"\\\1", self.repl), s)


class Left(_DictTransform):
    def __init__(self, child, n: Expression):
        super().__init__(child)
        self.n = int(n.value)

    def transform(self, s):
        return s[: self.n] if self.n >= 0 else ""


class Right(_DictTransform):
    def __init__(self, child, n: Expression):
        super().__init__(child)
        self.n = int(n.value)

    def transform(self, s):
        return s[-self.n:] if self.n > 0 else ""


class Overlay(_DictTransform):
    """overlay(s, replace, pos[, len]) — 1-based."""

    def __init__(self, child, repl: Expression, pos: Expression,
                 length: Expression | None = None):
        super().__init__(child)
        self.repl = str(repl.value)
        self.pos = int(pos.value)
        self.length = len(self.repl) if length is None else int(length.value)

    def transform(self, s):
        p = self.pos - 1
        return s[:p] + self.repl + s[p + self.length:]


class Soundex(_DictTransform):
    _CODES = {**{c: "1" for c in "bfpv"}, **{c: "2" for c in "cgjkqsxz"},
              **{c: "3" for c in "dt"}, "l": "4",
              **{c: "5" for c in "mn"}, "r": "6"}

    def transform(self, s):
        if not s or not s[0].isalpha():
            return s
        out = s[0].upper()
        prev = self._CODES.get(s[0].lower(), "")
        for ch in s[1:].lower():
            code = self._CODES.get(ch, "")
            if code and code != prev:
                out += code
            if ch not in "hw":
                prev = code
            if len(out) == 4:
                break
        return out.ljust(4, "0")


class Md5(_DictTransform):
    def transform(self, s):
        import hashlib

        return hashlib.md5(s.encode()).hexdigest()


class Sha1(_DictTransform):
    def transform(self, s):
        import hashlib

        return hashlib.sha1(s.encode()).hexdigest()


class Sha2(_DictTransform):
    def __init__(self, child, bits: Expression):
        super().__init__(child)
        self.bits = int(bits.value) or 256

    def transform(self, s):
        import hashlib

        if self.bits not in (224, 256, 384, 512):
            return None  # reference returns NULL for unsupported lengths
        h = hashlib.new(f"sha{self.bits}")
        h.update(s.encode())
        return h.hexdigest()


class Base64(_DictTransform):
    def transform(self, s):
        import base64 as b64

        return b64.b64encode(s.encode()).decode()


class Unbase64(_DictTransform):
    def transform(self, s):
        import base64 as b64

        try:
            return b64.b64decode(s.encode()).decode()
        except Exception:
            return None  # reference returns NULL for invalid base64


class FormatNumber(Expression):
    """format_number(x, d) — host-only (numeric → string has no bounded
    dictionary); RewriteHostOnlyExpressions lowers it to a vectorized
    host UDF."""

    child_fields = ("child",)

    def __init__(self, child: Expression, d: Expression):
        self.child = child
        self.d = int(d.value)

    @property
    def dtype(self):
        return string

    def format_fn(self):
        d = self.d

        def fn(a):
            out = []
            for v in a:
                out.append(None if v is None else f"{float(v):,.{d}f}")
            return np.array(out, dtype=object)

        return fn

    def eval(self, ctx):
        raise UnsupportedOperationError(
            "format_number must be lowered to a host UDF")


class Translate(_DictTransform):
    def __init__(self, child, matching: Expression, replace: Expression):
        super().__init__(child)
        self.table = str.maketrans(
            str(matching.value),
            str(replace.value).ljust(len(str(matching.value)))[
                : len(str(matching.value))])

    def transform(self, s):
        return s.translate(self.table)


class _ArrayLut(Expression):
    """Array function computed ONCE PER DICTIONARY ENTRY into value +
    validity lookup tables; device codes gather through them (arrays are
    dictionary-encoded — see ArrayType). Reference:
    sqlcat/expressions/collectionOperations.scala."""

    child_fields = ("child",)

    def __init__(self, child: Expression):
        self.child = child

    def value_of(self, lst):
        """→ (value, is_valid) for one dictionary list."""
        raise NotImplementedError

    def eval(self, ctx):
        c = ctx.eval(self.child)
        jnp = _jnp()

        def has_lut():
            sd = c.sdict or StringDict([[]])
            return np.array([self.value_of(v)[1]
                             for v in (sd.values or [[]])], bool)

        if dict_encoded(self.dtype):
            # dictionary-encoded result (string element, nested struct /
            # map / array): per-entry result value, codes pass through;
            # validity folds in per-entry presence
            if not ctx.is_trace:
                sd = c.sdict or StringDict([[]])
                out = StringDict([self.value_of(v)[0] if self.value_of(v)[1]
                                  else _dict_empty(self.dtype)
                                  for v in (sd.values or [[]])])
                ctx.aux(has_lut)
                return Val(self.dtype, None, True, out)
            hl = ctx.aux(None)
            codes = jnp.clip(c.data, 0, hl.shape[0] - 1)
            has = jnp.take(hl, codes)
            validity = has if c.validity is None else (c.validity & has)
            return Val(self.dtype, c.data, validity, None)

        dd = self.dtype.device_dtype

        def vals_lut():
            sd = c.sdict or StringDict([[]])
            vs = sd.values or [[]]
            out = np.zeros(len(vs), dd)
            for i, v in enumerate(vs):
                val, ok = self.value_of(v)
                out[i] = _to_device_value(self.dtype, val) if ok else 0
            return out

        if not ctx.is_trace:
            ctx.aux(vals_lut)
            ctx.aux(has_lut)
            return Val(self.dtype, None, True, None)
        vl = ctx.aux(None)
        hl = ctx.aux(None)
        codes = jnp.clip(c.data, 0, vl.shape[0] - 1)
        data = jnp.take(vl, codes)
        has = jnp.take(hl, codes)
        validity = has if c.validity is None else (c.validity & has)
        return Val(self.dtype, data, validity, None)


class Size(_ArrayLut):
    @property
    def dtype(self):
        return int32

    def value_of(self, lst):
        return len(lst), True


class ArrayContains(_ArrayLut):
    def __init__(self, child: Expression, value: Expression):
        super().__init__(child)
        self.value = value.value  # literal

    @property
    def dtype(self):
        return boolean

    def value_of(self, lst):
        return (self.value in lst), True


class ArrayMin(_ArrayLut):
    @property
    def dtype(self):
        ct = self.child.dtype
        return ct.element_type if isinstance(ct, ArrayType) else ct

    def value_of(self, lst):
        vals = [v for v in lst if v is not None]
        return (min(vals), True) if vals else (0, False)


class ArrayMax(ArrayMin):
    def value_of(self, lst):
        vals = [v for v in lst if v is not None]
        return (max(vals), True) if vals else (0, False)


class ElementAt(_ArrayLut):
    """element_at(arr, i) — 1-based, negative from the end; numeric
    elements gather through a LUT, string elements go through a
    dictionary transform (see build_element_at)."""

    def __init__(self, child: Expression, idx: Expression):
        super().__init__(child)
        self.idx = int(idx.value)

    @property
    def dtype(self):
        ct = self.child.dtype
        return ct.element_type if isinstance(ct, ArrayType) else ct

    def value_of(self, lst):
        i = self.idx - 1 if self.idx > 0 else len(lst) + self.idx
        if 0 <= i < len(lst) and lst[i] is not None:
            return lst[i], True
        return 0, False


class GetStructField(_ArrayLut):
    """struct.field access (reference: complexTypeExtractors.scala
    GetStructField) — per-dictionary-entry field extraction into a LUT
    (numeric fields) or a derived dictionary (string/nested fields)."""

    def __init__(self, child: Expression, name: str):
        super().__init__(child)
        self.field_name = name

    @property
    def dtype(self):
        ct = self.child.dtype
        if isinstance(ct, StructType):
            ft = ct.field_type(self.field_name)
            if ft is not None:
                return ft
        return null_type

    @property
    def nullable(self):
        return True

    def value_of(self, d):
        if isinstance(d, dict) and d.get(self.field_name) is not None:
            return d[self.field_name], True
        return 0, False

    def simple_string(self):
        return f"{self.child.simple_string()}.{self.field_name}"


class GetMapValue(_ArrayLut):
    """map[key] / element_at(map, key) (reference:
    complexTypeExtractors.scala GetMapValue) — per-entry lookup LUT."""

    def __init__(self, child: Expression, key: Expression):
        super().__init__(child)
        self.key = key.value  # literal key

    @property
    def dtype(self):
        ct = self.child.dtype
        return ct.value_type if isinstance(ct, MapType) else null_type

    def value_of(self, m):
        if isinstance(m, dict) and m.get(self.key) is not None:
            return m[self.key], True
        return 0, False


class MapContainsKey(_ArrayLut):
    def __init__(self, child: Expression, key: Expression):
        super().__init__(child)
        self.key = key.value

    @property
    def dtype(self):
        return boolean

    @property
    def nullable(self):
        return self.child.nullable

    def value_of(self, m):
        return (self.key in m) if isinstance(m, dict) else False, True


class _ArrayDictTransform(_DictTransform):
    """list → list function over dictionary values (codes unchanged)."""

    @property
    def dtype(self):
        return self.child.dtype


class MapKeys(_ArrayDictTransform):
    @property
    def dtype(self):
        ct = self.child.dtype
        return ArrayType(ct.key_type) if isinstance(ct, MapType) \
            else ArrayType()

    def transform(self, m):
        return list(m.keys()) if isinstance(m, dict) else []


class MapValues(_ArrayDictTransform):
    @property
    def dtype(self):
        ct = self.child.dtype
        return ArrayType(ct.value_type) if isinstance(ct, MapType) \
            else ArrayType()

    def transform(self, m):
        return list(m.values()) if isinstance(m, dict) else []


class SortArray(_ArrayDictTransform):
    def __init__(self, child: Expression, asc: Expression | None = None):
        super().__init__(child)
        self.asc = True if asc is None else bool(asc.value)

    def transform(self, lst):
        return sorted(lst, reverse=not self.asc)


class ArrayDistinct(_ArrayDictTransform):
    def transform(self, lst):
        return list(dict.fromkeys(lst))


class Flatten(_ArrayLut):
    """flatten(array<array<T>>) → array<T> (one level). A NULL
    sub-array nulls the whole result, per the reference
    (collectionOperations.scala Flatten) — the per-dictionary-entry
    validity fold carries the NULL."""

    @property
    def dtype(self):
        ct = self.child.dtype
        return ct.element_type if isinstance(ct, ArrayType) and \
            isinstance(ct.element_type, ArrayType) else ct

    def value_of(self, lst):
        out = []
        for sub in lst:
            if sub is None:
                return [], False
            out.extend(sub)
        return out, True


class Slice(_ArrayDictTransform):
    """slice(arr, start, length) — 1-based, negative start from the end
    (collectionOperations.scala Slice)."""

    def __init__(self, child: Expression, start: Expression,
                 length: Expression):
        super().__init__(child)
        self.start = int(start.value)
        self.length = int(length.value)
        if self.start == 0:
            raise AnalysisException(
                "Unexpected value for start in function slice: "
                "SQL array indices start at 1")

    def transform(self, lst):
        s = self.start - 1 if self.start > 0 else len(lst) + self.start
        if s < 0:
            return []
        return lst[s:s + self.length]


class ArrayRemove(_ArrayDictTransform):
    def __init__(self, child: Expression, value: Expression):
        super().__init__(child)
        self.value = value.value

    def transform(self, lst):
        return [v for v in lst if v != self.value]


class ArrayJoin(_ArrayLut):
    """array_join(arr, sep[, null_replacement]) → string."""

    def __init__(self, child: Expression, sep: Expression,
                 null_replacement: Expression | None = None):
        super().__init__(child)
        self.sep = str(sep.value)
        self.null_rep = None if null_replacement is None \
            else str(null_replacement.value)

    @property
    def dtype(self):
        return string

    def value_of(self, lst):
        parts = []
        for v in lst:
            if v is None:
                if self.null_rep is not None:
                    parts.append(self.null_rep)
            else:
                parts.append(str(v))
        return self.sep.join(parts), True


class ArrayPosition(_ArrayLut):
    """array_position(arr, value) → 1-based index of first match, 0 if
    absent (collectionOperations.scala ArrayPosition)."""

    def __init__(self, child: Expression, value: Expression):
        super().__init__(child)
        self.value = value.value

    @property
    def dtype(self):
        return int64

    def value_of(self, lst):
        for i, v in enumerate(lst):
            if v == self.value:
                return i + 1, True
        return 0, True


class GetJsonObject(_ArrayLut):
    """get_json_object(json_str, '$.path') — JsonPath subset: dotted
    fields and [n] indexing (reference: jsonExpressions.scala
    GetJsonObject). Misses and JSON nulls are real NULLs (per-entry
    validity fold); non-scalar results re-serialize as JSON, matching
    the reference."""

    def __init__(self, child: Expression, path: Expression):
        super().__init__(child)
        self.path = str(path.value)

    @property
    def dtype(self):
        return string

    def _data_args(self):
        return (("path", self.path),)

    def value_of(self, s):
        import json as _json
        import re as _re

        try:
            cur = _json.loads(s)
        except (ValueError, TypeError):
            return "", False
        p = self.path
        if p.startswith("$"):
            p = p[1:]
        # the whole path must tokenize — an unsupported segment ($[*],
        # quoted keys, odd characters) means NULL, not a partial walk
        tokens = list(_re.finditer(r"\.([A-Za-z_][\w]*)|\[(\d+)\]", p))
        consumed = "".join(m.group(0) for m in tokens)
        if consumed != p:
            return "", False
        for name, idx in ((m.group(1), m.group(2)) for m in tokens):
            if name:
                if not isinstance(cur, dict) or name not in cur:
                    return "", False
                cur = cur[name]
            else:
                i = int(idx)
                if not isinstance(cur, list) or i >= len(cur):
                    return "", False
                cur = cur[i]
        if cur is None:
            return "", False
        if isinstance(cur, (dict, list)):
            return _json.dumps(cur), True
        if isinstance(cur, bool):
            return ("true" if cur else "false"), True
        return str(cur), True


class Crc32(_ArrayLut):
    """crc32(string) → bigint over dictionary values (hash.scala Crc32)."""

    @property
    def dtype(self):
        return int64

    def value_of(self, s):
        import zlib

        return zlib.crc32(str(s).encode()), True


class ElementAtString(_ArrayLut):
    """element_at over array<string>: per-entry extraction with a real
    NULL for out-of-bounds / null elements (complexTypeExtractors.scala
    ElementAt null semantics, carried by the validity fold)."""

    def __init__(self, child: Expression, idx: Expression):
        super().__init__(child)
        self.idx = int(idx.value)

    @property
    def dtype(self):
        return string

    def _data_args(self):
        return (("idx", self.idx),)

    def value_of(self, lst):
        i = self.idx - 1 if self.idx > 0 else len(lst) + self.idx
        if 0 <= i < len(lst) and lst[i] is not None:
            return lst[i], True
        return "", False


def build_element_at(child: Expression, idx: Expression) -> Expression:
    if not isinstance(idx, Literal):
        from ..errors import AnalysisException

        raise AnalysisException(
            "element_at / [] requires a literal key; column-valued keys "
            "are not supported yet")
    ct = child.dtype
    if isinstance(ct, MapType):
        return GetMapValue(child, idx)
    if isinstance(ct, ArrayType) and isinstance(ct.element_type, StringType):
        return ElementAtString(child, idx)
    return ElementAt(child, idx)


def build_struct_ctor(args, names=None) -> Expression:
    """struct(...) / named_struct('n1', v1, ...) — a host-vectorized
    constructor producing a dictionary-encoded struct column (reference:
    complexTypeCreator.scala CreateNamedStruct)."""
    from .pyudf import PythonUDF

    if names is None:
        names, vals = [], []
        for i, a in enumerate(args):
            if isinstance(a, Alias):
                names.append(a.name)
                vals.append(a.child)
            elif isinstance(a, AttributeReference):
                names.append(a.name)
                vals.append(a)
            elif isinstance(a, GetStructField):
                names.append(a.field_name)
                vals.append(a)
            else:
                names.append(f"col{i + 1}")
                vals.append(a)
    else:
        vals = list(args)
    st = StructType(tuple(StructField(n, v.dtype, True)
                          for n, v in zip(names, vals)))
    captured = list(names)

    def make_struct(*cols):
        return dict(zip(captured, cols))

    return PythonUDF(make_struct, vals, st, name="named_struct",
                     vectorized=False)


def build_named_struct(args) -> Expression:
    if len(args) % 2 != 0:
        from ..errors import AnalysisException

        raise AnalysisException("named_struct expects name/value pairs")
    names = [str(a.value) for a in args[0::2]]
    return build_struct_ctor(args[1::2], names=names)


def build_array_ctor(args) -> Expression:
    """array(e1, e2, ...) (reference: complexTypeCreator.scala
    CreateArray) — host-evaluated dictionary-encoded array column."""
    from .pyudf import PythonUDF

    et: DataType = null_type
    for a in args:
        et = common_type(et, a.dtype) or a.dtype
    if not args:
        # array() — a single dummy input keeps the eval pipeline shaped
        return PythonUDF(lambda _x: [], [Literal(0)], ArrayType(et),
                         name="array", vectorized=False)

    def make_array(*cols):
        return list(cols)

    return PythonUDF(make_array, list(args), ArrayType(et), name="array",
                     vectorized=False)


class ArraySortNullsLast(_ArrayDictTransform):
    """array_sort(arr) — ascending with NULLs LAST, unlike sort_array's
    nulls-first (collectionOperations.scala ArraySort default)."""

    def transform(self, lst):
        return sorted([v for v in lst if v is not None]) + \
            [None] * sum(1 for v in lst if v is None)


def build_map_ctor(args) -> Expression:
    """map(k1, v1, k2, v2, ...) (reference: complexTypeCreator.scala
    CreateMap) — host-vectorized dictionary-encoded map column."""
    from ..errors import AnalysisException
    from .pyudf import PythonUDF

    if len(args) % 2 != 0:
        raise AnalysisException("map expects key/value pairs")
    kt: DataType = null_type
    vt: DataType = null_type
    for k in args[0::2]:
        kt = common_type(kt, k.dtype) or k.dtype
    for v in args[1::2]:
        vt = common_type(vt, v.dtype) or v.dtype
    n_pairs = len(args) // 2

    def make_map(*cols):
        return {cols[2 * i]: cols[2 * i + 1] for i in range(n_pairs)}

    return PythonUDF(make_map, list(args), MapType(kt, vt), name="map",
                     vectorized=False)


class _StringIntLut(Expression):
    """String function producing an integer per dictionary entry."""

    child_fields = ("child",)

    def __init__(self, child: Expression):
        self.child = child

    @property
    def dtype(self):
        return int32

    def int_of(self, s: str) -> int:
        raise NotImplementedError

    def eval(self, ctx):
        c = ctx.eval(self.child)
        jnp = _jnp()

        def make_lut():
            sd = c.sdict or StringDict([""])
            return np.array([self.int_of(v) for v in (sd.values or [""])],
                            np.int32)

        if not ctx.is_trace:
            ctx.aux(make_lut)
            return Val(int32, None, c.validity, None)
        lut = ctx.aux(None)
        return Val(int32, jnp.take(lut, jnp.clip(c.data, 0, lut.shape[0] - 1)),
                   c.validity, None)



class RegexpExtractAll(_ArrayLut):
    """regexp_extract_all(str, regexp[, idx]) → array<string>
    (reference: regexpExpressions.scala RegExpExtractAll)."""

    def __init__(self, child, pattern: Expression, group: Expression | None = None):
        super().__init__(child)
        self.pattern = str(pattern.value)
        self._rx = re.compile(self.pattern)
        if group is None:
            # like the reference: default group 1, but a group-less
            # pattern extracts the full match
            self.group = 1 if self._rx.groups >= 1 else 0
        else:
            self.group = int(group.value)
            if self.group > self._rx.groups:
                raise AnalysisException(
                    f"regexp_extract_all: regex group count is "
                    f"{self._rx.groups}, but the specified group index "
                    f"is {self.group}")

    @property
    def dtype(self):
        return ArrayType(string)

    def _data_args(self):
        return (("pattern", self.pattern), ("group", self.group))

    def value_of(self, s):
        return [m.group(self.group) or ""
                for m in self._rx.finditer(s)], True


class RegexpSubstr(_ArrayLut):
    """regexp_substr(str, regexp) → first match or NULL
    (RegExpSubStr)."""

    def __init__(self, child, pattern: Expression):
        super().__init__(child)
        self.pattern = str(pattern.value)
        self._rx = re.compile(self.pattern)

    @property
    def dtype(self):
        return string

    def _data_args(self):
        return (("pattern", self.pattern),)

    def value_of(self, s):
        m = self._rx.search(s)
        return (m.group(0), True) if m is not None else ("", False)


class RegexpInstr(_StringIntLut):
    """regexp_instr(str, regexp) → 1-based position of the first match,
    0 when none (RegExpInStr)."""

    def __init__(self, child, pattern: Expression):
        super().__init__(child)
        self.pattern = str(pattern.value)
        self._rx = re.compile(self.pattern)

    def _data_args(self):
        return (("pattern", self.pattern),)

    def int_of(self, s):
        m = self._rx.search(s)
        return (m.start() + 1) if m is not None else 0


class RegexpCount(_StringIntLut):
    """regexp_count(str, regexp) (RegExpCount)."""

    def __init__(self, child, pattern: Expression):
        super().__init__(child)
        self.pattern = str(pattern.value)
        self._rx = re.compile(self.pattern)

    def _data_args(self):
        return (("pattern", self.pattern),)

    def int_of(self, s):
        return sum(1 for _ in self._rx.finditer(s))


class ToNumber(_ArrayLut):
    """to_number / try_to_number(str, format) → decimal per the format
    ('9'/'0' digits, D or . decimal point, G or , grouping, S sign,
    $ currency — numberFormatExpressions.scala ToNumber). Strict mode
    raises on a non-conforming string; try mode yields NULL."""

    def __init__(self, child, fmt: Expression, strict: bool = False):
        super().__init__(child)
        self.fmt = str(fmt.value)
        self.strict = strict
        f = self.fmt.upper().replace("D", ".").replace("G", ",")
        self.scale = len(f.split(".", 1)[1].replace(",", "")) \
            if "." in f else 0
        digits = sum(1 for c in f if c in "90")
        self.precision = max(digits, 1)

    @property
    def dtype(self):
        return DecimalType(self.precision, self.scale)

    def _data_args(self):
        return (("fmt", self.fmt), ("strict", self.strict))

    def _miss(self, s):
        if self.strict:
            from ..errors import ExecutionError

            raise ExecutionError(
                f"to_number: {s!r} does not match format {self.fmt!r}")
        return 0, False

    def value_of(self, s):
        import decimal as _d
        import re as _re

        # validate against the format: the format's shape (digits,
        # grouping, decimal point, sign, currency) compiled to a regex —
        # a non-conforming string errors in strict mode (ToNumber) and
        # NULLs in try mode (TryToNumber)
        pat = []
        for ch in self.fmt.upper():
            if ch in "90":
                pat.append(r"\d")
            elif ch in "G,":
                pat.append(",?")
            elif ch in "D.":
                pat.append(r"\.?")
            elif ch == "S":
                pat.append("[+-]?")
            elif ch == "$":
                pat.append(r"\$?")
            else:
                return self._miss(s)
        rx = "[+-]?" + "".join(pat) if "S" not in self.fmt.upper() \
            else "".join(pat)
        t = s.strip()
        if not _re.fullmatch(rx.replace(r"\d", r"\d?"), t):
            return self._miss(s)
        neg = t.startswith("-") or t.endswith("-")
        t = t.strip("+-").replace(",", "").replace("$", "")
        try:
            v = _d.Decimal(t)
        except _d.InvalidOperation:
            return self._miss(s)
        if neg:
            v = -v
        return int(v.scaleb(self.scale).to_integral_value()), True


class Levenshtein(_StringIntLut):
    def __init__(self, child, other: Expression):
        super().__init__(child)
        self.other = str(other.value)

    def int_of(self, s):
        a, b = s, self.other
        if len(a) < len(b):
            a, b = b, a
        prev = list(range(len(b) + 1))
        for i, ca in enumerate(a, 1):
            cur = [i]
            for j, cb in enumerate(b, 1):
                cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                               prev[j - 1] + (ca != cb)))
            prev = cur
        return prev[-1]

class Ascii(_StringIntLut):
    def int_of(self, s):
        return ord(s[0]) if s else 0


class Instr(_StringIntLut):
    def __init__(self, child, sub: Expression):
        super().__init__(child)
        self.sub = str(sub.value)

    def int_of(self, s):
        return s.find(self.sub) + 1  # 1-based; 0 = not found


class ConcatWs(Expression):
    child_fields = ("args",)

    def __init__(self, sep: Expression, args: Sequence[Expression]):
        self.sep = str(sep.value)
        self.args = list(args)

    @property
    def dtype(self):
        return string

    def eval(self, ctx):
        col_idx = [i for i, a in enumerate(self.args)
                   if not isinstance(a, Literal)]
        if len(col_idx) > 1:
            raise UnsupportedOperationError(
                "concat_ws over multiple string columns not yet supported")
        if not col_idx:
            return Literal(self.sep.join(
                str(a.value) for a in self.args)).eval(ctx)
        i = col_idx[0]
        prefix = self.sep.join(str(a.value) for a in self.args[:i])
        suffix = self.sep.join(str(a.value) for a in self.args[i + 1:])
        sep = self.sep

        class _C(_DictTransform):
            def transform(self, s, _p=prefix, _s=suffix, _sep=sep):
                mid = s
                out = mid if not _p else _p + _sep + mid
                return out if not _s else out + _sep + _s

        return _C(self.args[i]).eval(ctx)


# ---------------------------------------------------------------------------
# Date/time — civil-calendar integer math on device
# ---------------------------------------------------------------------------

def _civil_from_days(days):
    """days-since-epoch → (year, month, day); Hinnant's algorithm in int32."""
    jnp = _jnp()
    z = days.astype(_jnp().int64) + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - jnp.floor_divide(doe, 1460) + jnp.floor_divide(doe, 36524)
        - jnp.floor_divide(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100))
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def _days_from_civil(y, m, d):
    jnp = _jnp()
    y = y.astype(jnp.int64) - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100) + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


class IntervalLiteral(Expression):
    """Calendar interval (months, days, microseconds) — only valid as an
    operand of date/timestamp +/- (reference: CalendarIntervalType)."""

    child_fields = ()

    def __init__(self, months: int = 0, days: int = 0, micros: int = 0):
        self.months = months
        self.days = days
        self.micros = micros

    @property
    def dtype(self):
        raise TypeCheckError(
            "INTERVAL can only be added to/subtracted from dates/timestamps")

    @property
    def resolved(self):
        return True

    @property
    def nullable(self):
        return False

    def negated(self) -> "IntervalLiteral":
        return IntervalLiteral(-self.months, -self.days, -self.micros)

    def simple_string(self):
        return f"interval({self.months}mo {self.days}d {self.micros}us)"


def build_make_interval(y, mo, w, d, h, mi, s) -> IntervalLiteral:
    """make_interval(years, months, weeks, days, hours, mins, secs) —
    literal arguments only, like interval literals themselves
    (intervalExpressions.scala MakeInterval)."""
    def val(e, default=0):
        if e is None:
            return default
        if isinstance(e, Literal) and e.value is not None:
            return e.value
        from ..errors import AnalysisException

        raise AnalysisException("make_interval expects literal arguments")

    months = int(val(y)) * 12 + int(val(mo))
    days = int(val(w)) * 7 + int(val(d))
    secs = val(s)
    micros = int(val(h)) * 3_600_000_000 + int(val(mi)) * 60_000_000 + \
        int(round(float(secs) * 1_000_000))
    return IntervalLiteral(months, days, micros)


def _apply_interval(ctx, side: "Val", iv: IntervalLiteral) -> "Val":
    jnp = _jnp()
    if isinstance(side.dtype, DateType):
        data = side.data
        if iv.days or iv.micros:
            extra_days = iv.days + iv.micros // 86_400_000_000
            data = data + jnp.int32(extra_days)
        out = Val(date, data, side.validity, None)
        if iv.months:
            tmp = AddMonths.__new__(AddMonths)
            # reuse the month-clamping math directly
            y, m, d = _civil_from_days(out.data)
            total = (y.astype(jnp.int64) * 12 + (m - 1)) + iv.months
            ny = jnp.floor_divide(total, 12).astype(jnp.int32)
            nm = (jnp.mod(total, 12) + 1).astype(jnp.int32)
            nmt = total + 1
            nmy = jnp.floor_divide(nmt, 12).astype(jnp.int32)
            nmm = (jnp.mod(nmt, 12) + 1).astype(jnp.int32)
            one = jnp.ones_like(nm)
            dim = (_days_from_civil(nmy, nmm, one)
                   - _days_from_civil(ny, nm, one)).astype(jnp.int32)
            nd = jnp.minimum(d, dim)
            out = Val(date, _days_from_civil(ny, nm, nd), side.validity, None)
        return out
    if isinstance(side.dtype, TimestampType):
        if iv.months:
            raise UnsupportedOperationError(
                "month intervals on timestamps not supported yet")
        delta = iv.days * 86_400_000_000 + iv.micros
        return Val(timestamp, side.data + jnp.int64(delta), side.validity,
                   None)
    raise TypeCheckError(
        f"cannot add INTERVAL to {side.dtype.simple_string()}")


class _DatePart(UnaryExpression):
    part = "year"

    @property
    def dtype(self):
        return int32

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if isinstance(c.dtype, TimestampType):
            c = cast_val(ctx, c, date)
        if not ctx.is_trace:
            return Val(int32, None, c.validity, None)
        jnp = _jnp()
        y, m, d = _civil_from_days(c.data)
        data = self._part(jnp, c.data, y, m, d)
        return Val(int32, data, c.validity, None)

    def _part(self, jnp, days, y, m, d):
        raise NotImplementedError


class Year(_DatePart):
    def _part(self, jnp, days, y, m, d):
        return y


class Month(_DatePart):
    def _part(self, jnp, days, y, m, d):
        return m


class DayOfMonth(_DatePart):
    def _part(self, jnp, days, y, m, d):
        return d


class Quarter(_DatePart):
    def _part(self, jnp, days, y, m, d):
        return (m - 1) // 3 + 1


class DayOfWeek(_DatePart):
    """1 = Sunday … 7 = Saturday (Spark semantics)."""

    def _part(self, jnp, days, y, m, d):
        return ((days.astype(jnp.int64) + 4) % 7 + 1).astype(jnp.int32)


class DayOfYear(_DatePart):
    def _part(self, jnp, days, y, m, d):
        jan1 = _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(d))
        return (days - jan1 + 1).astype(jnp.int32)


class WeekOfYear(_DatePart):
    """ISO week number."""

    def _part(self, jnp, days, y, m, d):
        # ISO: week containing the year's first Thursday is week 1
        dow = ((days.astype(jnp.int64) + 3) % 7)  # 0=Mon
        thursday = days.astype(jnp.int64) - dow + 3
        ty, _, _ = _civil_from_days(thursday)
        jan1 = _days_from_civil(ty, jnp.ones_like(m), jnp.ones_like(d)).astype(jnp.int64)
        return (jnp.floor_divide(thursday - jan1, 7) + 1).astype(jnp.int32)


class TruncDate(UnaryExpression):
    """trunc(date, fmt) / date_trunc(fmt, ts). `allow_day` is True only
    for date_trunc — Spark's trunc returns NULL for day-level formats
    (Cast-style graceful null, not an error)."""

    def __init__(self, child, fmt: str = "month", allow_day: bool = False):
        super().__init__(child)
        self.fmt = fmt.lower()
        self.allow_day = allow_day

    @property
    def dtype(self):
        return date

    def eval(self, ctx):
        c = ctx.eval(self.child)
        if isinstance(c.dtype, TimestampType):
            c = cast_val(ctx, c, date)
        if not ctx.is_trace:
            return Val(date, None, c.validity, None)
        jnp = _jnp()
        y, m, d = _civil_from_days(c.data)
        one = jnp.ones_like(m)
        if self.fmt in ("year", "yyyy", "yy"):
            data = _days_from_civil(y, one, one)
        elif self.fmt in ("quarter",):
            qm = ((m - 1) // 3) * 3 + 1
            data = _days_from_civil(y, qm, one)
        elif self.fmt in ("month", "mon", "mm"):
            data = _days_from_civil(y, m, one)
        elif self.fmt in ("week",):
            dow = ((c.data.astype(jnp.int64) + 3) % 7).astype(jnp.int32)  # 0=Mon
            data = (c.data - dow).astype(jnp.int32)
        elif self.fmt in ("day", "dd"):
            if not self.allow_day:  # trunc(): day-level → NULL (Spark)
                return Val(date, jnp.zeros_like(c.data),
                           jnp.zeros((ctx.capacity,), bool), None)
            data = c.data  # already truncated to days by the date cast
        else:
            raise UnsupportedOperationError(f"trunc format {self.fmt}")
        return Val(date, data, c.validity, None)


class MakeDate(Expression):
    child_fields = ("y", "m", "d")

    def __init__(self, y, m, d):
        self.y = y
        self.m = m
        self.d = d

    @property
    def dtype(self):
        return date

    def eval(self, ctx):
        y = ctx.eval(cast_if(self.y, int32))
        m = ctx.eval(cast_if(self.m, int32))
        d = ctx.eval(cast_if(self.d, int32))
        v = ctx.and_valid(y, m, d)
        if not ctx.is_trace:
            return Val(date, None, v, None)
        return Val(date, _days_from_civil(y.data, m.data, d.data), v, None)


class DateAdd(BinaryExpression):
    @property
    def dtype(self):
        return date

    def eval(self, ctx):
        l = ctx.eval(self.left)
        r = ctx.eval(cast_if(self.right, int32))
        v = ctx.and_valid(l, r)
        if not ctx.is_trace:
            return Val(date, None, v, None)
        return Val(date, l.data + r.data, v, None)


class DateSub(BinaryExpression):
    @property
    def dtype(self):
        return date

    def eval(self, ctx):
        l = ctx.eval(self.left)
        r = ctx.eval(cast_if(self.right, int32))
        v = ctx.and_valid(l, r)
        if not ctx.is_trace:
            return Val(date, None, v, None)
        return Val(date, l.data - r.data, v, None)


class DateDiff(BinaryExpression):
    @property
    def dtype(self):
        return int32

    def eval(self, ctx):
        l = ctx.eval(cast_if(self.left, date))
        r = ctx.eval(cast_if(self.right, date))
        v = ctx.and_valid(l, r)
        if not ctx.is_trace:
            return Val(int32, None, v, None)
        return Val(int32, (l.data - r.data).astype(_jnp().int32), v, None)


class Hour(UnaryExpression):
    @property
    def dtype(self):
        return int32

    def eval(self, ctx):
        c = ctx.eval(cast_if(self.child, timestamp))
        if not ctx.is_trace:
            return Val(int32, None, c.validity, None)
        jnp = _jnp()
        us_in_day = jnp.mod(c.data, 86_400_000_000)
        return Val(int32, (us_in_day // 3_600_000_000).astype(jnp.int32),
                   c.validity, None)


class Minute(UnaryExpression):
    @property
    def dtype(self):
        return int32

    def eval(self, ctx):
        c = ctx.eval(cast_if(self.child, timestamp))
        if not ctx.is_trace:
            return Val(int32, None, c.validity, None)
        jnp = _jnp()
        us = jnp.mod(c.data, 3_600_000_000)
        return Val(int32, (us // 60_000_000).astype(jnp.int32),
                   c.validity, None)


class Second(UnaryExpression):
    @property
    def dtype(self):
        return int32

    def eval(self, ctx):
        c = ctx.eval(cast_if(self.child, timestamp))
        if not ctx.is_trace:
            return Val(int32, None, c.validity, None)
        jnp = _jnp()
        us = jnp.mod(c.data, 60_000_000)
        return Val(int32, (us // 1_000_000).astype(jnp.int32),
                   c.validity, None)


class UnixTimestamp(UnaryExpression):
    @property
    def dtype(self):
        return int64

    def eval(self, ctx):
        c = ctx.eval(cast_if(self.child, timestamp))
        if not ctx.is_trace:
            return Val(int64, None, c.validity, None)
        return Val(int64, _jnp().floor_divide(c.data, 1_000_000),
                   c.validity, None)


class FromUnixtime(UnaryExpression):
    @property
    def dtype(self):
        return timestamp

    def eval(self, ctx):
        c = ctx.eval(cast_if(self.child, int64))
        if not ctx.is_trace:
            return Val(timestamp, None, c.validity, None)
        return Val(timestamp, c.data * 1_000_000, c.validity, None)


class AddMonths(BinaryExpression):
    @property
    def dtype(self):
        return date

    def eval(self, ctx):
        l = ctx.eval(cast_if(self.left, date))
        r = ctx.eval(cast_if(self.right, int32))
        v = ctx.and_valid(l, r)
        if not ctx.is_trace:
            return Val(date, None, v, None)
        jnp = _jnp()
        y, m, d = _civil_from_days(l.data)
        total = (y.astype(jnp.int64) * 12 + (m - 1)) + r.data
        ny = jnp.floor_divide(total, 12).astype(jnp.int32)
        nm = (jnp.mod(total, 12) + 1).astype(jnp.int32)
        # clamp day to end of month
        next_month_total = total + 1
        nmy = jnp.floor_divide(next_month_total, 12).astype(jnp.int32)
        nmm = (jnp.mod(next_month_total, 12) + 1).astype(jnp.int32)
        one = jnp.ones_like(nm)
        days_in_month = (_days_from_civil(nmy, nmm, one)
                         - _days_from_civil(ny, nm, one)).astype(jnp.int32)
        nd = jnp.minimum(d, days_in_month)
        return Val(date, _days_from_civil(ny, nm, nd), v, None)


class LastDay(UnaryExpression):
    @property
    def dtype(self):
        return date

    def eval(self, ctx):
        c = ctx.eval(cast_if(self.child, date))
        if not ctx.is_trace:
            return Val(date, None, c.validity, None)
        jnp = _jnp()
        y, m, d = _civil_from_days(c.data)
        ny = jnp.where(m == 12, y + 1, y)
        nm = jnp.where(m == 12, 1, m + 1)
        one = jnp.ones_like(m)
        return Val(date,
                   (_days_from_civil(ny, nm, one) - 1).astype(jnp.int32),
                   c.validity, None)


class MonthsBetween(BinaryExpression):
    @property
    def dtype(self):
        return float64

    def eval(self, ctx):
        l = ctx.eval(cast_if(self.left, date))
        r = ctx.eval(cast_if(self.right, date))
        v = ctx.and_valid(l, r)
        if not ctx.is_trace:
            return Val(float64, None, v, None)
        jnp = _jnp()
        ly, lm, ld = _civil_from_days(l.data)
        ry, rm, rd = _civil_from_days(r.data)
        months = (ly - ry) * 12 + (lm - rm)
        frac = (ld - rd).astype(jnp.float64) / 31.0
        return Val(float64, months.astype(jnp.float64) + frac, v, None)


# ---------------------------------------------------------------------------
# Aggregate functions (evaluated by the aggregation operator, not eval())
# ---------------------------------------------------------------------------

class AggregateFunction(Expression):
    child_fields = ("child",)

    def __init__(self, child: Expression | None):
        self.child = child

    @property
    def nullable(self):
        return True

    def eval(self, ctx):
        raise AnalysisException(
            f"aggregate function {type(self).__name__} cannot be evaluated "
            "outside an aggregation")


class Sum(AggregateFunction):
    @property
    def dtype(self):
        ct = self.child.dtype
        if isinstance(ct, DecimalType):
            return DecimalType(DecimalType.MAX_PRECISION, ct.scale)
        if isinstance(ct, IntegralType):
            return int64
        return float64


class Count(AggregateFunction):
    def __init__(self, child: Expression | None = None, distinct: bool = False):
        super().__init__(child)
        self.distinct = distinct

    @property
    def dtype(self):
        return int64

    @property
    def nullable(self):
        return False


class Min(AggregateFunction):
    @property
    def dtype(self):
        return self.child.dtype


class Max(AggregateFunction):
    @property
    def dtype(self):
        return self.child.dtype


class Mode(AggregateFunction):
    """mode(col) — most frequent non-null value (reference:
    sqlcat/expressions/aggregate/Mode.scala). Never lowered directly:
    the optimizer rewrites it into count-per-value + max-count join +
    min-value tie-break (RewriteModeAggregate), so it runs on the same
    device segment kernels as every other aggregate. Deterministic on
    ties (smallest value), where the reference is unspecified."""

    @property
    def dtype(self):
        return self.child.dtype


class BitAndAgg(AggregateFunction):
    """bit_and(col) (reference: sqlcat/expressions/aggregate/
    bitwiseAggregates.scala) — device bit-plane segment reduce.
    Result keeps the input's integral type, like the reference."""

    kind = "and"

    @property
    def dtype(self):
        ct = self.child.dtype
        if not isinstance(ct, IntegralType):
            raise TypeCheckError(
                f"bit_{self.kind} requires an integral column, got "
                f"{ct.simple_string()}")
        return ct


class BitOrAgg(BitAndAgg):
    kind = "or"


class BitXorAgg(BitAndAgg):
    kind = "xor"


class Average(AggregateFunction):
    @property
    def dtype(self):
        ct = self.child.dtype
        if isinstance(ct, DecimalType):
            return DecimalType(
                min(ct.precision + 4, DecimalType.MAX_PRECISION),
                min(ct.scale + 4, 10))
        return float64


class First(AggregateFunction):
    def __init__(self, child: Expression, ignore_nulls: bool = True):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    @property
    def dtype(self):
        return self.child.dtype


class AnyValue(First):
    pass


class _CentralMoment(AggregateFunction):
    ddof = 1

    @property
    def dtype(self):
        return float64


class StddevSamp(_CentralMoment):
    ddof = 1


class StddevPop(_CentralMoment):
    ddof = 0


class VarianceSamp(_CentralMoment):
    ddof = 1


class VariancePop(_CentralMoment):
    ddof = 0


class Percentile(AggregateFunction):
    """Exact percentile (the reference's percentile_approx computed exactly;
    non-mergeable, so the planner gathers before aggregating)."""

    def __init__(self, child: Expression, q: float):
        super().__init__(child)
        self.q = float(q)

    @property
    def dtype(self):
        ct = self.child.dtype
        return ct if isinstance(ct, (IntegralType, DateType, TimestampType,
                                     DecimalType)) else float64


class Median(Percentile):
    def __init__(self, child: Expression):
        super().__init__(child, 0.5)


class CollectSet(AggregateFunction):
    """collect_set (reference: sqlcat/expressions/aggregate/collect.scala)
    — non-mergeable here: the planner gathers to one partition; the lists
    are built host-side and dictionary-encoded (see ArrayType)."""

    @property
    def dtype(self):
        return ArrayType(self.child.dtype)


class CollectList(AggregateFunction):
    """collect_list (reference: collect.scala Collect/CollectList)."""

    @property
    def dtype(self):
        return ArrayType(self.child.dtype)
