"""Window expressions.

Role of the reference's windowExpressions.scala (WindowExpression,
WindowSpecDefinition, ranking functions) — evaluated by WindowExec via the
sort/segment kernels in ops/window.py.
"""

from __future__ import annotations

from typing import Sequence

from ..errors import UnsupportedOperationError
from ..types import DataType, float64, int32
from .expressions import (
    AggregateFunction, Expression, Literal, SortOrder,
)

__all__ = ["WindowFunction", "RowNumber", "Rank", "DenseRank", "PercentRank",
           "CumeDist", "NTile", "Lag", "Lead", "FirstValue", "LastValue",
           "NthValue", "WindowExpression"]


class WindowFunction(Expression):
    child_fields = ()

    @property
    def nullable(self):
        return False


class RowNumber(WindowFunction):
    @property
    def dtype(self):
        return int32


class Rank(WindowFunction):
    @property
    def dtype(self):
        return int32


class DenseRank(WindowFunction):
    @property
    def dtype(self):
        return int32


class PercentRank(WindowFunction):
    @property
    def dtype(self):
        return float64


class CumeDist(WindowFunction):
    @property
    def dtype(self):
        return float64


class NTile(WindowFunction):
    def __init__(self, n: Expression):
        if not isinstance(n, Literal):
            raise UnsupportedOperationError("ntile(n) needs a literal")
        self.n = int(n.value)

    @property
    def dtype(self):
        return int32


class Lag(WindowFunction):
    child_fields = ("child", "default")

    def __init__(self, child: Expression, offset: Expression | int = 1,
                 default: Expression | None = None):
        self.child = child
        self.offset = int(offset.value) if isinstance(offset, Literal) \
            else int(offset)
        self.default = default

    @property
    def dtype(self):
        return self.child.dtype

    @property
    def nullable(self):
        return True


class Lead(Lag):
    pass


class FirstValue(WindowFunction):
    """first_value(x): first row of the frame (default running frame →
    value at the partition start; reference: windowExpressions.scala
    First as a window function, RESPECT NULLS)."""

    child_fields = ("child",)

    def __init__(self, child: Expression):
        self.child = child

    @property
    def dtype(self):
        return self.child.dtype

    @property
    def nullable(self):
        return True


class LastValue(FirstValue):
    """last_value(x): last row of the frame — with ORDER BY the default
    frame ends at the CURRENT PEER GROUP (the classic gotcha), without
    ORDER BY the whole partition."""


class NthValue(WindowFunction):
    """nth_value(x, n): n-th row of the frame, NULL while the frame has
    fewer than n rows."""

    child_fields = ("child",)

    def __init__(self, child: Expression, n: Expression):
        if not isinstance(n, Literal):
            raise UnsupportedOperationError("nth_value(x, n) needs a "
                                            "literal n")
        self.child = child
        self.n = int(n.value)
        if self.n < 1:
            raise UnsupportedOperationError("nth_value n must be >= 1")

    @property
    def dtype(self):
        return self.child.dtype

    @property
    def nullable(self):
        return True


class UnresolvedWindowExpression(Expression):
    """Parsed `fn(...) OVER (...)` awaiting function resolution."""

    child_fields = ("function", "partition_spec", "order_spec")

    def __init__(self, function: Expression,
                 partition_spec: Sequence[Expression],
                 order_spec: Sequence["SortOrder"],
                 frame: tuple | None = None,
                 ref_name: str | None = None):
        self.function = function
        self.partition_spec = list(partition_spec)
        self.order_spec = list(order_spec)
        self.frame = frame
        # `fn() OVER w` — spec filled in from the query's WINDOW clause by
        # the parser before analysis
        self.ref_name = ref_name

    @property
    def resolved(self):
        return False


class WindowExpression(Expression):
    child_fields = ("function", "partition_spec", "order_spec")

    def __init__(self, function: Expression,
                 partition_spec: Sequence[Expression],
                 order_spec: Sequence[SortOrder],
                 frame: tuple | None = None):
        if not isinstance(function, (WindowFunction, AggregateFunction)):
            raise UnsupportedOperationError(
                f"{type(function).__name__} is not a window function")
        self.function = function
        self.partition_spec = list(partition_spec)
        self.order_spec = list(order_spec)
        # frame: None = Spark default; ("rows", lo, hi) with offsets where
        # None = unbounded (lo ≤ 0 ≤ hi row deltas)
        self.frame = frame

    @property
    def dtype(self) -> DataType:
        return self.function.dtype

    @property
    def nullable(self):
        return True

    def spec_signature(self):
        """Grouping key: window expressions sharing a spec evaluate in one
        WindowExec pass."""
        return (tuple(e.simple_string() for e in self.partition_spec),
                tuple((o.child.simple_string(), o.ascending, o.nulls_first)
                      for o in self.order_spec))

    def simple_string(self):
        p = ", ".join(e.simple_string() for e in self.partition_spec)
        o = ", ".join(x.child.simple_string() for x in self.order_spec)
        return (f"{self.function.simple_string()} OVER "
                f"(PARTITION BY {p} ORDER BY {o})")
