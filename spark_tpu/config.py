"""Typed configuration system.

Role of the reference's SparkConf + SQLConf (core/internal/config/package.scala,
sqlcat/.../internal/SQLConf.scala — typed ConfigBuilder entries with defaults,
docs, versioning; see SURVEY.md §5 "Config / flag system"), reduced to a
registry of typed entries with per-session overrides.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable


@dataclass(frozen=True)
class ConfigEntry:
    key: str
    default: Any
    doc: str = ""
    value_type: Callable[[str], Any] = str
    since: str = "0.1.0"


_REGISTRY: dict[str, ConfigEntry] = {}


def _register(entry: ConfigEntry) -> ConfigEntry:
    _REGISTRY[entry.key] = entry
    return entry


def _bool(v):
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("true", "1", "yes")


# --- core entries ----------------------------------------------------------

SHUFFLE_PARTITIONS = _register(ConfigEntry(
    "spark.sql.shuffle.partitions", 8,
    "Default number of partitions for exchanges (reference default: 200; "
    "TPU default is sized to a pod-slice's device count).", int))

BATCH_CAPACITY = _register(ConfigEntry(
    "spark.tpu.batch.capacity", 1 << 16,
    "Static row capacity of a ColumnarBatch tile. All kernels are compiled "
    "for power-of-two capacity buckets to bound XLA recompilation.", int))

MAX_BATCH_BUCKETS = _register(ConfigEntry(
    "spark.tpu.batch.maxCapacity", 1 << 24,
    "Upper bound for capacity-bucket growth on CapacityOverflowError.", int))

AUTO_BROADCAST_THRESHOLD = _register(ConfigEntry(
    "spark.sql.autoBroadcastJoinThreshold", 10 * 1024 * 1024,
    "Max estimated build-side bytes for broadcast hash join "
    "(reference: SQLConf.AUTO_BROADCASTJOIN_THRESHOLD).", int))

ADAPTIVE_ENABLED = _register(ConfigEntry(
    "spark.sql.adaptive.enabled", True,
    "Re-optimize at exchange boundaries from runtime stats "
    "(reference: sqlx/adaptive/AdaptiveSparkPlanExec.scala).", _bool))

COALESCE_PARTITIONS_ENABLED = _register(ConfigEntry(
    "spark.sql.adaptive.coalescePartitions.enabled", True,
    "AQE partition coalescing (reference: CoalesceShufflePartitions.scala).",
    _bool))

ADVISORY_PARTITION_BYTES = _register(ConfigEntry(
    "spark.sql.adaptive.advisoryPartitionSizeInBytes", 64 * 1024 * 1024,
    "Target partition size for AQE coalescing.", int))

SKEW_JOIN_ENABLED = _register(ConfigEntry(
    "spark.sql.adaptive.skewJoin.enabled", True,
    "Split skewed shuffle partitions (reference: OptimizeSkewedJoin.scala:57).",
    _bool))

ADAPTIVE_RUNTIME_FILTER = _register(ConfigEntry(
    "spark.tpu.adaptive.runtimeFilter", False,
    "Sideways information passing: when a hash-join build side "
    "materializes, harvest its key domain host-side from stats the "
    "engine already accumulates (dense-range memo min/max, StringDict "
    "code domains — ZERO extra syncs or launches) and push a filter "
    "into the not-yet-executed probe-side exchange. Probe map batches "
    "prune rows inside the existing fused shuffle kernel (aux "
    "operands, no new dispatch) or skip whole batches whose seeded "
    "range misses the domain. Distinct from the per-batch kernels of "
    "spark.tpu.join.runtimeFilter.", _bool))

ADAPTIVE_READMISSION = _register(ConfigEntry(
    "spark.tpu.adaptive.readmission", False,
    "Stage-boundary tier re-admission: after shuffle stages "
    "materialize, feed measured output stats back through the compile-"
    "tier chooser so the remaining plan can collapse into one whole-"
    "tier program; recurring queries re-plan from their warm-start "
    "manifest's observed volume before the first batch moves.", _bool))

ADAPTIVE_PARQUET_STATS = _register(ConfigEntry(
    "spark.tpu.adaptive.parquetStats", True,
    "Admit external parquet scans to the whole compile tier from "
    "footer statistics (row-group row counts + min/max) instead of "
    "excluding them categorically for lack of plan-time stats.", _bool))

ADAPTIVE_SKEW_REPARTITION = _register(ConfigEntry(
    "spark.tpu.adaptive.skewRepartition", True,
    "When mesh-exchange quota retries exhaust on pathological skew, "
    "split the remaining batches and re-plan the exchange as smaller "
    "mesh programs instead of falling straight back to the host "
    "shuffle (which stays as the terminal fallback).", _bool))

CASE_SENSITIVE = _register(ConfigEntry(
    "spark.sql.caseSensitive", False,
    "Case sensitivity of identifier resolution.", _bool))

ANSI_ENABLED = _register(ConfigEntry(
    "spark.sql.ansi.enabled", False,
    "ANSI SQL semantics (errors on overflow/invalid cast instead of null).",
    _bool))

SESSION_TIMEZONE = _register(ConfigEntry(
    "spark.sql.session.timeZone", "UTC", "Session timezone.", str))

DEFAULT_PARALLELISM = _register(ConfigEntry(
    "spark.default.parallelism", 8,
    "Default partition count for parallelize / scans.", int))

MAX_RESULT_ROWS = _register(ConfigEntry(
    "spark.tpu.collect.maxRows", 1 << 26,
    "Safety cap on rows materialized to the host by collect().", int))

DEVICE_MESH_AXIS = _register(ConfigEntry(
    "spark.tpu.mesh.dataAxis", "data",
    "Name of the mesh axis partitions are sharded over.", str))

MESH_ENABLED = _register(ConfigEntry(
    "spark.tpu.mesh.enabled", True,
    "Lower hash exchanges to lax.all_to_all over the device mesh when the "
    "partition count fits the mesh (the ICI data plane; reference analog: "
    "ShuffleExchangeExec lowering to the core shuffle). Falls back to the "
    "host sort-shuffle otherwise.", _bool))

DPP_ENABLED = _register(ConfigEntry(
    "spark.sql.dynamicPartitionPruning.enabled", True,
    "Prune probe-side scan splits from the join build side's distinct keys "
    "(reference: sqlx/dynamicpruning/PartitionPruning.scala).", _bool))

DPP_BUILD_THRESHOLD = _register(ConfigEntry(
    "spark.sql.dynamicPartitionPruning.buildThreshold", 4 << 20,
    "Max build-side rows for which distinct join-key values are collected "
    "for dynamic partition pruning.", int))

PARQUET_FILTER_PUSHDOWN = _register(ConfigEntry(
    "spark.sql.parquet.filterPushdown", True,
    "Prune parquet splits by hive partition values and row-group min/max "
    "statistics (reference: ParquetFileFormat/ParquetFilters).", _bool))

BLOOM_JOIN_FILTER = _register(ConfigEntry(
    "spark.tpu.join.runtimeFilter.bloom", False,
    "Device bloom-filter probe-side rows before the join sort-probe "
    "(reference: InjectRuntimeFilter.scala bloom branch).", _bool))

MINMAX_JOIN_FILTER = _register(ConfigEntry(
    "spark.tpu.join.runtimeFilter", False,
    "Min-max runtime join filter on single integral keys.", _bool))

SPECULATION = _register(ConfigEntry(
    "spark.speculation", False,
    "Re-launch straggler host tasks on another executor; first success "
    "wins, file commits arbitrated by the OutputCommitCoordinator "
    "(reference: TaskSetManager.scala:80-88).", _bool))

SHUFFLE_MAP_PARALLELISM = _register(ConfigEntry(
    "spark.tpu.shuffle.mapParallelism", 1,
    "Max map tasks per cluster shuffle map stage. 1 = stage-granular "
    "(one mapper computes the whole subtree); >1 slices the stage's "
    "multi-partition Fetch leaves across that many tasks on different "
    "executors; 0 = auto (min of alive executors and input partitions). "
    "Only hash/round-robin exchanges slice (range bounds are sampled "
    "per task, so slicing a range exchange would break global order).",
    int))

STATE_STORE_PARTITIONS = _register(ConfigEntry(
    "spark.sql.streaming.stateStore.numPartitions", 4,
    "Hash partitions for streaming state: each partition keeps its own "
    "snapshot+changelog lineage and a batch persists only touched "
    "partitions (reference: per-partition StateStore instances, "
    "sqlx/streaming/state/StateStore.scala:285).", int))

FUSION_ENABLED = _register(ConfigEntry(
    "spark.tpu.fusion.enabled", True,
    "Whole-stage kernel fusion: collapse each exchange-free chain of "
    "fusable operators (filter/project feeding a partial aggregate, limit, "
    "or hash-join probe) into ONE jitted program per batch "
    "(reference: WholeStageCodegenExec produce/consume splicing, "
    "sqlx/WholeStageCodegenExec.scala:673). Off = operator-at-a-time "
    "execution, kept as the differential-testing oracle.", _bool))

PARTITION_PARALLELISM = _register(ConfigEntry(
    "spark.tpu.exec.partitionParallelism", 0,
    "Concurrent partition-dispatch lanes inside an operator (async XLA "
    "dispatch pipelines across partitions instead of serial list "
    "comprehensions). 0 = auto (min(4, cpus)); 1 = serial.", int))

FUSION_MIN_ROWS = _register(ConfigEntry(
    "spark.tpu.fusion.minRows", 1 << 17,
    "Partition tile-capacity floor for running the whole-stage FUSED "
    "kernel. A fused program is compiled per (stage structure, signature, "
    "capacity) while the operator-at-a-time kernels are shared across "
    "query structures — below this many rows the XLA compile costs more "
    "than the dispatches it saves, so small partitions take the unfused "
    "kernels (same plan, runtime dispatch). 0 = always fuse.", int))

FUSION_DENSE_KEYS = _register(ConfigEntry(
    "spark.tpu.fusion.denseKeys", True,
    "Allow the fused partial aggregate to take the dense-range direct "
    "scatter path when the grouping key is a pass-through integral column "
    "whose (memoized) range fits a capacity bucket.", _bool))

FUSION_MESH = _register(ConfigEntry(
    "spark.tpu.fusion.mesh", True,
    "Mesh-native SPMD stage fusion: a fused shuffle exchange whose "
    "partition count matches the device mesh runs its WHOLE stage — "
    "traced filter/project pipeline, partition-id computation, per-shard "
    "bucket-by-destination and the ICI all-to-all — as ONE shard_map "
    "program per step, with the staged send buffers donated "
    "(donate_argnums) so the all-to-all reuses their HBM in-place. Off: "
    "the legacy composition materializes the pipeline per batch before "
    "the collective. Requires spark.tpu.fusion.enabled and "
    "spark.tpu.fusion.exchange; the minRows gate does not apply (the "
    "mesh stage is one program per step, not per batch).", _bool))

FUSION_EXCHANGE = _register(ConfigEntry(
    "spark.tpu.fusion.exchange", True,
    "Exchange map-side fusion: a stage whose terminal is a shuffle "
    "exchange traces its filter/project pipeline AND the partition-id "
    "computation (hash/range/round-robin) into ONE jitted kernel per map "
    "batch that emits the pid-grouped pipeline output; shuffle writes "
    "consume it directly — no intermediate materialized batch, <=1 "
    "dispatch per map batch. Requires spark.tpu.fusion.enabled; subject "
    "to the spark.tpu.fusion.minRows size gate.", _bool))

COMPILE_TIER = _register(ConfigEntry(
    "spark.tpu.compile.tier", "auto",
    "Compilation tier: 'mesh-whole' compiles the ENTIRE sharded query "
    "into ONE shard_map program per step — leaf planes row-sharded over "
    "the device mesh, hash exchanges as in-program lax.all_to_all, "
    "reduce-side consumers folded in behind the collective "
    "(physical/mesh_whole.py; needs spark.tpu.mesh.enabled, plain hash "
    "keys, one power-of-two partition count and enough devices — else "
    "falls back tier-by-tier with the reason on the decision); 'whole' "
    "compiles the query — exchanges lowered to in-program gathers — "
    "into ONE single-device jitted program per step (zero host shuffle "
    "round-trips; physical/whole_query.py); 'stage' compiles one "
    "program per stage per batch (PR 1/5/8 fusion, with the "
    "per-partition minRows runtime gate as the stage->operator "
    "fallback); 'operator' forces the shared operator-at-a-time kernels "
    "(the differential oracle). 'auto' (default) chooses from predicted "
    "compile cost, predicted fully-resident HBM (spark.tpu.memory.budget "
    "admission), and batch volume (spark.tpu.compile.whole.minRows), "
    "falling back tier-by-tier when statistics are unknown or budgets "
    "are exceeded — the generalization of the spark.tpu.fusion.minRows "
    "gate to whole programs; mesh-whole admits in auto ONLY when the "
    "single-device program exceeds the budget but a per-shard slice "
    "fits.", str))

WHOLE_MIN_ROWS = _register(ConfigEntry(
    "spark.tpu.compile.whole.minRows", 1 << 17,
    "Leaf-row volume floor for the auto tier to choose whole-query "
    "compilation (scaled up with program depth: deeper programs need "
    "more volume to amortize the bigger XLA compile). The whole-query "
    "analog of spark.tpu.fusion.minRows. Forced tier=whole ignores the "
    "floor (structural and memory admission still apply).", int))

ENCODING_ENABLED = _register(ConfigEntry(
    "spark.tpu.encoding.enabled", True,
    "Compressed execution: kernels operate directly on encoded columns. "
    "Single dictionary-encoded (string) grouping keys aggregate by direct "
    "scatter over the dense code domain (the dictionary IS the group "
    "table — no sort, no range probe), string join/exchange keys fuse "
    "into stage kernels via padded dictionary-hash aux tables, sorted "
    "run-length-encoded keys reduce per run without sorting, and cluster "
    "shuffle ships dictionary codes + one dictionary per map task instead "
    "of decoded values. Off = the decode-at-boundary oracle for "
    "differential testing.", _bool))

CODEGEN_CACHE_SIZE = _register(ConfigEntry(
    "spark.tpu.kernel.cacheSize", 1024,
    "Max entries in the jitted-kernel cache (role of the reference's "
    "CodeGenerator Janino class cache, codegen/CodeGenerator.scala:1557).",
    int))

# --- entries below were historically read by string literal at their use
# sites; registered here so config has a single typed source of truth
# (found and enforced by dev/tpulint.py's config-key rule) -----------------

VALIDATE_BATCHES = _register(ConfigEntry(
    "spark.tpu.debug.validateBatches", False,
    "Validate every operator's output batches (shape/dtype/mask "
    "invariants; columnar/validate.py). Debug only — syncs per batch.",
    _bool))

UI_OPERATOR_METRICS = _register(ConfigEntry(
    "spark.tpu.ui.operatorMetrics", True,
    "Record per-operator rows/time SQLMetrics for the plan graph/UI "
    "(exec/query_execution.py). One dict lookup per execute when off.",
    _bool))

AGG_BLOCK_ROWS = _register(ConfigEntry(
    "spark.tpu.agg.blockRows", 1 << 22,
    "Tile-capacity ceiling for a single aggregation chunk; larger "
    "partitions fold blockwise and merge partials (the sort-based "
    "fallback role of TungstenAggregationIterator).", int))

JOIN_RF_MIN_CAPACITY = _register(ConfigEntry(
    "spark.tpu.join.runtimeFilter.minCapacity", 1 << 20,
    "Probe batches below this capacity skip the runtime min-max join "
    "filter (the sort-probe is already cheap).", int))

DSV2_FILTER_PUSHDOWN = _register(ConfigEntry(
    "spark.tpu.datasource.filterPushdown", True,
    "Negotiate predicate pushdown with SupportsPushDownFilters sources "
    "(V2ScanRelationPushDown role).", _bool))

DSV2_AGG_PUSHDOWN = _register(ConfigEntry(
    "spark.tpu.datasource.aggPushdown", True,
    "Push whole group-by aggregates into SupportsPushDownAggregation "
    "sources.", _bool))

CLUSTER_MASTER = _register(ConfigEntry(
    "spark.tpu.master", "",
    "grpc://host:port of a standalone master to attach to "
    "(deploy/standalone.py; the spark-submit --master flow).", str))

CLUSTER_MASTER_SECRET = _register(ConfigEntry(
    "spark.tpu.master.secret", "",
    "Shared secret for the standalone master (or env "
    "SPARK_TPU_MASTER_SECRET).", str))

CLUSTER_ENABLED = _register(ConfigEntry(
    "spark.tpu.cluster.enabled", False,
    "Spawn a local process cluster for SQL execution (the reference's "
    "local-cluster mode).", _bool))

CLUSTER_WORKERS = _register(ConfigEntry(
    "spark.tpu.cluster.workers", 2,
    "Worker process count for the local process cluster.", int))

PUSH_SHUFFLE = _register(ConfigEntry(
    "spark.tpu.shuffle.push", False,
    "Push-based shuffle: mappers push blocks to reducer-side merged "
    "files (reference: push-based shuffle, core/shuffle/push).", _bool))

# --- observability (spark_tpu/obs/) ---------------------------------------

TRACE_ENABLED = _register(ConfigEntry(
    "spark.tpu.trace.enabled", True,
    "Always-on span tracing of the query lifecycle (parse/analyze/"
    "optimize/plan/stage/partition/exchange/collect; obs/tracing.py). "
    "Pure host bookkeeping — zero kernel launches, zero device syncs; "
    "export with session.tracer.write_chrome_trace() or bench.py "
    "--trace.", _bool))

TRACE_MAX_SPANS = _register(ConfigEntry(
    "spark.tpu.trace.maxSpans", 100_000,
    "Span-buffer cap per session tracer; spans past it are dropped and "
    "counted so a long-lived session stays bounded.", int))

KERNEL_ATTRIBUTION = _register(ConfigEntry(
    "spark.tpu.metrics.kernelAttribution", True,
    "Attribute KernelCache launch/compile-ms counters to the executing "
    "physical operator (obs/metrics.py contextvar scope, propagated into "
    "par_map lanes). Requires spark.tpu.ui.operatorMetrics; one "
    "contextvar read per kernel launch when on.", _bool))

CLUSTER_OBS_SHIPPING = _register(ConfigEntry(
    "spark.tpu.cluster.obsShipping", True,
    "Ship worker-side observability (per-operator metric records, spans, "
    "kernel-launch deltas) back with each cluster stage-task result and "
    "merge it into the driver's QueryMetrics/Tracer (the executor "
    "heartbeat metrics channel, reduced to per-task return). Off = "
    "cluster queries report driver-side observability only (saves the "
    "payload bytes on very wide fan-outs).", _bool))

# --- live telemetry (spark_tpu/obs/live.py) --------------------------------

HEARTBEAT_INTERVAL = _register(ConfigEntry(
    "spark.tpu.heartbeat.interval", 3.0,
    "Executor heartbeat period in seconds (exec/worker_main.py → driver; "
    "the reference's spark.executor.heartbeatInterval). Live obs deltas "
    "ride the same call, so this is also the worker-side flush cadence.",
    float))

HEARTBEAT_OBS = _register(ConfigEntry(
    "spark.tpu.heartbeat.obs", True,
    "Stream incremental observability deltas (open/closed spans since "
    "last flush, per-operator rows/batches/wall-ms, per-kind KernelCache "
    "launch/compile deltas) of running stage tasks on the executor "
    "heartbeat, feeding the driver's live store (obs/live.py). Pure host "
    "bookkeeping — zero kernel launches, no mid-query device syncs "
    "(parked row-masks stay parked until task end).", _bool))

PROGRESS_CONSOLE = _register(ConfigEntry(
    "spark.tpu.progress.console", False,
    "Render live per-stage progress bars (tasks done, rows/launches so "
    "far, straggler flags) to stderr while queries run, fed by the live "
    "telemetry store (reference: spark.ui.showConsoleProgress / "
    "ConsoleProgressBar).", _bool))

PROGRESS_UPDATE_INTERVAL = _register(ConfigEntry(
    "spark.tpu.progress.updateInterval", 0.5,
    "Console progress / local-mode flush repaint period in seconds.",
    float))

STRAGGLER_ENABLED = _register(ConfigEntry(
    "spark.tpu.straggler.enabled", True,
    "Flag straggling stage tasks from live heartbeat telemetry "
    "(obs.straggler findings in live status and EXPLAIN ANALYZE; signal "
    "hook for speculative execution).", _bool))

STRAGGLER_RATE_FRACTION = _register(ConfigEntry(
    "spark.tpu.straggler.rateFraction", 0.2,
    "A running task is a straggler when its progress rate (rows+batches+"
    "launches per second) falls below this fraction of the stage-wide "
    "median rate.", float))

STRAGGLER_MIN_SECONDS = _register(ConfigEntry(
    "spark.tpu.straggler.minSeconds", 1.0,
    "Minimum task runtime before rate-based straggler detection may "
    "fire (healthy short tasks must never be flagged).", float))

STRAGGLER_HEARTBEAT_DEADLINE = _register(ConfigEntry(
    "spark.tpu.straggler.heartbeatDeadline", 30.0,
    "A running task whose live telemetry goes silent for this many "
    "seconds is flagged as a straggler regardless of rate (executor "
    "frozen or partitioned).", float))

STRAGGLER_RATE_WEIGHTS = _register(ConfigEntry(
    "spark.tpu.straggler.rateWeights", "1,1,1",
    "Comma-separated rows,batches,launches weights of the straggler "
    "progress-rate unit (weighted sum per second vs the stage median). "
    "The default 1,1,1 preserves the original equal weighting; skew "
    "the weights for workloads where one dimension dominates cost "
    "(e.g. '1,0,0' for row-bound scans) so cost-skewed stages stop "
    "false-flagging.", str))

# --- resource observability (spark_tpu/obs/resources.py) -------------------

MEMORY_LEDGER = _register(ConfigEntry(
    "spark.tpu.memory.ledger", True,
    "Attributed HBM shadow ledger: every engine-held device buffer "
    "(columnar batches — column data, validity planes, row masks) "
    "registers its metadata-derived byte size to the current "
    "query/operator scope and deregisters on GC, giving live occupancy "
    "and per-query/per-stage watermarks (obs/resources.py). Pure host "
    "bookkeeping — zero kernel launches, no device syncs.", _bool))

MEMORY_BUDGET = _register(ConfigEntry(
    "spark.tpu.memory.budget", 0,
    "Per-query HBM admission budget in bytes (0 = unlimited): before "
    "dispatch, the plan analyzer's memory model predicts peak resident "
    "HBM and the query fails with MemoryBudgetExceeded naming the "
    "offending stage instead of an opaque XLA OOM mid-query (role of "
    "the reference's ExecutionMemoryPool acquireMemory refusal).", int))

KERNEL_COST = _register(ConfigEntry(
    "spark.tpu.metrics.kernelCost", True,
    "Capture each compiled kernel's XLA cost_analysis() (flops, bytes "
    "accessed) at first invocation via the lowering — no second backend "
    "compile — with an argument/output-metadata fallback; launches then "
    "attribute flops/bytes to the executing operator for EXPLAIN "
    "ANALYZE's achieved-GB/s roofline view and bench.py's measured "
    "hbm_gbps.", _bool))

MEMORY_PEAK_GBPS = _register(ConfigEntry(
    "spark.tpu.memory.peakGbps", 0.0,
    "Peak HBM bandwidth (GB/s) for achieved-vs-peak rendering; 0 = auto "
    "from the device kind (CPU backends report no roofline).", float))

KERNEL_MEMORY = _register(ConfigEntry(
    "spark.tpu.metrics.kernelMemory", False,
    "Capture each compiled kernel's XLA memory_analysis() temp (scratch) "
    "bytes at first invocation and fold them into EXPLAIN ANALYZE's HBM "
    "reconciliation and the query profile (the device ledger tracks "
    "engine-held tiles only — fused-kernel scratch is invisible to it). "
    "Off by default: the AOT lowering compile this requires is NOT "
    "shared with the dispatch path on this jax version, so capture "
    "costs one extra backend compile per distinct kernel.", _bool))

# --- query flight recorder (spark_tpu/obs/history.py) ----------------------

OBS_PROFILE_DIR = _register(ConfigEntry(
    "spark.tpu.obs.profileDir", "",
    "Directory for the persistent query flight recorder: at query close "
    "the driver appends a QueryProfile (plan fingerprint, per-operator "
    "metrics, launches/compile-ms by kind, tier decision, retry/fault "
    "counters, HBM watermarks, per-stage runtime stats) as one JSONL "
    "line keyed by the query's structural fingerprint, then compares "
    "the fresh profile against the fingerprint's stored baseline and "
    "raises obs.regression findings on deterministic-counter drift. "
    "Empty (default) = recorder off. Driver-owned: worker processes "
    "never write profiles regardless of this setting. Pure host "
    "bookkeeping — zero kernel launches, no mid-query device syncs "
    "(assembly runs after the query's last device interaction).", str))

OBS_PROFILE_RING = _register(ConfigEntry(
    "spark.tpu.obs.profileRing", 32,
    "Profiles retained per query fingerprint in the on-disk store (the "
    "JSONL file compacts to the newest N once it doubles the bound).",
    int))

OBS_PROFILE_BASELINE_N = _register(ConfigEntry(
    "spark.tpu.obs.profileBaselineN", 5,
    "Regression baseline window: the fresh profile compares against the "
    "MEDIAN of the last N stored profiles for the same structural query "
    "key.", int))

OBS_PROFILE_REGRESSION = _register(ConfigEntry(
    "spark.tpu.obs.profileRegression", True,
    "Raise obs.regression findings at query close when the fresh "
    "profile's deterministic counters (kernel launches by kind, compile "
    "count, retry/fault attempts) EXCEED the stored baseline (severity "
    "error), or wall/HBM drift past the advisory tolerance (severity "
    "info). Requires spark.tpu.obs.profileDir.", _bool))

OBS_PROFILE_WALL_TOLERANCE = _register(ConfigEntry(
    "spark.tpu.obs.profileWallTolerance", 1.5,
    "Advisory wall-clock drift factor: a fresh profile slower than "
    "tolerance x the baseline median wall-ms raises an info-severity "
    "obs.regression finding (wall time is noisy — never an error).",
    float))

# --- persistent caches (spark_tpu/exec/persist_cache.py) -------------------

CACHE_DIR = _register(ConfigEntry(
    "spark.tpu.cache.dir", "",
    "Root directory for the persistent caches: the XLA compile cache "
    "(<dir>/xla — jitted programs compiled once hit disk on every later "
    "process's first dispatch), the warm-start manifest (<dir>/"
    "manifest.jsonl — per-fingerprint tier decisions and join/mesh "
    "capacity outcomes, so a restarted server skips capacity-retry "
    "recompiles), and the result cache (<dir>/result — full "
    "plan-fingerprint + data-version keyed Arrow IPC payloads; a hit "
    "answers with ZERO kernel launches). Empty (default) = every "
    "persistent cache off; tier-1 exact-count tests and the plan "
    "analyzer's default launch model assume this default.", str))

CACHE_COMPILE = _register(ConfigEntry(
    "spark.tpu.cache.compile.enabled", True,
    "With spark.tpu.cache.dir set, point jax's XLA persistent "
    "compilation cache at <dir>/xla so every jitted program's backend "
    "compile is written to disk once and served from disk in later "
    "processes (the normal jax.jit dispatch path stays intact — no AOT "
    "lowered.compile(), whose compile is unshared with dispatch on this "
    "jax version). The obs layer counts compile.disk_hit distinctly "
    "from true cold compiles.", _bool))

CACHE_COMPILE_MAX_BYTES = _register(ConfigEntry(
    "spark.tpu.cache.compile.maxBytes", 0,
    "LRU byte bound for the on-disk XLA compile cache "
    "(jax_compilation_cache_max_size; least-recently-used entries are "
    "evicted past it). 0 = unbounded.", int))

CACHE_RESULT = _register(ConfigEntry(
    "spark.tpu.cache.result.enabled", True,
    "With spark.tpu.cache.dir set, cache full query RESULTS on disk "
    "keyed by plan fingerprint + a data-version component (warehouse "
    "parquet file identity, in-memory table content hash) — a repeated "
    "identical query answers from the Arrow IPC payload with zero "
    "kernel launches, shared across connect sessions, processes, and "
    "the cluster driver. Plans with non-deterministic expressions or "
    "unknown leaf data identity bypass the cache. Invalidated through "
    "the catalog write path on append/overwrite (and by the file "
    "identity in the key).", _bool))

CACHE_RESULT_MAX_BYTES = _register(ConfigEntry(
    "spark.tpu.cache.result.maxBytes", 256 << 20,
    "Byte budget for the on-disk result cache; past it the "
    "least-recently-hit payloads are evicted (flock-safe across "
    "processes). One result larger than an eighth of the budget is "
    "never cached.", int))

# --- chaos hardening (PR 11): fault injection, retry/backoff, exclusion ---

FAULTS_ENABLED = _register(ConfigEntry(
    "spark.tpu.faults.enabled", False,
    "Deterministic fault injection (utils/faults.py): named fault "
    "points threaded through the stack (rpc.call, block.fetch, "
    "worker.task, heartbeat.flush, kernel.compile, kernel.dispatch, "
    "shuffle.write) fire per spark.tpu.faults.points rules. Off "
    "(default) short-circuits every point to one module-bool read — "
    "zero overhead on healthy runs. Ships to workers like all conf.",
    _bool))

LOCKWATCH_ENABLED = _register(ConfigEntry(
    "spark.tpu.lockwatch.enabled", False,
    "Runtime lock-discipline validation (utils/lockwatch.py): swap "
    "registered process-global locks for watching proxies that record "
    "acquisition orders and held-lock sets at instrumented mutation "
    "sites; dev/validate_trace.py --race cross-checks the records "
    "against the static race_lint model. Off (default) runs raw "
    "unwrapped locks — zero overhead. SPARK_TPU_LOCKWATCH=1 enables at "
    "import time and ships to cluster workers via their environment.",
    _bool))

FAULTS_SEED = _register(ConfigEntry(
    "spark.tpu.faults.seed", 0,
    "Seed for probabilistic fault rules; identical seed + call order "
    "reproduces the identical fault schedule per process.", int))

FAULTS_POINTS = _register(ConfigEntry(
    "spark.tpu.faults.points", "",
    "';'-separated fault rules, each point=trigger[:arg][:action[:arg]]"
    "[@scope]. Triggers: once | nth:N | first:N | after:N (every call "
    "past the Nth — the blackout shape) | prob:P | always. "
    "Actions: raise (default) | kill (os._exit) | sleep:S. @scope "
    "restricts to processes with that host label or calls whose detail "
    "contains it (e.g. kernel.dispatch=once@whole_query).", str))

RPC_MAX_RETRIES = _register(ConfigEntry(
    "spark.tpu.rpc.maxRetries", 3,
    "Bounded retry count for transient RpcUnavailableError on "
    "conf-driven idempotent control-plane calls (finalize_merge; any "
    "caller constructing RetryPolicy.from_conf) — the reference's "
    "spark.rpc.numRetries role. Fire-and-forget cleanup RPCs "
    "(free_shuffle, push_block) use a fixed small best-effort policy "
    "instead, so a flapping peer can never stall shutdown on a "
    "generous conf.", int))

RPC_RETRY_BACKOFF_MS = _register(ConfigEntry(
    "spark.tpu.rpc.retryBackoffMs", 50.0,
    "Base backoff between control-plane RPC retries; grows "
    "exponentially per attempt with full jitter, capped at 2s.", float))

RPC_RETRY_DEADLINE = _register(ConfigEntry(
    "spark.tpu.rpc.retryDeadline", 10.0,
    "Wall-clock budget in seconds for one logical control-plane call "
    "including all its retries — retries never extend past it.", float))

FETCH_MAX_RETRIES = _register(ConfigEntry(
    "spark.tpu.shuffle.fetch.maxRetries", 2,
    "Bounded shuffle-block fetch retries (primary then shuffle-service "
    "fallback per round) BEFORE raising FetchFailedError — a transient "
    "block-server flap stops paying a full lineage stage regeneration "
    "(reference: spark.shuffle.io.maxRetries).", int))

FETCH_RETRY_WAIT_MS = _register(ConfigEntry(
    "spark.tpu.shuffle.fetch.retryWaitMs", 50.0,
    "Wait between shuffle fetch retry rounds (scaled linearly by "
    "attempt; reference: spark.shuffle.io.retryWait).", float))

EXCLUDE_ON_FAILURE = _register(ConfigEntry(
    "spark.tpu.excludeOnFailure.enabled", True,
    "Window-based executor exclusion (reference: TaskSetExcludelist / "
    "HealthTracker, spark.excludeOnFailure.*): executors accumulating "
    "maxFailures task failures inside windowSecs stop receiving tasks "
    "for timeoutSecs, then rejoin automatically (timed re-inclusion). "
    "Surfaced in live status, console executor rows, and EXPLAIN "
    "ANALYZE findings.", _bool))

EXCLUDE_MAX_FAILURES = _register(ConfigEntry(
    "spark.tpu.excludeOnFailure.maxFailures", 2,
    "Task failures inside the window before an executor is excluded "
    "(reference: spark.excludeOnFailure.task.maxTaskAttemptsPerExecutor "
    "family).", int))

EXCLUDE_WINDOW_SECS = _register(ConfigEntry(
    "spark.tpu.excludeOnFailure.windowSecs", 60.0,
    "Sliding window over which executor failures count toward "
    "exclusion; older failures expire.", float))

EXCLUDE_TIMEOUT_SECS = _register(ConfigEntry(
    "spark.tpu.excludeOnFailure.timeoutSecs", 30.0,
    "How long an excluded executor stays out of scheduling before "
    "timed re-inclusion (reference: spark.excludeOnFailure.timeout).",
    float))

STAGE_MAX_REGENS = _register(ConfigEntry(
    "spark.tpu.scheduler.maxStageRegens", 8,
    "Per-query cap on FetchFailed-driven stage regenerations; past it "
    "the query fails with the classified StageRegenerationLimitError "
    "instead of looping (reference: spark.stage.maxConsecutiveAttempts "
    "+ the DAGScheduler abort-on-repeated-fetch-failure path).", int))

HEARTBEAT_FLUSH_BUDGET = _register(ConfigEntry(
    "spark.tpu.heartbeat.flushBudget", 1 << 18,
    "Approximate byte cap on the live-obs payload of ONE executor "
    "heartbeat. Beyond it, remaining in-flight tasks ship minimal "
    "counter-only deltas and an overflow counter surfaces in live "
    "status; their closed spans stay in a bounded carry buffer and the "
    "trim rotates across tasks, so each task periodically ships in "
    "full (only a task closing more spans than the carry bound before "
    "its rotation turn loses its oldest — the task-return record still "
    "carries the complete set). 0 = uncapped.", int))


# --- multi-tenant serving (spark_tpu/serve/) -------------------------------

SERVE_POOLS = _register(ConfigEntry(
    "spark.tpu.scheduler.pools", "",
    "Comma-separated fair-scheduler pool declarations 'name[:weight]' "
    "(e.g. 'dash:2,batch:1'). The 'default' pool (weight 1) always "
    "exists. Per-pool overrides ride "
    "spark.tpu.scheduler.pool.<name>.{weight,maxConcurrent,queueSize,"
    "queueTimeout,hbmBudget}. Role of the reference's "
    "FairSchedulableBuilder + fairscheduler.xml pools.", str))

SERVE_POOL = _register(ConfigEntry(
    "spark.tpu.scheduler.pool", "default",
    "Fair-scheduler pool this session's queries are admitted under "
    "(SET spark.tpu.scheduler.pool=... — the reference's thread-local "
    "spark.scheduler.pool selection). Undeclared pools are created on "
    "demand with default settings.", str))

SERVE_MAX_CONCURRENT = _register(ConfigEntry(
    "spark.tpu.serve.maxConcurrent", 4,
    "Global cap on concurrently EXECUTING queries across all pools "
    "(fair-share slots; queued queries wait their pool's weighted "
    "turn). 0 = unlimited.", int))

SERVE_QUEUE_SIZE = _register(ConfigEntry(
    "spark.tpu.serve.queueSize", 64,
    "Default per-pool admission-queue bound; a query arriving at a "
    "full queue is rejected immediately with POOL_QUEUE_FULL (load "
    "shedding) instead of queueing unboundedly.", int))

SERVE_QUEUE_TIMEOUT = _register(ConfigEntry(
    "spark.tpu.serve.queueTimeout", 30.0,
    "Default per-pool queue timeout in seconds: a query that has not "
    "won a slot within it is rejected with ADMISSION_TIMEOUT.", float))

SERVE_SESSION_MODE = _register(ConfigEntry(
    "spark.tpu.serve.sessionMode", "isolated",
    "SQL-endpoint session model: 'isolated' (default) clones one "
    "session per connection (connection-local SET/temp views, shared "
    "KernelCache/warehouse/persistent caches — the reference's "
    "ThriftServer session-per-connection model); 'shared' keeps the "
    "legacy all-connections-share-one-session behavior (a connection "
    "can also opt in per-request with {\"session\": \"shared\"}).",
    str))

SERVE_DRAIN_TIMEOUT = _register(ConfigEntry(
    "spark.tpu.serve.drainTimeout", 30.0,
    "Graceful-drain budget in seconds for SQLEndpoint.stop()/SIGTERM: "
    "new queries are rejected with SERVER_DRAINING immediately; "
    "in-flight (and already-queued) queries get this long to finish "
    "and flush their query profiles before the socket closes.", float))

SERVE_SLO_MS = _register(ConfigEntry(
    "spark.tpu.serve.sloMs", 0.0,
    "Default per-query end-to-end latency SLO target in ms (submit to "
    "release, queue wait included) for every fair-scheduler pool; 0 "
    "disables SLO accounting. Per-pool overrides ride "
    "spark.tpu.serve.pool.<name>.sloMs. Queries over target bump the "
    "pool's burn counter and raise obs.slo findings in live status and "
    "EXPLAIN ANALYZE.", float))

SERVE_POOL_SLO = _register(ConfigEntry(
    "spark.tpu.serve.pool.<name>.sloMs", 0.0,
    "Per-pool end-to-end latency SLO target in ms, overriding "
    "spark.tpu.serve.sloMs for pool <name> (documentation template — "
    "substitute the pool name; read via the per-pool override path "
    "like the spark.tpu.scheduler.pool.<name>.* family).", float))

# --- service metrics plane (spark_tpu/obs/export.py) -----------------------

METRICS_EXPORT = _register(ConfigEntry(
    "spark.tpu.metrics.export", False,
    "Service metrics plane master switch: the process-wide "
    "MetricsRegistry scrape surface (Prometheus text /metrics on the "
    "history server, {\"metrics\": true} on the SQL endpoint), the "
    "time-series ticker thread, and per-executor registry deltas on "
    "the heartbeat. Structurally zero overhead when off (module-bool "
    "fast path; no ticker thread, no heartbeat field, no scrape "
    "collection). Role of the reference's spark.metrics.conf + "
    "PrometheusServlet.", _bool))

METRICS_TICK_INTERVAL = _register(ConfigEntry(
    "spark.tpu.metrics.tickInterval", 5.0,
    "Seconds between time-series ticker samples of the metric surface "
    "into the bounded in-memory ring (sparklines in serve status, the "
    "drain-time snapshot). Host-counter reads only — a tick launches "
    "no kernels and never syncs the device.", float))

METRICS_RING_SIZE = _register(ConfigEntry(
    "spark.tpu.metrics.ringSize", 120,
    "Points retained in the in-memory metrics time-series ring (at the "
    "default 5s tick interval, 120 points = 10 minutes of sparkline "
    "history; memory stays bounded regardless of uptime).", int))

# --- query black box (spark_tpu/obs/blackbox.py) ---------------------------

OBS_BUNDLES = _register(ConfigEntry(
    "spark.tpu.obs.bundles", False,
    "Anomaly-triggered diagnostic bundle capture: on any severity-error "
    "finding (obs.slo breach, obs.regression, obs.straggler, "
    "tier.degraded, exec.excluded, admission rejection, query failure) "
    "the driver assembles a self-contained postmortem bundle (Chrome "
    "trace, EXPLAIN reports, metrics snapshot + time-series window, "
    "QueryProfile with same-key baseline history, executor/HBM state, "
    "non-default config, the finding chain, pulled worker diagnostic "
    "rings) under spark.tpu.obs.bundleDir. Structurally zero overhead "
    "when off (module-bool fast path); armed-but-untriggered runs "
    "launch zero extra kernels — capture is pull-on-anomaly, never "
    "ship-always.", _bool))

OBS_BUNDLE_DIR = _register(ConfigEntry(
    "spark.tpu.obs.bundleDir", "",
    "Directory holding diagnostic bundles (one subdirectory per bundle "
    "plus a flock-safe index.jsonl retention ring). Empty (default) "
    "disables capture even when spark.tpu.obs.bundles is on; "
    "session.capture_diagnostics() requires it. dev/diagnose.py and "
    "the history server's /bundles pages read it offline.", str))

OBS_BUNDLE_RING = _register(ConfigEntry(
    "spark.tpu.obs.bundle.ring", 16,
    "Retention bound on stored bundles: once more than this many exist "
    "the oldest bundle directories are deleted at capture time (under "
    "the index flock), so disk stays bounded no matter how unhealthy "
    "the fleet gets.", int))

OBS_BUNDLE_SAMPLE_HEALTHY = _register(ConfigEntry(
    "spark.tpu.obs.bundle.sampleHealthy", 0,
    "Deterministic 1-in-N tail-sampling of HEALTHY queries into "
    "bundles (reason 'sampled') for comparison baselines: every Nth "
    "trigger-free query close captures. 0 (default) samples none — "
    "healthy runs write nothing.", int))


class SQLConf:
    """Session-local config with string overrides over typed defaults.

    Thread-safe; `get` accepts either a ConfigEntry or a string key.
    """

    def __init__(self, overrides: dict[str, Any] | None = None):
        self._lock = threading.RLock()
        self._values: dict[str, Any] = dict(overrides or {})

    def set(self, key: str | ConfigEntry, value: Any) -> "SQLConf":
        k = key.key if isinstance(key, ConfigEntry) else key
        with self._lock:
            self._values[k] = value
        return self

    def overrides(self) -> dict:
        """Snapshot of explicit overrides (for shipping to executors)."""
        with self._lock:
            return dict(self._values)

    def unset(self, key: str | ConfigEntry) -> None:
        k = key.key if isinstance(key, ConfigEntry) else key
        with self._lock:
            self._values.pop(k, None)

    def get(self, key: str | ConfigEntry, default: Any = None) -> Any:
        entry = key if isinstance(key, ConfigEntry) else _REGISTRY.get(key)
        k = entry.key if entry else key
        with self._lock:
            if k in self._values:
                raw = self._values[k]
                if entry is not None and isinstance(raw, str):
                    return entry.value_type(raw)
                return raw
        if entry is not None:
            return entry.default
        return default

    def copy(self) -> "SQLConf":
        with self._lock:
            return SQLConf(dict(self._values))

    # convenience typed accessors used on hot paths
    @property
    def shuffle_partitions(self) -> int:
        return int(self.get(SHUFFLE_PARTITIONS))

    @property
    def batch_capacity(self) -> int:
        return int(self.get(BATCH_CAPACITY))

    @property
    def case_sensitive(self) -> bool:
        return bool(self.get(CASE_SENSITIVE))

    @property
    def ansi_enabled(self) -> bool:
        return bool(self.get(ANSI_ENABLED))


def registry() -> dict[str, ConfigEntry]:
    return dict(_REGISTRY)
