"""Sort-based grouped aggregation kernel.

Role of the reference's HashAggregateExec + UnsafeFixedWidthAggregationMap
(sqlx/aggregate/HashAggregateExec.scala:50, corej/unsafe/map/BytesToBytesMap.java)
and its sort-based fallback (TungstenAggregationIterator). TPU-native design:
no hash table at all — `lax.sort` (bitonic/radix, MXU-adjacent, fully
data-parallel) groups equal keys adjacently, then `segment_sum`-family ops
reduce each run. Static shapes throughout: output has the same capacity as
input (worst case all rows distinct) with a row mask for live groups.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
from jax import lax


class GroupLayout(NamedTuple):
    """Result of grouping rows by key columns."""

    perm: jnp.ndarray        # int32[cap] permutation sorting rows (inactive last)
    seg_ids: jnp.ndarray     # int32[cap] segment id per SORTED row (0-based)
    start_flag: jnp.ndarray  # bool[cap] first-row-of-group flag per sorted row
    active: jnp.ndarray      # bool[cap] row_mask per sorted row
    num_groups: jnp.ndarray  # int32 scalar — number of live groups


def group_rows(key_cols: Sequence[jnp.ndarray],
               key_valids: Sequence[jnp.ndarray | None],
               row_mask: jnp.ndarray) -> GroupLayout:
    """Sort rows so equal keys (SQL semantics: null == null, inactive rows
    last) are adjacent; derive segment structure."""
    cap = row_mask.shape[0]
    inactive = (~row_mask).astype(jnp.int32)
    operands = [inactive]
    for c, v in zip(key_cols, key_valids):
        if v is not None:
            operands.append((~v).astype(jnp.int32))  # nulls group together
            operands.append(jnp.where(v, c, jnp.zeros_like(c)))
        else:
            operands.append(c)
    num_keys = len(operands)
    operands.append(lax.iota(jnp.int32, cap))
    sorted_ops = lax.sort(tuple(operands), num_keys=num_keys, is_stable=True)
    perm = sorted_ops[-1]
    skeys = sorted_ops[:num_keys]
    active = jnp.take(row_mask, perm)

    changed = jnp.zeros(cap, dtype=bool).at[0].set(True)
    for k in skeys:
        diff = jnp.concatenate([jnp.ones(1, dtype=bool), k[1:] != k[:-1]])
        changed = changed | diff
    start_flag = changed & active
    seg_ids = jnp.cumsum(start_flag.astype(jnp.int32)) - 1
    seg_ids = jnp.maximum(seg_ids, 0)
    num_groups = jnp.sum(start_flag.astype(jnp.int32))
    return GroupLayout(perm, seg_ids, start_flag, active, num_groups)


def group_rows_presorted(key: jnp.ndarray, row_mask: jnp.ndarray
                         ) -> GroupLayout:
    """GroupLayout for a single key column whose values are ALREADY
    non-decreasing (ingest RunInfo.is_sorted metadata, no validity plane):
    the RLE-aware segment reduce. Equal keys are contiguous by
    construction, so the segment structure derives from run BOUNDARIES
    (one adjacent-difference + a per-run first-live scatter) and the
    O(cap log cap) grouping sort is skipped entirely — the reduce visits
    each run once instead of re-discovering it. Mask-only filters never
    reorder rows, so sortedness established at ingest survives them;
    masked rows inside a run contribute nothing (weights), and runs with
    no live rows produce no group."""
    cap = row_mask.shape[0]
    pos = lax.iota(jnp.int32, cap)
    changed = jnp.concatenate([jnp.ones(1, dtype=bool),
                               key[1:] != key[:-1]])
    run_id = jnp.cumsum(changed.astype(jnp.int32)) - 1
    # first LIVE row of each value run opens its group: a masked row
    # between two live rows of one run must not split the group
    p = jnp.where(row_mask, pos, cap)
    first_live = jax.ops.segment_min(p, run_id, num_segments=cap)
    start_flag = row_mask & (pos == jnp.take(first_live, run_id))
    seg_ids = jnp.maximum(jnp.cumsum(start_flag.astype(jnp.int32)) - 1, 0)
    num_groups = jnp.sum(start_flag.astype(jnp.int32))
    return GroupLayout(pos, seg_ids, start_flag, row_mask, num_groups)


def scatter_group_keys(layout: GroupLayout, key_col: jnp.ndarray,
                       key_valid: jnp.ndarray | None):
    """Gather each group's key value into output slot seg_id.

    Returns (data[cap], validity[cap] | None) in group-output order."""
    cap = layout.perm.shape[0]
    sorted_vals = jnp.take(key_col, layout.perm)
    idx = jnp.where(layout.start_flag, layout.seg_ids, cap)  # drop non-starts
    out = jnp.zeros(cap, dtype=key_col.dtype).at[idx].set(sorted_vals, mode="drop")
    out_valid = None
    if key_valid is not None:
        sv = jnp.take(key_valid, layout.perm)
        out_valid = jnp.zeros(cap, dtype=bool).at[idx].set(sv, mode="drop")
    return out, out_valid


def group_output_mask(layout: GroupLayout):
    cap = layout.perm.shape[0]
    return lax.iota(jnp.int32, cap) < layout.num_groups


# --- segment aggregation primitives ---------------------------------------

def _weights(layout: GroupLayout, valid: jnp.ndarray | None):
    w = layout.active
    if valid is not None:
        w = w & jnp.take(valid, layout.perm)
    return w


def seg_sum(layout: GroupLayout, values: jnp.ndarray, valid=None):
    cap = values.shape[0]
    v = jnp.take(values, layout.perm)
    w = _weights(layout, valid)
    acc_dtype = jnp.float64 if jnp.issubdtype(v.dtype, jnp.floating) else jnp.int64
    vv = jnp.where(w, v.astype(acc_dtype), jnp.zeros((), acc_dtype))
    total = jax.ops.segment_sum(vv, layout.seg_ids, num_segments=cap)
    cnt = jax.ops.segment_sum(w.astype(jnp.int64), layout.seg_ids, num_segments=cap)
    return total, cnt  # caller derives sum validity: cnt > 0


def seg_count(layout: GroupLayout, valid=None):
    cap = layout.perm.shape[0]
    w = _weights(layout, valid)
    return jax.ops.segment_sum(w.astype(jnp.int64), layout.seg_ids, num_segments=cap)


def seg_min(layout: GroupLayout, values: jnp.ndarray, valid=None):
    cap = values.shape[0]
    v = jnp.take(values, layout.perm)
    w = _weights(layout, valid)
    big = _max_ident(v.dtype)
    vv = jnp.where(w, v, big)
    m = jax.ops.segment_min(vv, layout.seg_ids, num_segments=cap)
    cnt = jax.ops.segment_sum(w.astype(jnp.int32), layout.seg_ids, num_segments=cap)
    return m, cnt > 0


def seg_max(layout: GroupLayout, values: jnp.ndarray, valid=None):
    cap = values.shape[0]
    v = jnp.take(values, layout.perm)
    w = _weights(layout, valid)
    small = _min_ident(v.dtype)
    vv = jnp.where(w, v, small)
    m = jax.ops.segment_max(vv, layout.seg_ids, num_segments=cap)
    cnt = jax.ops.segment_sum(w.astype(jnp.int32), layout.seg_ids, num_segments=cap)
    return m, cnt > 0


def bitplane_reduce(values: jnp.ndarray, weights: jnp.ndarray,
                    seg_ids: jnp.ndarray, num_segments: int, kind: str):
    """bit_and / bit_or / bit_xor per segment (reference:
    sqlcat/expressions/aggregate/bitwiseAggregates.scala). jax has no
    bitwise segment reduce, so decompose into 64 bit PLANES and ride
    ONE [cap, 64] segment_sum — then OR = plane sum > 0, AND = plane
    sum == segment count, XOR = plane sum parity. Arithmetic shift on
    int64 keeps two's-complement bit patterns exact for negatives.
    Planes are int32 (counts < 2^31), halving the HBM transient vs a
    naive int64 matrix. Shared by the sorted-segment, dense-range, and
    ungrouped kernels."""
    v = values.astype(jnp.int64)
    shifts = jnp.arange(64, dtype=jnp.int64)
    bits = ((v[:, None] >> shifts[None, :]) & 1).astype(jnp.int32)
    bits = jnp.where(weights[:, None], bits, jnp.int32(0))
    sums = jax.ops.segment_sum(bits, seg_ids, num_segments=num_segments)
    cnt = jax.ops.segment_sum(weights.astype(jnp.int32), seg_ids,
                              num_segments=num_segments)
    if kind == "and":
        plane = (sums == cnt[:, None]) & (cnt[:, None] > 0)
    elif kind == "xor":
        plane = (sums & 1) == 1
    else:
        plane = sums > 0
    out = (plane.astype(jnp.int64) << shifts[None, :]).sum(axis=1)
    return out, cnt > 0


def seg_bitreduce(layout: GroupLayout, values: jnp.ndarray, valid=None,
                  kind: str = "or"):
    cap = values.shape[0]
    v = jnp.take(values, layout.perm)
    w = _weights(layout, valid)
    return bitplane_reduce(v, w, layout.seg_ids, cap, kind)


def seg_first(layout: GroupLayout, values: jnp.ndarray, valid=None):
    """First value per group in sorted order (the reference's First agg is
    also order-dependent)."""
    cap = values.shape[0]
    v = jnp.take(values, layout.perm)
    w = _weights(layout, valid)
    # first row of each group where weight holds: use segment_min over
    # (position if w else cap)
    pos = lax.iota(jnp.int32, cap)
    p = jnp.where(w, pos, cap)
    first_pos = jax.ops.segment_min(p, layout.seg_ids, num_segments=cap)
    has = first_pos < cap
    fp = jnp.minimum(first_pos, cap - 1)
    return jnp.take(v, fp), has


# --- primitive-op dispatch tables ------------------------------------------
# One traced consume loop per aggregation layout, shared by the standalone
# HashAggregateExec kernels and the whole-stage fused kernels
# (physical/fusion.py) so both paths reduce with byte-identical op code.

def apply_group_ops(layout: GroupLayout, ops: Sequence[str], val_datas,
                    val_valids):
    """Sorted-segment reduce of each (op, values, validity) triple over a
    GroupLayout. Returns [(buffer, validity | None)] per op."""
    bufs = []
    for op, vd, vv in zip(ops, val_datas, val_valids):
        if op in ("count", "countstar"):
            cnt = seg_count(layout, vv if op == "count" else None)
            bufs.append((cnt, None))
        elif op == "sum":
            total, cnt = seg_sum(layout, vd, vv)
            bufs.append((total, cnt > 0))
        elif op == "sumsq":
            x = vd.astype(jnp.float64)
            total, cnt = seg_sum(layout, x * x, vv)
            bufs.append((total, cnt > 0))
        elif op == "min":
            m, has = seg_min(layout, vd, vv)
            bufs.append((m, has))
        elif op == "max":
            m, has = seg_max(layout, vd, vv)
            bufs.append((m, has))
        elif op == "first":
            f, has = seg_first(layout, vd, vv)
            bufs.append((f, has))
        elif op in ("bitand", "bitor", "bitxor"):
            r, has = seg_bitreduce(layout, vd, vv, kind=op[3:])
            bufs.append((r, has))
        else:
            raise ValueError(op)
    return bufs


def apply_dense_ops(seg, out_cap: int, cap: int, ops: Sequence[str],
                    val_datas, val_valids, live_mask):
    """Direct scatter reduce keyed by precomputed segment ids (dense-range
    fast path; `live_mask` is the row mask after filters). Returns
    [(buffer, validity | None)] per op."""
    bufs = []
    for op, vd, vv in zip(ops, val_datas, val_valids):
        w = live_mask if vv is None else (live_mask & vv)
        if op in ("count", "countstar"):
            ww = live_mask if op == "countstar" else w
            cnt = jax.ops.segment_sum(
                ww.astype(jnp.int64), seg, num_segments=out_cap)
            bufs.append((cnt, None))
        elif op in ("sum", "sumsq"):
            acc = jnp.float64 if jnp.issubdtype(vd.dtype, jnp.floating) \
                else jnp.int64
            x = vd.astype(acc)
            if op == "sumsq":
                x = vd.astype(jnp.float64)
                x = x * x
            total = jax.ops.segment_sum(
                jnp.where(w, x, jnp.zeros((), x.dtype)), seg,
                num_segments=out_cap)
            cnt = jax.ops.segment_sum(w.astype(jnp.int64), seg,
                                      num_segments=out_cap)
            bufs.append((total, cnt > 0))
        elif op == "min":
            big = _max_ident(vd.dtype)
            m = jax.ops.segment_min(jnp.where(w, vd, big), seg,
                                    num_segments=out_cap)
            cnt = jax.ops.segment_sum(w.astype(jnp.int32), seg,
                                      num_segments=out_cap)
            bufs.append((m, cnt > 0))
        elif op == "max":
            small = _min_ident(vd.dtype)
            m = jax.ops.segment_max(jnp.where(w, vd, small), seg,
                                    num_segments=out_cap)
            cnt = jax.ops.segment_sum(w.astype(jnp.int32), seg,
                                      num_segments=out_cap)
            bufs.append((m, cnt > 0))
        elif op == "first":
            pos = lax.iota(jnp.int32, cap)
            p = jnp.where(w, pos, cap)
            fp = jax.ops.segment_min(p, seg, num_segments=out_cap)
            has = fp < cap
            bufs.append((jnp.take(vd, jnp.minimum(fp, cap - 1)), has))
        elif op in ("bitand", "bitor", "bitxor"):
            r, has = bitplane_reduce(vd, w, seg, out_cap, op[3:])
            bufs.append((r, has))
        else:
            raise ValueError(op)
    return bufs


def apply_global_ops(ops: Sequence[str], val_datas, val_valids, row_mask):
    """Whole-tile (ungrouped) reduce. Returns [(scalar, has | None)]."""
    outs = []
    for op, vd, vv in zip(ops, val_datas, val_valids):
        if op in ("count", "countstar"):
            w = row_mask if (vv is None or op == "countstar") \
                else (row_mask & vv)
            outs.append((jnp.sum(w.astype(jnp.int64)), None))
        elif op == "sum":
            s, c = masked_sum(vd, row_mask, vv)
            outs.append((s, c > 0))
        elif op == "sumsq":
            x = vd.astype(jnp.float64)
            s, c = masked_sum(x * x, row_mask, vv)
            outs.append((s, c > 0))
        elif op == "min":
            m, has = masked_min(vd, row_mask, vv)
            outs.append((m, has))
        elif op == "max":
            m, has = masked_max(vd, row_mask, vv)
            outs.append((m, has))
        elif op == "first":
            w = row_mask if vv is None else (row_mask & vv)
            pos = jnp.argmax(w)  # first True (0 if none)
            has = jnp.any(w)
            outs.append((vd[pos], has))
        elif op in ("bitand", "bitor", "bitxor"):
            w = row_mask if vv is None else (row_mask & vv)
            seg0 = jnp.zeros(vd.shape[0], dtype=jnp.int32)
            r, has = bitplane_reduce(vd, w, seg0, 1, op[3:])
            outs.append((r[0], has[0]))
        else:
            raise ValueError(op)
    return outs


def _max_ident(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf, dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(True)
    return jnp.asarray(jnp.iinfo(dtype).max, dtype)


def _min_ident(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(-jnp.inf, dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(False)
    return jnp.asarray(jnp.iinfo(dtype).min, dtype)


def group_percentile(key_cols, key_valids, values, value_valid, row_mask,
                     q: float):
    """Exact per-group percentile: one sort by (keys, value) makes each
    group's values contiguous and ordered; the q-th element is a gather at
    seg_start + floor(q·(n_valid−1)). Non-mergeable across partitions (the
    planner gathers to one partition first). Returns (vals, has) in the
    same group order as group_rows over the same keys."""
    cap = row_mask.shape[0]
    w = row_mask if value_valid is None else (row_mask & value_valid)
    operands = [(~row_mask).astype(jnp.int32)]
    for c, v in zip(key_cols, key_valids):
        if v is not None:
            operands.append((~v).astype(jnp.int32))
            operands.append(jnp.where(v, c, jnp.zeros_like(c)))
        else:
            operands.append(c)
    n_keys = len(operands)
    operands.append((~w).astype(jnp.int32))  # null/masked values last
    operands.append(values)
    operands.append(lax.iota(jnp.int32, cap))
    out = lax.sort(tuple(operands), num_keys=n_keys + 2, is_stable=True)
    perm = out[-1]
    skeys = out[:n_keys]
    svals = out[-2]
    active = jnp.take(row_mask, perm)
    sw = jnp.take(w, perm)

    changed = jnp.zeros(cap, dtype=bool).at[0].set(True)
    for k in skeys:
        changed = changed | jnp.concatenate(
            [jnp.ones(1, dtype=bool), k[1:] != k[:-1]])
    start_flag = changed & active
    seg_ids = jnp.maximum(jnp.cumsum(start_flag.astype(jnp.int32)) - 1, 0)

    pos = lax.iota(jnp.int32, cap)
    seg_start = jnp.full((cap,), 0, jnp.int32).at[
        jnp.where(start_flag, seg_ids, cap)].set(pos, mode="drop")
    n_valid = jax.ops.segment_sum(sw.astype(jnp.int32), seg_ids,
                                  num_segments=cap)
    idx = seg_start + jnp.floor(
        q * jnp.maximum(n_valid - 1, 0)).astype(jnp.int32)
    vals = jnp.take(svals, jnp.clip(idx, 0, cap - 1))
    return vals, n_valid > 0


def masked_percentile(values, row_mask, valid, q: float):
    """Global exact percentile via one sort."""
    cap = values.shape[0]
    w = row_mask if valid is None else (row_mask & valid)
    big = _max_ident(values.dtype)
    sv = jnp.sort(jnp.where(w, values, big))
    n = jnp.sum(w.astype(jnp.int32))
    idx = jnp.floor(q * jnp.maximum(n - 1, 0)).astype(jnp.int32)
    return jnp.take(sv, jnp.clip(idx, 0, cap - 1)), n > 0


# --- ungrouped (global) aggregation ---------------------------------------

def masked_sum(values, row_mask, valid=None):
    w = row_mask if valid is None else (row_mask & valid)
    acc_dtype = jnp.float64 if jnp.issubdtype(values.dtype, jnp.floating) else jnp.int64
    s = jnp.sum(jnp.where(w, values.astype(acc_dtype), jnp.zeros((), acc_dtype)))
    c = jnp.sum(w.astype(jnp.int64))
    return s, c


def masked_min(values, row_mask, valid=None):
    w = row_mask if valid is None else (row_mask & valid)
    m = jnp.min(jnp.where(w, values, _max_ident(values.dtype)))
    return m, jnp.any(w)


def masked_max(values, row_mask, valid=None):
    w = row_mask if valid is None else (row_mask & valid)
    m = jnp.max(jnp.where(w, values, _min_ident(values.dtype)))
    return m, jnp.any(w)
