"""Pallas TPU kernels for the engine's hot scatter-shaped ops.

XLA fuses elementwise work well, but data-dependent scatter (histogram,
dense-key group-by) lowers to serialized HBM scatters on TPU. These
kernels recast scatter as ONE-HOT MATMUL on the MXU: each grid step loads
a row block into VMEM, builds `onehot[block, buckets]`, and accumulates
`values @ onehot` into a VMEM scratch that lives across the sequential
grid — one HBM write at the end. (Reference analog: the vectorized hash
map of AggregateBenchmark / the shuffle partition histogram in
sqlx/shuffle/ShuffleExchangeExec; rebuilt here for the MXU instead of
per-core hash tables.)

On CPU (tests; no TPU chip available) the kernels run in interpret mode —
same program, Python semantics. Counts and blockwise partial sums stay
exact in float32 (≤ 2^24 per block); int64-exact sums keep using the
XLA scatter path (see ops/grouping.py).
"""

from __future__ import annotations

import functools

import numpy as np


def _pl():
    import jax
    from jax.experimental import pallas as pl

    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:  # pragma: no cover
        pltpu = None
    interpret = jax.default_backend() != "tpu"
    return jax, pl, pltpu, interpret


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@functools.lru_cache(maxsize=64)
def _histogram_fn(rows: int, buckets: int, block: int):
    jax, pl, pltpu, interpret = _pl()
    import jax.numpy as jnp

    grid = rows // block

    def kernel(pid_ref, mask_ref, out_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        pids = pid_ref[:]                       # [1, block] int32
        m = mask_ref[:]                         # [1, block] f32 0/1
        iota = jax.lax.broadcasted_iota(jnp.int32, (block, buckets), 1)
        onehot = (pids.reshape(block, 1) == iota).astype(jnp.float32)
        acc_ref[:] += m @ onehot                # [1, buckets] on the MXU

        @pl.when(i == grid - 1)
        def _flush():
            out_ref[:] = acc_ref[:]

    def build(pids2, mask2):
        return pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((1, block), lambda i: (0, i)),
                pl.BlockSpec((1, block), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((1, buckets), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, buckets), jnp.float32),
            scratch_shapes=[pltpu.VMEM((1, buckets), jnp.float32)]
            if pltpu is not None else [],
            interpret=interpret,
        )(pids2, mask2)

    return jax.jit(build)


def partition_histogram(pids, mask, num_partitions: int, block: int = 1024):
    """Exact per-partition live-row counts: int32 pids[cap] + bool
    mask[cap] → int32[num_partitions]. One MXU matmul per block."""
    import jax.numpy as jnp

    cap = int(pids.shape[0])
    buckets = _round_up(max(num_partitions, 1), 128)
    block = min(block, _round_up(cap, 8))
    rows = _round_up(cap, block)
    p2 = jnp.full((rows,), buckets - 1, jnp.int32).at[:cap].set(
        jnp.clip(pids.astype(jnp.int32), 0, buckets - 1))
    m2 = jnp.zeros((rows,), jnp.float32).at[:cap].set(
        mask.astype(jnp.float32))
    # rows where mask=0 contribute nothing regardless of pid
    out = _histogram_fn(rows, buckets, block)(
        p2.reshape(1, rows), m2.reshape(1, rows))
    return out[0, :num_partitions].astype(jnp.int32)


@functools.lru_cache(maxsize=64)
def _group_sum_fn(rows: int, groups: int, block: int):
    jax, pl, pltpu, interpret = _pl()
    import jax.numpy as jnp

    grid = rows // block

    def kernel(key_ref, val_ref, out_ref, acc_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        keys = key_ref[:]                       # [1, block] int32
        vals = val_ref[:]                       # [1, block] f32 (pre-masked)
        iota = jax.lax.broadcasted_iota(jnp.int32, (block, groups), 1)
        onehot = (keys.reshape(block, 1) == iota).astype(jnp.float32)
        acc_ref[:] += vals @ onehot             # [1, groups]

        @pl.when(i == grid - 1)
        def _flush():
            out_ref[:] = acc_ref[:]

    def build(keys2, vals2):
        return pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((1, block), lambda i: (0, i)),
                pl.BlockSpec((1, block), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((1, groups), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, groups), jnp.float32),
            scratch_shapes=[pltpu.VMEM((1, groups), jnp.float32)]
            if pltpu is not None else [],
            interpret=interpret,
        )(keys2, vals2)

    return jax.jit(build)


def dense_group_sum_f32(keys, values, mask, num_groups: int,
                        block: int = 1024):
    """Grouped float sum over DENSE int keys in [0, num_groups):
    the MXU one-hot path of the dense-range aggregation fast path
    (float32 accumulation — int64-exact sums stay on the XLA scatter)."""
    import jax.numpy as jnp

    cap = int(keys.shape[0])
    groups = _round_up(max(num_groups, 1), 128)
    block = min(block, _round_up(cap, 8))
    rows = _round_up(cap, block)
    k2 = jnp.full((rows,), groups - 1, jnp.int32).at[:cap].set(
        jnp.clip(keys.astype(jnp.int32), 0, groups - 1))
    v2 = jnp.zeros((rows,), jnp.float32).at[:cap].set(
        jnp.where(mask, values.astype(jnp.float32), 0.0))
    out = _group_sum_fn(rows, groups, block)(
        k2.reshape(1, rows), v2.reshape(1, rows))
    return out[0, :num_groups]
