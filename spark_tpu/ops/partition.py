"""Exchange partitioning kernels.

Role of the reference's ShuffleExchangeExec partition-key extraction
(sqlx/exchange/ShuffleExchangeExec.scala:344 prepareShuffleDependency, :396
getPartitionKeyExtractor) and Partitioner.scala (HashPartitioner /
RangePartitioner). On TPU the partition id is computed for a whole batch in
one fused kernel; rows are then grouped by pid with `lax.sort` so the host
(or an ICI all-to-all) can slice contiguous per-partition runs.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
from jax import lax

from .hashing import hash_columns, partition_ids


class PartitionedRows(NamedTuple):
    perm: jnp.ndarray    # int32[cap]: row order grouped by pid (inactive last)
    pids: jnp.ndarray    # int32[cap]: pid per sorted slot (invalid where inactive)
    counts: jnp.ndarray  # int64[num_partitions]: live rows per partition


def hash_partition(key_cols: Sequence[jnp.ndarray],
                   key_valids: Sequence[jnp.ndarray | None],
                   row_mask: jnp.ndarray,
                   num_partitions: int, seed: int = 42) -> PartitionedRows:
    h = hash_columns(key_cols, list(key_valids), seed=seed)
    pids = partition_ids(h, num_partitions)
    return _group_by_pid(pids, row_mask, num_partitions)


def round_robin_partition(row_mask: jnp.ndarray, num_partitions: int,
                          start=0) -> PartitionedRows:
    """Round-robin over live rows (reference: round-robin partitioning in
    ShuffleExchangeExec). `start` — the running row offset across the
    exchange's batches — may be a TRACED int32 scalar: callers pass it
    as a kernel argument so one compiled kernel per (capacity,
    num_partitions) serves every batch position (exec/shuffle.py)."""
    cap = row_mask.shape[0]
    live_rank = jnp.cumsum(row_mask.astype(jnp.int32)) - 1
    pids = ((live_rank + start) % num_partitions).astype(jnp.int32)
    return _group_by_pid(pids, row_mask, num_partitions)


def range_partition(sort_keys: jnp.ndarray, bounds: jnp.ndarray,
                    row_mask: jnp.ndarray, num_partitions: int,
                    descending: bool = False) -> PartitionedRows:
    """Range partitioning against sampled bounds (reference:
    RangePartitioner's sampled bounds, core/Partitioner.scala:388). `bounds`
    is int64/float64[num_partitions-1] ascending in the sort-key domain."""
    pids = jnp.searchsorted(bounds, sort_keys, side="right").astype(jnp.int32)
    if descending:
        pids = (num_partitions - 1) - pids
    return _group_by_pid(pids, row_mask, num_partitions)


def _group_by_pid(pids: jnp.ndarray, row_mask: jnp.ndarray,
                  num_partitions: int) -> PartitionedRows:
    cap = row_mask.shape[0]
    key = jnp.where(row_mask, pids, num_partitions)  # inactive last
    skey, perm = lax.sort((key, lax.iota(jnp.int32, cap)), num_keys=1,
                          is_stable=True)
    counts = jnp.zeros(num_partitions + 1, dtype=jnp.int64).at[
        jnp.minimum(skey, num_partitions)].add(1)
    return PartitionedRows(perm, skey, counts[:num_partitions])
