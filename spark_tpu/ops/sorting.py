"""Sort kernels.

Role of the reference's SortExec / UnsafeExternalRowSorter / RadixSort
(sqlx/SortExec.scala:39, corej/util/collection/unsafe/sort/RadixSort.java).
TPU-native: `lax.sort` over multiple key operands (XLA lowers to an on-device
sorting network) with order-preserving key transforms for DESC and null
placement; payload columns ride along via a permutation gather.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
from jax import lax


class SortKeySpec(NamedTuple):
    ascending: bool = True
    nulls_first: bool | None = None  # None => Spark default (first if asc)

    @property
    def nulls_first_effective(self) -> bool:
        if self.nulls_first is None:
            return self.ascending
        return self.nulls_first


def _directional(key: jnp.ndarray, ascending: bool) -> jnp.ndarray:
    """Transform key so ascending lax.sort yields the requested order.

    Signed ints: bitwise NOT is an exact order reversal (~x = -x-1, total,
    no overflow — the trick the reference's PrefixComparators play with
    unsigned prefixes). Floats: negate after NaN-normalization (SQL: NaN
    sorts greatest)."""
    if ascending:
        if jnp.issubdtype(key.dtype, jnp.floating):
            return jnp.where(jnp.isnan(key), jnp.asarray(jnp.inf, key.dtype), key)
        return key
    if key.dtype == jnp.bool_:
        return ~key
    if jnp.issubdtype(key.dtype, jnp.floating):
        k = jnp.where(jnp.isnan(key), jnp.asarray(jnp.inf, key.dtype), key)
        return -k
    return ~key


def sort_permutation(keys: Sequence[jnp.ndarray],
                     valids: Sequence[jnp.ndarray | None],
                     specs: Sequence[SortKeySpec],
                     row_mask: jnp.ndarray) -> jnp.ndarray:
    """Permutation ordering live rows by the sort spec; inactive rows last.

    keys are in the numeric sort-key domain (Column.sort_keys())."""
    cap = row_mask.shape[0]
    operands: list[jnp.ndarray] = [(~row_mask).astype(jnp.int32)]
    for key, valid, spec in zip(keys, valids, specs):
        if valid is not None:
            nf = spec.nulls_first_effective
            null_key = (valid if nf else ~valid).astype(jnp.int32)
            operands.append(null_key)
            key = jnp.where(valid, key, jnp.zeros_like(key))
        operands.append(_directional(key, spec.ascending))
    nk = len(operands)
    operands.append(lax.iota(jnp.int32, cap))
    out = lax.sort(tuple(operands), num_keys=nk, is_stable=True)
    return out[-1]


def take_rows(arrays: Sequence[jnp.ndarray], perm: jnp.ndarray):
    return [jnp.take(a, perm) for a in arrays]


def limit_mask(row_mask_sorted: jnp.ndarray, n: int) -> jnp.ndarray:
    """Keep the first n live rows (post-sort): LocalLimit/GlobalLimit kernel
    (reference: sqlx/limit.scala)."""
    live_rank = jnp.cumsum(row_mask_sorted.astype(jnp.int32))
    return row_mask_sorted & (live_rank <= n)
