"""Equi-join kernel: sorted build side + searchsorted probe + cumsum expansion.

Role of the reference's hash joins — BroadcastHashJoinExec / ShuffledHashJoinExec
over HashedRelation (sqlx/joins/ShuffledHashJoinExec.scala:38, buildHashedRelation
:103, sqlx/joins/HashedRelation.scala) and SortMergeJoinExec (:39). TPU-native
design: pointer-chasing hash tables don't vectorize; instead the build side is
sorted by a combined 64-bit key hash (`lax.sort`), each probe row finds its
match range via two `searchsorted` binary searches, and the variable-fanout
output is flattened into a STATIC-capacity batch with the classic
cumsum/searchsorted expansion. Hash false-positives are eliminated by gathering
and comparing the actual key columns (so 64-bit hashing is a grouping
accelerator, not a correctness assumption).

Output capacity overflow is reported via a scalar (`needed`) that the host
checks to retry at the next capacity bucket (SURVEY.md §7 'Hard parts' (1)).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax.numpy as jnp
from jax import lax

from .hashing import hash_columns

I64_MAX = jnp.iinfo(jnp.int64).max


class BuildSide(NamedTuple):
    """Build-side index: key-hash-sorted."""

    sorted_hash: jnp.ndarray  # int64[Bcap], inactive rows pushed to +inf
    perm: jnp.ndarray         # int32[Bcap] original row index per sorted slot


def build_index(key_cols: Sequence[jnp.ndarray],
                key_valids: Sequence[jnp.ndarray | None],
                row_mask: jnp.ndarray) -> BuildSide:
    h = hash_columns(key_cols, list(key_valids))
    # null join keys never match (SQL equi-join); drop them from the index
    usable = row_mask
    for v in key_valids:
        if v is not None:
            usable = usable & v
    hh = jnp.where(usable, h, I64_MAX)
    cap = row_mask.shape[0]
    sh, perm = lax.sort((hh, lax.iota(jnp.int32, cap)), num_keys=1, is_stable=True)
    return BuildSide(sh, perm)


class JoinResult(NamedTuple):
    probe_idx: jnp.ndarray   # int32[OC] source probe-row index per output row
    build_idx: jnp.ndarray   # int32[OC] source build-row index (clipped when unmatched)
    matched: jnp.ndarray     # bool[OC] true => real build match (false => null-extended)
    out_mask: jnp.ndarray    # bool[OC] live output rows
    needed: jnp.ndarray      # int32 scalar: total rows the join wanted to emit


def probe_join(build: BuildSide,
               build_key_cols: Sequence[jnp.ndarray],
               build_key_valids: Sequence[jnp.ndarray | None],
               probe_key_cols: Sequence[jnp.ndarray],
               probe_key_valids: Sequence[jnp.ndarray | None],
               probe_mask: jnp.ndarray,
               out_capacity: int,
               join_type: str = "inner") -> JoinResult:
    """join_type: inner | left_outer | left_semi | left_anti.

    'left' always refers to the probe side; the planner flips sides for
    right joins (as the reference's planner does for build-side selection,
    sqlx/SparkStrategies.scala join selection)."""
    pcap = probe_mask.shape[0]
    oc = out_capacity

    ph = hash_columns(probe_key_cols, list(probe_key_valids))
    usable = probe_mask
    for v in probe_key_valids:
        if v is not None:
            usable = usable & v
    ph = jnp.where(usable, ph, I64_MAX - 1)  # sentinel that matches nothing

    lo = jnp.searchsorted(build.sorted_hash, ph, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(build.sorted_hash, ph, side="right").astype(jnp.int32)
    counts = jnp.where(usable, hi - lo, 0)

    # --- verify hash ranges by comparing true keys, count real matches ----
    # For semi/anti we must not rely on hash ranges alone. Verified counts
    # also matter for left_outer's null-extension decision. We verify during
    # expansion (cheap: one gather per key col) and fix the semi/anti/outer
    # masks after expansion via a max-scatter back to probe rows.

    if join_type in ("left_semi", "left_anti", "left_outer"):
        ecounts = jnp.maximum(counts, jnp.where(probe_mask, 1, 0))
    else:
        ecounts = counts

    offsets = jnp.cumsum(ecounts)  # inclusive, int64 under x64
    total = offsets[pcap - 1] if pcap > 0 else jnp.int64(0)

    j = lax.iota(jnp.int64, oc)
    src = jnp.searchsorted(offsets, j, side="right").astype(jnp.int32)
    src = jnp.minimum(src, pcap - 1)
    base = offsets[src] - ecounts[src]
    within = (j - base).astype(jnp.int32)
    in_range = j < total

    has_build = within < counts[src]
    bpos = jnp.minimum(build.perm.shape[0] - 1, lo[src] + within)
    bidx = jnp.take(build.perm, bpos)

    # verify true key equality (null keys already excluded via sentinels)
    pair_ok = has_build
    for bc, bv, pc_, pv in zip(build_key_cols, build_key_valids,
                               probe_key_cols, probe_key_valids):
        b_val = jnp.take(bc, bidx)
        p_val = jnp.take(pc_, src)
        eq = b_val == p_val
        if bv is not None:
            eq = eq & jnp.take(bv, bidx)
        if pv is not None:
            eq = eq & jnp.take(pv, src)
        pair_ok = pair_ok & eq

    live_probe = jnp.take(probe_mask, src)

    if join_type == "inner":
        out_mask = in_range & live_probe & pair_ok
        return JoinResult(src, bidx, pair_ok, out_mask, total.astype(jnp.int64))

    # count of VERIFIED matches per probe row (scatter-add over output rows)
    vmatch = jnp.zeros(pcap, dtype=jnp.int32).at[src].add(
        (in_range & pair_ok).astype(jnp.int32), mode="drop")

    if join_type == "left_semi":
        first_slot = within == 0
        out_mask = in_range & live_probe & first_slot & (jnp.take(vmatch, src) > 0)
        return JoinResult(src, bidx, pair_ok, out_mask, total.astype(jnp.int64))

    if join_type == "left_anti":
        first_slot = within == 0
        out_mask = in_range & live_probe & first_slot & (jnp.take(vmatch, src) == 0)
        return JoinResult(src, bidx, pair_ok, out_mask, total.astype(jnp.int64))

    if join_type == "left_outer":
        # matched rows pass; unmatched probe rows emit exactly one null-extended
        # row in their first slot
        no_match = jnp.take(vmatch, src) == 0
        null_row = no_match & (within == 0)
        out_mask = in_range & live_probe & (pair_ok | null_row)
        return JoinResult(src, bidx, pair_ok, out_mask, total.astype(jnp.int64))

    raise ValueError(f"unsupported join type {join_type}")


def cross_join(probe_mask: jnp.ndarray, build_mask: jnp.ndarray,
               out_capacity: int) -> JoinResult:
    """Cartesian product (reference: CartesianProductExec). Build side is
    compacted first so output is probe-major."""
    pcap = probe_mask.shape[0]
    bcap = build_mask.shape[0]
    nb = jnp.sum(build_mask.astype(jnp.int32))
    # compact build row ids
    order = jnp.argsort(~build_mask, stable=True).astype(jnp.int32)
    counts = jnp.where(probe_mask, nb, 0)
    offsets = jnp.cumsum(counts)
    total = offsets[pcap - 1]
    j = lax.iota(jnp.int64, out_capacity)
    src = jnp.searchsorted(offsets, j, side="right").astype(jnp.int32)
    src = jnp.minimum(src, pcap - 1)
    within = (j - (offsets[src] - counts[src])).astype(jnp.int32)
    bidx = jnp.take(order, jnp.minimum(within, bcap - 1))
    out_mask = (j < total) & jnp.take(probe_mask, src)
    return JoinResult(src, bidx, jnp.ones_like(out_mask), out_mask,
                      total.astype(jnp.int64))
