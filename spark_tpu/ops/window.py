"""Window function kernels.

Role of the reference's WindowExec + window function frames
(sqlx/window/WindowExec.scala, sqlcat/expressions/windowExpressions.scala).
TPU-native design: one `lax.sort` by (partition keys, order keys) makes
partitions and peer groups contiguous; every ranking/frame computation is
then a cumsum/segment-op over the sorted layout, and results scatter back to
the original row order. No per-row loops, no frame iterators.

Default frames (Spark semantics):
  ranking fns — whole partition by definition;
  aggregates with ORDER BY — RANGE UNBOUNDED PRECEDING..CURRENT ROW
    (peer rows share the value);
  aggregates without ORDER BY — whole partition.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .sorting import SortKeySpec, _directional


class WindowLayout(NamedTuple):
    perm: jnp.ndarray        # sorted-row → original-row index
    active: jnp.ndarray      # bool per sorted row
    pos: jnp.ndarray         # int32 global position
    seg_start: jnp.ndarray   # int32 per sorted row: position of partition start
    seg_id: jnp.ndarray      # int32 partition id per sorted row
    peer_id: jnp.ndarray     # int32 peer-group id per sorted row
    peer_first: jnp.ndarray  # position of first row of the peer group
    peer_last: jnp.ndarray   # position of last row of the peer group
    seg_size: jnp.ndarray    # int32 rows in the partition


def build_layout(part_keys: Sequence[jnp.ndarray],
                 part_valids: Sequence[jnp.ndarray | None],
                 order_keys: Sequence[jnp.ndarray],
                 order_valids: Sequence[jnp.ndarray | None],
                 order_specs: Sequence[SortKeySpec],
                 row_mask: jnp.ndarray) -> WindowLayout:
    cap = row_mask.shape[0]
    operands: list[jnp.ndarray] = [(~row_mask).astype(jnp.int32)]
    n_pkeys_ops = 0
    for k, v in zip(part_keys, part_valids):
        if v is not None:
            operands.append((~v).astype(jnp.int32))
            operands.append(jnp.where(v, k, jnp.zeros_like(k)))
            n_pkeys_ops += 2
        else:
            operands.append(k)
            n_pkeys_ops += 1
    n_order_start = len(operands)
    for k, v, s in zip(order_keys, order_valids, order_specs):
        if v is not None:
            nf = s.nulls_first_effective
            operands.append((v if nf else ~v).astype(jnp.int32))
            k = jnp.where(v, k, jnp.zeros_like(k))
        operands.append(_directional(k, s.ascending))
    nk = len(operands)
    operands.append(lax.iota(jnp.int32, cap))
    out = lax.sort(tuple(operands), num_keys=nk, is_stable=True)
    perm = out[-1]
    sorted_keys = out[:nk]
    active = jnp.take(row_mask, perm)
    pos = lax.iota(jnp.int32, cap)

    def change_flag(keys):
        flag = jnp.zeros(cap, dtype=bool).at[0].set(True)
        for k in keys:
            flag = flag | jnp.concatenate(
                [jnp.ones(1, dtype=bool), k[1:] != k[:-1]])
        return flag

    pchange = change_flag(sorted_keys[: 1 + n_pkeys_ops])
    ochange = pchange | change_flag(sorted_keys)  # any key change

    seg_id = jnp.cumsum(pchange.astype(jnp.int32)) - 1
    peer_id = jnp.cumsum(ochange.astype(jnp.int32)) - 1

    seg_start_by_id = jnp.full((cap,), 0, jnp.int32).at[
        jnp.where(pchange, seg_id, cap)].set(pos, mode="drop")
    seg_start = jnp.take(seg_start_by_id, seg_id)
    peer_first_by_id = jnp.full((cap,), 0, jnp.int32).at[
        jnp.where(ochange, peer_id, cap)].set(pos, mode="drop")
    peer_first = jnp.take(peer_first_by_id, peer_id)
    peer_last_by_id = jax.ops.segment_max(pos, peer_id, num_segments=cap)
    peer_last = jnp.take(peer_last_by_id, peer_id)
    seg_size = jax.ops.segment_sum(active.astype(jnp.int32), seg_id,
                                   num_segments=cap)
    seg_size = jnp.take(seg_size, seg_id)
    return WindowLayout(perm, active, pos, seg_start, seg_id, peer_id,
                        peer_first, peer_last, seg_size)


# --- per-function computations (all return values in SORTED order) ---------

def w_row_number(lo: WindowLayout):
    return (lo.pos - lo.seg_start + 1).astype(jnp.int32)


def w_rank(lo: WindowLayout):
    return (lo.peer_first - lo.seg_start + 1).astype(jnp.int32)


def w_dense_rank(lo: WindowLayout):
    start_peer = jnp.take(lo.peer_id, lo.seg_start)
    return (lo.peer_id - start_peer + 1).astype(jnp.int32)


def w_percent_rank(lo: WindowLayout):
    denom = jnp.maximum(lo.seg_size - 1, 1)
    return (w_rank(lo) - 1).astype(jnp.float64) / denom


def w_cume_dist(lo: WindowLayout):
    return (lo.peer_last - lo.seg_start + 1).astype(jnp.float64) / \
        jnp.maximum(lo.seg_size, 1)


def w_ntile(lo: WindowLayout, n: int):
    rn0 = (lo.pos - lo.seg_start).astype(jnp.int64)
    return (rn0 * n // jnp.maximum(lo.seg_size, 1) + 1).astype(jnp.int32)


def _sorted_vals(lo: WindowLayout, values, valid):
    v = jnp.take(values, lo.perm)
    w = lo.active if valid is None else (lo.active & jnp.take(valid, lo.perm))
    return v, w


def w_agg_unbounded(lo: WindowLayout, values, valid, kind: str):
    """sum/count/min/max/avg over the whole partition, broadcast to rows."""
    cap = values.shape[0]
    v, w = _sorted_vals(lo, values, valid)
    if kind == "count":
        tot = jax.ops.segment_sum(w.astype(jnp.int64), lo.seg_id, cap)
        return jnp.take(tot, lo.seg_id), None
    acc = jnp.float64 if jnp.issubdtype(v.dtype, jnp.floating) else jnp.int64
    if kind in ("sum", "avg"):
        s = jax.ops.segment_sum(jnp.where(w, v.astype(acc), 0), lo.seg_id, cap)
        c = jax.ops.segment_sum(w.astype(jnp.int64), lo.seg_id, cap)
        if kind == "sum":
            return jnp.take(s, lo.seg_id), jnp.take(c, lo.seg_id) > 0
        c_safe = jnp.maximum(c, 1)
        a = s.astype(jnp.float64) / c_safe
        return jnp.take(a, lo.seg_id), jnp.take(c, lo.seg_id) > 0
    from .grouping import _max_ident, _min_ident

    if kind == "min":
        m = jax.ops.segment_min(jnp.where(w, v, _max_ident(v.dtype)),
                                lo.seg_id, cap)
    else:
        m = jax.ops.segment_max(jnp.where(w, v, _min_ident(v.dtype)),
                                lo.seg_id, cap)
    c = jax.ops.segment_sum(w.astype(jnp.int32), lo.seg_id, cap)
    return jnp.take(m, lo.seg_id), jnp.take(c, lo.seg_id) > 0


def w_agg_running(lo: WindowLayout, values, valid, kind: str):
    """RANGE UNBOUNDED PRECEDING..CURRENT ROW (peers share the value)."""
    cap = values.shape[0]
    v, w = _sorted_vals(lo, values, valid)
    acc = jnp.float64 if jnp.issubdtype(v.dtype, jnp.floating) else jnp.int64
    vv = jnp.where(w, v.astype(acc), 0)
    csum = jnp.cumsum(vv)
    ccnt = jnp.cumsum(w.astype(jnp.int64))
    before_seg_sum = jnp.where(lo.seg_start > 0,
                               jnp.take(csum, jnp.maximum(lo.seg_start - 1, 0)),
                               0)
    before_seg_cnt = jnp.where(lo.seg_start > 0,
                               jnp.take(ccnt, jnp.maximum(lo.seg_start - 1, 0)),
                               0)
    run_sum = jnp.take(csum, lo.peer_last) - before_seg_sum
    run_cnt = jnp.take(ccnt, lo.peer_last) - before_seg_cnt
    if kind == "count":
        return run_cnt, None
    if kind == "sum":
        return run_sum, run_cnt > 0
    if kind == "avg":
        return run_sum.astype(jnp.float64) / jnp.maximum(run_cnt, 1), \
            run_cnt > 0
    # running min/max via cummin/cummax reset at segment start: use
    # associative_scan over (value, seg_id) pairs
    big = jnp.where(w, v, _ident(kind, v.dtype))

    def combine(a, b):
        av, aseg = a
        bv, bseg = b
        same = aseg == bseg
        if kind == "min":
            m = jnp.minimum(av, bv)
        else:
            m = jnp.maximum(av, bv)
        return jnp.where(same, m, bv), bseg

    scanned, _ = lax.associative_scan(combine, (big, lo.seg_id))
    run = jnp.take(scanned, lo.peer_last)
    return run, run_cnt > 0


def w_agg_rows(lo: WindowLayout, values, valid, kind: str,
               lo_off, hi_off):
    """ROWS BETWEEN <lo_off> AND <hi_off> frame for sum/count/avg, via
    segment-clipped cumulative sums. Offsets are row deltas relative to the
    current row; None means unbounded on that side."""
    import jax

    cap = values.shape[0]
    v, w = _sorted_vals(lo, values, valid)
    acc = jnp.float64 if jnp.issubdtype(v.dtype, jnp.floating) else jnp.int64
    vv = jnp.where(w, v.astype(acc), 0)
    csum = jnp.cumsum(vv)
    ccnt = jnp.cumsum(w.astype(jnp.int64))
    seg_end = lo.seg_start + lo.seg_size - 1

    lo_idx = lo.seg_start if lo_off is None else \
        jnp.maximum(lo.pos + lo_off, lo.seg_start)
    hi_idx = seg_end if hi_off is None else \
        jnp.minimum(lo.pos + hi_off, seg_end)
    empty = hi_idx < lo_idx

    def rng(c):
        hi_v = jnp.take(c, jnp.clip(hi_idx, 0, cap - 1))
        lo_m1 = lo_idx - 1
        lo_v = jnp.where(lo_m1 >= 0,
                         jnp.take(c, jnp.clip(lo_m1, 0, cap - 1)), 0)
        return jnp.where(empty, 0, hi_v - lo_v)

    total = rng(csum)
    cnt = rng(ccnt)
    if kind == "count":
        return cnt, None
    if kind == "sum":
        return total, cnt > 0
    if kind == "avg":
        return total.astype(jnp.float64) / jnp.maximum(cnt, 1), cnt > 0
    if kind in ("min", "max"):
        return _range_minmax(v, w, lo_idx, hi_idx, empty, kind), cnt > 0
    raise ValueError(kind)


def w_agg_value_range(lo: WindowLayout, order_key, values, valid, kind: str,
                      lo_off, hi_off, kmin: int, band: int):
    """RANGE BETWEEN <lo_off> AND <hi_off> with VALUE offsets over a single
    integral order key. Keys are banded per partition —
    enc = seg_id·band + (key − kmin) — so one global `searchsorted` finds
    each row's value-window inside its own partition (band exceeds the key
    span plus the largest offset, so queries never cross partitions)."""
    import jax

    cap = values.shape[0]
    k = jnp.take(order_key, lo.perm).astype(jnp.int64)
    enc = lo.seg_id.astype(jnp.int64) * band + (k - kmin)
    lo_q = enc + (lo_off if lo_off is not None else -(band - 1))
    hi_q = enc + (hi_off if hi_off is not None else (band - 1))
    lo_idx = jnp.searchsorted(enc, lo_q, side="left").astype(jnp.int32)
    hi_idx = (jnp.searchsorted(enc, hi_q, side="right") - 1).astype(jnp.int32)
    seg_end = lo.seg_start + lo.seg_size - 1
    lo_idx = jnp.maximum(lo_idx, lo.seg_start)
    hi_idx = jnp.minimum(hi_idx, seg_end)
    empty = hi_idx < lo_idx

    v, w = _sorted_vals(lo, values, valid)
    acc = jnp.float64 if jnp.issubdtype(v.dtype, jnp.floating) else jnp.int64
    csum = jnp.cumsum(jnp.where(w, v.astype(acc), 0))
    ccnt = jnp.cumsum(w.astype(jnp.int64))

    def rng(c):
        hi_v = jnp.take(c, jnp.clip(hi_idx, 0, cap - 1))
        lo_m1 = lo_idx - 1
        lo_v = jnp.where(lo_m1 >= 0,
                         jnp.take(c, jnp.clip(lo_m1, 0, cap - 1)), 0)
        return jnp.where(empty, 0, hi_v - lo_v)

    total = rng(csum)
    cnt = rng(ccnt)
    if kind == "count":
        return cnt, None
    if kind == "sum":
        return total, cnt > 0
    if kind == "avg":
        return total.astype(jnp.float64) / jnp.maximum(cnt, 1), cnt > 0
    if kind in ("min", "max"):
        return _range_minmax(v, w, lo_idx, hi_idx, empty, kind), cnt > 0
    raise ValueError(kind)


def _ident(kind, dtype):
    from .grouping import _max_ident, _min_ident

    return _max_ident(dtype) if kind == "min" else _min_ident(dtype)


def _range_minmax(v, w, lo_idx, hi_idx, empty, kind):
    """min/max over per-row index ranges [lo_idx, hi_idx] of the sorted
    value array, via a sparse table (doubling): level j holds the reduce
    of windows of length 2^j — O(n log n) fully vectorized build, O(1)
    two-window query per row. This is the TPU analog of the reference's
    per-row frame scan (sqlx/window/WindowFunctionFrame SlidingWindow)."""
    cap = v.shape[0]
    ident = _ident(kind, v.dtype)
    op = jnp.minimum if kind == "min" else jnp.maximum
    a = jnp.where(w, v, ident)
    levels = [a]
    step = 1
    while step < cap:
        prev = levels[-1]
        shifted = jnp.concatenate(
            [prev[step:], jnp.full((step,), ident, prev.dtype)])
        levels.append(op(prev, shifted))
        step <<= 1
    sp = jnp.stack(levels)  # [L, cap]
    length = jnp.maximum(hi_idx - lo_idx + 1, 1)
    k = jnp.floor(
        jnp.log2(length.astype(jnp.float64))).astype(jnp.int32)
    # integer-exact guard against float log sloppiness: need 2^k <= length
    k = jnp.clip(jnp.where((1 << k) > length, k - 1, k),
                 0, len(levels) - 1)
    p1 = sp[k, jnp.clip(lo_idx, 0, cap - 1)]
    p2_at = jnp.clip(hi_idx - (1 << k) + 1, 0, cap - 1)
    return jnp.where(empty, ident, op(p1, sp[k, p2_at]))


def w_shift(lo: WindowLayout, values, valid, offset: int,
            default_data=None):
    """lag (offset>0) / lead (offset<0) within the partition."""
    cap = values.shape[0]
    v = jnp.take(values, lo.perm)
    src = lo.pos - offset
    seg_end = lo.seg_start + lo.seg_size - 1
    in_seg = (src >= lo.seg_start) & (src <= seg_end)
    srcc = jnp.clip(src, 0, cap - 1)
    out = jnp.take(v, srcc)
    out_valid = in_seg
    if valid is not None:
        sv = jnp.take(valid, lo.perm)
        out_valid = out_valid & jnp.take(sv, srcc)
    if default_data is not None:
        out = jnp.where(in_seg, out, default_data)
        out_valid = None if valid is None else (out_valid | ~in_seg)
    return out, out_valid


def w_first_value(lo: WindowLayout, values, valid):
    """first_value: the frame's first row — default running frame starts
    at the partition start."""
    v = jnp.take(values, lo.perm)
    out = jnp.take(v, lo.seg_start)
    out_valid = None
    if valid is not None:
        sv = jnp.take(valid, lo.perm)
        out_valid = jnp.take(sv, lo.seg_start)
    return out, out_valid


def w_last_value(lo: WindowLayout, values, valid, whole: bool = False):
    """last_value: the frame's last row — default frame ends at the
    current PEER GROUP's last row; whole=True (explicit
    UNBOUNDED..UNBOUNDED) uses the partition's last row."""
    v = jnp.take(values, lo.perm)
    end = (lo.seg_start + lo.seg_size - 1) if whole else lo.peer_last
    out = jnp.take(v, end)
    out_valid = None
    if valid is not None:
        sv = jnp.take(valid, lo.perm)
        out_valid = jnp.take(sv, end)
    return out, out_valid


def w_nth_value(lo: WindowLayout, values, valid, n: int,
                whole: bool = False):
    """nth_value(x, n): NULL until the frame reaches n rows."""
    cap = values.shape[0]
    v = jnp.take(values, lo.perm)
    idx = lo.seg_start + (n - 1)
    end = (lo.seg_start + lo.seg_size - 1) if whole else lo.peer_last
    exists = idx <= end
    idxc = jnp.clip(idx, 0, cap - 1)
    out = jnp.take(v, idxc)
    out_valid = exists
    if valid is not None:
        sv = jnp.take(valid, lo.perm)
        out_valid = out_valid & jnp.take(sv, idxc)
    return out, out_valid


def scatter_back(lo: WindowLayout, sorted_vals, sorted_valid=None):
    """Sorted-order results → original row order."""
    cap = sorted_vals.shape[0]
    out = jnp.zeros(cap, dtype=sorted_vals.dtype).at[lo.perm].set(sorted_vals)
    ov = None
    if sorted_valid is not None:
        ov = jnp.zeros(cap, dtype=bool).at[lo.perm].set(sorted_valid)
    return out, ov
