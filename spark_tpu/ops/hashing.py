"""Device hashing kernels.

Role of the reference's Murmur3_x86_32 (common/unsafe/.../hash/Murmur3_x86_32.java)
used for shuffle partition ids and hash-map keys. TPU-native choice: a 64-bit
splitmix finalizer over int64 lanes — vectorizes to pure VPU element-wise ops,
no byte-level loops, and 64 bits make hash-equality a safe join/group-by
comparison domain (collision probability ~n²/2⁶⁵).
"""

from __future__ import annotations

import jax.numpy as jnp

# python ints (converted lazily) — module-level device arrays would touch the
# backend at import time
_M1 = 0xBF58476D1CE4E5B9
_M2 = 0x94D049BB133111EB
_GOLDEN = 0x9E3779B97F4A7C15


def mix64(x):
    """splitmix64 finalizer (public-domain constant set)."""
    x = jnp.asarray(x).astype(jnp.int64).view(jnp.uint64)
    x = x ^ (x >> 30)
    x = x * jnp.uint64(_M1)
    x = x ^ (x >> 27)
    x = x * jnp.uint64(_M2)
    x = x ^ (x >> 31)
    return x.view(jnp.int64)


def _to_i64_lanes(col):
    """Reinterpret a column's device data as int64 lanes for hashing."""
    d = jnp.asarray(col)
    if d.dtype == jnp.bool_:
        return d.astype(jnp.int64)
    if d.dtype in (jnp.float32, jnp.float64):
        # normalize -0.0 == 0.0 so they hash equal
        d = jnp.where(d == 0, jnp.zeros_like(d), d)
        if d.dtype == jnp.float32:
            return d.view(jnp.int32).astype(jnp.int64)
        return d.view(jnp.int64)
    return d.astype(jnp.int64)


def hash_columns(cols, validities=None, seed: int = 42):
    """Combined 64-bit hash over one or more key columns.

    cols: list of device arrays (pre-mapped to eq-key domain for strings).
    validities: optional list of bool arrays; a null key contributes a fixed
    tag (so null == null for grouping, like the reference's grouping
    semantics).
    Returns int64[capacity].
    """
    h = None
    for i, c in enumerate(cols):
        lane = _to_i64_lanes(c)
        k = mix64(lane)
        if validities is not None and validities[i] is not None:
            null_tag = mix64(jnp.int64(0x6E756C6C + i))
            k = jnp.where(validities[i], k, null_tag)
        if h is None:
            h = k
        else:
            hu = h.view(jnp.uint64) * jnp.uint64(31) + k.view(jnp.uint64) \
                + jnp.uint64(_GOLDEN)
            h = mix64(hu.view(jnp.int64))
    if h is None:
        raise ValueError("hash_columns needs at least one column")
    # nonlinear seed fold: h' = mix64(h ^ mix64(seed)). A linear fold
    # (h + seed) would leave h' % p correlated with h % p, defeating the
    # grace-join re-split of already-hash-partitioned data.
    return mix64(h ^ mix64(jnp.int64(seed)))


def partition_ids(hashes, num_partitions: int):
    """Non-negative modulo (reference: Partitioner.scala HashPartitioner pmod)."""
    p = jnp.int64(num_partitions)
    m = hashes % p
    return jnp.where(m < 0, m + p, m).astype(jnp.int32)
