"""Device kernel library — the Tungsten replacement (SURVEY.md §7 step 2).

Pure-jnp functions designed to be composed inside a single `jax.jit` per
physical operator pipeline; XLA fuses them the way the reference's
WholeStageCodegen fuses Java iterators (sqlx/WholeStageCodegenExec.scala:47).
"""

from .hashing import hash_columns, mix64, partition_ids  # noqa: F401
from .grouping import (  # noqa: F401
    GroupLayout, group_rows, scatter_group_keys, group_output_mask,
    seg_sum, seg_count, seg_min, seg_max, seg_first,
    masked_sum, masked_min, masked_max,
)
from .sorting import SortKeySpec, sort_permutation, take_rows, limit_mask  # noqa: F401
from .joining import BuildSide, JoinResult, build_index, probe_join, cross_join  # noqa: F401
from .partition import (  # noqa: F401
    PartitionedRows, hash_partition, round_robin_partition, range_partition,
)
