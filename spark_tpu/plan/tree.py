"""Immutable tree framework with rule-based transforms.

Role of the reference's Catalyst tree framework:
  - TreeNode (sqlcat/trees/TreeNode.scala:70): children, transformUp/Down,
    withNewChildren, fastEquals, treeString
  - RuleExecutor (sqlcat/rules/RuleExecutor.scala:125, execute at :215):
    fixed-point batches of rules

Python re-design: nodes are plain objects whose children live in declared
`child_fields`; transforms rebuild nodes structurally. We skip the reference's
tree-pattern bitmask pruning (an optimization for 100+-rule batches) in favor
of cheap Python iteration; rule batches and fixed-point semantics are kept
because the optimizer design depends on them.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterator, Sequence, TypeVar

T = TypeVar("T", bound="TreeNode")

_id_counter = itertools.count()


def next_id() -> int:
    """Monotonic id source for expression ids (reference: NamedExpression.newExprId)."""
    return next(_id_counter)


class TreeNode:
    """Base of Expression and LogicalPlan/PhysicalPlan trees.

    Subclasses declare `child_fields`: names of attributes holding a child
    node, a list of child nodes, or None. Everything else is 'data'.
    """

    child_fields: tuple[str, ...] = ()

    # --- children ---------------------------------------------------------
    @property
    def children(self) -> list["TreeNode"]:
        out: list[TreeNode] = []
        for f in self.child_fields:
            v = getattr(self, f)
            if v is None:
                continue
            if isinstance(v, (list, tuple)):
                out.extend(c for c in v if c is not None)
            else:
                out.append(v)
        return out

    def with_new_children(self: T, new_children: Sequence["TreeNode"]) -> T:
        """Rebuild this node with children replaced positionally."""
        it = iter(new_children)
        kwargs: dict[str, Any] = {}
        for f in self.child_fields:
            v = getattr(self, f)
            if v is None:
                kwargs[f] = None
            elif isinstance(v, (list, tuple)):
                kwargs[f] = type(v)(next(it) for _ in v if _ is not None)
            else:
                kwargs[f] = next(it)
        return self.copy(**kwargs)

    def copy(self: T, **overrides: Any) -> T:
        """Shallow copy with attribute overrides. Subclasses with __init__
        side effects should override."""
        new = object.__new__(type(self))
        new.__dict__.update(self.__dict__)
        new.__dict__.update(overrides)
        new.__dict__.pop("_dtype_memo", None)  # children may have changed
        return new

    # --- traversal --------------------------------------------------------
    def foreach(self, f: Callable[["TreeNode"], None]) -> None:
        f(self)
        for c in self.children:
            c.foreach(f)

    def foreach_up(self, f: Callable[["TreeNode"], None]) -> None:
        for c in self.children:
            c.foreach_up(f)
        f(self)

    def collect(self, pf: Callable[["TreeNode"], Any]) -> list[Any]:
        out: list[Any] = []

        def go(n: TreeNode) -> None:
            r = pf(n)
            if r is not None:
                out.append(r)

        self.foreach(go)
        return out

    def find(self, pred: Callable[["TreeNode"], bool]) -> "TreeNode | None":
        if pred(self):
            return self
        for c in self.children:
            r = c.find(pred)
            if r is not None:
                return r
        return None

    def exists(self, pred: Callable[["TreeNode"], bool]) -> bool:
        return self.find(pred) is not None

    def iter_nodes(self) -> Iterator["TreeNode"]:
        yield self
        for c in self.children:
            yield from c.iter_nodes()

    # --- transforms -------------------------------------------------------
    def map_children(self: T, f: Callable[["TreeNode"], "TreeNode"]) -> T:
        if not self.child_fields:
            return self
        changed = False
        kwargs: dict[str, Any] = {}
        for fld in self.child_fields:
            v = getattr(self, fld)
            if v is None:
                kwargs[fld] = None
            elif isinstance(v, (list, tuple)):
                nv = [f(c) if c is not None else None for c in v]
                if any(a is not b for a, b in zip(nv, v)):
                    changed = True
                kwargs[fld] = type(v)(nv)
            else:
                nv1 = f(v)
                if nv1 is not v:
                    changed = True
                kwargs[fld] = nv1
        return self.copy(**kwargs) if changed else self

    def transform_down(self: T, rule: Callable[["TreeNode"], "TreeNode"]) -> T:
        after = rule(self)
        if after is None:
            after = self
        return after.map_children(lambda c: c.transform_down(rule))

    def transform_up(self: T, rule: Callable[["TreeNode"], "TreeNode"]) -> T:
        with_new = self.map_children(lambda c: c.transform_up(rule))
        out = rule(with_new)
        return with_new if out is None else out

    transform = transform_up

    # --- equality ---------------------------------------------------------
    # attributes that duplicate child_fields content and must stay out of
    # equality (comparing them both as data and as children makes equality
    # traverse shared subtrees twice — exponential on expression DAGs)
    equality_excluded_fields: tuple[str, ...] = ()

    def _data_args(self) -> tuple:
        """Non-child attributes participating in equality. Default: all
        __dict__ entries not in child_fields (best-effort)."""
        skip = set(self.child_fields) | set(self.equality_excluded_fields)
        items = []
        for k in sorted(self.__dict__):
            # private attrs are caches (_hash, _cast_cache, _pipeline…) —
            # _cast_cache in particular holds a Cast whose child is THIS
            # node, which would make equality cyclic
            if k in skip or k.startswith("_"):
                continue
            v = self.__dict__[k]
            if isinstance(v, list):
                v = tuple(v)
            items.append((k, v))
        return tuple(items)

    def fast_equals(self, other: "TreeNode") -> bool:
        return self is other or self.semantic_equals(other)

    def semantic_equals(self, other: "TreeNode") -> bool:
        if type(self) is not type(other):
            return False
        if self._data_args() != other._data_args():
            return False
        a, b = self.children, other.children
        return len(a) == len(b) and all(x.semantic_equals(y) for x, y in zip(a, b))

    def __eq__(self, other: object) -> bool:  # expressions override (DSL)
        return isinstance(other, TreeNode) and self.semantic_equals(other)

    def __hash__(self) -> int:
        h = getattr(self, "_hash", None)
        if h is None:
            try:
                h = hash((type(self).__name__, self._data_args(),
                          tuple(hash(c) for c in self.children)))
            except TypeError:
                h = hash(type(self).__name__)
            self.__dict__["_hash"] = h
        return h

    # --- pretty printing --------------------------------------------------
    def node_name(self) -> str:
        return type(self).__name__

    def arg_string(self) -> str:
        parts = []
        for k, v in self._data_args():
            if v is None or v == () or v == "":
                continue
            parts.append(f"{k}={v!r}")
        return ", ".join(parts)

    def simple_string(self) -> str:
        a = self.arg_string()
        return f"{self.node_name()}({a})" if a else self.node_name()

    def tree_string(self, depth: int = 0) -> str:
        pad = "  " * depth
        lines = [pad + ("+- " if depth else "") + self.simple_string()]
        for c in self.children:
            lines.append(c.tree_string(depth + 1))
        return "\n".join(lines)

    def __repr__(self) -> str:
        return self.simple_string()


# ---------------------------------------------------------------------------
# RuleExecutor
# ---------------------------------------------------------------------------

class Rule:
    """A named plan→plan transform (reference: sqlcat/rules/Rule.scala)."""

    name: str = ""

    def __call__(self, plan: T) -> T:
        return self.apply(plan)

    def apply(self, plan: T) -> T:
        raise NotImplementedError

    def rule_name(self) -> str:
        return self.name or type(self).__name__


class FixedPoint:
    def __init__(self, max_iterations: int = 100):
        self.max_iterations = max_iterations


class Once(FixedPoint):
    def __init__(self):
        super().__init__(1)


class Batch:
    def __init__(self, name: str, strategy: FixedPoint, rules: Sequence[Rule | Callable]):
        self.name = name
        self.strategy = strategy
        self.rules = list(rules)


class RuleExecutor:
    """Runs batches of rules to fixed point
    (reference: sqlcat/rules/RuleExecutor.scala:215 execute)."""

    def __init__(self) -> None:
        self.rule_timings: dict[str, float] = {}

    def batches(self) -> list[Batch]:
        raise NotImplementedError

    def execute(self, plan: T, tracker=None) -> T:
        import time

        cur = plan
        for batch in self.batches():
            iteration = 0
            while True:
                iteration += 1
                before = cur
                for rule in batch.rules:
                    t0 = time.perf_counter()
                    result = rule(cur)
                    if result is not None:
                        cur = result
                    name = rule.rule_name() if isinstance(rule, Rule) else getattr(
                        rule, "__name__", str(rule))
                    dt = time.perf_counter() - t0
                    self.rule_timings[name] = self.rule_timings.get(name, 0.0) + dt
                    if tracker is not None:
                        tracker.record_rule(name, dt)
                if cur.fast_equals(before):
                    break
                if iteration >= batch.strategy.max_iterations:
                    if batch.strategy.max_iterations > 1:
                        import warnings

                        warnings.warn(
                            f"Batch {batch.name!r} did not converge in "
                            f"{batch.strategy.max_iterations} iterations")
                    break
        return cur
