"""Persistent warehouse catalog.

Role of the reference's external catalog + warehouse layout
(sql/hive metastore integration, sql/core InMemoryCatalog +
spark.sql.warehouse.dir): saved tables live as parquet under the warehouse
directory with a JSON catalog file; sessions reload it on first use.
"""

from __future__ import annotations

import json
import os
import threading


class Warehouse:
    def __init__(self, path: str, on_write=None):
        self.path = path
        self._lock = threading.Lock()
        # catalog write-path hook, called with the table directory after
        # every save/append/overwrite/drop: the session wires it to the
        # persistent result cache's dependency invalidation
        # (exec/persist_cache.invalidate_path) so cached query results
        # over a table die the moment the table changes
        self.on_write = on_write
        os.makedirs(path, exist_ok=True)

    def _notify_write(self, p: str) -> None:
        if self.on_write is not None:
            try:
                self.on_write(p)
            except Exception:
                pass  # cache invalidation must never fail a write

    @property
    def _catalog_file(self) -> str:
        return os.path.join(self.path, "_catalog.json")

    def _load(self) -> dict:
        if os.path.exists(self._catalog_file):
            with open(self._catalog_file) as f:
                return json.load(f)
        return {"tables": {}}

    def _save(self, cat: dict) -> None:
        tmp = self._catalog_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(cat, f, indent=2)
        os.replace(tmp, self._catalog_file)

    def table_path(self, name: str) -> str:
        return os.path.join(self.path, name)

    def save_table(self, name: str, table, mode: str = "error") -> str:
        """Write an Arrow table as a managed parquet table."""
        import pyarrow.parquet as pq

        from ..errors import AnalysisException

        with self._lock:
            cat = self._load()
            exists = name in cat["tables"]
            p = self.table_path(name)
            if exists and mode in ("error", "errorifexists"):
                raise AnalysisException(
                    f"Table {name} already exists",
                    error_class="TABLE_OR_VIEW_ALREADY_EXISTS")
            os.makedirs(p, exist_ok=True)
            if mode == "append" and exists:
                i = len([f for f in os.listdir(p) if f.endswith(".parquet")])
                pq.write_table(table, os.path.join(p, f"part-{i:05d}.parquet"))
            else:
                for f in os.listdir(p):
                    if f.endswith(".parquet"):
                        os.remove(os.path.join(p, f))
                pq.write_table(table, os.path.join(p, "part-00000.parquet"))
            cat["tables"][name] = {"format": "parquet", "path": p}
            self._save(cat)
        self._notify_write(p)
        return p

    def drop_table(self, name: str) -> bool:
        import shutil

        with self._lock:
            cat = self._load()
            if name not in cat["tables"]:
                return False
            p = cat["tables"].pop(name)["path"]
            self._save(cat)
        shutil.rmtree(p, ignore_errors=True)
        self._notify_write(p)
        return True

    def list_tables(self) -> list[str]:
        return sorted(self._load()["tables"])

    def lookup(self, name: str):
        """Returns a LogicalRelation for a saved table, or None."""
        cat = self._load()
        meta = cat["tables"].get(name)
        if meta is None:
            return None
        from ..io.sources import ParquetSource
        from ..expr.expressions import AttributeReference
        from .logical import LogicalRelation

        src = ParquetSource(meta["path"])
        attrs = [AttributeReference(f.name, f.dataType, f.nullable)
                 for f in src.schema.fields]
        return LogicalRelation(src, attrs, name)
