"""Session catalog.

Role of the reference's SessionCatalog/CatalogManager
(sqlcat/catalog/SessionCatalog.scala) reduced to an in-memory registry of
temp views and tables; a persistent metastore SPI can plug in behind
`external`.
"""

from __future__ import annotations

from typing import Callable

from ..errors import AnalysisException
from .logical import LogicalPlan


class Catalog:
    def __init__(self, case_sensitive: bool = False):
        self._tables: dict[str, LogicalPlan] = {}
        self.case_sensitive = case_sensitive
        self.external = None  # Warehouse (plan/warehouse.py) when configured
        # SQL session variables: name(lower) → Literal (reference: session
        # variables in SqlScriptingContextManager / VariableManager)
        self.variables: dict = {}

    def _norm(self, name: str) -> str:
        return name if self.case_sensitive else name.lower()

    def register(self, name: str, plan: LogicalPlan) -> None:
        self._tables[self._norm(name)] = plan

    def drop(self, name: str) -> bool:
        return self._tables.pop(self._norm(name), None) is not None

    def lookup(self, name_parts) -> LogicalPlan:
        name = ".".join(name_parts)
        p = self._tables.get(self._norm(name))
        if p is None and len(name_parts) > 1:
            p = self._tables.get(self._norm(name_parts[-1]))
        if p is None and self.external is not None:
            p = self.external.lookup(self._norm(name_parts[-1]))
        if p is None:
            raise AnalysisException(
                f"Table or view not found: {name}",
                error_class="TABLE_OR_VIEW_NOT_FOUND")
        return p

    def list_tables(self) -> list[str]:
        out = set(self._tables)
        if self.external is not None:
            out |= set(self.external.list_tables())
        return sorted(out)
