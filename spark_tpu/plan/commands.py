"""SQL commands (DDL / utility statements).

Role of the reference's command framework (sqlx/command/ — RunnableCommand:
CreateViewCommand, ShowTablesCommand, DescribeTableCommand, ExplainCommand,
CacheTableCommand...). Commands execute eagerly in session.sql and return
their result rows as a LocalRelation-backed DataFrame, matching the
reference's behavior.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .logical import LogicalPlan


class Command:
    """Marker base; session.sql dispatches on these."""


@dataclass
class CreateViewCommand(Command):
    name: str
    query: LogicalPlan
    replace: bool = True
    materialize: bool = False  # True for CREATE TABLE ... AS


@dataclass
class DropRelationCommand(Command):
    name: str
    if_exists: bool = False


@dataclass
class ShowTablesCommand(Command):
    pass


@dataclass
class ShowFunctionsCommand(Command):
    """SHOW FUNCTIONS [LIKE 'pattern'] (FunctionRegistry listing)."""

    pattern: Optional[str] = None


@dataclass
class DescribeCommand(Command):
    name: str


@dataclass
class ExplainCommand(Command):
    query: LogicalPlan
    extended: bool = False
    analyze: bool = False


@dataclass
class CacheTableCommand(Command):
    name: str
    uncache: bool = False


@dataclass
class SetCommand(Command):
    key: Optional[str]
    value: Optional[str]


@dataclass
class DeclareVariableCommand(Command):
    """DECLARE [VARIABLE] name [type] [DEFAULT expr] (reference: SQL
    session variables, sqlcat CreateVariable + analysis
    ResolveSetVariable / ColumnResolutionHelper variable fallback)."""

    name: str
    dtype: Optional[object] = None      # DataType
    default_expr: Optional[object] = None  # Expression
    replace: bool = False


@dataclass
class SetVariableCommand(Command):
    name: str
    value_expr: object = None  # Expression


@dataclass
class DropVariableCommand(Command):
    name: str
    if_exists: bool = False


@dataclass
class AnalyzeTableCommand(Command):
    """ANALYZE TABLE t COMPUTE STATISTICS [FOR COLUMNS a, b | FOR ALL
    COLUMNS] (reference: AnalyzeTableCommand / AnalyzeColumnCommand,
    sqlx/command/AnalyzeColumnCommand.scala — row count + per-column
    ndv/min/max/nulls persisted for the CBO)."""

    name: str
    columns: Optional[list] = None  # None → all columns


@dataclass
class InsertIntoCommand(Command):
    name: str
    query: LogicalPlan
    overwrite: bool = False


@dataclass
class UpdateCommand(Command):
    """UPDATE t SET c = e, ... [WHERE cond] (reference: v2 DML,
    sqlcat/plans/logical/v2Commands.scala UpdateTable) — executed
    set-based: one projection `IF(cond, new, old)` per column, then the
    target table is rewritten."""

    name: str
    assignments: list  # [(column_name, Expression)]
    condition: object = None


@dataclass
class DeleteCommand(Command):
    """DELETE FROM t [WHERE cond] (reference: DeleteFromTable)."""

    name: str
    condition: object = None


@dataclass
class MergeClause:
    kind: str                 # "update" | "delete" | "insert"
    extra: object = None      # additional AND condition
    assignments: list = field(default_factory=list)
    insert_cols: list = field(default_factory=list)
    insert_vals: list = field(default_factory=list)
    insert_star: bool = False


@dataclass
class MergeCommand(Command):
    """MERGE INTO target USING source ON cond WHEN ... (reference:
    MergeIntoTable). Set-based: matched rows rewrite via a left_outer
    join against the source, unmatched source rows insert via left_anti."""

    name: str
    target: LogicalPlan
    source: LogicalPlan
    condition: object
    matched: list          # [MergeClause] kind update/delete
    not_matched: list      # [MergeClause] kind insert


def run_command(session, cmd: Command):
    """Execute a command; returns a DataFrame of result rows."""
    import pyarrow as pa

    from ..api.dataframe import DataFrame
    from ..errors import AnalysisException
    from .logical import WithCTE

    # a command's embedded query (CTAS/INSERT/EXPLAIN/MERGE source) can
    # carry WithCTE materializations — resolve them the same way
    # session.sql does for plain queries, or analysis would hit the
    # unresolved __cte_mat_* placeholder relations
    for fname, val in list(vars(cmd).items()):
        if isinstance(val, WithCTE):
            setattr(cmd, fname, session._materialize_ctes(val))

    def df_of(table: pa.Table) -> DataFrame:
        return session.createDataFrame(table)

    if isinstance(cmd, CreateViewCommand):
        if not cmd.replace and session.catalog.tableExists(cmd.name):
            raise AnalysisException(
                f"Temp view {cmd.name} already exists",
                error_class="TEMP_TABLE_OR_VIEW_ALREADY_EXISTS")
        plan = cmd.query
        if not cmd.materialize:
            # a plan-stored view must not reference itself — resolution
            # would recurse forever (reference: CheckAnalysis
            # RECURSIVE_VIEW; Spark prohibits v AS SELECT ... FROM v).
            # Subquery-expression plans count too (… WHERE x IN
            # (SELECT … FROM v)).
            from ..plan.subquery import SubqueryExpression
            from .logical import UnresolvedRelation as _UR

            full = cmd.name.lower()

            def check_plan(p):
                for n in p.iter_nodes():
                    # exact-name match only: salesdb.v inside view v is a
                    # DIFFERENT relation, not a self-reference
                    if isinstance(n, _UR) and \
                            ".".join(n.name_parts).lower() == full:
                        raise AnalysisException(
                            f"Recursive view {cmd.name} detected: the "
                            "view body references the view itself",
                            error_class="RECURSIVE_VIEW")
                    for e in n.expressions():
                        for x in e.iter_nodes():
                            if isinstance(x, SubqueryExpression):
                                check_plan(x.plan)

            check_plan(plan)
        if cmd.materialize:
            df = DataFrame(session, plan)
            table = df.toArrow()
            wh = session.catalog_.external
            if wh is not None:
                # managed table in the warehouse
                wh.save_table(cmd.name, table,
                              mode="overwrite" if cmd.replace else "error")
                return df_of(pa.table({"result": pa.array([], pa.string())}))
            attrs = list(df.query_execution.analyzed.output)
            from .logical import LocalRelation

            plan = LocalRelation(attrs, table)
        session.catalog_.register(cmd.name, plan)
        return df_of(pa.table({"result": pa.array([], pa.string())}))

    if isinstance(cmd, DropRelationCommand):
        dropped = session.catalog_.drop(cmd.name)
        if not dropped and session.catalog_.external is not None:
            dropped = session.catalog_.external.drop_table(cmd.name)
        if not dropped and not cmd.if_exists:
            raise AnalysisException(
                f"Table or view not found: {cmd.name}",
                error_class="TABLE_OR_VIEW_NOT_FOUND")
        return df_of(pa.table({"result": pa.array([], pa.string())}))

    if isinstance(cmd, InsertIntoCommand):
        df = DataFrame(session, cmd.query)
        table = df.toArrow()
        wh = session.catalog_.external
        if wh is not None and cmd.name in wh.list_tables():
            target = wh.lookup(cmd.name)
            names = [a.name for a in target.output]
            if table.num_columns != len(names):
                raise AnalysisException(
                    f"INSERT INTO {cmd.name}: {table.num_columns} columns "
                    f"provided, table has {len(names)}")
            table = table.rename_columns(names)  # positional, like the ref
            wh.save_table(cmd.name, table,
                          mode="overwrite" if cmd.overwrite else "append")
            return df_of(pa.table({"result": pa.array([], pa.string())}))
        # temp view append: concat into the registered relation
        from .logical import LocalRelation

        existing = session.catalog_.lookup(cmd.name.split("."))
        if not isinstance(existing, LocalRelation):
            raise AnalysisException(
                f"INSERT INTO requires a saved table or materialized view: "
                f"{cmd.name}")
        table = table.rename_columns(existing.table.column_names)
        merged = table if cmd.overwrite else pa.concat_tables(
            [existing.table, table], promote_options="permissive")
        session.catalog_.register(
            cmd.name, LocalRelation(list(existing.attrs), merged))
        return df_of(pa.table({"result": pa.array([], pa.string())}))

    if isinstance(cmd, (UpdateCommand, DeleteCommand, MergeCommand)):
        return _run_dml(session, cmd, df_of)

    if isinstance(cmd, ShowTablesCommand):
        names = session.catalog_.list_tables()
        return df_of(pa.table({
            "namespace": pa.array([""] * len(names)),
            "tableName": pa.array(names),
            "isTemporary": pa.array([True] * len(names)),
        }))

    if isinstance(cmd, ShowFunctionsCommand):
        from ..expr.registry import filter_names

        return df_of(pa.table(
            {"function": pa.array(filter_names(cmd.pattern))}))

    if isinstance(cmd, DescribeCommand):
        plan = session.catalog_.lookup(cmd.name.split("."))
        from ..api.dataframe import DataFrame as DF

        analyzed = DF(session, plan).query_execution.analyzed
        return df_of(pa.table({
            "col_name": pa.array([a.name for a in analyzed.output]),
            "data_type": pa.array([a.dtype.simple_string()
                                   for a in analyzed.output]),
            "comment": pa.array([None] * len(analyzed.output), pa.string()),
        }))

    if isinstance(cmd, ExplainCommand):
        from ..api.dataframe import DataFrame as DF

        qe = DF(session, cmd.query).query_execution
        text = qe.explain_string()
        if cmd.analyze:
            qe.to_arrow()  # execute for real timings
            lines = [text, "", "== Analyzed Runtime =="]
            for phase, t in qe.phase_times.items():
                lines.append(f"{phase}: {t * 1000:.1f} ms")
            counters = session._metrics.snapshot()["counters"]
            for k in sorted(counters):
                lines.append(f"{k}: {counters[k]}")
            text = "\n".join(lines)
        return df_of(pa.table({"plan": pa.array([text])}))

    if isinstance(cmd, CacheTableCommand):
        from ..api.dataframe import DataFrame as DF

        plan = session.catalog_.lookup(cmd.name.split("."))
        df = DF(session, plan)
        if cmd.uncache:
            session._uncache_df(df)
        else:
            df.cache()
        return df_of(pa.table({"result": pa.array([], pa.string())}))

    if isinstance(cmd, SetCommand):
        if cmd.key is None:
            from ..config import registry

            items = sorted(registry().items())
            return df_of(pa.table({
                "key": pa.array([k for k, _ in items]),
                "value": pa.array([str(session.conf.get(k))
                                   for k, _ in items]),
            }))
        if cmd.value is not None:
            session.conf.set(cmd.key, cmd.value)
        return df_of(pa.table({
            "key": pa.array([cmd.key]),
            "value": pa.array([str(session.conf.get(cmd.key))]),
        }))

    if isinstance(cmd, (DeclareVariableCommand, SetVariableCommand,
                        DropVariableCommand)):
        from ..expr.expressions import Literal

        varstore = session.catalog_.variables
        key = cmd.name.lower()
        if isinstance(cmd, DropVariableCommand):
            if key not in varstore and not cmd.if_exists:
                raise AnalysisException(f"variable {cmd.name} not found")
            removed = varstore.pop(key, None) is not None
            if not removed and key in varstore:
                # session-clone scope (ChainMap): pop only touches the
                # connection-local layer, so a variable still visible
                # after it lives on the SERVER session — reporting
                # success for a drop that removed nothing would lie
                raise AnalysisException(
                    f"variable {cmd.name} is declared on the server "
                    "session and cannot be dropped from a connection "
                    "session")
            return df_of(pa.table({"variable": pa.array([cmd.name])}))
        if isinstance(cmd, SetVariableCommand) and key not in varstore:
            raise AnalysisException(
                f"variable {cmd.name} not declared (DECLARE it first)")
        if isinstance(cmd, DeclareVariableCommand) and key in varstore \
                and not cmd.replace:
            raise AnalysisException(
                f"variable {cmd.name} already exists "
                "(DECLARE OR REPLACE to overwrite)",
                error_class="VARIABLE_ALREADY_EXISTS")
        expr = cmd.default_expr \
            if isinstance(cmd, DeclareVariableCommand) else cmd.value_expr
        # the variable's declared type is sticky: assignments cast to it
        # (reference: SetVariable casts to the variable's type)
        target_dt = cmd.dtype if isinstance(cmd, DeclareVariableCommand) \
            else varstore[key].dtype
        if expr is None:
            value, dt = None, target_dt
        else:
            from ..expr.expressions import Alias, Cast
            from .logical import OneRowRelation, Project

            if target_dt is not None:
                expr = Cast(expr, target_dt)
            table = DataFrame(session, Project(
                [Alias(expr, "v")], OneRowRelation())).toArrow()
            value = table.column(0)[0].as_py() if table.num_rows else None
            from ..columnar.arrow import schema_from_arrow

            dt = target_dt if target_dt is not None else \
                schema_from_arrow(table.schema).fields[0].dataType
        varstore[key] = Literal(value, dt) if dt is not None \
            else Literal(value)
        return df_of(pa.table({
            "variable": pa.array([cmd.name]),
            "value": pa.array([None if value is None else str(value)]),
        }))

    if isinstance(cmd, AnalyzeTableCommand):
        from ..api.dataframe import DataFrame as _DF
        from .logical import LocalRelation, LogicalRelation
        from .stats import compute_table_stats

        plan = session.catalog_.lookup([cmd.name])
        table = _DF(session, plan).toArrow()
        stats = compute_table_stats(table, cmd.columns)
        # attach to the catalog plan's relation leaf so estimate()
        # (plan/stats.py) sees it wherever the view is spliced — only
        # when the "table" IS one relation (a multi-relation view's
        # per-leaf stats would be wrong)
        leaves = [n for n in plan.iter_nodes()
                  if isinstance(n, (LocalRelation, LogicalRelation))]
        if len(leaves) == 1:
            leaves[0]._cbo_stats = stats
        session._table_stats[session.catalog_._norm(cmd.name)] = stats
        return df_of(pa.table({
            "table": pa.array([cmd.name]),
            "rows": pa.array([stats.row_count]),
            "columns_analyzed": pa.array([len(stats.col_stats)]),
        }))

    raise AnalysisException(f"unknown command {type(cmd).__name__}")


# ---------------------------------------------------------------------------
# DML execution (UPDATE / DELETE / MERGE) — set-based table rewrites
# ---------------------------------------------------------------------------

def _write_target(session, name: str, new_tbl):
    """Replace a warehouse table or registered temp relation in place."""
    from ..errors import AnalysisException
    from .logical import LocalRelation

    wh = session.catalog_.external
    if wh is not None and name in wh.list_tables():
        target = wh.lookup(name)
        names = [a.name for a in target.output]
        wh.save_table(name, new_tbl.rename_columns(names), mode="overwrite")
        return
    existing = session.catalog_.lookup(name.split("."))
    if not isinstance(existing, LocalRelation):
        raise AnalysisException(
            f"DML requires a saved table or materialized view: {name}")
    new_tbl = new_tbl.rename_columns(existing.table.column_names)
    session.catalog_.register(
        name, LocalRelation(list(existing.attrs), new_tbl))


def _run_dml(session, cmd, df_of):
    import pyarrow as pa

    from ..api.dataframe import DataFrame
    from ..expr.expressions import (
        Alias, And, Cast, EqualNullSafe, If, IsNotNull, IsNull, Literal,
        Not, Or, UnresolvedAttribute, UnresolvedStar,
    )
    from .logical import (
        Filter, Join, Project, SubqueryAlias, UnresolvedRelation,
    )

    def empty_result():
        return df_of(pa.table({"result": pa.array([], pa.string())}))

    if isinstance(cmd, DeleteCommand):
        rel = UnresolvedRelation(cmd.name.split("."))
        if cmd.condition is None:
            plan = Filter(Literal(False), rel)
        else:
            # keep rows where the predicate is false OR unknown
            plan = Filter(Or(Not(cmd.condition), IsNull(cmd.condition)), rel)
        _write_target(session, cmd.name, DataFrame(session, plan).toArrow())
        return empty_result()

    if isinstance(cmd, UpdateCommand):
        rel = UnresolvedRelation(cmd.name.split("."))
        attrs = DataFrame(session, rel).query_execution.analyzed.output
        amap = {n.lower(): e for n, e in cmd.assignments}
        proj = []
        for a in attrs:
            old = UnresolvedAttribute([a.name])
            if a.name.lower() in amap:
                newe = amap[a.name.lower()]
                e = newe if cmd.condition is None \
                    else If(cmd.condition, newe, old)
                proj.append(Alias(Cast(e, a.dtype), a.name))
            else:
                proj.append(Alias(old, a.name))
        new_tbl = DataFrame(session, Project(proj, rel)).toArrow()
        _write_target(session, cmd.name, new_tbl)
        return empty_result()

    # ---- MERGE -----------------------------------------------------------
    talias = cmd.target.alias
    target_attrs = DataFrame(session,
                             cmd.target).query_execution.analyzed.output

    matched_ref = IsNotNull(UnresolvedAttribute(["__merge_m"]))

    def base_cond(cl, matched_flag):
        c = matched_flag
        if cl.extra is not None:
            c = And(c, EqualNullSafe(cl.extra, Literal(True)))
        return c

    def effective(clauses, matched_flag):
        """First-match-wins: clause i fires iff its condition holds AND no
        earlier clause's does."""
        eff, prior = [], None
        for cl in clauses:
            c = base_cond(cl, matched_flag)
            if prior is not None:
                c = And(c, Not(prior))
            eff.append(c)
            prior = c if prior is None else Or(prior, c)
        return eff

    # matched side: target LEFT OUTER source(+flag). The target gets a
    # host-assigned row id so multi-source matches are detectable — the
    # reference raises MERGE_CARDINALITY_VIOLATION when a target row that
    # an UPDATE/DELETE clause would touch matches more than one source row
    # instead of silently duplicating it. The join runs ONCE: the update
    # projection, row id, matched flag, and delete condition are computed
    # in a single pass, then the cardinality check and delete filter
    # happen host-side on the materialized result.
    from ..errors import ExecutionError
    from ..expr.expressions import AttributeReference
    from ..types import int64 as _i64
    from .logical import LocalRelation

    tgt_tbl = DataFrame(session, cmd.target).toArrow()
    if not cmd.matched:
        # insert-only MERGE: the matched side is the target unchanged (no
        # cardinality constraint applies — reference behavior)
        tables = [tgt_tbl]
    else:
        rid_tbl = tgt_tbl.append_column(
            "__merge_rid", pa.array(range(tgt_tbl.num_rows), pa.int64()))
        rid_attrs = [AttributeReference(a.name, a.dtype, True)
                     for a in target_attrs] + \
            [AttributeReference("__merge_rid", _i64, False)]
        target_rel = SubqueryAlias(talias, LocalRelation(rid_attrs, rid_tbl)) \
            if talias else LocalRelation(rid_attrs, rid_tbl)

        src_flag = Project([UnresolvedStar(None),
                            Alias(Literal(True), "__merge_m")], cmd.source)
        joined = Join(target_rel, src_flag, "left_outer", cmd.condition)

        eff = effective(cmd.matched, matched_ref)
        del_cond = None
        for cl, c in zip(cmd.matched, eff):
            if cl.kind == "delete":
                del_cond = c if del_cond is None else Or(del_cond, c)
        proj = []
        for a in target_attrs:
            old = UnresolvedAttribute([talias, a.name])
            e = old
            for cl, c in reversed(list(zip(cmd.matched, eff))):
                if cl.kind != "update":
                    continue
                am = {n.lower(): x for n, x in cl.assignments}
                if a.name.lower() in am:
                    e = If(c, am[a.name.lower()], e)
            proj.append(Alias(Cast(e, a.dtype), a.name))
        aux = [Alias(UnresolvedAttribute(["__merge_rid"]), "__merge_rid"),
               Alias(matched_ref, "__merge_mf")]
        if del_cond is not None:
            aux.append(Alias(del_cond, "__merge_del"))
        out = DataFrame(session, Project(proj + aux, joined)).toArrow()

        rids = [r for r, m in zip(out.column("__merge_rid").to_pylist(),
                                  out.column("__merge_mf").to_pylist()) if m]
        if len(rids) != len(set(rids)):
            raise ExecutionError(
                "MERGE_CARDINALITY_VIOLATION: a target row of the MERGE "
                "matched more than one source row; rewrite the source to "
                "have at most one match per target row")
        if del_cond is not None:
            keep = pa.array([d is not True for d in
                             out.column("__merge_del").to_pylist()])
            out = out.filter(keep)
        tables = [out.select([a.name for a in target_attrs])]

    # not-matched side: source LEFT ANTI target → inserts
    if cmd.not_matched:
        anti = Join(cmd.source, cmd.target, "left_anti", cmd.condition)
        src_attrs = DataFrame(session,
                              cmd.source).query_execution.analyzed.output
        ins_eff = effective(cmd.not_matched, Literal(True))
        for cl, c in zip(cmd.not_matched, ins_eff):
            branch = anti if (cl.extra is None and len(cmd.not_matched) == 1) \
                else Filter(c, anti)
            if cl.insert_star:
                proj_i = [Alias(Cast(UnresolvedAttribute([s.name]), a.dtype),
                                a.name)
                          for s, a in zip(src_attrs, target_attrs)]
            else:
                cmap = {n.lower(): v for n, v in zip(cl.insert_cols,
                                                     cl.insert_vals)}
                proj_i = [Alias(Cast(cmap.get(a.name.lower(),
                                              Literal(None)), a.dtype),
                                a.name)
                          for a in target_attrs]
            tables.append(
                DataFrame(session, Project(proj_i, branch)).toArrow())

    new_tbl = pa.concat_tables(tables, promote_options="permissive")
    _write_target(session, cmd.name, new_tbl)
    return df_of(pa.table({"result": pa.array([], pa.string())}))
