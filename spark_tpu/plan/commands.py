"""SQL commands (DDL / utility statements).

Role of the reference's command framework (sqlx/command/ — RunnableCommand:
CreateViewCommand, ShowTablesCommand, DescribeTableCommand, ExplainCommand,
CacheTableCommand...). Commands execute eagerly in session.sql and return
their result rows as a LocalRelation-backed DataFrame, matching the
reference's behavior.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .logical import LogicalPlan


class Command:
    """Marker base; session.sql dispatches on these."""


@dataclass
class CreateViewCommand(Command):
    name: str
    query: LogicalPlan
    replace: bool = True
    materialize: bool = False  # True for CREATE TABLE ... AS


@dataclass
class DropRelationCommand(Command):
    name: str
    if_exists: bool = False


@dataclass
class ShowTablesCommand(Command):
    pass


@dataclass
class DescribeCommand(Command):
    name: str


@dataclass
class ExplainCommand(Command):
    query: LogicalPlan
    extended: bool = False
    analyze: bool = False


@dataclass
class CacheTableCommand(Command):
    name: str
    uncache: bool = False


@dataclass
class SetCommand(Command):
    key: Optional[str]
    value: Optional[str]


@dataclass
class InsertIntoCommand(Command):
    name: str
    query: LogicalPlan
    overwrite: bool = False


def run_command(session, cmd: Command):
    """Execute a command; returns a DataFrame of result rows."""
    import pyarrow as pa

    from ..api.dataframe import DataFrame
    from ..errors import AnalysisException

    def df_of(table: pa.Table) -> DataFrame:
        return session.createDataFrame(table)

    if isinstance(cmd, CreateViewCommand):
        if not cmd.replace and session.catalog.tableExists(cmd.name):
            raise AnalysisException(
                f"Temp view {cmd.name} already exists",
                error_class="TEMP_TABLE_OR_VIEW_ALREADY_EXISTS")
        plan = cmd.query
        if cmd.materialize:
            df = DataFrame(session, plan)
            table = df.toArrow()
            wh = session.catalog_.external
            if wh is not None:
                # managed table in the warehouse
                wh.save_table(cmd.name, table,
                              mode="overwrite" if cmd.replace else "error")
                return df_of(pa.table({"result": pa.array([], pa.string())}))
            attrs = list(df.query_execution.analyzed.output)
            from .logical import LocalRelation

            plan = LocalRelation(attrs, table)
        session.catalog_.register(cmd.name, plan)
        return df_of(pa.table({"result": pa.array([], pa.string())}))

    if isinstance(cmd, DropRelationCommand):
        dropped = session.catalog_.drop(cmd.name)
        if not dropped and session.catalog_.external is not None:
            dropped = session.catalog_.external.drop_table(cmd.name)
        if not dropped and not cmd.if_exists:
            raise AnalysisException(
                f"Table or view not found: {cmd.name}",
                error_class="TABLE_OR_VIEW_NOT_FOUND")
        return df_of(pa.table({"result": pa.array([], pa.string())}))

    if isinstance(cmd, InsertIntoCommand):
        df = DataFrame(session, cmd.query)
        table = df.toArrow()
        wh = session.catalog_.external
        if wh is not None and cmd.name in wh.list_tables():
            target = wh.lookup(cmd.name)
            names = [a.name for a in target.output]
            if table.num_columns != len(names):
                raise AnalysisException(
                    f"INSERT INTO {cmd.name}: {table.num_columns} columns "
                    f"provided, table has {len(names)}")
            table = table.rename_columns(names)  # positional, like the ref
            wh.save_table(cmd.name, table,
                          mode="overwrite" if cmd.overwrite else "append")
            return df_of(pa.table({"result": pa.array([], pa.string())}))
        # temp view append: concat into the registered relation
        from .logical import LocalRelation

        existing = session.catalog_.lookup(cmd.name.split("."))
        if not isinstance(existing, LocalRelation):
            raise AnalysisException(
                f"INSERT INTO requires a saved table or materialized view: "
                f"{cmd.name}")
        table = table.rename_columns(existing.table.column_names)
        merged = table if cmd.overwrite else pa.concat_tables(
            [existing.table, table], promote_options="permissive")
        session.catalog_.register(
            cmd.name, LocalRelation(list(existing.attrs), merged))
        return df_of(pa.table({"result": pa.array([], pa.string())}))

    if isinstance(cmd, ShowTablesCommand):
        names = session.catalog_.list_tables()
        return df_of(pa.table({
            "namespace": pa.array([""] * len(names)),
            "tableName": pa.array(names),
            "isTemporary": pa.array([True] * len(names)),
        }))

    if isinstance(cmd, DescribeCommand):
        plan = session.catalog_.lookup(cmd.name.split("."))
        from ..api.dataframe import DataFrame as DF

        analyzed = DF(session, plan).query_execution.analyzed
        return df_of(pa.table({
            "col_name": pa.array([a.name for a in analyzed.output]),
            "data_type": pa.array([a.dtype.simple_string()
                                   for a in analyzed.output]),
            "comment": pa.array([None] * len(analyzed.output), pa.string()),
        }))

    if isinstance(cmd, ExplainCommand):
        from ..api.dataframe import DataFrame as DF

        qe = DF(session, cmd.query).query_execution
        text = qe.explain_string()
        if cmd.analyze:
            qe.to_arrow()  # execute for real timings
            lines = [text, "", "== Analyzed Runtime =="]
            for phase, t in qe.phase_times.items():
                lines.append(f"{phase}: {t * 1000:.1f} ms")
            counters = session._metrics.snapshot()["counters"]
            for k in sorted(counters):
                lines.append(f"{k}: {counters[k]}")
            text = "\n".join(lines)
        return df_of(pa.table({"plan": pa.array([text])}))

    if isinstance(cmd, CacheTableCommand):
        from ..api.dataframe import DataFrame as DF

        plan = session.catalog_.lookup(cmd.name.split("."))
        df = DF(session, plan)
        if cmd.uncache:
            session._uncache_df(df)
        else:
            df.cache()
        return df_of(pa.table({"result": pa.array([], pa.string())}))

    if isinstance(cmd, SetCommand):
        if cmd.key is None:
            from ..config import registry

            items = sorted(registry().items())
            return df_of(pa.table({
                "key": pa.array([k for k, _ in items]),
                "value": pa.array([str(session.conf.get(k))
                                   for k, _ in items]),
            }))
        if cmd.value is not None:
            session.conf.set(cmd.key, cmd.value)
        return df_of(pa.table({
            "key": pa.array([cmd.key]),
            "value": pa.array([str(session.conf.get(cmd.key))]),
        }))

    raise AnalysisException(f"unknown command {type(cmd).__name__}")
