"""Analyzer: resolves relations, columns, functions; coerces types.

Role of the reference's Analyzer (sqlcat/analysis/Analyzer.scala:364, rule
batches at :566) — ~100 rules there; here the load-bearing subset:
ResolveRelations, ResolveReferences (incl. star expansion and qualifier
handling via expr_ids), ResolveFunctions, alias extraction for aggregates,
HAVING/ORDER-BY resolution against aggregates, decimal coercion, and
CheckAnalysis.
"""

from __future__ import annotations

import difflib
from typing import Sequence

from ..errors import AnalysisException, UnresolvedColumnError
from ..types import DecimalType, common_type
from .catalog import Catalog
from .logical import (
    Aggregate, Distinct, Filter, Join, LogicalPlan, Project, Sort,
    SubqueryAlias, UnresolvedRelation,
)
from .tree import Batch, FixedPoint, Once, Rule, RuleExecutor
from ..expr.expressions import (
    Alias, AttributeReference, Cast, EqualTo, Expression, Literal, SortOrder,
    Subtract, Add, UnresolvedAttribute, UnresolvedFunction, UnresolvedStar,
    AggregateFunction, cast_if,
)
from ..expr.registry import build_function


def _resolve_name(name_parts: tuple[str, ...],
                  attrs: Sequence[AttributeReference],
                  case_sensitive: bool) -> AttributeReference | None:
    def norm(s: str) -> str:
        return s if case_sensitive else s.lower()

    # qualified references must suffix-match the attribute's qualifier
    matches = []
    for a in attrs:
        if norm(a.name) == norm(name_parts[-1]):
            quals = tuple(norm(q) for q in name_parts[:-1])
            if quals:
                aq = tuple(norm(q) for q in a.qualifier)
                if len(aq) < len(quals) or aq[-len(quals):] != quals:
                    continue
            matches.append(a)
    if len(matches) == 1:
        return matches[0]
    if len(matches) > 1:
        # ambiguous unless they are the same attribute id
        ids = {m.expr_id for m in matches}
        if len(ids) == 1:
            return matches[0]
        raise AnalysisException(
            f"Reference `{'.'.join(name_parts)}` is ambiguous",
            error_class="AMBIGUOUS_REFERENCE")
    return None


def _resolve_struct_path(name_parts, attrs, case_sensitive):
    """a.b.c where a prefix resolves to a struct-typed column: peel the
    remaining parts as field accesses (reference: complexTypeExtractors
    ExtractValue resolution in the analyzer)."""
    from ..types import StructType
    from ..expr.expressions import GetStructField

    def norm(s):
        return s if case_sensitive else s.lower()

    for k in range(len(name_parts) - 1, 0, -1):
        base = _resolve_name(name_parts[:k], attrs, case_sensitive)
        if base is None or not isinstance(base.dtype, StructType):
            continue
        out = base
        ok = True
        for p in name_parts[k:]:
            dt = out.dtype
            if not isinstance(dt, StructType):
                ok = False
                break
            actual = next((f.name for f in dt.fields
                           if norm(f.name) == norm(p)), None)
            if actual is None:
                ok = False
                break
            out = GetStructField(out, actual)
        if ok:
            return out
    return None


class ResolveRelations(Rule):
    def __init__(self, catalog: Catalog):
        self.catalog = catalog

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        def rule(node):
            if isinstance(node, UnresolvedRelation):
                resolved = self.catalog.lookup(node.name_parts)
                # fresh attribute instances per scan? No — reuse; self-joins
                # get disambiguated by deduplicate rule below.
                return SubqueryAlias(node.name_parts[-1], resolved)
            return node

        return plan.transform_up(rule)


class DeduplicateRelations(Rule):
    """Re-instance attribute ids on the right side of a self-join
    (reference: Analyzer DeduplicateRelations)."""

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        from .logical import UsingJoin

        def rule(node):
            if isinstance(node, (Join, UsingJoin)):
                try:
                    left_ids = {a.expr_id for a in node.left.output}
                    right_ids = {a.expr_id for a in node.right.output}
                except AnalysisException:
                    return node  # children await alias resolution
                overlap = left_ids & right_ids
                if overlap:
                    mapping: dict[int, AttributeReference] = {}
                    new_right = _remap_plan(node.right, mapping, overlap)
                    # any resolved condition references re-resolve later;
                    # a UsingJoin builds its condition after this remap
                    return node.copy(right=new_right)
            return node

        return plan.transform_up(rule)


def _remap_plan(plan: LogicalPlan, mapping: dict[int, AttributeReference],
                overlap: set[int]) -> LogicalPlan:
    """Deep-copy a subtree giving fresh expr_ids to attributes in `overlap`
    (and anything they produce)."""

    def remap_expr(e: Expression) -> Expression:
        if isinstance(e, AttributeReference) and e.expr_id in mapping_ids():
            return mapping[e.expr_id]
        if isinstance(e, Alias) and e.expr_id in overlap:
            # a view body minting its output via Aliases (e.g.
            # `SELECT 1 g, 10 v UNION ALL ...`) must re-mint those ids
            # too, or the subquery's copy stays aliased to the outer's.
            # One new id per OLD id (same rationale as relations above).
            na = mapping.get(e.expr_id)
            if na is None:
                new = Alias(e.child, e.name)    # fresh expr_id
                mapping[e.expr_id] = new.to_attribute()
                return new
            return Alias(e.child, e.name, expr_id=na.expr_id)
        return e

    def mapping_ids():
        return mapping

    def go(node: LogicalPlan) -> LogicalPlan:
        node = node.map_children(go)
        # remap produced attrs
        from .logical import LogicalRelation, LocalRelation, RangeRelation

        if isinstance(node, (LogicalRelation, LocalRelation)):
            attrs = node.attrs if hasattr(node, "attrs") else node.output
            new_attrs = []
            changed = False
            for a in attrs:
                if a.expr_id in overlap:
                    # one new instance PER OLD ID for the whole subtree: a
                    # relation occurring in several union branches must
                    # keep one id so references above the union stay bound
                    # to the union's (first-branch) output — q75 shape
                    na = mapping.get(a.expr_id)
                    if na is None:
                        na = a.new_instance()
                        mapping[a.expr_id] = na
                    new_attrs.append(na)
                    changed = True
                else:
                    new_attrs.append(a)
            if changed:
                node = node.copy(attrs=new_attrs)
        elif isinstance(node, RangeRelation) and node.attr.expr_id in overlap:
            na = mapping.get(node.attr.expr_id)
            if na is None:
                na = node.attr.new_instance()
                mapping[node.attr.expr_id] = na
            node = node.copy(attr=na)
        if isinstance(node, (Project, Aggregate)):
            # aliases produce new ids too; only inputs need remapping
            pass
        node = node.transform_expressions(remap_expr)
        return node

    return go(plan)


class ResolveReferences(Rule):
    def __init__(self, case_sensitive: bool = False):
        self.case_sensitive = case_sensitive

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        cs = self.case_sensitive

        # memo holds (node, verdict): keeping the node referenced pins its
        # id() for the pass, so a GC'd copy can't alias a stale entry
        _dedup_memo: dict[int, tuple[LogicalPlan, bool]] = {}

        def _awaits_dedup(n: LogicalPlan) -> bool:
            """True when a descendant self-join still has overlapping
            attribute ids: resolving any expression ABOVE it would bind
            both qualified sides to the same id (e.g. `c.y = p.y + 1` in a
            WHERE over a comma self-join — the TPC-DS q75 shape).
            Memoized per apply() so the fixpoint pass stays O(n)."""
            hit = _dedup_memo.get(id(n))
            if hit is not None and hit[0] is n:
                return hit[1]
            out = any(_awaits_dedup(c) for c in n.children)
            if not out and isinstance(n, Join):
                try:
                    lids = {a.expr_id for a in n.left.output}
                    rids = {a.expr_id for a in n.right.output}
                    out = bool(lids & rids)
                except AnalysisException:
                    out = False
            _dedup_memo[id(n)] = (n, out)
            return out

        def rule(node: LogicalPlan):
            if not all(c.resolved for c in node.children):
                return node
            try:
                inputs = node.input_attrs()
            except AnalysisException:
                return node  # child awaits ResolveAliases
            if _awaits_dedup(node):
                return node

            # star expansion in Project/Aggregate
            if isinstance(node, (Project, Aggregate)):
                lst = node.project_list if isinstance(node, Project) else node.aggregate_exprs
                if any(isinstance(e, UnresolvedStar) for e in lst):
                    expanded: list[Expression] = []
                    for e in lst:
                        if isinstance(e, UnresolvedStar):
                            if e.target is None:
                                expanded.extend(inputs)
                            else:
                                t = e.target if cs else e.target.lower()
                                hits = [a for a in inputs
                                        if t in tuple(q if cs else q.lower()
                                                      for q in a.qualifier)]
                                if not hits:
                                    raise AnalysisException(
                                        f"cannot resolve {e.target}.*")
                                expanded.extend(hits)
                        else:
                            expanded.append(e)
                    if isinstance(node, Project):
                        return node.copy(project_list=expanded)
                    return node.copy(aggregate_exprs=expanded)

            def resolve_expr(e: Expression) -> Expression:
                if isinstance(e, UnresolvedAttribute):
                    a = _resolve_name(e.name_parts, inputs, cs)
                    if a is not None:
                        return a
                    nested = _resolve_struct_path(e.name_parts, inputs, cs)
                    if nested is not None:
                        return nested
                    return e
                if isinstance(e, UnresolvedFunction):
                    if all(c.resolved or isinstance(c, UnresolvedStar)
                           for c in e.args):
                        return build_function(e.fname, e.args, e.distinct)
                    return e
                from ..expr.window import (
                    UnresolvedWindowExpression, WindowExpression,
                )

                if isinstance(e, UnresolvedWindowExpression):
                    if e.function.resolved and \
                            all(p.resolved for p in e.partition_spec) and \
                            all(o.resolved for o in e.order_spec):
                        return WindowExpression(e.function, e.partition_spec,
                                                e.order_spec, e.frame)
                    return e
                return e

            # Sort/Filter-over-Aggregate may reference aggregate output or
            # grouping child columns — handled by ResolveAggsInSortHaving.
            return node.transform_expressions(resolve_expr)

        return plan.transform_up(rule)


class ResolveAliases(Rule):
    """Wrap top-level non-named project/aggregate expressions in Aliases."""

    def apply(self, plan):
        def rule(node):
            if isinstance(node, Project):
                if node.expressions_resolved and any(
                        not isinstance(e, (Alias, AttributeReference, UnresolvedStar))
                        for e in node.project_list):
                    return node.copy(project_list=[_auto_alias(e)
                                                   for e in node.project_list])
            from .logical import GroupingSets

            if isinstance(node, (Aggregate, GroupingSets)):
                if node.expressions_resolved and any(
                        not isinstance(e, (Alias, AttributeReference, UnresolvedStar))
                        for e in node.aggregate_exprs):
                    return node.copy(aggregate_exprs=[_auto_alias(e)
                                                      for e in node.aggregate_exprs])
            return node

        return plan.transform_up(rule)


def _auto_alias(e: Expression) -> Expression:
    if isinstance(e, (Alias, AttributeReference, UnresolvedStar)):
        return e
    name = _pretty_name(e)
    return Alias(e, name)


def _pretty_name(e: Expression) -> str:
    from ..expr.expressions import (
        Average, Count, GetStructField, Max, Min, Sum, Cast as _Cast,
    )

    if isinstance(e, GetStructField):
        return e.field_name  # `a.b` names its output `b`, like the reference
    if isinstance(e, Sum):
        return f"sum({_pretty_name(e.child)})"
    if isinstance(e, Count):
        return f"count({_pretty_name(e.child) if e.child else '1'})"
    if isinstance(e, Min):
        return f"min({_pretty_name(e.child)})"
    if isinstance(e, Max):
        return f"max({_pretty_name(e.child)})"
    if isinstance(e, Average):
        return f"avg({_pretty_name(e.child)})"
    if isinstance(e, AttributeReference):
        return e.name
    if isinstance(e, UnresolvedAttribute):
        return e.name
    if isinstance(e, Literal):
        return str(e.value)
    if isinstance(e, _Cast):
        return _pretty_name(e.child)
    sym = getattr(e, "symbol", None)
    if sym is not None and hasattr(e, "left") and hasattr(e, "right"):
        return f"({_pretty_name(e.left)} {sym} {_pretty_name(e.right)})"
    kids = [c for c in e.children if c is not None]
    if kids:
        return (f"{e.sql_name()}"
                f"({', '.join(_pretty_name(c) for c in kids)})")
    return e.simple_string()


class ResolveGroupByAlias(Rule):
    """GROUP BY may reference a SELECT-list alias (reference:
    sqlcat/analysis/Analyzer ResolveReferences' GROUP BY alias fallback,
    golden file group-by-alias.sql): a grouping expression that stays
    unresolved against the child's columns resolves to the aliased
    select expression, provided that expression is not itself an
    aggregate."""

    def __init__(self, case_sensitive: bool = False):
        self.cs = case_sensitive

    def apply(self, plan):
        from .logical import GroupingSets

        def rule(node):
            if not isinstance(node, (Aggregate, GroupingSets)):
                return node
            if all(g.resolved for g in node.grouping_exprs):
                return node
            aliases = {}
            for e in node.aggregate_exprs:
                if isinstance(e, Alias) and e.child.resolved and \
                        not _contains_agg(e.child):
                    key = e.name if self.cs else e.name.lower()
                    aliases.setdefault(key, e.child)

            def fix(g):
                if isinstance(g, UnresolvedAttribute) and \
                        len(g.name_parts) == 1:
                    key = g.name_parts[0] if self.cs \
                        else g.name_parts[0].lower()
                    sub = aliases.get(key)
                    if sub is not None:
                        return sub
                return g

            new_groups = [fix(g) for g in node.grouping_exprs]
            if all(a is b for a, b in zip(new_groups, node.grouping_exprs)):
                return node
            return node.copy(grouping_exprs=new_groups)

        return plan.transform_up(rule)


def _contains_agg(e: Expression) -> bool:
    if isinstance(e, AggregateFunction):
        return True
    return any(_contains_agg(c) for c in e.children
               if isinstance(c, Expression))


class ResolveSessionVariables(Rule):
    """Single-part references that columns did NOT resolve fall back to
    declared session variables and substitute their literal value —
    column wins over variable, the reference's resolution order
    (ColumnResolutionHelper resolveColumnsByPlanChildren → variable
    fallback)."""

    def __init__(self, catalog: Catalog):
        self.catalog = catalog

    def apply(self, plan):
        variables = getattr(self.catalog, "variables", None)
        if not variables:
            return plan

        def fix(e):
            if isinstance(e, UnresolvedAttribute) and \
                    len(e.name_parts) == 1:
                hit = variables.get(e.name_parts[0].lower())
                if hit is not None:
                    return hit
            return e

        def rule(node):
            # only where the children are fully resolved: a column with
            # the same name must win first
            if all(c.resolved for c in node.children):
                return node.map_expressions(
                    lambda ex: ex.transform_up(fix))
            return node

        return plan.transform_up(rule)


class GlobalAggregates(Rule):
    """Project whose list contains an aggregate function (outside any
    window expression) becomes a global Aggregate with no grouping —
    df.select(count("*")) / selectExpr("sum(x)") parity (reference:
    sqlcat/analysis/Analyzer.scala GlobalAggregates)."""

    def apply(self, plan):
        def has_plain_agg(e) -> bool:
            from ..expr.window import (
                UnresolvedWindowExpression, WindowExpression,
            )

            if isinstance(e, (WindowExpression, UnresolvedWindowExpression)):
                return False  # window aggregates aggregate per-row
            if isinstance(e, AggregateFunction):
                return True
            return any(has_plain_agg(c) for c in e.children
                       if isinstance(c, Expression))

        def rule(node):
            if isinstance(node, Project) and \
                    any(has_plain_agg(e) for e in node.project_list):
                return Aggregate([], list(node.project_list), node.child)
            return node

        return plan.transform_up(rule)


class ResolveAggsInSortHaving(Rule):
    """Resolve HAVING filters and ORDER BY over an Aggregate: references to
    aggregate results resolve to output attrs; bare aggregate functions get
    pulled into the aggregate (reference: ResolveAggregateFunctions)."""

    def __init__(self, case_sensitive: bool = False):
        self.cs = case_sensitive

    def apply(self, plan):
        def rule(node):
            tgt = _skip_alias(node.child) \
                if isinstance(node, (Filter, Sort)) else None
            if isinstance(node, Sort) and isinstance(tgt, Filter) and \
                    isinstance(_skip_alias(tgt.child), Aggregate):
                # ORDER BY over HAVING over Aggregate: resolve the sort
                # keys against the aggregate below the filter
                tgt = _skip_alias(tgt.child)
            if isinstance(node, (Filter, Sort)) and isinstance(
                    tgt, Aggregate):
                agg = tgt
                if not agg.resolved:
                    return node
                if any(not isinstance(e, (Alias, AttributeReference))
                       for e in agg.aggregate_exprs):
                    return node  # wait for ResolveAliases
                out_attrs = agg.output

                extra: list[Alias] = []

                def resolve(e: Expression) -> Expression:
                    if isinstance(e, UnresolvedAttribute):
                        a = _resolve_name(e.name_parts, out_attrs, self.cs)
                        if a is not None:
                            return a
                        a = _resolve_name(e.name_parts, agg.child.output, self.cs)
                        if a is not None:
                            return a
                        # struct path over the agg child (ORDER BY s.a
                        # where s.a is a grouping expression): bind to the
                        # matching aggregate output
                        nested = _resolve_struct_path(
                            e.name_parts, agg.child.output, self.cs)
                        if nested is not None:
                            for ae in agg.aggregate_exprs:
                                if isinstance(ae, Alias) and \
                                        ae.child.semantic_equals(nested):
                                    return ae.to_attribute()
                            return nested
                        return e
                    if isinstance(e, UnresolvedFunction):
                        if all(c.resolved or isinstance(c, UnresolvedStar)
                               for c in e.args):
                            f = build_function(e.fname, e.args, e.distinct)
                            if isinstance(f, AggregateFunction):
                                return match_agg(f)
                            return f
                        return e
                    # an aggregate already built by general function
                    # resolution (e.g. count(*), whose args resolve
                    # immediately) still has to bind to the aggregate's
                    # output or be pulled into it
                    if isinstance(e, AggregateFunction) and e.resolved:
                        return match_agg(e)
                    return e

                def match_agg(f: Expression) -> Expression:
                    for ae in agg.aggregate_exprs:
                        if isinstance(ae, Alias) and \
                                ae.child.semantic_equals(f):
                            return ae.to_attribute()
                    al = Alias(f, _pretty_name(f))
                    extra.append(al)
                    return al.to_attribute()

                # resolve against agg child FIRST for agg args
                def resolve_inner_attrs(e):
                    if isinstance(e, UnresolvedAttribute):
                        a = _resolve_name(e.name_parts, agg.child.output, self.cs)
                        if a is not None:
                            return a
                    return e

                if isinstance(node, Filter):
                    cond = node.condition.transform_up(resolve_inner_attrs)
                    cond = cond.transform_up(resolve)
                    if extra:
                        new_agg = agg.copy(
                            aggregate_exprs=agg.aggregate_exprs + extra)
                        child = _replace_agg(node.child, new_agg)
                        return Project(
                            list(out_attrs),
                            Filter(cond, child))
                    if cond is not node.condition:
                        return node.copy(condition=cond)
                    return node
                else:
                    orders = []
                    changed = False
                    for o in node.orders:
                        c = o.child.transform_up(resolve_inner_attrs)
                        c = c.transform_up(resolve)
                        # a whole order expression that semantically equals
                        # a select-list item binds to that output (q62:
                        # ORDER BY substr(col,1,20) over GROUP BY the same
                        # expression — col no longer exists post-aggregate)
                        for ae in agg.aggregate_exprs:
                            if isinstance(ae, Alias) and not isinstance(
                                    c, AttributeReference) and \
                                    ae.child.semantic_equals(c):
                                c = ae.to_attribute()
                                break
                        if c is not o.child:
                            changed = True
                            orders.append(SortOrder(c, o.ascending, o.nulls_first))
                        else:
                            orders.append(o)
                    if extra:
                        new_agg = agg.copy(
                            aggregate_exprs=agg.aggregate_exprs + extra)
                        child = _replace_agg(node.child, new_agg)
                        return Project(
                            list(out_attrs),
                            Sort(orders, node.is_global, child))
                    if changed:
                        return node.copy(orders=orders)
                    return node
            return node

        return plan.transform_up(rule)


def _skip_alias(p: LogicalPlan) -> LogicalPlan:
    while isinstance(p, SubqueryAlias):
        p = p.child
    return p


def _replace_agg(p: LogicalPlan, new_agg: Aggregate) -> LogicalPlan:
    if isinstance(p, (SubqueryAlias, Filter)):
        return p.copy(child=_replace_agg(p.child, new_agg))
    return new_agg


class _ResolveRelationsDedup(Rule):
    """ResolveRelations for subquery scopes: re-instances attributes that
    collide with the outer scope's ids."""

    def __init__(self, catalog: Catalog, outer_ids: set[int]):
        self.catalog = catalog
        self.outer_ids = set(outer_ids)

    def apply(self, plan: LogicalPlan) -> LogicalPlan:
        def rule(node):
            if isinstance(node, UnresolvedRelation):
                resolved = self.catalog.lookup(node.name_parts)
                # a view body may carry unaliased union-branch literals
                # (`... UNION ALL SELECT 1, 20`): alias them before
                # touching .output (the main path gets this from the
                # ResolveAliases fixed-point; this early access must
                # self-serve)
                resolved = ResolveAliases().apply(resolved)
                overlap = {a.expr_id for a in resolved.output} & self.outer_ids
                if overlap:
                    mapping: dict[int, AttributeReference] = {}
                    resolved = _remap_plan(resolved, mapping, overlap)
                return SubqueryAlias(node.name_parts[-1], resolved)
            return node

        return plan.transform_up(rule)


class ResolveSubqueries(Rule):
    """Resolve subquery plans, allowing leftover references to resolve
    against the OUTER scope (correlation; reference: Analyzer
    ResolveSubquery + outer reference wrapping)."""

    def __init__(self, analyzer: "Analyzer"):
        self.analyzer = analyzer

    def apply(self, plan):
        from .subquery import SubqueryExpression

        an = self.analyzer

        def rule(node):
            if not all(c.resolved for c in node.children):
                return node
            try:
                outer = node.input_attrs()
            except AnalysisException:
                return node

            def needs_alias(p):
                # `x IN (SELECT 1)`: the plan is RESOLVED (a literal
                # resolves trivially) so execute_subquery would be
                # skipped — but its bare project output still needs the
                # alias pass or Project.output raises at optimizer time
                from .logical import GroupingSets

                for n in p.iter_nodes():
                    if isinstance(n, Project):
                        exprs = n.project_list
                    elif isinstance(n, (Aggregate, GroupingSets)):
                        exprs = n.aggregate_exprs
                    else:
                        continue
                    if any(not isinstance(ex, (Alias, AttributeReference,
                                               UnresolvedStar))
                           and ex.resolved for ex in exprs):
                        return True
                return False

            def fix(e):
                if isinstance(e, SubqueryExpression) and \
                        (not e.plan.resolved or needs_alias(e.plan)):
                    sub = an.execute_subquery(e.plan, outer)
                    return e.copy(plan=sub)
                return e

            return node.transform_expressions(fix)

        return plan.transform_up(rule)


class ExtractGenerators(Rule):
    """Project containing explode() → Project over Generate
    (reference: Analyzer ExtractGenerator)."""

    def apply(self, plan):
        from ..expr.expressions import Explode
        from .logical import Generate

        def rule(node):
            if not isinstance(node, Project) or not node.expressions_resolved:
                return node
            gens = [e for pe in node.project_list
                    for e in pe.iter_nodes() if isinstance(e, Explode)]
            if not gens:
                return node
            if len(gens) > 1:
                raise AnalysisException(
                    "only one generator per SELECT is supported")
            gen = gens[0]
            elem = AttributeReference("col", gen.dtype, True)

            def replace(e):
                return elem if e is gen else e

            new_list = []
            for e in node.project_list:
                if isinstance(e, Alias):
                    new_list.append(Alias(e.child.transform_up(replace),
                                          e.name, e.expr_id))
                else:
                    new_list.append(e.transform_up(replace))
            # a computed generator source (explode(map_keys(m)), ...)
            # binds to a column first so Generate only sees attributes
            from ..expr.expressions import (
                Literal as _Lit, Split as _Split,
            )

            src = gen.child
            child_plan = node.child
            simple = isinstance(src, (AttributeReference, _Lit)) or \
                (isinstance(src, _Split)
                 and isinstance(src.child, (AttributeReference, _Lit)))
            if not simple:
                bound = Alias(src, "__gen_src")
                child_plan = Project(
                    list(node.child.output) + [bound], node.child)
                src = bound.to_attribute()
            return Project(new_list, Generate(src, elem, child_plan))

        return plan.transform_up(rule)


class ExtractWindowFromAggregate(Rule):
    """Window functions inside a grouped SELECT evaluate over the grouped
    rows (reference: Analyzer ExtractWindowExpressions' aggregate path):
    Aggregate(g, outs-with-windows) → Project(outs', Aggregate(g, aggs)),
    after which the Project-level window extraction applies."""

    def apply(self, plan):
        from ..expr.window import WindowExpression
        from .logical import GroupingSets

        def rule(node):
            if not isinstance(node, (Aggregate, GroupingSets)) or \
                    not node.expressions_resolved:
                return node
            if not any(isinstance(x, WindowExpression)
                       for e in node.aggregate_exprs
                       for x in e.iter_nodes()):
                return node

            from ..expr.expressions import AggregateFunction as AF
            from ..expr.expressions import Grouping, GroupingID

            # every aggregate function (including those inside window specs)
            # computes in the inner aggregate — EXCEPT a window function
            # head itself: `sum(sum(x)) OVER (...)` aggregates sum(x)
            # inside, then windows over the grouped rows (the TPC-DS
            # q12/q20/q98 shape)
            funcs: list[AF] = []

            def collect(e: Expression):
                if isinstance(e, WindowExpression):
                    for c in e.function.children:
                        collect(c)
                    for p in e.partition_spec:
                        collect(p)
                    for o in e.order_spec:
                        collect(o)
                    return
                if isinstance(e, (AF, Grouping, GroupingID)):
                    if not any(e.semantic_equals(f) for f in funcs):
                        funcs.append(e)
                    return
                for c in e.children:
                    collect(c)

            for e in node.aggregate_exprs:
                collect(e)

            g_aliases: list[tuple[Expression, AttributeReference]] = []
            inner_outs: list[Expression] = []
            for i, g in enumerate(node.grouping_exprs):
                if isinstance(g, AttributeReference):
                    inner_outs.append(g)
                    g_aliases.append((g, g))
                else:
                    al = Alias(g, f"_wg{i}")
                    inner_outs.append(al)
                    g_aliases.append((g, al.to_attribute()))
            f_aliases = [Alias(f, f"_wa{i}") for i, f in enumerate(funcs)]
            inner = node.copy(aggregate_exprs=inner_outs + f_aliases)

            def fix(x: Expression) -> Expression:
                if isinstance(x, (AF, Grouping, GroupingID)):
                    for f, al in zip(funcs, f_aliases):
                        if x.semantic_equals(f):
                            return al.to_attribute()
                for g, a in g_aliases:
                    if x.semantic_equals(g):
                        return a
                return x

            outs = []
            for e in node.aggregate_exprs:
                if isinstance(e, Alias):
                    outs.append(Alias(e.child.transform_up(fix), e.name,
                                      e.expr_id))
                elif isinstance(e, AttributeReference):
                    outs.append(fix(e))
                else:
                    outs.append(e.transform_up(fix))
            return Project(outs, inner)

        return plan.transform_up(rule)


class ExtractWindowExpressions(Rule):
    """Pull WindowExpressions out of projections into Window operators
    (reference: Analyzer ExtractWindowExpressions). Expressions sharing a
    (partition, order) spec evaluate in one Window node; distinct specs
    chain."""

    def apply(self, plan):
        from ..expr.window import WindowExpression
        from .logical import Window

        def rule(node):
            if not isinstance(node, Project) or not node.expressions_resolved:
                return node
            if not any(isinstance(x, WindowExpression)
                       for e in node.project_list for x in e.iter_nodes()):
                return node

            collected: list[Alias] = []

            def extract(x: Expression) -> Expression:
                if isinstance(x, WindowExpression):
                    al = Alias(x, f"_we{len(collected)}")
                    collected.append(al)
                    return al.to_attribute()
                return x

            new_list: list[Expression] = []
            for e in node.project_list:
                if isinstance(e, Alias):
                    if isinstance(e.child, WindowExpression):
                        collected.append(e)
                        new_list.append(e.to_attribute())
                        continue
                    new_list.append(
                        Alias(e.child.transform_up(extract), e.name,
                              e.expr_id))
                else:
                    new_list.append(e.transform_up(extract))

            # group by spec signature
            groups: dict = {}
            order: list = []
            for al in collected:
                sig = al.child.spec_signature()
                if sig not in groups:
                    groups[sig] = []
                    order.append(sig)
                groups[sig].append(al)

            child = node.child
            for sig in order:
                exprs = groups[sig]
                w0: "WindowExpression" = exprs[0].child
                child = Window(exprs, list(w0.partition_spec),
                               list(w0.order_spec), child)
            return Project(new_list, child)

        return plan.transform_up(rule)


class ResolveSortHiddenRefs(Rule):
    """ORDER BY may reference columns of the FROM clause that are not in the
    SELECT list (reference: Analyzer ResolveMissingReferences) — resolve them
    against the project's child and re-project afterwards."""

    def __init__(self, case_sensitive: bool = False):
        self.cs = case_sensitive

    def apply(self, plan):
        def rule(node):
            if not (isinstance(node, Sort) and isinstance(node.child, Project)
                    and node.child.resolved):
                return node
            proj = node.child
            try:
                outputs = proj.output
                hidden = proj.child.output
            except AnalysisException:
                return node
            missing: list[AttributeReference] = []
            changed = [False]

            def resolve(e):
                if isinstance(e, UnresolvedAttribute):
                    a = _resolve_name(e.name_parts, outputs, self.cs)
                    if a is not None:
                        changed[0] = True
                        return a
                    a = _resolve_name(e.name_parts, hidden, self.cs)
                    if a is not None:
                        changed[0] = True
                        if all(x.expr_id != a.expr_id for x in missing) and \
                                all(x.expr_id != a.expr_id for x in outputs):
                            missing.append(a)
                        return a
                    nested = _resolve_struct_path(e.name_parts, hidden,
                                                  self.cs)
                    if nested is not None:
                        # sort on a hidden struct field: carry the BASE
                        # struct column through the inner project
                        changed[0] = True
                        base = nested
                        while not isinstance(base, AttributeReference):
                            base = base.child
                        if all(x.expr_id != base.expr_id
                               for x in missing) and \
                                all(x.expr_id != base.expr_id
                                    for x in outputs):
                            missing.append(base)
                        return nested
                return e

            new_orders = [SortOrder(o.child.transform_up(resolve),
                                    o.ascending, o.nulls_first)
                          for o in node.orders]
            if missing:
                inner = Project(list(proj.project_list) + missing, proj.child)
                return Project(list(outputs),
                               Sort(new_orders, node.is_global, inner))
            if changed[0]:
                return node.copy(orders=new_orders)
            return node

        return plan.transform_up(rule)


class WidenSetOperationTypes(Rule):
    """Positionally coerce Union/Intersect/Except branches to common types
    (reference: TypeCoercion WidenSetOperationTypes)."""

    def apply(self, plan):
        from .logical import Except, Intersect, Union

        def widen(children: list[LogicalPlan]) -> list[LogicalPlan] | None:
            outs = [c.output for c in children]
            n = len(outs[0])
            if any(len(o) != n for o in outs):
                raise AnalysisException(
                    "set operation branches have different column counts",
                    error_class="NUM_COLUMNS_MISMATCH")
            targets = []
            for i in range(n):
                t = outs[0][i].dtype
                for o in outs[1:]:
                    ct = common_type(t, o[i].dtype)
                    if ct is None:
                        raise AnalysisException(
                            f"incompatible set-op column types: "
                            f"{t.simple_string()} vs "
                            f"{o[i].dtype.simple_string()}")
                    t = ct
                targets.append(t)
            changed = False
            new_children = []
            for ci, (c, o) in enumerate(zip(children, outs)):
                if all(a.dtype == t for a, t in zip(o, targets)):
                    new_children.append(c)
                    continue
                projs = []
                for a, t in zip(o, targets):
                    if a.dtype == t:
                        projs.append(a)
                    else:
                        # the FIRST branch defines the set-op's output ids:
                        # keep them so references above (ORDER BY v) stay
                        # bound across the widening rewrite
                        keep = a.expr_id if ci == 0 else None
                        projs.append(Alias(cast_if(a, t), a.name,
                                           expr_id=keep))
                new_children.append(Project(projs, c))
                changed = True
            return new_children if changed else None

        def rule(node):
            if isinstance(node, Union) and node.resolved:
                nc = widen(node.children_plans)
                if nc is not None:
                    return Union(nc)
            from .logical import Except as Ex, Intersect as Ix

            if isinstance(node, (Ix, Ex)) and node.resolved:
                nc = widen([node.left, node.right])
                if nc is not None:
                    return node.copy(left=nc[0], right=nc[1])
            return node

        return plan.transform_up(rule)


class CoerceDecimalArithmetic(Rule):
    """Align decimal scales in Add/Subtract (device repr is scaled int64)."""

    def apply(self, plan):
        def fix(e: Expression) -> Expression:
            from ..expr.expressions import IntervalLiteral

            if isinstance(e, (Add, Subtract)) and e.left.resolved \
                    and e.right.resolved \
                    and not isinstance(e.left, IntervalLiteral) \
                    and not isinstance(e.right, IntervalLiteral):
                lt, rt = e.left.dtype, e.right.dtype
                if isinstance(lt, DecimalType) and isinstance(rt, DecimalType) \
                        and lt.scale != rt.scale:
                    ct = common_type(lt, rt)
                    return type(e)(cast_if(e.left, ct), cast_if(e.right, ct))
            return e

        def rule(node):
            if node.expressions_resolved:
                return node.transform_expressions(fix)
            return node

        return plan.transform_up(rule)


class CheckAnalysis(Rule):
    def apply(self, plan):
        from .subquery import ScalarSubquery, SubqueryExpression

        def check(node):
            for e in node.expressions():
                for sub in e.iter_nodes():
                    if isinstance(sub, SubqueryExpression):
                        if isinstance(sub, ScalarSubquery) and \
                                len(sub.plan.output) != 1:
                            raise AnalysisException(
                                "scalar subquery must return one column")
                        self.apply(sub.plan)
                        continue
                    if isinstance(sub, UnresolvedAttribute):
                        cands = [a.name for a in node.input_attrs()]
                        close = difflib.get_close_matches(sub.name, cands, 3)
                        raise UnresolvedColumnError(sub.name, close or cands[:5])
                    if isinstance(sub, (UnresolvedFunction,)):
                        raise AnalysisException(
                            f"unresolved function {sub.fname}")
                    if isinstance(sub, UnresolvedStar):
                        raise AnalysisException("unexpected * in expression")
            if isinstance(node, UnresolvedRelation):
                raise AnalysisException(f"unresolved relation {node.name}")
            # aggregates: non-grouping bare columns
            if isinstance(node, Aggregate) and node.resolved:
                grouping_ids = set()
                for g in node.grouping_exprs:
                    if isinstance(g, AttributeReference):
                        grouping_ids.add(g.expr_id)
                for e in node.aggregate_exprs:
                    _check_agg_expr(e, grouping_ids, node)
            return None

        plan.foreach(check)
        return plan


def _check_agg_expr(e: Expression, grouping_ids: set[int], agg: Aggregate):
    def matches_grouping(x: Expression) -> bool:
        for g in agg.grouping_exprs:
            gc = g.child if isinstance(g, Alias) else g
            if x.semantic_equals(g) or x.semantic_equals(gc):
                return True
        return False

    def ok(x: Expression, inside_agg: bool) -> bool:
        if not inside_agg and matches_grouping(x):
            return True
        if isinstance(x, AggregateFunction):
            return all(ok(c, True) for c in x.children)
        if isinstance(x, AttributeReference) and not inside_agg:
            if x.expr_id not in grouping_ids:
                raise AnalysisException(
                    f"column {x.name} is neither grouped nor aggregated",
                    error_class="MISSING_AGGREGATION")
            return True
        return all(ok(c, inside_agg) for c in x.children)

    ok(e.child if isinstance(e, Alias) else e, False)


class ResolveUsingJoin(Rule):
    """JOIN USING (c1, …) → equi Join + a projection emitting each
    using column once (reference: Analyzer.commonNaturalJoinProcessing):
    inner/left take the LEFT side's column, right_outer the RIGHT's,
    full_outer coalesces both; semi/anti keep the bare left output."""

    def __init__(self, case_sensitive: bool = False):
        self.cs = case_sensitive

    def apply(self, plan):
        from ..expr.expressions import And, Coalesce
        from .logical import UsingJoin

        def find(attrs, name):
            matches = [a for a in attrs
                       if a.name == name or (
                           not self.cs
                           and a.name.lower() == name.lower())]
            if len({a.expr_id for a in matches}) > 1:
                raise AnalysisException(
                    f"USING column `{name}` is ambiguous",
                    error_class="AMBIGUOUS_REFERENCE")
            if not matches:
                raise AnalysisException(
                    f"USING column {name} not found among "
                    f"[{', '.join(a.name for a in attrs)}]")
            return matches[0]

        def rule(node):
            if not isinstance(node, UsingJoin) or \
                    not (node.left.resolved and node.right.resolved):
                return node
            try:
                lout = node.left.output
                rout = node.right.output
            except AnalysisException:
                return node     # children await alias resolution
            lats = [find(lout, c) for c in node.using_cols]
            rats = [find(rout, c) for c in node.using_cols]
            cond = None
            for la, ra in zip(lats, rats):
                c = EqualTo(la, ra)
                cond = c if cond is None else And(cond, c)
            joined = Join(node.left, node.right, node.join_type, cond)
            jt = joined.join_type
            if jt in ("left_semi", "left_anti"):
                return joined
            # project the JOIN's output attrs (null-padded sides carry
            # nullable=True there — the raw children's attrs would lie
            # to nullability-driven rewrites downstream). Deviation from
            # the reference: the dropped right-side key is NOT kept as a
            # hidden attribute, so `r.k` after USING (k) is unresolvable
            # (Spark's hiddenOutput keeps it addressable).
            by_id = {a.expr_id: a for a in joined.output}
            jl = [by_id[a.expr_id] for a in lats]
            jr = [by_id[a.expr_id] for a in rats]
            if jt == "right_outer":
                keys: list[Expression] = list(jr)
            elif jt == "full_outer":
                keys = [Alias(Coalesce([la, ra]), la.name)
                        for la, ra in zip(jl, jr)]
            else:
                keys = list(jl)
            drop = {a.expr_id for a in lats} | {a.expr_id for a in rats}
            rest = [by_id[a.expr_id] for a in node.left.output
                    if a.expr_id not in drop] + \
                   [by_id[a.expr_id] for a in node.right.output
                    if a.expr_id not in drop]
            return Project(keys + rest, joined)

        return plan.transform_up(rule)


class FoldIntervalArithmetic(Rule):
    """Interval–interval and interval–numeric arithmetic folds to one
    IntervalLiteral (reference: intervalExpressions.scala MultiplyInterval
    / DivideInterval; interval addition in datetimeExpressions). Interval
    values are literal-born here, so the algebra is closed at analysis
    time and +/- against dates/timestamps sees a single interval."""

    def apply(self, plan):
        from ..expr.expressions import (
            Add as _Add, Divide as _Div, IntervalLiteral as _IL,
            Literal as _L, Multiply as _Mul, Subtract as _Sub,
            UnaryMinus as _Neg,
        )

        def num(e):
            return e.value if isinstance(e, _L) and \
                isinstance(e.value, (int, float)) and \
                not isinstance(e.value, bool) else None

        def fold(e):
            if isinstance(e, _Neg) and isinstance(e.child, _IL):
                return e.child.negated()
            if isinstance(e, (_Add, _Sub)) and \
                    isinstance(e.left, _IL) and isinstance(e.right, _IL):
                r = e.right if isinstance(e, _Add) else e.right.negated()
                return _IL(e.left.months + r.months, e.left.days + r.days,
                           e.left.micros + r.micros)
            if isinstance(e, _Mul):
                iv, n = (e.left, num(e.right)) \
                    if isinstance(e.left, _IL) else (e.right, num(e.left))
                if isinstance(iv, _IL) and n is not None:
                    return _IL(int(iv.months * n), int(iv.days * n),
                               int(iv.micros * n))
            if isinstance(e, _Div) and isinstance(e.left, _IL):
                n = num(e.right)
                if n:
                    # day fractions spill into micros (exact day-time
                    # division); calendar months stay integral
                    days_f = e.left.days / n
                    days = int(days_f)
                    micros = int(e.left.micros / n
                                 + (days_f - days) * 86_400_000_000)
                    return _IL(int(e.left.months / n), days, micros)
            return e

        def rule(node):
            return node.transform_expressions(
                lambda x: x.transform_up(fold))

        return plan.transform_up(rule)


class Analyzer(RuleExecutor):
    def __init__(self, catalog: Catalog, case_sensitive: bool = False):
        super().__init__()
        self.catalog = catalog
        self.case_sensitive = case_sensitive

    def batches(self):
        cs = self.case_sensitive
        return [
            Batch("Resolution", FixedPoint(50), [
                ResolveRelations(self.catalog),
                DeduplicateRelations(),
                ResolveUsingJoin(cs),
                ResolveReferences(cs),
                ResolveGroupByAlias(cs),
                ResolveSubqueries(self),
                GlobalAggregates(),
                ResolveAggsInSortHaving(cs),
                ResolveSortHiddenRefs(cs),
                # AFTER the HAVING/ORDER rules: a real column reachable
                # through the aggregate child must win over a session
                # variable of the same name
                ResolveSessionVariables(self.catalog),
                ExtractGenerators(),
                ExtractWindowFromAggregate(),
                ExtractWindowExpressions(),
                FoldIntervalArithmetic(),
                ResolveAliases(),
            ]),
            Batch("Coercion", FixedPoint(10), [
                CoerceDecimalArithmetic(),
                WidenSetOperationTypes(),
            ]),
            Batch("Check", Once(), [CheckAnalysis()]),
        ]

    def execute_subquery(self, plan: LogicalPlan,
                         outer: Sequence[AttributeReference]) -> LogicalPlan:
        """Resolve a subquery plan; unresolved column references fall back to
        the outer scope (correlated references). Relations resolved inside
        the subquery get FRESH attribute ids when they collide with the
        outer scope (same-table self-reference; the reference handles this
        via DeduplicateRelations over the whole tree)."""
        cs = self.case_sensitive
        outer_ids = {a.expr_id for a in outer}
        resolution = Batch("Resolution", FixedPoint(50), [
            _ResolveRelationsDedup(self.catalog, outer_ids),
            DeduplicateRelations(),
            ResolveReferences(cs),
            ResolveGroupByAlias(cs),
            # NO ResolveSessionVariables here: inside a subquery a bare
            # name must resolve inner column → OUTER column (correlation)
            # → variable, so the variable fallback lives in node_fix below
            ResolveSubqueries(self),
            GlobalAggregates(),
            ResolveAggsInSortHaving(cs),
            ResolveSortHiddenRefs(cs),
            ExtractGenerators(),
            ExtractWindowFromAggregate(),
            ExtractWindowExpressions(),
            ResolveAliases(),
        ])
        cur = plan
        for _ in range(50):
            before = cur
            for rule in resolution.rules:
                cur = rule(cur)

            # resolve leftovers: INNER scope first (SQL shadowing), then the
            # outer scope (correlation)
            def node_fix(n):
                if not all(c.resolved for c in n.children):
                    return n
                try:
                    inputs = n.input_attrs()
                except AnalysisException:
                    return n

                def fix(e):
                    if isinstance(e, UnresolvedAttribute):
                        a = _resolve_name(e.name_parts, inputs, cs)
                        if a is not None:
                            return a
                        a = _resolve_name(e.name_parts, outer, cs)
                        if a is not None:
                            return a
                        if len(e.name_parts) == 1:
                            # last resort: session variable (column —
                            # inner or outer — always wins over it)
                            hit = getattr(self.catalog, "variables",
                                          {}).get(e.name_parts[0].lower())
                            if hit is not None:
                                return hit
                    return e

                return n.transform_expressions(
                    lambda ex: ex.transform_up(fix))

            cur = cur.transform_up(node_fix)
            if cur.fast_equals(before):
                break
        cur = CoerceDecimalArithmetic()(cur)
        return cur
