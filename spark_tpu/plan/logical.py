"""Logical plan nodes.

Role of the reference's sqlcat/plans/logical/basicLogicalOperators.scala
(Project, Filter, Aggregate, Join, Sort, Limit, Union, SubqueryAlias,
LocalRelation, Range...). Same lazy-tree architecture — SURVEY.md §7 keeps
Spark's logical layer because it is backend-agnostic.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

from ..errors import AnalysisException
from ..types import StructField, StructType, int64
from .tree import TreeNode
from ..expr.expressions import (
    Alias, AttributeReference, Expression, SortOrder,
)

__all__ = [
    "LogicalPlan", "LeafNode", "UnaryNode", "BinaryNode",
    "UnresolvedRelation", "LogicalRelation", "LocalRelation", "RangeRelation",
    "Project", "Filter", "Aggregate", "Sort", "Limit", "Offset", "Sample",
    "Join", "Union", "Distinct", "SubqueryAlias", "Repartition",
    "OneRowRelation", "Window", "Expand",
]


class LogicalPlan(TreeNode):
    @property
    def output(self) -> list[AttributeReference]:
        raise NotImplementedError(type(self).__name__)

    @property
    def resolved(self) -> bool:
        return self.expressions_resolved and all(c.resolved for c in self.children)

    @property
    def expressions_resolved(self) -> bool:
        return all(e.resolved for e in self.expressions())

    def expressions(self) -> list[Expression]:
        """All expressions directly held by this node."""
        out = []
        for k, v in self.__dict__.items():
            if k in self.child_fields:
                continue
            if isinstance(v, Expression):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                out.extend(x for x in v if isinstance(x, Expression))
        return out

    def map_expressions(self, f) -> "LogicalPlan":
        changed = False
        overrides: dict[str, Any] = {}
        for k, v in self.__dict__.items():
            if k in self.child_fields or k.startswith("_"):
                continue
            if isinstance(v, Expression):
                nv = f(v)
                if nv is not v:
                    changed = True
                overrides[k] = nv
            elif isinstance(v, (list, tuple)) and any(isinstance(x, Expression) for x in v):
                nl = [f(x) if isinstance(x, Expression) else x for x in v]
                if any(a is not b for a, b in zip(nl, v)):
                    changed = True
                overrides[k] = type(v)(nl) if isinstance(v, tuple) else nl
        return self.copy(**overrides) if changed else self

    def transform_expressions(self, rule) -> "LogicalPlan":
        return self.map_expressions(lambda e: e.transform_up(rule))

    def input_attrs(self) -> list[AttributeReference]:
        out = []
        for c in self.children:
            out.extend(c.output)
        return out

    def schema(self) -> StructType:
        return StructType([
            StructField(a.name, a.dtype, a.nullable) for a in self.output])

    def stats_rows(self) -> int | None:
        """Crude row-count estimate (reference: statsEstimation/)."""
        ests = [c.stats_rows() for c in self.children]
        if any(e is None for e in ests):
            return None
        return sum(ests) if ests else None


class LeafNode(LogicalPlan):
    child_fields = ()


class UnaryNode(LogicalPlan):
    child_fields = ("child",)

    @property
    def output(self) -> list[AttributeReference]:
        return self.child.output


class BinaryNode(LogicalPlan):
    child_fields = ("left", "right")


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------

class UnresolvedRelation(LeafNode):
    def __init__(self, name_parts: Sequence[str]):
        self.name_parts = tuple(name_parts)

    @property
    def name(self) -> str:
        return ".".join(self.name_parts)

    @property
    def resolved(self) -> bool:
        return False

    @property
    def output(self):
        raise AnalysisException(f"unresolved relation {self.name}")


class LogicalRelation(LeafNode):
    """A resolved data source (reference: execution/datasources/LogicalRelation)."""

    def __init__(self, source, attrs: list[AttributeReference], name: str = ""):
        self.source = source  # duck-typed: .schema, .partitions(), .estimated_rows
        self.attrs = attrs
        self.name = name

    @property
    def output(self):
        return self.attrs

    def _data_args(self):
        return (("name", self.name), ("ids", tuple(a.expr_id for a in self.attrs)))

    def stats_rows(self):
        return getattr(self.source, "estimated_rows", None)

    def simple_string(self):
        return f"Relation[{self.name}]({', '.join(a.name for a in self.attrs)})"


class LocalRelation(LeafNode):
    """In-memory rows (reference: sqlcat/plans/logical/LocalRelation.scala)."""

    def __init__(self, attrs: list[AttributeReference], table):
        self.attrs = attrs
        self.table = table  # pyarrow.Table

    @property
    def output(self):
        return self.attrs

    def _data_args(self):
        return (("ids", tuple(a.expr_id for a in self.attrs)),)

    def stats_rows(self):
        return self.table.num_rows


class OneRowRelation(LeafNode):
    @property
    def output(self):
        return []

    def stats_rows(self):
        return 1


class RangeRelation(LeafNode):
    """spark.range() (reference: sqlcat/plans/logical/Range)."""

    def __init__(self, start: int, end: int, step: int, num_partitions: int,
                 attr: AttributeReference | None = None):
        self.start = start
        self.end = end
        self.step = step
        self.num_partitions = num_partitions
        self.attr = attr or AttributeReference("id", int64, nullable=False)

    @property
    def output(self):
        return [self.attr]

    def stats_rows(self):
        return max(0, (self.end - self.start + self.step - 1) // self.step)


# ---------------------------------------------------------------------------
# Unary operators
# ---------------------------------------------------------------------------

class Project(UnaryNode):
    def __init__(self, project_list: Sequence[Expression], child: LogicalPlan):
        self.project_list = list(project_list)
        self.child = child

    @property
    def output(self):
        out = []
        for e in self.project_list:
            if isinstance(e, Alias):
                out.append(e.to_attribute())
            elif isinstance(e, AttributeReference):
                out.append(e)
            else:
                raise AnalysisException(
                    f"project expression needs alias: {e.simple_string()}")
        return out

    def stats_rows(self):
        return self.child.stats_rows()


class Filter(UnaryNode):
    def __init__(self, condition: Expression, child: LogicalPlan):
        self.condition = condition
        self.child = child

    def stats_rows(self):
        r = self.child.stats_rows()
        return None if r is None else max(1, r // 4)


class Aggregate(UnaryNode):
    """grouping_exprs + aggregate_exprs (the output list mixing grouping
    attrs and Alias(AggregateFunction) — reference:
    sqlcat/plans/logical/basicLogicalOperators.scala Aggregate)."""

    def __init__(self, grouping_exprs: Sequence[Expression],
                 aggregate_exprs: Sequence[Expression], child: LogicalPlan):
        self.grouping_exprs = list(grouping_exprs)
        self.aggregate_exprs = list(aggregate_exprs)
        self.child = child

    @property
    def output(self):
        out = []
        for e in self.aggregate_exprs:
            if isinstance(e, Alias):
                out.append(e.to_attribute())
            elif isinstance(e, AttributeReference):
                out.append(e)
            else:
                raise AnalysisException(
                    f"aggregate expression needs alias: {e.simple_string()}")
        return out

    def stats_rows(self):
        r = self.child.stats_rows()
        if not self.grouping_exprs:
            return 1
        return None if r is None else max(1, r // 10)


class Sort(UnaryNode):
    def __init__(self, orders: Sequence[SortOrder], is_global: bool,
                 child: LogicalPlan):
        self.orders = list(orders)
        self.is_global = is_global
        self.child = child

    def stats_rows(self):
        return self.child.stats_rows()


class Limit(UnaryNode):
    def __init__(self, n: int, child: LogicalPlan):
        self.n = n
        self.child = child

    def stats_rows(self):
        r = self.child.stats_rows()
        return self.n if r is None else min(self.n, r)


class Offset(UnaryNode):
    def __init__(self, n: int, child: LogicalPlan):
        self.n = n
        self.child = child


class Sample(UnaryNode):
    def __init__(self, fraction: float, seed: int, child: LogicalPlan):
        self.fraction = fraction
        self.seed = seed
        self.child = child


class Distinct(UnaryNode):
    def __init__(self, child: LogicalPlan):
        self.child = child


class EventTimeWatermark(UnaryNode):
    """withWatermark(column, delay) marker (role of the reference's
    EventTimeWatermark logical node, sqlcat/plans/logical/
    EventTimeWatermark.scala): batch execution passes through; the
    streaming runtime reads it to drive late-row filtering, state
    eviction, and outer-join finalization per input stream."""

    def __init__(self, column: str, delay_us: int, child: LogicalPlan):
        self.column = column
        self.delay_us = delay_us
        self.child = child


class SubqueryAlias(UnaryNode):
    def __init__(self, alias: str, child: LogicalPlan):
        self.alias = alias
        self.child = child

    @property
    def output(self):
        return [AttributeReference(a.name, a.dtype, a.nullable, a.expr_id,
                                   qualifier=(self.alias,))
                for a in self.child.output]

    def stats_rows(self):
        return self.child.stats_rows()


class WithCTE(UnaryNode):
    """Top-level holder for CTEs the parser chose to MATERIALIZE rather
    than inline: `materializations` is [(unique_name, plan)] in
    definition order; `child` references each by its unique name.
    A CTE instantiated N times would inline its subtree N times — for
    q64's 18-table cross_sales that doubles an already-huge XLA program.
    The session executes each plan once and splices the result in as an
    in-memory relation (role of Spark's WithCTE + CTERelationRef with
    spark.sql.optimizer.cteInline semantics,
    sqlcat/optimizer/InlineCTE.scala / plans/logical/ctes.scala)."""

    def __init__(self, materializations, child: LogicalPlan):
        self.materializations = list(materializations)
        self.child = child

    @property
    def output(self):
        return self.child.output


class Repartition(UnaryNode):
    def __init__(self, num_partitions: int | None, shuffle: bool,
                 partition_exprs: Sequence[Expression], child: LogicalPlan):
        self.num_partitions = num_partitions
        self.shuffle = shuffle
        self.partition_exprs = list(partition_exprs)
        self.child = child


class Window(UnaryNode):
    """Window operator: window_exprs are Alias(WindowExpression) appended to
    child output (reference: sqlcat/plans/logical Window)."""

    def __init__(self, window_exprs: Sequence[Expression],
                 partition_spec: Sequence[Expression],
                 order_spec: Sequence[SortOrder], child: LogicalPlan):
        self.window_exprs = list(window_exprs)
        self.partition_spec = list(partition_spec)
        self.order_spec = list(order_spec)
        self.child = child

    @property
    def output(self):
        return self.child.output + [e.to_attribute() for e in self.window_exprs]


class Generate(UnaryNode):
    """Row generator (reference: sqlcat/plans/logical Generate over
    Explode): appends the generator's element column, expanding each input
    row by its element count."""

    def __init__(self, generator: Expression, element_attr, child: LogicalPlan):
        self.generator = generator  # e.g. Split(col, delim)
        self.element_attr = element_attr
        self.child = child

    @property
    def output(self):
        return self.child.output + [self.element_attr]


class PythonEval(UnaryNode):
    """Append host-evaluated Python UDF columns (reference:
    ArrowEvalPythonExec's logical shadow)."""

    def __init__(self, udf_aliases: Sequence[Expression], child: LogicalPlan):
        self.udf_aliases = list(udf_aliases)
        self.child = child

    @property
    def output(self):
        return self.child.output + [a.to_attribute() for a in self.udf_aliases]


class Expand(UnaryNode):
    """Multiplies each row by projection sets (rollup/cube/count-distinct;
    reference: sqlcat/plans/logical Expand)."""

    def __init__(self, projections: Sequence[Sequence[Expression]],
                 out_attrs: list[AttributeReference], child: LogicalPlan):
        self.projections = [list(p) for p in projections]
        self.out_attrs = out_attrs
        self.child = child

    @property
    def output(self):
        return self.out_attrs


# ---------------------------------------------------------------------------
# Binary / n-ary
# ---------------------------------------------------------------------------

JOIN_TYPES = ("inner", "left_outer", "right_outer", "full_outer", "left_semi",
              "left_anti", "cross")


def normalize_join_type(jt: str) -> str:
    s = jt.lower().replace("_", "").replace(" ", "")
    mapping = {
        "inner": "inner", "cross": "cross",
        "left": "left_outer", "leftouter": "left_outer",
        "right": "right_outer", "rightouter": "right_outer",
        "full": "full_outer", "fullouter": "full_outer", "outer": "full_outer",
        "semi": "left_semi", "leftsemi": "left_semi",
        "anti": "left_anti", "leftanti": "left_anti",
    }
    if s not in mapping:
        raise AnalysisException(f"unsupported join type {jt}")
    return mapping[s]


class UsingJoin(BinaryNode):
    """JOIN ... USING (c1, ...) before resolution (reference: the
    UsingJoin hint consumed by Analyzer.commonNaturalJoinProcessing).
    ResolveUsingJoin rewrites it into an equi Join + a projection that
    emits each using column ONCE."""

    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 join_type: str, using_cols: list):
        self.left = left
        self.right = right
        self.join_type = normalize_join_type(join_type)
        self.using_cols = list(using_cols)

    @property
    def resolved(self):
        return False    # always rewritten by ResolveUsingJoin

    @property
    def output(self):
        from ..errors import AnalysisException

        raise AnalysisException(
            f"unresolved USING join on {self.using_cols}")


class Join(BinaryNode):
    def __init__(self, left: LogicalPlan, right: LogicalPlan, join_type: str,
                 condition: Expression | None):
        self.left = left
        self.right = right
        self.join_type = normalize_join_type(join_type)
        self.condition = condition

    @property
    def output(self):
        jt = self.join_type
        if jt in ("left_semi", "left_anti"):
            return self.left.output
        lo = self.left.output
        ro = self.right.output
        if jt in ("right_outer",):
            lo = [a.with_nullability(True) for a in lo]
        if jt in ("left_outer",):
            ro = [a.with_nullability(True) for a in ro]
        if jt == "full_outer":
            lo = [a.with_nullability(True) for a in lo]
            ro = [a.with_nullability(True) for a in ro]
        return lo + ro

    def stats_rows(self):
        l = self.left.stats_rows()
        r = self.right.stats_rows()
        if l is None or r is None:
            return None
        return max(l, r)


class Intersect(BinaryNode):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 is_all: bool = False):
        self.left = left
        self.right = right
        self.is_all = is_all

    @property
    def output(self):
        return self.left.output


class Except(BinaryNode):
    def __init__(self, left: LogicalPlan, right: LogicalPlan,
                 is_all: bool = False):
        self.left = left
        self.right = right
        self.is_all = is_all

    @property
    def output(self):
        return self.left.output


class GroupingSets(UnaryNode):
    """GROUP BY ROLLUP/CUBE/GROUPING SETS — rewritten post-resolution into a
    Union of Aggregates (the reference lowers via Expand,
    sqlcat/analysis/ResolveGroupingAnalytics). `sets` holds INDICES into
    grouping_exprs so resolution machinery sees one expression list."""

    def __init__(self, sets: Sequence[Sequence[int]],
                 grouping_exprs: Sequence[Expression],
                 aggregate_exprs: Sequence[Expression], child: LogicalPlan):
        self.sets = [list(s) for s in sets]
        self.grouping_exprs = list(grouping_exprs)
        self.aggregate_exprs = list(aggregate_exprs)
        self.child = child

    @property
    def output(self):
        return Aggregate(self.grouping_exprs, self.aggregate_exprs,
                         self.child).output


class Union(LogicalPlan):
    child_fields = ("children_plans",)

    def __init__(self, children_plans: Sequence[LogicalPlan]):
        self.children_plans = list(children_plans)

    @property
    def output(self):
        first = self.children_plans[0].output
        # nullability is the OR across children
        nullables = [any(c.output[i].nullable for c in self.children_plans)
                     for i in range(len(first))]
        return [a.with_nullability(n) for a, n in zip(first, nullables)]
