"""Statistics framework: table/column stats + plan-level estimation.

Role of the reference's stats estimation layer
(sqlcat/plans/logical/statsEstimation/ — BasicStatsPlanVisitor,
FilterEstimation, JoinEstimation; column stats from ANALYZE TABLE ...
COMPUTE STATISTICS FOR COLUMNS persisted in the catalog,
sqlcat/catalog/interface.scala CatalogStatistics). TPU-first deltas:
stats are computed COLUMNAR from the Arrow table in one pass (no row
scans), and the estimator is a pure function over the logical plan used
by ReorderJoins' greedy cost model and the broadcast-threshold pick.

Cardinality model (the reference's, simplified):
  Filter   — selectivity per conjunct: equality 1/ndv, range from
             min/max interpolation, null checks from null_count; 0.25
             fallback. Conjuncts multiply.
  Join     — |L ⋈ R| = |L|·|R| / max(ndv(lk), ndv(rk)) over equi keys.
  Aggregate— min(Π ndv(group cols), |child|·0.9).
  Project/others — pass-through.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

from ..expr.expressions import (
    And, AttributeReference, EqualTo, Expression, GreaterThan,
    GreaterThanOrEqual, In, IsNotNull, IsNull, LessThan, LessThanOrEqual,
    Literal, Not, Or,
)
from . import logical as L


@dataclass
class ColumnStat:
    """Per-column statistics (CatalogColumnStat role)."""

    distinct_count: Optional[int] = None
    min: object = None
    max: object = None
    null_count: Optional[int] = None

    @staticmethod
    def from_arrow(col) -> "ColumnStat":
        import pyarrow.compute as pc

        try:
            ndv = pc.count_distinct(col).as_py()
        except Exception:
            ndv = None
        nulls = col.null_count
        mn = mx = None
        try:
            mm = pc.min_max(col)
            mn, mx = mm["min"].as_py(), mm["max"].as_py()
        except Exception:
            pass
        return ColumnStat(ndv, mn, mx, nulls)


@dataclass
class Statistics:
    """Plan-level statistics (logical.Statistics role)."""

    row_count: Optional[int] = None
    col_stats: dict = None  # attr name (lower) → ColumnStat

    def __post_init__(self):
        if self.col_stats is None:
            self.col_stats = {}


def compute_table_stats(table, columns=None) -> Statistics:
    """One columnar pass over an Arrow table (ANALYZE TABLE role)."""
    cols = {}
    for name in (columns or table.column_names):
        if name in table.column_names:
            cols[name.lower()] = ColumnStat.from_arrow(table.column(name))
    return Statistics(table.num_rows, cols)


# ---------------------------------------------------------------------------
# Estimation
# ---------------------------------------------------------------------------

_FALLBACK_SELECTIVITY = 0.25


def _attr_of(e: Expression):
    return e if isinstance(e, AttributeReference) else None


def _num(v):
    import datetime

    if isinstance(v, bool):
        return float(v)
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, (datetime.date, datetime.datetime)):
        return v.toordinal() if isinstance(v, datetime.date) and \
            not isinstance(v, datetime.datetime) else v.timestamp()
    return None


def _range_selectivity(cs: ColumnStat, op: str, value) -> float:
    lo, hi, v = _num(cs.min), _num(cs.max), _num(value)
    if lo is None or hi is None or v is None or hi <= lo:
        return _FALLBACK_SELECTIVITY
    frac = (v - lo) / (hi - lo)
    frac = min(1.0, max(0.0, frac))
    if op in ("<", "<="):
        return frac
    return 1.0 - frac


def _conjunct_selectivity(c: Expression, stats: Statistics) -> float:
    def col_stat(e):
        a = _attr_of(e)
        return stats.col_stats.get(a.name.lower()) if a is not None else None

    if isinstance(c, EqualTo):
        for side, other in ((c.left, c.right), (c.right, c.left)):
            cs = col_stat(side)
            if cs is not None and isinstance(other, Literal) and \
                    cs.distinct_count:
                return 1.0 / cs.distinct_count
    if isinstance(c, (LessThan, LessThanOrEqual)):
        cs = col_stat(c.left)
        if cs is not None and isinstance(c.right, Literal):
            return _range_selectivity(cs, "<", c.right.value)
    if isinstance(c, (GreaterThan, GreaterThanOrEqual)):
        cs = col_stat(c.left)
        if cs is not None and isinstance(c.right, Literal):
            return _range_selectivity(cs, ">", c.right.value)
    if isinstance(c, In):
        cs = col_stat(c.child)
        if cs is not None and cs.distinct_count and c.items:
            return min(1.0, len(c.items) / cs.distinct_count)
    if isinstance(c, IsNull):
        cs = col_stat(c.child)
        if cs is not None and cs.null_count is not None and stats.row_count:
            return cs.null_count / max(stats.row_count, 1)
    if isinstance(c, IsNotNull):
        cs = col_stat(c.child)
        if cs is not None and cs.null_count is not None and stats.row_count:
            return 1.0 - cs.null_count / max(stats.row_count, 1)
    if isinstance(c, Not):
        return 1.0 - _conjunct_selectivity(c.child, stats)
    if isinstance(c, Or):
        a = _conjunct_selectivity(c.left, stats)
        b = _conjunct_selectivity(c.right, stats)
        return min(1.0, a + b - a * b)
    if isinstance(c, And):
        return _conjunct_selectivity(c.left, stats) * \
            _conjunct_selectivity(c.right, stats)
    return _FALLBACK_SELECTIVITY


def estimate(plan: L.LogicalPlan, catalog_stats=None) -> Statistics:
    """Bottom-up statistics for a logical plan (BasicStatsPlanVisitor).
    `catalog_stats`: name(lower) → Statistics from ANALYZE TABLE."""
    catalog_stats = catalog_stats or {}

    def go(node) -> Statistics:
        attached = getattr(node, "_cbo_stats", None)  # ANALYZE TABLE
        if attached is not None:
            return attached
        if isinstance(node, L.LocalRelation):
            return Statistics(node.table.num_rows if node.table is not None
                              else None)
        if isinstance(node, L.LogicalRelation):
            named = catalog_stats.get(node.name.lower())
            if named is not None:
                return named
            return Statistics(getattr(node.source, "estimated_rows", None))
        if isinstance(node, L.Filter):
            child = go(node.child)
            if child.row_count is None:
                return child
            from .optimizer import split_conjuncts

            sel = 1.0
            for c in split_conjuncts(node.condition):
                sel *= _conjunct_selectivity(c, child)
            return Statistics(max(1, int(child.row_count * sel)),
                              child.col_stats)
        if isinstance(node, L.Join):
            lt, rt = go(node.left), go(node.right)
            if lt.row_count is None or rt.row_count is None:
                return Statistics(None)
            merged = {**lt.col_stats, **rt.col_stats}
            if node.join_type in ("left_semi", "left_anti"):
                return Statistics(max(1, lt.row_count // 2), lt.col_stats)
            if node.condition is None:
                return Statistics(lt.row_count * rt.row_count, merged)
            from .optimizer import split_conjuncts

            denom = 1
            for c in split_conjuncts(node.condition):
                if isinstance(c, EqualTo):
                    la, ra = _attr_of(c.left), _attr_of(c.right)
                    nl = lt.col_stats.get(la.name.lower()) if la else None
                    nr = rt.col_stats.get(ra.name.lower()) if ra else None
                    nds = [s.distinct_count for s in (nl, nr)
                           if s is not None and s.distinct_count]
                    if nds:
                        denom = max(denom, max(nds))
            est = max(1, (lt.row_count * rt.row_count) // max(denom, 1))
            if node.join_type in ("left_outer", "full_outer"):
                est = max(est, lt.row_count)
            if node.join_type in ("right_outer", "full_outer"):
                est = max(est, rt.row_count)
            return Statistics(est, merged)
        if isinstance(node, (L.Aggregate, L.Distinct)):
            child = go(node.child)
            if child.row_count is None:
                return child
            groups = getattr(node, "grouping_exprs", None)
            if groups is None:  # Distinct
                return Statistics(max(1, int(child.row_count * 0.9)),
                                  child.col_stats)
            if not groups:
                return Statistics(1, child.col_stats)
            ndv = 1
            for g in groups:
                a = _attr_of(g)
                cs = child.col_stats.get(a.name.lower()) if a else None
                ndv *= cs.distinct_count if cs and cs.distinct_count \
                    else int(math.sqrt(child.row_count) + 1)
            return Statistics(
                max(1, min(ndv, int(child.row_count * 0.9))),
                child.col_stats)
        if isinstance(node, L.Limit):
            child = go(node.child)
            n = getattr(node, "limit", None) or getattr(node, "n", None)
            if child.row_count is not None and isinstance(n, int):
                return Statistics(min(child.row_count, n), child.col_stats)
            return child
        if isinstance(node, L.Union):
            subs = [go(c) for c in node.children]
            if any(s.row_count is None for s in subs):
                return Statistics(None)
            return Statistics(sum(s.row_count for s in subs))
        # pass-through unary default
        kids = node.children
        if len(kids) == 1:
            return go(kids[0])
        if not kids:
            return Statistics(node.stats_rows())
        return Statistics(node.stats_rows())

    return go(plan)
