"""Logical optimizer.

Role of the reference's Optimizer (sqlcat/optimizer/Optimizer.scala:51,
defaultBatches :100 — ~120 rules). The subset that matters for TPC-DS-class
plans (SURVEY.md §7 step 3): predicate pushdown (through projects, aliases,
joins, unions, aggregates), filter combination/pruning, column pruning,
constant folding, boolean simplification, cast simplification, distinct→
aggregate, collapse projects, and empty-relation propagation.
"""

from __future__ import annotations

import datetime
import math
from typing import Sequence

from ..types import BooleanType, NullType, boolean
from .logical import (
    Aggregate, Distinct, Filter, Join, Limit, LocalRelation, LogicalPlan,
    LogicalRelation, Project, RangeRelation, Repartition, Sample, Sort,
    SubqueryAlias, Union, Window, Expand, Offset,
)
from .tree import Batch, FixedPoint, Once, Rule, RuleExecutor
from ..expr.expressions import (
    Add, Alias, And, AttributeReference, BinaryComparison, Cast, CaseWhen,
    Coalesce, Divide, EqualTo, Expression, GreaterThan, GreaterThanOrEqual,
    In, IsNotNull, IsNull, LessThan, LessThanOrEqual, Literal, Multiply, Not,
    NotEqualTo, Or, Remainder, SortOrder, Subtract, UnaryMinus,
    AggregateFunction,
)

__all__ = ["Optimizer", "split_conjuncts", "substitute_attrs"]


def split_conjuncts(e: Expression) -> list[Expression]:
    if isinstance(e, And):
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def join_conjuncts(es: Sequence[Expression]) -> Expression | None:
    out = None
    for e in es:
        out = e if out is None else And(out, e)
    return out


def substitute_attrs(e: Expression, mapping: dict[int, Expression]) -> Expression:
    def rule(x):
        if isinstance(x, AttributeReference) and x.expr_id in mapping:
            return mapping[x.expr_id]
        return x

    return e.transform_up(rule)


def alias_map(project_list: Sequence[Expression]) -> dict[int, Expression]:
    m: dict[int, Expression] = {}
    for e in project_list:
        if isinstance(e, Alias):
            m[e.expr_id] = e.child
    return m


# ---------------------------------------------------------------------------
# Constant folding
# ---------------------------------------------------------------------------

def const_value(e: Expression):
    """Evaluate a literal-only expression host-side. Returns (ok, value)."""
    if isinstance(e, Literal):
        return True, e.value
    if isinstance(e, Cast):
        ok, v = const_value(e.child)
        if not ok:
            return False, None
        try:
            return True, _py_cast(v, e.to)
        except Exception:
            return False, None
    if isinstance(e, UnaryMinus):
        ok, v = const_value(e.child)
        return (True, -v) if ok and v is not None else (ok, None)
    if isinstance(e, Not):
        ok, v = const_value(e.child)
        return (True, (not v) if v is not None else None) if ok else (False, None)
    binops = {
        Add: lambda a, b: a + b, Subtract: lambda a, b: a - b,
        Multiply: lambda a, b: a * b,
        Divide: lambda a, b: a / b if b else None,
        Remainder: lambda a, b: math.fmod(a, b) if b else None,
        EqualTo: lambda a, b: a == b, NotEqualTo: lambda a, b: a != b,
        LessThan: lambda a, b: a < b, LessThanOrEqual: lambda a, b: a <= b,
        GreaterThan: lambda a, b: a > b, GreaterThanOrEqual: lambda a, b: a >= b,
    }
    for cls, fn in binops.items():
        if type(e) is cls:
            ok1, a = const_value(e.left)
            ok2, b = const_value(e.right)
            if not (ok1 and ok2):
                return False, None
            if a is None or b is None:
                return True, None
            try:
                return True, fn(a, b)
            except Exception:
                return False, None
    return False, None


def _py_cast(v, to):
    from ..types import (
        BooleanType, DateType, FractionalType, IntegralType, StringType,
        TimestampType, DecimalType,
    )

    if v is None:
        return None
    if isinstance(to, IntegralType):
        return int(v)
    if isinstance(to, DecimalType):
        # fold to an exact Decimal at the TARGET scale — handing the raw
        # float through made Literal treat 1.25 as scaled-int 1 (0.01)
        import decimal as _d

        dv = v if isinstance(v, _d.Decimal) else _d.Decimal(str(v))
        return dv.quantize(_d.Decimal(1).scaleb(-to.scale),
                           rounding=_d.ROUND_HALF_UP)
    if isinstance(to, FractionalType):
        return float(v)
    if isinstance(to, BooleanType):
        return bool(v)
    if isinstance(to, StringType):
        return str(v)
    if isinstance(to, DateType):
        if isinstance(v, str):
            return datetime.date.fromisoformat(v.strip()[:10])
        return v
    if isinstance(to, TimestampType):
        if isinstance(v, str):
            return datetime.datetime.fromisoformat(v.strip())
        return v
    raise ValueError


class ConstantFolding(Rule):
    def apply(self, plan):
        def fold(e: Expression) -> Expression:
            if isinstance(e, Literal) or not e.resolved:
                return e
            if isinstance(e, (AggregateFunction, Alias, AttributeReference,
                              SortOrder)):
                return e
            if any(isinstance(c, AttributeReference) for c in e.iter_nodes()):
                return e
            ok, v = const_value(e)
            if ok:
                try:
                    dt = e.dtype
                    if isinstance(dt, NullType) and v is not None:
                        return Literal(v)
                    return Literal(v, dt) if v is not None else Literal(None, dt)
                except Exception:
                    return e
            return e

        def rule(node):
            if node.expressions_resolved:
                return node.transform_expressions(fold)
            return node

        return plan.transform_up(rule)


class BooleanSimplification(Rule):
    def apply(self, plan):
        t = lambda e: isinstance(e, Literal) and e.value is True
        f = lambda e: isinstance(e, Literal) and e.value is False

        def split_disjuncts(e: Expression) -> list[Expression]:
            if isinstance(e, Or):
                return split_disjuncts(e.left) + split_disjuncts(e.right)
            return [e]

        def simp(e: Expression) -> Expression:
            if isinstance(e, And):
                if t(e.left):
                    return e.right
                if t(e.right):
                    return e.left
                if f(e.left) or f(e.right):
                    return Literal(False)
            if isinstance(e, Or):
                if f(e.left):
                    return e.right
                if f(e.right):
                    return e.left
                if t(e.left) or t(e.right):
                    return Literal(True)
                # common-factor extraction (reference: BooleanSimplification
                # "(a && b) || (a && c) => a && (b || c)") — load-bearing
                # for TPC-DS q13/q48/q85, where all join keys sit inside OR
                # branches and factoring them out re-enables equi-joins
                branches = [split_conjuncts(b) for b in split_disjuncts(e)]
                if len(branches) > 1:
                    common = [c for c in branches[0]
                              if all(any(c.semantic_equals(x) for x in b)
                                     for b in branches[1:])]
                    if common:
                        residuals = []
                        for b in branches:
                            rest = [x for x in b
                                    if not any(x.semantic_equals(c)
                                               for c in common)]
                            residuals.append(join_conjuncts(rest) or
                                             Literal(True))
                        out = join_conjuncts(common)
                        if not any(t(r) for r in residuals):
                            disj = residuals[0]
                            for r in residuals[1:]:
                                disj = Or(disj, r)
                            out = And(out, disj)
                        return out
            if isinstance(e, Not):
                if t(e.child):
                    return Literal(False)
                if f(e.child):
                    return Literal(True)
                if isinstance(e.child, Not):
                    return e.child.child
            return e

        def rule(node):
            if node.expressions_resolved:
                return node.transform_expressions(simp)
            return node

        return plan.transform_up(rule)


class SimplifyCasts(Rule):
    def apply(self, plan):
        def simp(e):
            if isinstance(e, Cast) and e.child.resolved and e.child.dtype == e.to:
                return e.child
            return e

        def rule(node):
            if node.expressions_resolved:
                return node.transform_expressions(simp)
            return node

        return plan.transform_up(rule)


class CombineFilters(Rule):
    def apply(self, plan):
        def rule(node):
            if isinstance(node, Filter) and isinstance(node.child, Filter):
                return Filter(And(node.child.condition, node.condition),
                              node.child.child)
            return node

        return plan.transform_up(rule)


class PushDownPredicates(Rule):
    """Push filters through Project/SubqueryAlias/Union and into Join sides
    (reference: Optimizer PushDownPredicates + PushPredicateThroughJoin)."""

    def apply(self, plan):
        def rule(node):
            if not isinstance(node, Filter):
                return node
            child = node.child
            if isinstance(child, Project):
                if any(isinstance(e, AggregateFunction)
                       for pe in child.project_list
                       for e in pe.iter_nodes()):
                    return node
                m = alias_map(child.project_list)
                new_cond = substitute_attrs(node.condition, m)
                return Project(child.project_list, Filter(new_cond, child.child))
            if isinstance(child, SubqueryAlias):
                return SubqueryAlias(child.alias, Filter(node.condition, child.child))
            if isinstance(child, Union):
                return Union([Filter(_remap_union_cond(node.condition, child, i), c)
                              for i, c in enumerate(child.children_plans)])
            if isinstance(child, Join):
                return self._push_into_join(node, child)
            if isinstance(child, Aggregate):
                # push predicates that reference only grouping attrs
                group_ids = {g.expr_id for g in child.grouping_exprs
                             if isinstance(g, AttributeReference)}
                # aliases of grouping exprs in output
                out_to_group: dict[int, Expression] = {}
                for e in child.aggregate_exprs:
                    if isinstance(e, Alias):
                        out_to_group[e.expr_id] = e.child
                    elif isinstance(e, AttributeReference):
                        out_to_group[e.expr_id] = e
                pushable, kept = [], []
                for c in split_conjuncts(node.condition):
                    refs = c.references()
                    mapped = substitute_attrs(c, out_to_group)
                    if any(isinstance(x, AggregateFunction)
                           for x in mapped.iter_nodes()):
                        kept.append(c)
                        continue
                    mrefs = mapped.references()
                    child_ids = {a.expr_id for a in child.child.output}
                    if mrefs <= child_ids and _only_grouping_refs(mapped, child):
                        pushable.append(mapped)
                    else:
                        kept.append(c)
                if pushable:
                    new_agg = child.copy(
                        child=Filter(join_conjuncts(pushable), child.child))
                    if kept:
                        return Filter(join_conjuncts(kept), new_agg)
                    return new_agg
                return node
            return node

        return plan.transform_up(rule)

    def _push_into_join(self, filt: Filter, join: Join):
        left_ids = {a.expr_id for a in join.left.output}
        right_ids = {a.expr_id for a in join.right.output}
        left_push, right_push, kept = [], [], []
        jt = join.join_type
        for c in split_conjuncts(filt.condition):
            refs = c.references()
            if refs and refs <= left_ids and jt in ("inner", "left_outer",
                                                    "left_semi", "left_anti", "cross"):
                left_push.append(c)
            elif refs and refs <= right_ids and jt in ("inner", "right_outer", "cross"):
                right_push.append(c)
            else:
                kept.append(c)
        if not left_push and not right_push:
            return filt
        new_left = Filter(join_conjuncts(left_push), join.left) if left_push else join.left
        new_right = Filter(join_conjuncts(right_push), join.right) if right_push else join.right
        new_join = join.copy(left=new_left, right=new_right)
        if kept:
            return Filter(join_conjuncts(kept), new_join)
        return new_join


def _only_grouping_refs(e: Expression, agg: Aggregate) -> bool:
    group_ids = {g.expr_id for g in agg.grouping_exprs
                 if isinstance(g, AttributeReference)}

    def ok(x):
        if isinstance(x, AttributeReference):
            return x.expr_id in group_ids or any(
                g.semantic_equals(x) for g in agg.grouping_exprs)
        return all(ok(c) for c in x.children)

    return ok(e)


def _remap_union_cond(cond: Expression, union: Union, i: int) -> Expression:
    out = union.output
    branch = union.children_plans[i].output
    m = {a.expr_id: b for a, b in zip(out, branch)}
    return substitute_attrs(cond, m)


class RewriteHostOnlyExpressions(Rule):
    """Expressions with no device form become vectorized host UDFs
    (reference analog: expressions lacking codegen fall back to interpreted
    eval — here the fallback is the Arrow-UDF path):
      * concat/concat_ws over 2+ string COLUMNS (dictionary products are
        unbounded);
      * cast(non-string AS string) (value universe unknown host-side)."""

    def apply(self, plan):
        import numpy as np

        from ..expr.expressions import Cast, Concat, ConcatWs, Literal
        from ..expr.pyudf import PythonUDF
        from ..types import DateType, StringType, TimestampType, string

        def to_str_fn(dt):
            import datetime

            if isinstance(dt, DateType):
                return lambda a: np.array(
                    [(datetime.date(1970, 1, 1)
                      + datetime.timedelta(days=int(v))).isoformat()
                     for v in a], dtype=object)
            if isinstance(dt, TimestampType):
                return lambda a: np.array(
                    [(datetime.datetime(1970, 1, 1)
                      + datetime.timedelta(microseconds=int(v))).isoformat(
                          sep=" ")
                     for v in a], dtype=object)
            return lambda a: np.array([_fmt_num(v) for v in a], dtype=object)

        def fix(e: Expression) -> Expression:
            from ..expr.expressions import DateFormat

            if isinstance(e, DateFormat):
                import datetime

                strf = DateFormat.to_strftime(e.fmt)
                src_dt = e.child.dtype

                def fmt_fn(a, _strf=strf, _dt=src_dt):
                    from ..types import TimestampType as TT

                    out = []
                    for v in a:
                        if v is None:
                            out.append(None)
                        elif isinstance(_dt, TT):
                            out.append((datetime.datetime(1970, 1, 1)
                                        + datetime.timedelta(
                                            microseconds=int(v)))
                                       .strftime(_strf))
                        else:
                            out.append((datetime.date(1970, 1, 1)
                                        + datetime.timedelta(days=int(v)))
                                       .strftime(_strf))
                    return np.array(out, dtype=object)

                return PythonUDF(fmt_fn, [e.child], string,
                                 name="date_format", vectorized=True)
            if isinstance(e, (Concat, ConcatWs)):
                cols = [a for a in e.args if not isinstance(a, Literal)]
                if len(cols) >= 2:
                    sep = e.sep if isinstance(e, ConcatWs) else ""
                    parts = [a if not isinstance(a, Literal)
                             else a for a in e.args]

                    def concat_fn(*arrays, _sep=sep):
                        out = []
                        for vals in zip(*arrays):
                            if any(v is None for v in vals):
                                out.append(None)
                            else:
                                out.append(_sep.join(str(v) for v in vals))
                        return np.array(out, dtype=object)

                    return PythonUDF(concat_fn, list(e.args), string,
                                     name="concat")
            if isinstance(e, Cast) and isinstance(e.to, StringType) and \
                    e.child.resolved and \
                    not isinstance(e.child.dtype, StringType):
                return PythonUDF(to_str_fn(e.child.dtype), [e.child],
                                 string, name="cast_str")
            from ..expr.expressions import FormatNumber

            if isinstance(e, FormatNumber):
                return PythonUDF(e.format_fn(), [e.child], string,
                                 name="format_number")
            return e

        def rule(node):
            if node.expressions_resolved:
                return node.transform_expressions(
                    lambda ex: ex.transform_up(fix))
            return node

        return plan.transform_up(rule)


def _fmt_num(v):
    if v is None:
        return None
    if isinstance(v, float):
        return repr(v)
    import numpy as _np

    if isinstance(v, _np.floating):
        return repr(float(v))
    if isinstance(v, (bool, _np.bool_)):
        return str(bool(v)).lower()
    return str(v)


class ExtractPythonUDFs(Rule):
    """Pull PythonUDFs out of projections/filters into PythonEval operators
    (reference: sqlx/python/ExtractPythonUDFs.scala)."""

    def apply(self, plan):
        from ..expr.pyudf import PythonUDF
        from .logical import PythonEval

        def rule(node):
            if not isinstance(node, (Project, Filter)):
                return node
            if not any(isinstance(x, PythonUDF)
                       for e in node.expressions()
                       for x in e.iter_nodes()):
                return node
            collected: list[Alias] = []

            def extract(x: Expression) -> Expression:
                if isinstance(x, PythonUDF):
                    al = Alias(x, f"_pyudf{len(collected)}")
                    collected.append(al)
                    return al.to_attribute()
                return x

            new_node = node.map_expressions(
                lambda e: e.transform_up(extract))
            child = PythonEval(collected, node.child)
            new_node = new_node.copy(child=child)
            if isinstance(new_node, Filter):
                return Project(list(node.output), new_node)
            return new_node

        return plan.transform_up(rule)


class MergeFilterIntoJoin(Rule):
    """Filter over cross/inner Join → join condition (reference:
    PushPredicateThroughJoin's join-condition path — turns comma-style
    FROM a, b WHERE a.k = b.k into an equi join)."""

    def apply(self, plan):
        def rule(node):
            if isinstance(node, Filter) and isinstance(node.child, Join) and \
                    node.child.join_type in ("inner", "cross"):
                join = node.child
                lids = {a.expr_id for a in join.left.output}
                rids = {a.expr_id for a in join.right.output}
                both, keep = [], []
                for c in split_conjuncts(node.condition):
                    refs = c.references()
                    if refs & lids and refs & rids:
                        both.append(c)
                    else:
                        keep.append(c)
                if not both:
                    return node
                cond = join.condition
                for c in both:
                    cond = c if cond is None else And(cond, c)
                new_join = Join(join.left, join.right, "inner", cond)
                if keep:
                    return Filter(join_conjuncts(keep), new_join)
                return new_join
            return node

        return plan.transform_up(rule)


class InferFiltersFromJoinKeys(Rule):
    """Add IsNotNull on equi-join keys (reference: InferFiltersFromConstraints,
    simplified) — lets scans drop null keys before the shuffle."""

    def apply(self, plan):
        def rule(node):
            if isinstance(node, Join) and node.join_type in ("inner",) and \
                    node.condition is not None and node.resolved:
                conds = split_conjuncts(node.condition)
                left_ids = {a.expr_id for a in node.left.output}
                right_ids = {a.expr_id for a in node.right.output}
                lnew, rnew = [], []
                for c in conds:
                    if isinstance(c, EqualTo):
                        for side in (c.left, c.right):
                            if isinstance(side, AttributeReference) and side.nullable:
                                if side.expr_id in left_ids:
                                    lnew.append(IsNotNull(side))
                                elif side.expr_id in right_ids:
                                    rnew.append(IsNotNull(side))
                changed = False
                nl, nr = node.left, node.right
                if lnew and not _already_filtered(node.left, lnew):
                    nl = Filter(join_conjuncts(lnew), node.left)
                    changed = True
                if rnew and not _already_filtered(node.right, rnew):
                    nr = Filter(join_conjuncts(rnew), node.right)
                    changed = True
                if changed:
                    return node.copy(left=nl, right=nr)
            return node

        return plan.transform_down(rule)


def _already_filtered(p: LogicalPlan, conds: list[Expression]) -> bool:
    existing: list[Expression] = []
    q = p
    while isinstance(q, Filter):
        existing.extend(split_conjuncts(q.condition))
        q = q.child
    return all(any(c.semantic_equals(e) for e in existing) for c in conds)


class ColumnPruning(Rule):
    """Single top-down pass narrowing projects, aggregates, and scans to the
    columns actually required above them (reference: Optimizer ColumnPruning;
    the scan narrowing is what drives parquet column pushdown)."""

    def apply(self, plan):
        required = {a.expr_id for a in plan.output}
        out = self._prune(plan, required)
        return _collapse_adjacent_projects(out)

    def _prune(self, node: LogicalPlan, required: set[int]) -> LogicalPlan:
        if isinstance(node, Project):
            new_list = [e for e in node.project_list
                        if _out_id(e) in required]
            if not new_list:
                new_list = node.project_list[:1]
            child_req: set[int] = set()
            for e in new_list:
                child_req |= e.references()
            return Project(new_list, self._prune(node.child, child_req))
        if isinstance(node, Aggregate):
            new_aggs = [e for e in node.aggregate_exprs
                        if _out_id(e) in required]
            if not new_aggs:
                new_aggs = node.aggregate_exprs[:1]
            child_req = set()
            for e in list(node.grouping_exprs) + new_aggs:
                child_req |= e.references()
            return Aggregate(node.grouping_exprs, new_aggs,
                             self._prune(node.child, child_req))
        if isinstance(node, (Filter, Sort, Limit, Offset, Sample, Repartition,
                             Distinct, SubqueryAlias)):
            child_req = set(required)
            for e in node.expressions():
                child_req |= e.references()
            if isinstance(node, Distinct):
                child_req |= {a.expr_id for a in node.child.output}
            new_child = self._prune(node.child, child_req)
            if new_child is not node.child:
                return node.copy(child=new_child)
            return node
        if isinstance(node, Join):
            cond_refs: set[int] = set()
            if node.condition is not None:
                cond_refs = node.condition.references()
            lids = {a.expr_id for a in node.left.output}
            rids = {a.expr_id for a in node.right.output}
            lreq = (required | cond_refs) & lids
            rreq = (required | cond_refs) & rids
            nl = self._prune_side(node.left, lreq)
            nr = self._prune_side(node.right, rreq)
            if nl is not node.left or nr is not node.right:
                return node.copy(left=nl, right=nr)
            return node
        if isinstance(node, LogicalRelation):
            keep = [a for a in node.attrs if a.expr_id in required]
            if not keep:
                keep = node.attrs[:1]
            if len(keep) != len(node.attrs):
                return node.copy(attrs=keep)
            return node
        if isinstance(node, Window):
            child_req = {a.expr_id for a in node.child.output}
            for e in node.expressions():
                child_req |= e.references()
            return node.copy(child=self._prune(node.child, child_req))
        # Union (positional semantics), LocalRelation, leaves: conservative
        return node.map_children(
            lambda c: self._prune(c, {a.expr_id for a in c.output}))

    def _prune_side(self, side: LogicalPlan, req: set[int]) -> LogicalPlan:
        have = [a.expr_id for a in side.output]
        if set(have) - req:
            keep = [a for a in side.output if a.expr_id in req]
            if not keep:
                keep = side.output[:1]
            return Project(keep, self._prune(side, set(req)))
        return self._prune(side, req)


def _out_id(e: Expression) -> int | None:
    if isinstance(e, Alias):
        return e.expr_id
    if isinstance(e, AttributeReference):
        return e.expr_id
    return None


def _collapse_adjacent_projects(plan: LogicalPlan) -> LogicalPlan:
    def rule(node):
        if isinstance(node, Project) and isinstance(node.child, Project):
            m = alias_map(node.child.project_list)
            new_list = []
            for e in node.project_list:
                if isinstance(e, Alias):
                    new_list.append(
                        Alias(substitute_attrs(e.child, m), e.name, e.expr_id))
                else:
                    sub = substitute_attrs(e, m)
                    if sub is e or isinstance(sub, AttributeReference):
                        # keep the outer name/id stable
                        if isinstance(sub, AttributeReference) and \
                                isinstance(e, AttributeReference) and \
                                sub.expr_id != e.expr_id:
                            new_list.append(Alias(sub, e.name, e.expr_id))
                        else:
                            new_list.append(e if sub is e else sub)
                    else:
                        new_list.append(Alias(sub, e.name, e.expr_id))
            return Project(new_list, node.child.child)
        return node

    return plan.transform_up(rule)


class CollapseProjects(Rule):
    def apply(self, plan):
        return _collapse_adjacent_projects(plan)


class RemoveNoopProject(Rule):
    def apply(self, plan):
        def rule(node):
            if isinstance(node, Project):
                child_out = node.child.output
                if len(node.project_list) == len(child_out) and all(
                        isinstance(e, AttributeReference) and
                        e.expr_id == a.expr_id and e.name == a.name
                        for e, a in zip(node.project_list, child_out)):
                    return node.child
            return node

        return plan.transform_up(rule)


class RewriteModeAggregate(Rule):
    """mode(v) [GROUP BY g] → per-value counts, a max-count self-join,
    and a min-value tie-break — three plain aggregates + one equi join,
    so the whole thing rides the existing device segment kernels
    (reference: sqlcat/expressions/aggregate/Mode.scala implements a
    typed-imperative map; the relational rewrite is the columnar
    answer). Deterministic on ties (smallest value wins)."""

    def apply(self, plan):
        from ..errors import UnsupportedOperationError
        from ..expr.expressions import Count, Max, Min, Mode

        def rule(node):
            if not isinstance(node, Aggregate) or not node.resolved:
                return node
            modes = [x for e in node.aggregate_exprs
                     for x in e.iter_nodes() if isinstance(x, Mode)]
            if not modes:
                return node
            grouping = list(node.grouping_exprs)
            if not all(isinstance(g, AttributeReference)
                       for g in grouping):
                raise UnsupportedOperationError(
                    "mode() requires plain grouping columns")
            other_aggs = [x for e in node.aggregate_exprs
                          for x in e.iter_nodes()
                          if isinstance(x, AggregateFunction)
                          and not isinstance(x, Mode)]
            args = {m.child.expr_id for m in modes
                    if isinstance(m.child, AttributeReference)}
            if other_aggs or len(args) != 1 or                     not all(isinstance(m.child, AttributeReference)
                            for m in modes):
                raise UnsupportedOperationError(
                    "mode() needs a plain column argument and cannot "
                    "mix with other aggregates or a second mode column")
            v = modes[0].child

            # 1. count per (grouping, value); NULL values count 0, so
            #    they only win when the group is all-NULL — Mode ignores
            #    nulls, and an all-null group's mode is NULL
            c_alias = Alias(Count(v), "__mode_c")
            counts = Aggregate(grouping + [v],
                               grouping + [v, c_alias], node.child)
            c_attr = c_alias.to_attribute()

            # 2. max count per grouping, over an id-independent copy of
            #    the counts subtree (it appears on both join sides)
            from .subquery import _fresh_plan

            mapping: dict = {}
            counts2 = _fresh_plan(counts, mapping)
            g2 = [mapping.get(g.expr_id, g) for g in grouping]
            c2 = mapping.get(c_attr.expr_id, c_attr)
            mc_alias = Alias(Max(c2), "__mode_mc")
            maxc = Aggregate(list(g2), list(g2) + [mc_alias], counts2)
            mc_attr = mc_alias.to_attribute()

            cond: Expression = EqualTo(c_attr, mc_attr)
            from ..expr.expressions import EqualNullSafe

            for g, gg in zip(grouping, g2):
                # null-safe: a NULL grouping key is a real group and
                # must survive the self-join
                cond = And(cond, EqualNullSafe(g, gg))
            joined = Join(counts, maxc, "inner", cond)

            # 3. tie-break: smallest winning value, then PROJECT the
            #    original output expressions with every Mode node
            #    substituted — covers mode() under aliases, arithmetic,
            #    or scalar functions, with output ids preserved
            mv_alias = Alias(Min(v), "__mode_val")
            final = Aggregate(grouping,
                              grouping + [mv_alias], joined)
            mv_attr = mv_alias.to_attribute()

            def sub(x):
                return mv_attr if isinstance(x, Mode) else x

            out_exprs = [e.transform_up(sub)
                         for e in node.aggregate_exprs]
            return Project(out_exprs, final)

        return plan.transform_up(rule)


class RewriteDistinctAggregates(Rule):
    """count(DISTINCT x) [GROUP BY g] → two-level aggregation:
    inner Aggregate(g, x) dedups, outer counts (reference:
    sqlcat/optimizer/RewriteDistinctAggregates.scala — the single-distinct
    fast path; the multi-distinct Expand rewrite is round-2 work)."""

    def apply(self, plan):
        from ..errors import UnsupportedOperationError
        from ..expr.expressions import Count

        def rule(node):
            if not isinstance(node, Aggregate) or not node.resolved:
                return node
            distincts = []
            others = []
            for e in node.aggregate_exprs:
                for x in e.iter_nodes():
                    if isinstance(x, AggregateFunction):
                        if getattr(x, "distinct", False):
                            distincts.append(x)
                        else:
                            others.append(x)
            if not distincts:
                return node
            if others:
                return self._rewrite_mixed(node, distincts)
            first_child = distincts[0].child
            if any(not d.child.semantic_equals(first_child)
                   for d in distincts[1:]):
                raise UnsupportedOperationError(
                    "multiple DISTINCT aggregates on different expressions "
                    "are not supported yet")

            # inner: dedup (g..., x)
            inner_group: list[Expression] = []
            inner_outs: list[Expression] = []
            group_attr: list[tuple[Expression, AttributeReference]] = []
            for i, g in enumerate(node.grouping_exprs):
                if isinstance(g, AttributeReference):
                    inner_group.append(g)
                    inner_outs.append(g)
                    group_attr.append((g, g))
                else:
                    al = Alias(g, f"_g{i}")
                    inner_group.append(g)
                    inner_outs.append(al)
                    group_attr.append((g, al.to_attribute()))
            if isinstance(first_child, AttributeReference):
                x_attr = first_child
                inner_outs.append(first_child)
            else:
                xal = Alias(first_child, "_dx")
                x_attr = xal.to_attribute()
                inner_outs.append(xal)
            inner = Aggregate(inner_group + [first_child], inner_outs,
                              node.child)

            # outer: original outputs with fn(distinct x) → fn(x)
            def fix(e: Expression) -> Expression:
                if isinstance(e, AggregateFunction) and \
                        getattr(e, "distinct", False):
                    if isinstance(e, Count):
                        return Count(x_attr, distinct=False)
                    out = e.copy(child=x_attr)
                    out.distinct = False
                    return out
                for g, a in group_attr:
                    if e.semantic_equals(g):
                        return a
                return e

            outer_group = [a for _, a in group_attr]
            outer_outs = []
            for e in node.aggregate_exprs:
                if isinstance(e, Alias):
                    outer_outs.append(
                        Alias(e.child.transform_up(fix), e.name, e.expr_id))
                else:
                    outer_outs.append(e.transform_up(fix))
            return Aggregate(outer_group, outer_outs, inner)

        return plan.transform_up(rule)

    def _rewrite_mixed(self, node: Aggregate, distincts):
        """Mixed DISTINCT + plain aggregates: split into two aggregates over
        the same child and join them back on the grouping keys (the
        reference uses a single Expand; the join formulation reuses existing
        operators). Null-safe key equality keeps null-keyed groups."""
        from ..errors import UnsupportedOperationError
        from ..expr.expressions import AggregateFunction as AF

        # grouping attrs for both sides (aliased when complex)
        def key_aliases(suffix: str):
            outs, attrs = [], []
            for i, g in enumerate(node.grouping_exprs):
                al = Alias(g, f"_k{suffix}{i}")
                outs.append(al)
                attrs.append(al.to_attribute())
            return outs, attrs

        nd_keys, nd_attrs = key_aliases("n")
        d_keys, d_attrs = key_aliases("d")

        nd_funcs, d_funcs = [], []
        for e in node.aggregate_exprs:
            for x in e.iter_nodes():
                if isinstance(x, AF):
                    bucket = d_funcs if getattr(x, "distinct", False) \
                        else nd_funcs
                    if not any(x.semantic_equals(f) for f in bucket):
                        bucket.append(x)

        nd_aliases = [Alias(f, f"_nd{i}") for i, f in enumerate(nd_funcs)]
        d_aliases = [Alias(f, f"_d{i}") for i, f in enumerate(d_funcs)]

        nd_agg = Aggregate(node.grouping_exprs, nd_keys + nd_aliases,
                           node.child)
        d_agg = Aggregate(node.grouping_exprs, d_keys + d_aliases,
                          node.child)
        # recursively rewrite the distinct side (now distinct-only)
        d_agg = self.apply(d_agg)

        if node.grouping_exprs:
            cond = None
            for l, r in zip(nd_attrs, d_attrs):
                for c in _null_safe_eq_conjuncts(l, r):
                    cond = c if cond is None else And(cond, c)
            joined = Join(nd_agg, d_agg, "inner", cond)
        else:
            joined = Join(nd_agg, d_agg, "cross", None)

        nd_map = {id(f): a.to_attribute() for f, a in zip(nd_funcs, nd_aliases)}
        d_map = list(zip(d_funcs, [a.to_attribute() for a in d_aliases]))
        g_map = list(zip(node.grouping_exprs, nd_attrs))

        def fix(x: Expression) -> Expression:
            if isinstance(x, AF):
                if getattr(x, "distinct", False):
                    for f, a in d_map:
                        if x.semantic_equals(f):
                            return a
                else:
                    for f, a in zip(nd_funcs,
                                    [al.to_attribute() for al in nd_aliases]):
                        if x.semantic_equals(f):
                            return a
            for g, a in g_map:
                if x.semantic_equals(g):
                    return a
            return x

        outs = []
        for e in node.aggregate_exprs:
            if isinstance(e, Alias):
                outs.append(Alias(e.child.transform_up(fix), e.name,
                                  e.expr_id))
            elif isinstance(e, AttributeReference):
                outs.append(Alias(fix(e), e.name, e.expr_id))
            else:
                outs.append(e.transform_up(fix))
        return Project(outs, joined)


class ReplaceSetOps(Rule):
    """INTERSECT → semi join + distinct; EXCEPT → anti join + distinct
    (reference: ReplaceIntersectWithSemiJoin / ReplaceExceptWithAntiJoin).
    Null-safe equality per column."""

    def apply(self, plan):
        from ..expr.expressions import EqualNullSafe
        from .logical import Except, Intersect

        def rule(node):
            if isinstance(node, (Intersect, Except)) and node.resolved:
                # null-safe equality expressed as plain equi keys so the hash
                # join kernel applies: (isnull(l)=isnull(r)) AND
                # (coalesce(l,d)=coalesce(r,d))
                cond = None
                for l, r in zip(node.left.output, node.right.output):
                    for c in _null_safe_eq_conjuncts(l, r):
                        cond = c if cond is None else And(cond, c)
                jt = "left_semi" if isinstance(node, Intersect) else "left_anti"
                return Distinct(Join(node.left, node.right, jt, cond))
            return node

        return plan.transform_up(rule)


def _null_safe_eq_conjuncts(l: Expression, r: Expression) -> list[Expression]:
    from ..expr.expressions import Coalesce, IsNull
    from ..types import (
        BooleanType, DateType, NumericType, StringType, TimestampType,
    )

    if not (l.nullable or r.nullable):
        return [EqualTo(l, r)]
    dt = l.dtype
    if isinstance(dt, StringType):
        d = Literal("")
    elif isinstance(dt, BooleanType):
        d = Literal(False)
    elif isinstance(dt, (NumericType, DateType, TimestampType)):
        d = Literal(0)
    else:
        d = Literal(0)
    from ..expr.expressions import cast_if

    d = cast_if(d, dt)
    return [EqualTo(IsNull(l), IsNull(r)),
            EqualTo(Coalesce([l, d]), Coalesce([r, d]))]


class ExpandGroupingSets(Rule):
    """GroupingSets → Union of per-set Aggregates with NULL fills for the
    grouping keys absent from each set."""

    def apply(self, plan):
        from .logical import GroupingSets

        def rule(node):
            if not isinstance(node, GroupingSets) or not node.resolved:
                return node
            branches = []
            for si, idxs in enumerate(node.sets):
                keys = [node.grouping_exprs[i] for i in idxs]
                out_exprs: list[Expression] = []
                for e in node.aggregate_exprs:
                    out_exprs.append(self._fill(e, keys, node.grouping_exprs,
                                                si))
                branches.append(Aggregate(list(keys), out_exprs, node.child))
            return Union(branches) if len(branches) > 1 else branches[0]

        return plan.transform_up(rule)

    def _fill(self, e: Expression, keys, all_keys, set_index: int):
        from ..expr.expressions import Cast, Grouping, GroupingID

        def in_set(x):
            return any(x.semantic_equals(k)
                       or (isinstance(k, Alias) and x.semantic_equals(k.child))
                       for k in keys)

        def rule(x):
            # grouping()/grouping_id() fold to literals per branch — BEFORE
            # the null-fill below can touch their key argument
            if isinstance(x, Grouping):
                return Literal(0 if in_set(x.child) else 1)
            if isinstance(x, GroupingID):
                args = x.args or list(all_keys)
                gid = 0
                for a in args:
                    gid = (gid << 1) | (0 if in_set(a) else 1)
                return Literal(gid)
            if any(x.semantic_equals(g) for g in all_keys) and not in_set(x):
                return Cast(Literal(None), x.dtype)
            return x

        if isinstance(e, Alias):
            filled = e.child.transform_down(rule)
            return Alias(filled, e.name,
                         e.expr_id if set_index == 0 else None)
        if isinstance(e, AttributeReference):
            if any(e.semantic_equals(g) for g in all_keys) and not in_set(e):
                return Alias(Cast(Literal(None), e.dtype), e.name,
                             e.expr_id if set_index == 0 else None)
            return e if set_index == 0 else Alias(
                e, e.name)
        return e


class ReorderJoins(Rule):
    """Greedy left-deep reordering of inner-join chains by estimated row
    counts (reference: Optimizer ReorderJoin + CostBasedJoinReorder,
    simplified): start from the smallest relation and repeatedly attach
    the smallest CONNECTED relation (one sharing a join predicate with
    the rows already joined), so selective dimension tables join before
    large facts. Members without a row estimate (subquery aggregates)
    sort last but STILL participate: bailing out kept q64's written
    order, which crosses two 73k-row date_dim instances before the
    customer table that connects them — the reference's stats-free
    ReorderJoin also only needs connectivity (createOrderedJoin)."""

    def apply(self, plan):
        def rule(node):
            # Fire on Filter(Join) as well as bare Join: a comma-list
            # FROM parses as a cross-join chain with EVERY WHERE conjunct
            # in one Filter above — waiting for pushdown to trickle the
            # conds onto join nodes leaves the chain looking condition-
            # less here and q64's 73k×73k date_dim cross in place.
            # Multi-table conjuncts join the reorder as edges; single-
            # table ones stay in the Filter for scan pruning/DPP.
            filter_conds: list[Expression] = []
            join = node
            if isinstance(node, Filter) and isinstance(node.child, Join):
                filter_conds = split_conjuncts(node.condition)
                join = node.child
            if not isinstance(join, Join) or \
                    join.join_type not in ("inner", "cross"):
                return node
            items: list[LogicalPlan] = []
            conds: list[Expression] = []

            def flatten(n):
                if isinstance(n, Join) and n.join_type in ("inner", "cross"):
                    flatten(n.left)
                    flatten(n.right)
                    if n.condition is not None:
                        conds.extend(split_conjuncts(n.condition))
                else:
                    items.append(n)

            flatten(join)
            if len(items) <= 2:
                return node
            single_table: list[Expression] = []
            if filter_conds:
                item_ids = [{a.expr_id for a in it.output} for it in items]
                for c in filter_conds:
                    refs = c.references()
                    touched = sum(1 for ids in item_ids if refs & ids)
                    (conds if touched >= 2 else single_table).append(c)
            if not conds:
                # condition-less (pure cross) chain: reordering gains
                # nothing, and the Project this rule would wrap around a
                # reordered result fragments the PARENT chain's flatten
                return node
            from .stats import Statistics, estimate as _est

            ests = {}
            istats: dict[int, Statistics] = {}
            for it in items:
                s = _est(it)
                istats[id(it)] = s
                ests[id(it)] = float("inf") if s.row_count is None \
                    else s.row_count

            remaining = list(items)
            def _key(x):  # deterministic tie-break → stable fixpoint
                out0 = x.output[0].expr_id if x.output else 0
                return (ests[id(x)], out0)

            def _pair_cost(a, b) -> float:
                ra, rb = ests[id(a)], ests[id(b)]
                if ra == float("inf") or rb == float("inf"):
                    return float("inf")
                aids = {x.expr_id for x in a.output}
                bids = {x.expr_id for x in b.output}
                denom, connected = 1, False
                for cd in conds:
                    refs = cd.references()
                    if not (refs and refs <= (aids | bids)
                            and refs & aids and refs & bids):
                        continue
                    connected = True
                    if isinstance(cd, EqualTo):
                        for side in (cd.left, cd.right):
                            if isinstance(side, AttributeReference):
                                for st in (istats[id(a)], istats[id(b)]):
                                    cs = st.col_stats.get(side.name.lower())
                                    if cs is not None and cs.distinct_count:
                                        denom = max(denom,
                                                    cs.distinct_count)
                return (ra * rb) / denom if connected else float("inf")

            # seed with the cheapest CONNECTED pair, not the smallest
            # relation: a small low-ndv table picked first drags its huge
            # join in as the only connected continuation
            best, best_cost = None, float("inf")
            for i, a in enumerate(items):
                for b in items[i + 1:]:
                    c = _pair_cost(a, b)
                    if c < best_cost:
                        best, best_cost = (a, b), c
            cur = min(best, key=_key) if best is not None \
                else min(remaining, key=_key)
            remaining.remove(cur)
            joined_ids = {a.expr_id for a in cur.output}
            unused = list(conds)
            result = cur
            cur_rows = ests[id(cur)]
            cur_colstats = dict(istats[id(cur)].col_stats)

            def _joined_rows(cand) -> float:
                """CBO greedy cost: estimated |result ⋈ cand| using the
                connecting equi keys' ndv (CostBasedJoinReorder role —
                without ANALYZE'd ndv this degrades to candidate-size
                order, the stats-free ReorderJoin behavior)."""
                crows = ests[id(cand)]
                if cur_rows == float("inf") or crows == float("inf"):
                    return crows
                cstats = istats[id(cand)].col_stats
                cids = {a.expr_id for a in cand.output}
                denom = 1
                for cd in unused:
                    if not isinstance(cd, EqualTo):
                        continue
                    refs = cd.references()
                    if not (refs and refs <= (joined_ids | cids)
                            and refs & joined_ids and refs & cids):
                        continue
                    for side in (cd.left, cd.right):
                        if isinstance(side, AttributeReference):
                            cs = (cstats.get(side.name.lower())
                                  or cur_colstats.get(side.name.lower()))
                            if cs is not None and cs.distinct_count:
                                denom = max(denom, cs.distinct_count)
                return (cur_rows * crows) / max(denom, 1)

            while remaining:
                def connects(cand, equi_only: bool):
                    cids = {a.expr_id for a in cand.output}
                    for cd in unused:
                        if equi_only and not isinstance(cd, EqualTo):
                            continue
                        refs = cd.references()
                        if refs and refs <= (joined_ids | cids) \
                                and refs & joined_ids and refs & cids:
                            return True
                    return False

                # equi-connected candidates FIRST: a candidate linked only
                # by a non-equality predicate (q64: cd1.x <> cd2.x) would
                # otherwise be attached as a near-cartesian nested-loop
                # join; the equality chain keeps every step hash-joinable
                # (reference: ReorderJoin createOrderedJoin considers
                # equi-join conditions)
                cands = [r for r in remaining if connects(r, True)] or \
                        [r for r in remaining if connects(r, False)]
                pool = cands or remaining
                pick = min(pool, key=lambda x: (_joined_rows(x), _key(x)))
                remaining.remove(pick)
                cur_rows = _joined_rows(pick)
                cur_colstats.update(istats[id(pick)].col_stats)
                pick_ids = {a.expr_id for a in pick.output}
                joined_ids |= pick_ids
                applicable = [cd for cd in unused
                              if cd.references() <= joined_ids]
                for cd in applicable:
                    unused.remove(cd)
                result = Join(result, pick, "inner",
                              join_conjuncts(applicable))
            leftover = unused + single_table
            if leftover:  # single-table conds + any cond beyond the chain
                result = Filter(join_conjuncts(leftover), result)
            if [a.expr_id for a in result.output] != \
                    [a.expr_id for a in node.output]:
                result = Project(list(node.output), result)
            return result

        return plan.transform_up(rule)


class ReplaceDistinct(Rule):
    def apply(self, plan):
        def rule(node):
            if isinstance(node, Distinct):
                out = node.child.output
                return Aggregate(list(out), list(out), node.child)
            return node

        return plan.transform_up(rule)


class EliminateSubqueryAliases(Rule):
    """Once resolution is done, aliases are noise (reference:
    EliminateSubqueryAliases runs first in the optimizer)."""

    def apply(self, plan):
        def rule(node):
            if isinstance(node, SubqueryAlias):
                return node.child
            return node

        return plan.transform_up(rule)


class PruneFilters(Rule):
    def apply(self, plan):
        def rule(node):
            if isinstance(node, Filter):
                c = node.condition
                if isinstance(c, Literal):
                    if c.value is True:
                        return node.child
                    return LocalRelation(
                        list(node.output), _empty_table(node.output))
            return node

        return plan.transform_up(rule)


class CombineUnions(Rule):
    """Flatten nested unions (reference: CombineUnions) — fewer positional
    rewraps, one UnionExec."""

    def apply(self, plan):
        def rule(node):
            if isinstance(node, Union) and any(
                    isinstance(c, Union) for c in node.children_plans):
                flat: list[LogicalPlan] = []
                for c in node.children_plans:
                    if isinstance(c, Union):
                        flat.extend(c.children_plans)
                    else:
                        flat.append(c)
                return Union(flat)
            return node

        return plan.transform_up(rule)


class PropagateEmptyRelation(Rule):
    """Empty local relations collapse the operators above them (reference:
    PropagateEmptyRelation)."""

    def apply(self, plan):
        def is_empty(p: LogicalPlan) -> bool:
            return isinstance(p, LocalRelation) and p.table.num_rows == 0

        def empty_of(node: LogicalPlan) -> LogicalPlan:
            return LocalRelation(list(node.output), _empty_table(node.output))

        def rule(node):
            if isinstance(node, (Filter, Sort, Limit, Offset, Sample,
                                 Repartition)) and is_empty(node.child):
                return empty_of(node)
            if isinstance(node, Project) and is_empty(node.child) and \
                    node.resolved:
                return empty_of(node)
            if isinstance(node, Join) and node.resolved:
                if node.join_type in ("inner", "cross", "left_semi") and \
                        (is_empty(node.left) or is_empty(node.right)):
                    return empty_of(node)
                if node.join_type in ("left_outer", "left_anti") and \
                        is_empty(node.left):
                    return empty_of(node)
            if isinstance(node, Union) and node.resolved:
                alive = [c for c in node.children_plans if not is_empty(c)]
                if not alive:
                    return empty_of(node)
                if len(alive) < len(node.children_plans):
                    if len(alive) == 1:
                        keep = alive[0]
                        # preserve output ids positionally
                        return Project(
                            [Alias(b, a.name, a.expr_id)
                             for a, b in zip(node.output, keep.output)],
                            keep)
                    return Union(alive)
            return node

        return plan.transform_up(rule)


class CombineLimits(Rule):
    def apply(self, plan):
        def rule(node):
            if isinstance(node, Limit) and isinstance(node.child, Limit):
                return Limit(min(node.n, node.child.n), node.child.child)
            return node

        return plan.transform_up(rule)


def _empty_table(attrs):
    import pyarrow as pa

    from ..types import to_arrow_type

    return pa.table(
        {a.name: pa.array([], type=to_arrow_type(a.dtype)) for a in attrs}
        if attrs else {"__dummy": pa.array([], pa.int32())})


def _rewrite_predicate_subquery():
    from .subquery import RewritePredicateSubquery

    return RewritePredicateSubquery()


def _rewrite_existence_subquery():
    from .subquery import RewriteExistenceSubquery

    return RewriteExistenceSubquery()


def _rewrite_correlated_scalar():
    from .subquery import RewriteCorrelatedScalarSubquery

    return RewriteCorrelatedScalarSubquery()


class OptimizeSubqueryPlans(Rule):
    """Apply structural rules inside subquery expression plans (reference:
    Optimizer OptimizeSubqueries) — an INTERSECT/ROLLUP/DISTINCT inside an
    IN/EXISTS/scalar subquery must be rewritten before the subquery
    itself is unwrapped into a join."""

    def __init__(self, rules):
        self.rules = rules

    def apply(self, plan):
        from .subquery import SubqueryExpression

        def fix_expr(ex):
            if isinstance(ex, SubqueryExpression):
                p = self.apply(ex.plan)  # nested subqueries first
                for r in self.rules:
                    p = r.apply(p)
                if p is not ex.plan:
                    return ex.copy(plan=p)
            return ex

        def rule(node):
            return node.map_expressions(
                lambda e: e.transform_up(fix_expr))

        return plan.transform_up(rule)


def _finish_analysis_rules():
    return [
        EliminateSubqueryAliases(),
        ReplaceSetOps(),
        ExpandGroupingSets(),
        ReplaceDistinct(),
        RewriteModeAggregate(),
        RewriteDistinctAggregates(),
    ]


class Optimizer(RuleExecutor):
    def __init__(self):
        super().__init__()

    def batches(self):
        return [
            Batch("Finish analysis", Once(), [
                # subquery plans also get boolean simplification here so
                # OR-factored correlated equalities (q41) surface as
                # conjuncts before the Subqueries batch decorrelates
                OptimizeSubqueryPlans(_finish_analysis_rules() +
                                      [BooleanSimplification()]),
                *_finish_analysis_rules(),
            ]),
            Batch("Subqueries", FixedPoint(10), [
                _rewrite_predicate_subquery(),
                _rewrite_existence_subquery(),
                _rewrite_correlated_scalar(),
            ]),
            Batch("Operator optimization", FixedPoint(100), [
                CombineFilters(),
                MergeFilterIntoJoin(),
                PushDownPredicates(),
                ReorderJoins(),
                ConstantFolding(),
                BooleanSimplification(),
                SimplifyCasts(),
                PruneFilters(),
                PropagateEmptyRelation(),
                CombineUnions(),
                CombineLimits(),
                CollapseProjects(),
                RemoveNoopProject(),
            ]),
            Batch("Join hygiene", Once(), [
                InferFiltersFromJoinKeys(),
                PushDownPredicates(),
                CombineFilters(),
            ]),
            Batch("Python UDFs", FixedPoint(10), [
                RewriteHostOnlyExpressions(),
                ExtractPythonUDFs(),
            ]),
            Batch("Column pruning", FixedPoint(20), [
                ColumnPruning(),
                RemoveNoopProject(),
            ]),
        ]
