"""Subquery expressions and decorrelation.

Role of the reference's subquery machinery — expressions
(sqlcat/expressions/subquery.scala: ScalarSubquery, ListQuery/InSubquery,
Exists) and the optimizer rewrites (sqlcat/optimizer/subquery.scala:
RewritePredicateSubquery → semi/anti joins; decorrelation of equality
predicates). Uncorrelated scalar subqueries evaluate once at execution and
substitute as literals (the reference materializes them via
SubqueryExec/ScalarSubquery reuse).
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..errors import AnalysisException, UnsupportedOperationError
from ..expr.expressions import (
    Alias, And, AttributeReference, EqualTo, Expression, IsNotNull, IsNull,
    Literal, Not, Or,
)
from .logical import Aggregate, Filter, Join, Limit, LogicalPlan, Project
from .tree import Rule

__all__ = ["ScalarSubquery", "InSubquery", "Exists",
           "RewritePredicateSubquery", "split_correlation"]


class SubqueryExpression(Expression):
    child_fields = ()

    def __init__(self, plan: LogicalPlan):
        self.plan = plan

    @property
    def resolved(self):
        # plan resolution happens in the analyzer rule ResolveSubqueries
        return self.plan.resolved

    def _data_args(self):
        return (("plan_id", id(self.plan)),)


class ScalarSubquery(SubqueryExpression):
    """(SELECT single_value ...) used as an expression."""

    @property
    def dtype(self):
        return self.plan.output[0].dtype

    @property
    def nullable(self):
        return True

    def simple_string(self):
        return "scalar-subquery(...)"


class InSubquery(SubqueryExpression):
    """x IN (SELECT col ...)"""

    def __init__(self, value: Expression, plan: LogicalPlan):
        self.value = value
        self.plan = plan

    child_fields = ("value",)

    @property
    def dtype(self):
        from ..types import boolean

        return boolean

    def simple_string(self):
        return f"{self.value.simple_string()} IN (subquery)"


class Exists(SubqueryExpression):
    @property
    def dtype(self):
        from ..types import boolean

        return boolean

    @property
    def nullable(self):
        return False

    def simple_string(self):
        return "EXISTS(subquery)"


# ---------------------------------------------------------------------------
# Correlation analysis
# ---------------------------------------------------------------------------

def split_correlation(subplan: LogicalPlan, outer_ids: set[int],
                      with_residuals: bool = False):
    """Pull correlated predicates out of the subquery (the reference's
    pullOutCorrelatedPredicates). Returns
    (decorrelated_plan, [(outer_expr, inner_attr)], residuals, ok):
    `outer = inner` conjuncts become join pairs; with_residuals=True also
    pulls arbitrary correlated conjuncts (e.g. `outer.w <> inner.w`, the
    TPC-DS q16/q94 shape) to be re-applied as join-condition residuals."""
    from .optimizer import join_conjuncts, split_conjuncts

    from .logical import Limit, Union, Window

    pairs: list[tuple[Expression, Expression]] = []
    residuals: list[Expression] = []
    failed = [False]

    def _sensitive(n: LogicalPlan) -> bool:
        return isinstance(n, (Aggregate, Limit, Union, Window)) or (
            isinstance(n, Join) and n.join_type not in ("inner", "cross"))

    def go(node: LogicalPlan, crossed: bool) -> LogicalPlan:
        # `crossed`: a row-count-sensitive operator lies between this node
        # and the subquery root. A residual stripped from below one would
        # re-apply at the join AFTER that operator changed what it sees
        # (an Aggregate aggregating rows the residual should have
        # excluded, a Limit selecting from unfiltered input, ...) — only
        # sound when crossed is False.
        child_crossed = crossed or _sensitive(node)
        node = node.map_children(lambda c: go(c, child_crossed))
        if isinstance(node, Filter):
            keep = []
            for c in split_conjuncts(node.condition):
                refs = c.references()
                outer_refs = refs & outer_ids
                if not outer_refs:
                    keep.append(c)
                    continue
                if isinstance(c, EqualTo):
                    lr = c.left.references()
                    rr = c.right.references()
                    if lr <= outer_ids and not (rr & outer_ids):
                        pairs.append((c.left, c.right))
                        continue
                    if rr <= outer_ids and not (lr & outer_ids):
                        pairs.append((c.right, c.left))
                        continue
                if with_residuals and not crossed:
                    residuals.append(c)
                    continue
                failed[0] = True
                keep.append(c)
            cond = join_conjuncts(keep)
            if cond is None:
                return node.child
            if len(keep) != len(split_conjuncts(node.condition)):
                return Filter(cond, node.child)
        return node

    out = go(subplan, False)
    # any remaining outer references → unsupported correlation
    for n in out.iter_nodes():
        for e in n.expressions():
            if e.references() & outer_ids:
                failed[0] = True
    return out, pairs, residuals, not failed[0]


# ---------------------------------------------------------------------------
# Predicate subquery rewrite (Filter conditions only, like the reference)
# ---------------------------------------------------------------------------

class RewritePredicateSubquery(Rule):
    """EXISTS/IN in WHERE → left_semi / left_anti joins
    (reference: sqlcat/optimizer/subquery.scala RewritePredicateSubquery)."""

    def apply(self, plan):
        from .optimizer import join_conjuncts, split_conjuncts

        def rule(node):
            if not isinstance(node, Filter):
                return node
            has_sub = any(isinstance(x, (InSubquery, Exists))
                          for x in node.condition.iter_nodes())
            if not has_sub:
                return node

            outer_ids = {a.expr_id for a in node.child.output}
            base = node.child
            kept: list[Expression] = []
            for conj in split_conjuncts(node.condition):
                base, handled = self._rewrite_one(conj, base, outer_ids)
                if not handled:
                    kept.append(conj)
            if kept:
                # EXISTS/IN under OR (not a top-level conjunct): lower each
                # to an existence-join boolean flag (reference plans these
                # as ExistenceJoin) — the TPC-DS q10/q35 shape
                # `exists(...) and (exists(...) or exists(...))`
                new_kept = []
                for conj in kept:
                    while True:
                        target = next(
                            (x for x in conj.iter_nodes()
                             if isinstance(x, (InSubquery, Exists))), None)
                        if target is None:
                            break
                        base, rep = _existence_flag(target, base, outer_ids)

                        def replace(x, _t=target, _r=rep):
                            return _r if x is _t else x

                        conj = conj.transform_up(replace)
                    new_kept.append(conj)
                return Filter(join_conjuncts(new_kept), base)
            return base

        return plan.transform_up(rule)

    def _rewrite_one(self, conj: Expression, base: LogicalPlan,
                     outer_ids: set[int]):
        neg = False
        e = conj
        if isinstance(e, Not):
            inner = e.child
            if isinstance(inner, (InSubquery, Exists)):
                neg = True
                e = inner
        if isinstance(e, InSubquery):
            sub, pairs, residuals, ok = split_correlation(
                e.plan, outer_ids, with_residuals=True)
            if not ok:
                raise UnsupportedOperationError(
                    "unsupported correlated IN subquery")
            sub, pairs, residuals = _refresh_lowered(sub, pairs, residuals)
            value_attr = sub.output[0]
            sub = _expose_correlation_keys(sub, pairs, residuals,
                                           outer_ids)
            eq: Expression = EqualTo(e.value, value_attr)
            if neg and (e.value.nullable or value_attr.nullable):
                # null-aware anti join (reference: subquery.scala
                # RewritePredicateSubquery null-aware path): a NULL on
                # either side makes NOT IN unknown, so "eq OR eq IS NULL"
                # counts as a match and the row is anti-filtered
                eq = Or(eq, IsNull(eq))
            cond: Expression = eq
            for outer_e, inner_e in pairs:
                cond = And(cond, EqualTo(outer_e, inner_e))
            for r in residuals:
                cond = And(cond, r)
            jt = "left_anti" if neg else "left_semi"
            return Join(base, sub, jt, cond), True
        if isinstance(e, Exists):
            sub, pairs, residuals, ok = split_correlation(
                e.plan, outer_ids, with_residuals=True)
            if not ok:
                raise UnsupportedOperationError(
                    "unsupported correlated EXISTS subquery")
            sub, pairs, residuals = _refresh_lowered(sub, pairs, residuals)
            if pairs or residuals:
                sub = _expose_correlation_keys(sub, pairs, residuals,
                                               outer_ids)
                cond = None
                for outer_e, inner_e in pairs:
                    c = EqualTo(outer_e, inner_e)
                    cond = c if cond is None else And(cond, c)
                for r in residuals:
                    cond = r if cond is None else And(cond, r)
            else:
                # uncorrelated EXISTS: constant-key semi join
                one = Alias(Literal(1), "__one")
                sub = Project([one], sub)
                cond = EqualTo(Literal(1), sub.output[0])
            jt = "left_anti" if neg else "left_semi"
            return Join(base, sub, jt, cond), True
        return base, False


def _refresh_lowered(sub, pairs, residuals):
    """Fresh ids for a subquery plan about to be spliced as a join side
    (the same view lowered twice in one WHERE — or shared with the outer
    query — must not alias already-spliced ids; see _fresh_plan).
    Correlation pairs keep their OUTER side; inner sides and residuals
    remap to the fresh ids. Residuals' outer references are untouched
    (they are not produced by `sub`, so never in the mapping)."""
    fm: dict = {}
    sub = _fresh_plan(sub, fm)

    def remap(e):
        return e.transform_up(
            lambda x: fm.get(x.expr_id, x)
            if isinstance(x, AttributeReference) else x)

    pairs = [(oe, remap(ie)) for oe, ie in pairs]
    residuals = [remap(r) for r in residuals]
    return sub, pairs, residuals


def _expose_correlation_keys(
        sub: LogicalPlan,
        pairs: Sequence[tuple[Expression, Expression]],
        residuals: Sequence[Expression] = (),
        outer_ids: set[int] | None = None) -> LogicalPlan:
    """Rewrite the decorrelated subplan so the inner key attributes appear
    in its output. An aggregate regains them as GROUPING keys (turning a
    per-outer-row aggregate into a grouped one — the decorrelation core);
    a projection just widens. Residual predicates' inner attributes are
    exposed the same way."""
    keys: list[AttributeReference] = []
    for _, ie in pairs:
        if not isinstance(ie, AttributeReference):
            raise UnsupportedOperationError(
                "correlated predicate must compare to a plain subquery column")
        keys.append(ie)
    for r in residuals:
        for x in r.iter_nodes():
            if isinstance(x, AttributeReference) and \
                    (outer_ids is None or x.expr_id not in outer_ids) and \
                    not any(x.expr_id == k.expr_id for k in keys):
                keys.append(x)
    out_ids = {a.expr_id for a in sub.output}
    missing = [k for k in keys if k.expr_id not in out_ids]
    if not missing:
        return sub
    if isinstance(sub, Aggregate):
        child_ids = {a.expr_id for a in sub.child.output}
        if all(k.expr_id in child_ids for k in missing):
            return Aggregate(
                list(sub.grouping_exprs) + missing,
                list(missing) + list(sub.aggregate_exprs),
                sub.child)
    if isinstance(sub, Project):
        child_ids = {a.expr_id for a in sub.child.output}
        if all(k.expr_id in child_ids for k in missing):
            return Project(list(sub.project_list) + missing, sub.child)
    raise UnsupportedOperationError(
        "correlated key is not reachable from the subquery output")


def _fresh_plan(plan: LogicalPlan, mapping: dict | None = None):
    """Deep-copy a RESOLVED plan with fresh expression ids everywhere —
    relations re-instanced, aliases re-minted, references remapped — so
    the copy can coexist with the original in one tree (or be embedded
    as an independent subquery) without id collisions."""
    from ..expr.expressions import Alias as _Alias
    from .logical import LocalRelation, LogicalRelation, RangeRelation

    mapping = {} if mapping is None else mapping

    def fix_expr(e):
        if isinstance(e, SubqueryExpression):
            return e.copy(plan=_fresh_plan(e.plan, mapping))
        if isinstance(e, _Alias):
            na = _Alias(e.child, e.name)  # new expr_id
            mapping[e.expr_id] = na.to_attribute()
            return na
        if isinstance(e, AttributeReference) and e.expr_id in mapping:
            return mapping[e.expr_id]
        return e

    def go(node):
        node = node.map_children(go)
        if isinstance(node, (LogicalRelation, LocalRelation)):
            new_attrs = []
            for a in node.attrs:
                na = mapping.get(a.expr_id)
                if na is None:
                    na = a.new_instance()
                    mapping[a.expr_id] = na
                new_attrs.append(na)
            node = node.copy(attrs=new_attrs)
        elif isinstance(node, RangeRelation):
            na = mapping.get(node.attr.expr_id)
            if na is None:  # one fresh id per OLD id (union-branch shape)
                na = node.attr.new_instance()
                mapping[node.attr.expr_id] = na
            node = node.copy(attr=na)
        return node.map_expressions(lambda ex: ex.transform_up(fix_expr))

    return go(plan)


def _existence_flag(target, child: LogicalPlan, outer_ids: set[int]):
    """Lower one IN/EXISTS expression to a left_outer "existence join"
    producing a boolean flag over `child` (reference: sqlcat
    ExistenceJoin). Returns (joined_plan, replacement_expression).
    Both uncorrelated AND equality-correlated IN carry full three-valued
    null semantics: unmatched + (NULL probe over a non-empty set, or a
    NULL among the set's values) → NULL, matching the reference's
    null-aware join (sqlcat/optimizer/subquery.scala)."""
    sub, pairs, _res, ok = split_correlation(target.plan, outer_ids)
    if not ok:
        raise UnsupportedOperationError(
            "unsupported correlated subquery in value position")
    # fresh ids for the spliced subtree: the same view lowered twice in
    # one SELECT (or appearing in both the outer query and the subquery)
    # must not alias the ids the previous lowering already spliced in
    sub, pairs, _ = _refresh_lowered(sub, pairs, [])
    flag = Alias(Literal(True), "__exists")
    cond = None
    null_case = None  # three-valued IN: unmatched + nulls present → NULL
    corr_probe = None  # correlated IN: per-key has-null probe join
    if isinstance(target, InSubquery):
        from ..expr.expressions import CaseWhen, Max

        value_attr = sub.output[0]
        if not pairs:
            # x IN (sub) with no match is NULL — not false — when x is
            # NULL or the subquery contains a NULL (reference: In's
            # null semantics). The has-null probe is an uncorrelated
            # scalar subquery over the SAME plan; it materializes in its
            # own QueryExecution so sharing the subtree is safe.
            hn_map: dict = {}
            sub_copy = _fresh_plan(sub, hn_map)
            hn_value = hn_map.get(value_attr.expr_id, value_attr)
            # one probe, three states: NULL = subquery empty, 1 = has a
            # NULL value, 0 = non-empty all non-null. IN over an EMPTY
            # set is false even for a NULL probe (reference In.eval).
            probe = ScalarSubquery(Aggregate([], [Alias(Max(CaseWhen(
                [(IsNull(hn_value), Literal(1))], Literal(0))),
                "__has_null")], sub_copy))
            null_case = Or(EqualTo(probe, Literal(1)),
                           And(IsNull(target.value), IsNotNull(probe)))
        else:
            # CORRELATED x IN (subq): same three states, but per
            # correlation key — a grouped left_outer probe join whose
            # has-null column is NULL when this outer row's set is
            # empty, 1 when it contains a NULL, 0 otherwise (the
            # reference's null-aware ExistenceJoin semantics,
            # sqlcat/optimizer/subquery.scala)
            hn_map = {}
            sub_copy = _fresh_plan(sub, hn_map)
            hn_value = hn_map.get(value_attr.expr_id, value_attr)
            ie_copies = []
            pairs_copy = []
            for oe, ie in pairs:
                ic = hn_map.get(ie.expr_id, ie)
                ie_copies.append(ic)
                pairs_copy.append((oe, ic))
            sub_copy = _expose_correlation_keys(sub_copy, pairs_copy)
            hn_alias = Alias(Max(CaseWhen(
                [(IsNull(hn_value), Literal(1))], Literal(0))),
                "__has_null")
            probe_plan = Aggregate(list(ie_copies),
                                   list(ie_copies) + [hn_alias], sub_copy)
            cond2 = None
            for oe, ic in pairs_copy:
                c = EqualTo(oe, ic)
                cond2 = c if cond2 is None else And(cond2, c)
            corr_probe = (probe_plan, cond2, probe_plan.output[-1])
        sub = _expose_correlation_keys(sub, pairs)
        keys = [value_attr] + [ie for _, ie in pairs]
        dsub = Aggregate(list(keys), list(keys) + [flag], sub)
        cond = EqualTo(target.value, value_attr)
        for outer_e, ie in pairs:
            cond = And(cond, EqualTo(outer_e, ie))
    elif pairs:
        sub = _expose_correlation_keys(sub, pairs)
        keys = [ie for _, ie in pairs]
        dsub = Aggregate(list(keys), list(keys) + [flag], sub)
        for outer_e, ie in pairs:
            c = EqualTo(outer_e, ie)
            cond = c if cond is None else And(cond, c)
    else:
        # uncorrelated EXISTS: 0/1-row flag relation, cross-style
        # left_outer (condition-less nested loop)
        dsub = Project([flag], Limit(1, sub))
    flag_attr = dsub.output[-1]
    joined = Join(child, dsub, "left_outer", cond)
    if corr_probe is not None:
        probe_plan, cond2, hn_attr = corr_probe
        joined = Join(joined, probe_plan, "left_outer", cond2)
        null_case = Or(EqualTo(hn_attr, Literal(1)),
                       And(IsNull(target.value), IsNotNull(hn_attr)))
    rep = IsNotNull(flag_attr)
    if null_case is not None:
        from ..expr.expressions import CaseWhen
        from ..types import boolean

        rep = CaseWhen([(rep, Literal(True)),
                        (null_case, Literal(None, boolean))],
                       Literal(False))
    return joined, rep


class RewriteExistenceSubquery(Rule):
    """IN/EXISTS used as a VALUE (inside a projection) → existence join
    (reference: sqlcat ExistenceJoin planned by RewritePredicateSubquery
    when the predicate is not a top-level Filter conjunct)."""

    def apply(self, plan):
        def rule(node):
            if not isinstance(node, Project):
                return node
            target = None
            for e in node.project_list:
                for x in e.iter_nodes():
                    if isinstance(x, (InSubquery, Exists)):
                        target = x
                        break
                if target is not None:
                    break
            if target is None:
                return node
            outer_ids = {a.expr_id for a in node.child.output}
            joined, rep = _existence_flag(target, node.child, outer_ids)

            def replace(x: Expression) -> Expression:
                return rep if x is target else x

            new_node = node.map_expressions(
                lambda e: e.transform_up(replace))
            return new_node.copy(child=joined)

        return plan.transform_up(rule)


class RewriteCorrelatedScalarSubquery(Rule):
    """Equality-correlated scalar subqueries with a top aggregate →
    left_outer join against the grouped aggregate (reference:
    sqlcat/optimizer/subquery.scala RewriteCorrelatedScalarSubquery —
    the TPC-DS q1/q6 shape: `x > (SELECT avg(y) FROM t WHERE t.k = outer.k)`)."""

    def apply(self, plan):
        def rule(node):
            if not isinstance(node, (Filter, Project)):
                return node
            subs = [x for e in node.expressions()
                    for x in e.iter_nodes()
                    if isinstance(x, ScalarSubquery)]
            corr = None
            outer_ids = {a.expr_id for a in node.child.output} \
                if node.children else set()
            for s in subs:
                if any(e2.references() & outer_ids
                       for n2 in s.plan.iter_nodes()
                       for e2 in n2.expressions()):
                    corr = s
                    break
            if corr is None:
                return node

            sub, pairs, _res, ok = split_correlation(corr.plan, outer_ids)
            if not ok or not pairs:
                raise UnsupportedOperationError(
                    "unsupported correlated scalar subquery (only equality "
                    "correlation is supported)")
            if not isinstance(sub, Aggregate) or sub.grouping_exprs:
                raise UnsupportedOperationError(
                    "correlated scalar subquery must be a simple aggregate")
            inner_keys: list[AttributeReference] = []
            for _, ie in pairs:
                if not isinstance(ie, AttributeReference):
                    raise UnsupportedOperationError(
                        "correlated key must be a plain column")
                inner_keys.append(ie)
            # regroup the aggregate by the correlation keys
            regrouped = Aggregate(
                list(inner_keys),
                list(inner_keys) + list(sub.aggregate_exprs),
                sub.child)
            value_attr = regrouped.output[len(inner_keys)]

            cond = None
            for (outer_e, _), ik in zip(pairs, inner_keys):
                c = EqualTo(outer_e, ik)
                cond = c if cond is None else And(cond, c)
            joined = Join(node.child, regrouped, "left_outer", cond)

            def replace(x: Expression) -> Expression:
                if x is corr:
                    return value_attr
                return x

            new_node = node.map_expressions(
                lambda e: e.transform_up(replace))
            new_node = new_node.copy(child=joined)
            if isinstance(new_node, Project):
                return new_node
            # the join widened a Filter's schema; restore the original output
            return Project(list(node.output), new_node)

        return plan.transform_up(rule)


