"""Concurrent load generator for the serving layer.

Shared by `bench.py --serve` and the `--serve` CI gate
(dev/validate_trace.py): N concurrent per-connection sessions replay a
mixed dashboard-style query set through one QueryService, and the
report carries the numbers the serving acceptance gates on — per-pool
completion counts and p50/p99 latency, peak queue depth, the
weight-normalized fairness ratio, and the driver KernelCache launch
delta across the run (to reconcile against the per-query attributed
totals in the stored profiles).

Worker threads are handed their work through `obs.metrics.scoped_submit`
(the obs-layer contract for thread pools): the submitting context rides
into the pool thread, so any scope active at submit time — and every
span/launch the queries record — stays correctly attributed.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor

from ..config import SERVE_POOL
from ..obs.export import Histogram
from ..obs.metrics import scoped_submit

__all__ = ["run_serve_load"]


def run_serve_load(service, queries, sessions: int = 8, reps: int = 2,
                   pools=("default",), pool_of=None,
                   session_mode: str | None = None) -> dict:
    """Drive `sessions` concurrent cloned sessions through `service`,
    each replaying `reps` rounds of the `queries` list under its pool
    (`pool_of(i)` or round-robin over `pools`). Returns the load
    report; individual query failures are recorded, not raised."""
    from ..physical.compile import GLOBAL_KERNEL_CACHE as KC

    kinds_before = dict(KC.launches_by_kind)
    # shared-mode workers use the server session, whose Metrics is
    # cumulative across its lifetime — baseline it so the report's
    # counters cover THIS load only (isolated clones start at zero)
    shared_before = service.session._metrics.snapshot()["counters"]
    t_start = time.perf_counter()

    def worker(i: int):
        sess = service.open_session(session_mode)
        pool = pool_of(i) if pool_of is not None \
            else pools[i % len(pools)]
        sess.conf.set(SERVE_POOL, pool)
        out = []
        for _ in range(int(reps)):
            for q in queries:
                t0 = time.perf_counter()
                err = None
                try:
                    service.execute_sql(sess, q)
                except Exception as e:
                    err = f"{type(e).__name__}: {e}"
                out.append((pool, (time.perf_counter() - t0) * 1000,
                            err))
        # isolated sessions count their own metrics — ship this clone's
        # counters so the report can aggregate (result_cache.hit etc.);
        # a shared-session worker ships None (summing the one shared
        # Metrics once per worker would multiply-count it)
        return out, (sess._metrics.snapshot()["counters"]
                     if sess is not service.session else None)

    results = []
    counters: dict = {}
    with ThreadPoolExecutor(max_workers=int(sessions),
                            thread_name_prefix="serve-load") as px:
        futs = [scoped_submit(px, worker, i) for i in range(int(sessions))]
        shared_any = False
        for f in futs:
            out, snap = f.result()
            results.extend(out)
            if snap is None:
                shared_any = True
                continue
            for k, v in snap.items():
                counters[k] = counters.get(k, 0) + v
    if shared_any:
        for k, v in service.session._metrics.snapshot()[
                "counters"].items():
            d = v - shared_before.get(k, 0)
            if d:
                counters[k] = counters.get(k, 0) + d
    wall_s = time.perf_counter() - t_start

    per_pool: dict = {}
    errors = []
    for pool, ms, err in results:
        ent = per_pool.setdefault(pool, {"completed": 0, "errors": 0,
                                         "hist": Histogram()})
        if err is None:
            ent["completed"] += 1
            ent["hist"].observe(ms)
        else:
            ent["errors"] += 1
            errors.append(err)
    status = service.status()
    report = {"wall_s": round(wall_s, 3),
              "sessions": int(sessions),
              "queries_total": len(results),
              "queue_depth_peak": max(
                  (p["queue_peak"] for p in status["pools"].values()),
                  default=0),
              "errors": errors[:8],
              "counters": {k: v for k, v in sorted(counters.items())
                           if k.startswith(("result_cache.", "compile.",
                                            "cache.", "obs."))},
              "pools": {}}
    for pool, ent in sorted(per_pool.items()):
        st = status["pools"].get(pool, {})
        weight = st.get("weight", 1.0) or 1.0
        # histogram-derived percentiles (mergeable fixed log buckets —
        # the same numbers a cross-process scrape merge would report)
        hist = ent["hist"]
        report["pools"][pool] = {
            "weight": weight,
            "completed": ent["completed"],
            "errors": ent["errors"],
            "p50_ms": hist.percentile_ms(0.50),
            "p95_ms": hist.percentile_ms(0.95),
            "p99_ms": hist.percentile_ms(0.99),
            "wait_p99_ms": st.get("wait_p99_ms"),
            "throughput_qps": round(ent["completed"] / max(wall_s, 1e-9),
                                    3),
        }
    # fairness under CONTENTION: total completions converge once the
    # lighter pool runs alone after the heavy pool drains, so the
    # honest share is the grant ratio while several pools had backlog
    report["contended_grants"] = service.scheduler.contended_grants()
    report["fairness_ratio"] = service.scheduler.fairness_ratio()
    kinds_after = dict(KC.launches_by_kind)
    report["driver_launch_delta"] = int(
        sum(kinds_after.values()) - sum(kinds_before.values()))
    return report
