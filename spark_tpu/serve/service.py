"""Multi-tenant query service: session routing + fair-pool admission.

The serving brain shared by the SQL endpoint (connect/sql_endpoint.py),
`bench.py --serve`, and the `--serve` CI gate
(dev/validate_trace.py). Role of the reference's
SparkSQLSessionManager + SparkSQLOperationManager over a shared
SparkContext (sql/hive-thriftserver): many logical sessions, one
engine process — here with weighted fair-scheduler pools and plan-time
HBM admission layered in front of execution.

One QueryService wraps one long-lived "server" TpuSession:

  * `open_session()` clones a per-connection session
    (TpuSession.newSession — connection-local SET/temp views, shared
    KernelCache/warehouse/persistent caches/cluster) or hands back the
    shared server session when `spark.tpu.serve.sessionMode=shared`
    (or the caller asks for "shared").

  * `execute_sql()` / `collect()` run a statement: parsing, analysis,
    planning and the admission decision happen on the calling thread
    (pure host work, zero launches), then the collect executes inside
    the session's fair-scheduler pool slot. With an HBM budget
    configured the plan analyzer's predicted peak is pre-flighted
    through the existing `check_memory_budget` path AND reserved
    against the aggregate in-flight budget — an over-budget query
    fails plan-time, a momentarily-unfittable one queues. Admitted
    queries execute exactly as they would without the serving layer.

  * `drain()` starts graceful shutdown: new statements raise
    ServerDraining, in-flight (and already-queued) queries finish and
    flush their query profiles, then the call returns.
"""

from __future__ import annotations

import threading

from ..config import (
    MEMORY_BUDGET, SERVE_DRAIN_TIMEOUT, SERVE_POOL, SERVE_SESSION_MODE,
)
from ..errors import ServerDraining
from .pools import FairScheduler, pool_configs

__all__ = ["QueryService"]


class QueryService:
    def __init__(self, session):
        self.session = session
        self.scheduler = FairScheduler(session.conf)
        self._lock = threading.Lock()
        self.sessions_opened = 0
        self.drain_snapshot = None
        # service metrics plane (spark.tpu.metrics.export): wire the
        # scrape sources over this service's pools/session and start
        # the time-series ticker — structurally nothing when off
        from ..obs import export as _export

        _export.configure(session.conf)
        if _export.ENABLED:
            _export.register_default_sources(session=session,
                                             scheduler=self.scheduler)
            _export.start_ticker()

    # -- sessions ---------------------------------------------------------
    def open_session(self, mode: str | None = None):
        """A session for one connection/tenant: a clone by default, the
        shared server session when the server (or this caller) opts
        into 'shared'."""
        if self.scheduler.draining:
            raise ServerDraining()
        mode = mode or str(self.session.conf.get(SERVE_SESSION_MODE))
        with self._lock:
            self.sessions_opened += 1
        if mode == "shared":
            return self.session
        return self.session.newSession()

    # -- execution --------------------------------------------------------
    def _predicted_hbm(self, qe, conf) -> int:
        """Plan-time HBM reservation for admission (zero launches). Only
        computed when some budget is configured — otherwise the analyzer
        is skipped entirely and admission is slot-only."""
        budget = int(conf.get(MEMORY_BUDGET))
        if budget <= 0 and not any(p.hbm_budget
                                   for p in pool_configs(conf).values()):
            return 0
        report = qe.analysis_report()
        # same pre-flight execute() would run — but HERE, before the
        # query ever queues, so an over-budget plan rejects immediately
        # with the named stage instead of waiting out a queue slot
        from ..obs.resources import check_memory_budget

        check_memory_budget(
            qe.physical, conf, report=report,
            cluster=getattr(qe.session, "_sql_cluster", None) is not None)
        # hand the report to execute()'s own pre-flight so the serving
        # hot path analyzes each plan ONCE, not twice
        qe._preflight_report = report
        return int(report.predicted_peak_hbm or 0)

    def collect(self, session, df, pool: str | None = None,
                timeout: float | None = None):
        """Admit one DataFrame collect through the session's pool."""
        if self.scheduler.draining:
            raise ServerDraining()
        qe = df.query_execution
        conf = session.conf
        hbm = self._predicted_hbm(qe, conf)
        if pool is None:
            pool = str(conf.get(SERVE_POOL) or "default")
        try:
            ticket = self.scheduler.submit(pool, hbm=hbm)
            self.scheduler.wait(ticket, timeout=timeout)
        except Exception as admission_err:
            # black box: an admission rejection (queue full / timeout)
            # bundles the serving/metrics state that explains it
            # (rate-limited; never masks the rejection itself)
            from ..obs import blackbox

            if blackbox.ENABLED:
                try:
                    blackbox.record_rejection(self.session, admission_err,
                                              pool=pool, qe=qe)
                except Exception:
                    pass
            raise
        try:
            table = df.toArrow()
            ctx = getattr(qe, "_last_ctx", None)
            if ctx is not None:
                self.scheduler.note_query(
                    ticket, getattr(ctx, "query_id", None))
            return table
        finally:
            # an SLO breach at release becomes an obs.slo finding on
            # the query's live record — the list EXPLAIN ANALYZE and
            # pool status already surface
            finding = self.scheduler.release(ticket)
            if finding is not None:
                live = getattr(self.session, "live_obs", None)
                if live is not None:
                    live.add_finding(ticket.query_id, finding)

    def execute_sql(self, session, sql: str):
        """One SQL statement for one session. Commands and other
        host-only statements (their result is a bare local relation —
        SET, DDL, SHOW) return without admission; real queries collect
        inside the session's pool slot."""
        if self.scheduler.draining:
            raise ServerDraining()
        out = session.sql(sql)
        if out is None or not hasattr(out, "toArrow"):
            return out
        from ..plan.logical import LocalRelation

        if isinstance(getattr(out, "plan", None), LocalRelation):
            # command result: already materialized host metadata
            return out.toArrow()
        # per-statement /*+ POOL(x) */ hint (session.sql validated it
        # against the declared pools and stamped the DataFrame)
        return self.collect(session, out,
                            pool=getattr(out, "_pool_hint", None))

    # -- lifecycle / status -----------------------------------------------
    def drain(self, timeout: float | None = None) -> bool:
        """Graceful shutdown: reject new queries (ServerDraining), let
        in-flight and already-queued queries finish — their close-time
        query profiles flush as part of normal query close — and
        return True when everything quiesced inside the timeout."""
        if timeout is None:
            timeout = float(self.session.conf.get(SERVE_DRAIN_TIMEOUT))
        self.scheduler.drain()
        ok = self.scheduler.quiesce(timeout)
        from ..obs import export as _export

        if _export.ENABLED:
            # drain-time snapshot: one last tick so the ring's tail is
            # the quiesced state, then freeze the time series
            _export.tick_once()
            self.drain_snapshot = _export.timeseries_snapshot()
            _export.stop_ticker()
        return ok

    def status(self) -> dict:
        """Per-pool live serving status incl. SLO findings from the
        live store (stragglers/regressions of each pool's recent
        queries) and — with the metrics plane on — sparkline series
        from the time-series ring."""
        st = self.scheduler.status(
            live_obs=getattr(self.session, "live_obs", None))
        st["sessions_opened"] = self.sessions_opened
        from ..obs import export as _export

        if _export.ENABLED:
            st["sparklines"] = _export.sparklines()
            if self.drain_snapshot is not None:
                st["drain_timeseries"] = self.drain_snapshot
        from ..obs import blackbox

        if blackbox.ENABLED:
            from ..config import OBS_BUNDLE_DIR

            bdir = str(self.session.conf.get(OBS_BUNDLE_DIR) or "")
            if bdir:
                st["bundles"] = blackbox.list_bundles(bdir)[:8]
        return st
