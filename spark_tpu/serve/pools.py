"""Fair-scheduler pools: weighted admission control for serving.

Role of the reference's fair scheduler (core/scheduler/Pool.scala,
SchedulableBuilder.scala — FairSchedulableBuilder parsing
fairscheduler.xml pools with weight/minShare, selected per thread via
spark.scheduler.pool), re-shaped for an engine whose unit of admission
is a whole QUERY and whose scarce resources are device dispatch slots
and HBM:

  * **Pools** come from `spark.tpu.scheduler.pools` declarations
    ('name[:weight]') plus per-pool override keys
    `spark.tpu.scheduler.pool.<name>.{weight,maxConcurrent,queueSize,
    queueTimeout,hbmBudget}`. The 'default' pool always exists; a
    session picks its pool with `SET spark.tpu.scheduler.pool`.

  * **Weighted fairness** is stride scheduling over grant counts: each
    grant advances the pool's virtual time by 1/weight and the next
    slot goes to the backlogged pool with the LOWEST post-grant virtual
    time (ties break by arrival order). A pool waking from idle is
    advanced to the global virtual clock first, so sleeping never banks
    credit. Under sustained backlog two pools with weights 2:1 are
    granted slots 2:1 — deterministically, independent of timing.

  * **Admission** is plan-time and zero-launch: a slot is granted only
    when the global `spark.tpu.serve.maxConcurrent` cap, the pool's own
    `maxConcurrent`, and the HBM reservation all allow it. The HBM leg
    aggregates the plan analyzer's predicted peak (the same number the
    existing `check_memory_budget` pre-flight rejects on) across
    IN-FLIGHT queries: a query that fits the budget alone but not next
    to the current in-flight set WAITS in its pool's queue instead of
    dispatching into an XLA OOM. Queues are bounded (`queueSize`, full
    ⇒ immediate PoolQueueFull) and timed (`queueTimeout`, expiry ⇒
    AdmissionTimeout). Admitted queries execute exactly as they would
    without the serving layer — plan_lint's launch model is untouched.

Pure host bookkeeping throughout: no kernel launches, no device syncs.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

from ..config import (
    MEMORY_BUDGET, SERVE_MAX_CONCURRENT, SERVE_POOL, SERVE_POOL_SLO,
    SERVE_POOLS, SERVE_QUEUE_SIZE, SERVE_QUEUE_TIMEOUT, SERVE_SLO_MS,
)
from ..errors import AdmissionTimeout, PoolQueueFull, ServerDraining
from ..obs.export import Histogram

__all__ = ["FairScheduler", "PoolConfig", "pool_configs"]

_QIDS = 32      # recent query ids retained per pool (SLO finding join)
_SLO_WINDOW = 64    # recent SLO verdicts per pool (rolling burn rate)


@dataclass
class PoolConfig:
    name: str
    weight: float = 1.0
    max_concurrent: int = 0      # 0 = only the global cap applies
    queue_size: int = 64
    queue_timeout_s: float = 30.0
    hbm_budget: int = 0          # 0 = inherit spark.tpu.memory.budget
    slo_ms: float = 0.0          # end-to-end latency SLO; 0 = off


def _one_pool(conf, name: str, weight: float | None = None) -> PoolConfig:
    base = SERVE_POOL.key   # "spark.tpu.scheduler.pool" (registered)

    def get(suffix, default, cast):
        v = conf.get(f"{base}.{name}.{suffix}", None)
        return cast(v) if v is not None else default

    # SLO targets live under the serve.* family (registered template
    # spark.tpu.serve.pool.<name>.sloMs; default spark.tpu.serve.sloMs)
    slo = conf.get(SERVE_POOL_SLO.key.replace("<name>", name), None)
    return PoolConfig(
        name=name,
        weight=max(get("weight", weight if weight is not None else 1.0,
                       float), 1e-9),
        max_concurrent=get("maxConcurrent", 0, int),
        queue_size=get("queueSize", int(conf.get(SERVE_QUEUE_SIZE)), int),
        queue_timeout_s=get("queueTimeout",
                            float(conf.get(SERVE_QUEUE_TIMEOUT)), float),
        hbm_budget=get("hbmBudget", 0, int),
        slo_ms=float(slo) if slo is not None
        else float(conf.get(SERVE_SLO_MS)))


def pool_configs(conf) -> dict[str, PoolConfig]:
    """Declared pools (+ the always-present 'default')."""
    names: dict[str, float | None] = {"default": None}
    for part in str(conf.get(SERVE_POOLS) or "").split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            n, w = part.split(":", 1)
            names[n.strip()] = float(w)
        else:
            names[part] = None
    return {n: _one_pool(conf, n, w) for n, w in names.items()}


class _Ticket:
    __slots__ = ("pool", "hbm", "seq", "granted", "released", "enq_t",
                 "grant_t", "query_id")

    def __init__(self, pool: str, hbm: int, seq: int):
        self.pool = pool
        self.hbm = int(hbm)
        self.seq = seq
        self.granted = False
        self.released = False
        self.enq_t = time.perf_counter()
        self.grant_t = 0.0
        self.query_id = None


class _PoolState:
    __slots__ = ("cfg", "queue", "running", "hbm_inflight", "served",
                 "granted", "completed", "rejected_timeout",
                 "rejected_full", "queue_peak", "hist_wait", "hist_exec",
                 "hist_e2e", "busy_ms", "recent_qids", "slo_breaches",
                 "slo_ok", "slo_window")

    def __init__(self, cfg: PoolConfig):
        self.cfg = cfg
        self.queue: deque[_Ticket] = deque()
        self.running = 0
        self.hbm_inflight = 0
        self.served = 0.0    # stride virtual-time counter (float: idle
        self.granted = 0     # catch-up snaps it to the clock); `granted`
        #                      is the integer lifetime grant count
        self.completed = 0
        self.rejected_timeout = 0
        self.rejected_full = 0
        self.queue_peak = 0
        # mergeable fixed log-bucket latency distributions (replacing
        # the PR 15 sample rings): admission wait (enqueue→grant),
        # execution (grant→release), end-to-end (enqueue→release) —
        # cross-process merge reproduces single-registry quantiles
        self.hist_wait = Histogram()
        self.hist_exec = Histogram()
        self.hist_e2e = Histogram()
        self.busy_ms = 0.0
        self.recent_qids: deque = deque(maxlen=_QIDS)
        # SLO burn accounting (cfg.slo_ms > 0): lifetime ok/breach
        # counters plus a rolling verdict window for the burn rate
        self.slo_breaches = 0
        self.slo_ok = 0
        self.slo_window: deque = deque(maxlen=_SLO_WINDOW)

    def burn_rate(self):
        """Fraction of recent completions over the SLO target (rolling
        window); None before any SLO-tracked completion."""
        if not self.slo_window:
            return None
        return round(sum(self.slo_window) / len(self.slo_window), 4)


class FairScheduler:
    """Weighted fair admission over pools. submit() enqueues (raises
    PoolQueueFull/ServerDraining), wait() blocks for the grant (raises
    AdmissionTimeout, which also dequeues the ticket), release() frees
    the slot and dispatches the next winner (QueryService.collect is
    the canonical submit → wait → try/finally-release caller)."""

    def __init__(self, conf):
        self._conf = conf
        self._cond = threading.Condition()
        self._pools: dict[str, _PoolState] = {
            name: _PoolState(cfg)
            for name, cfg in pool_configs(conf).items()}
        self._seq = 0
        self._running_total = 0
        self._hbm_total = 0
        self._vclock = 0.0      # global virtual time (stride scheduling)
        self._draining = False
        # (granted pool, pools-with-queued-demand-at-grant): the
        # fairness evidence — only grants made while SEVERAL pools had
        # backlog say anything about weighted share (after one pool's
        # demand drains, the survivor rightly takes every slot)
        self.grant_log: deque = deque(maxlen=4096)

    # -- admission --------------------------------------------------------
    def _pool_state(self, name: str) -> _PoolState:
        st = self._pools.get(name)
        if st is None:
            # undeclared pool: created on demand with default settings
            # (the reference logs a warning and falls back similarly)
            st = self._pools[name] = _PoolState(_one_pool(self._conf,
                                                          name))
        return st

    def submit(self, pool: str = "default", hbm: int = 0) -> _Ticket:
        with self._cond:
            if self._draining:
                raise ServerDraining()
            st = self._pool_state(pool)
            if len(st.queue) >= max(int(st.cfg.queue_size), 1):
                st.rejected_full += 1
                raise PoolQueueFull(pool, st.cfg.queue_size)
            if not st.queue and st.running == 0:
                # waking from idle: advance to the global virtual clock
                # so an idle period never banks scheduling credit
                st.served = max(st.served,
                                self._vclock * st.cfg.weight)
            self._seq += 1
            t = _Ticket(pool, hbm, self._seq)
            st.queue.append(t)
            st.queue_peak = max(st.queue_peak, len(st.queue))
            self._dispatch()
            return t

    def wait(self, ticket: _Ticket, timeout: float | None = None) -> None:
        with self._cond:
            st = self._pool_state(ticket.pool)
            if timeout is None:
                timeout = st.cfg.queue_timeout_s
            deadline = ticket.enq_t + max(float(timeout), 0.0)
            while not ticket.granted:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            if not ticket.granted:
                try:
                    st.queue.remove(ticket)
                except ValueError:
                    pass
                st.rejected_timeout += 1
                # the removal may unblock another pool's head
                self._dispatch()
                raise AdmissionTimeout(ticket.pool, float(timeout))

    def release(self, ticket: _Ticket) -> dict | None:
        """Free the slot and dispatch the next winner. Records the
        ticket's execution and end-to-end latency into the pool's
        mergeable histograms; with an SLO target configured, returns
        the obs.slo finding when this completion breached it (the
        caller — QueryService.collect — forwards it to the live store,
        which feeds EXPLAIN ANALYZE and pool status), else None."""
        with self._cond:
            if ticket.released or not ticket.granted:
                return None
            ticket.released = True
            now = time.perf_counter()
            st = self._pool_state(ticket.pool)
            st.running -= 1
            st.hbm_inflight -= ticket.hbm
            st.completed += 1
            lat = (now - ticket.grant_t) * 1000
            e2e = (now - ticket.enq_t) * 1000
            st.hist_exec.observe(lat)
            st.hist_e2e.observe(e2e)
            st.busy_ms += lat
            self._running_total -= 1
            self._hbm_total -= ticket.hbm
            finding = None
            slo = st.cfg.slo_ms
            if slo > 0:
                breached = e2e > slo
                st.slo_window.append(1 if breached else 0)
                if breached:
                    st.slo_breaches += 1
                    finding = {
                        "severity": "warning", "kind": "obs.slo",
                        "query": ticket.query_id, "pool": ticket.pool,
                        "slo_ms": slo, "e2e_ms": round(e2e, 3),
                        "burn_rate": st.burn_rate(),
                        "msg": f"SLO burn: pool {ticket.pool!r} query "
                               f"took {e2e:.1f}ms end-to-end against a "
                               f"{slo:.0f}ms target (burn rate "
                               f"{st.burn_rate():.0%} of recent "
                               "completions)"}
                else:
                    st.slo_ok += 1
            self._dispatch()
            self._cond.notify_all()
        return finding

    def note_query(self, ticket: _Ticket, query_id: str | None) -> None:
        """Associate an executed query id with the ticket's pool so
        status() can surface the query's live findings as pool SLO
        signals."""
        if not query_id:
            return
        ticket.query_id = query_id
        with self._cond:
            self._pool_state(ticket.pool).recent_qids.append(query_id)

    # -- the weighted pick ------------------------------------------------
    def _dispatch(self) -> None:
        """Grant every slot currently grantable (caller holds the lock).
        Pure host arithmetic — the decision reads plan-time metadata
        only."""
        mx = int(self._conf.get(SERVE_MAX_CONCURRENT))
        gbudget = int(self._conf.get(MEMORY_BUDGET))
        granted_any = False
        while True:
            if mx > 0 and self._running_total >= mx:
                break
            best = None
            for st in self._pools.values():
                if not st.queue:
                    continue
                cfg = st.cfg
                if cfg.max_concurrent > 0 \
                        and st.running >= cfg.max_concurrent:
                    continue
                head = st.queue[0]
                pbudget = cfg.hbm_budget or gbudget
                # HBM reservation: wait for in-flight queries to free
                # budget. An EMPTY pool/process always admits its head —
                # the per-query check_memory_budget pre-flight already
                # rejected anything that cannot fit alone, so this can
                # never deadlock on an impossible reservation.
                if pbudget > 0 and st.hbm_inflight + head.hbm > pbudget \
                        and st.running > 0:
                    continue
                if gbudget > 0 and self._hbm_total + head.hbm > gbudget \
                        and self._running_total > 0:
                    continue
                key = ((st.served + 1.0) / cfg.weight, head.seq)
                if best is None or key < best[0]:
                    best = (key, st)
            if best is None:
                break
            st = best[1]
            self.grant_log.append(
                (st.cfg.name,
                 frozenset(n for n, s in self._pools.items() if s.queue)))
            t = st.queue.popleft()
            t.granted = True
            t.grant_t = time.perf_counter()
            st.running += 1
            st.served += 1
            st.granted += 1
            st.hbm_inflight += t.hbm
            st.hist_wait.observe((t.grant_t - t.enq_t) * 1000)
            self._running_total += 1
            self._hbm_total += t.hbm
            self._vclock = max(self._vclock, st.served / st.cfg.weight)
            granted_any = True
        if granted_any:
            self._cond.notify_all()

    # -- drain / status ---------------------------------------------------
    def drain(self) -> None:
        """Reject new submissions from now on; already-queued queries
        are accepted work and still run to completion."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    @property
    def draining(self) -> bool:
        return self._draining

    def in_flight(self) -> int:
        with self._cond:
            return self._running_total + sum(len(st.queue)
                                             for st in self._pools.values())

    def quiesce(self, timeout: float = 30.0) -> bool:
        """Wait until nothing is running or queued (True) or the
        timeout passes (False)."""
        deadline = time.monotonic() + max(timeout, 0.0)
        with self._cond:
            while self._running_total > 0 or any(
                    st.queue for st in self._pools.values()):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True

    def status(self, live_obs=None, findings_limit: int = 8) -> dict:
        """Per-pool live serving status: queued/running/rejected depths,
        admission latency percentiles, HBM reservations, and — when the
        session's live store is passed — the straggler/regression
        findings raised for this pool's recent queries (per-pool SLO
        signals)."""
        with self._cond:
            pools = {}
            qids = {}
            for name, st in self._pools.items():
                pools[name] = {
                    "weight": st.cfg.weight,
                    "running": st.running,
                    "queued": len(st.queue),
                    "queue_peak": st.queue_peak,
                    "admitted": st.granted,
                    "completed": st.completed,
                    "rejected_timeout": st.rejected_timeout,
                    "rejected_full": st.rejected_full,
                    "busy_ms": round(st.busy_ms, 3),
                    "hbm_inflight": st.hbm_inflight,
                    # histogram-derived percentiles (bucket upper edges
                    # — identical across any process merge)
                    "p50_ms": st.hist_exec.percentile_ms(0.50),
                    "p95_ms": st.hist_exec.percentile_ms(0.95),
                    "p99_ms": st.hist_exec.percentile_ms(0.99),
                    "wait_p50_ms": st.hist_wait.percentile_ms(0.50),
                    "wait_p99_ms": st.hist_wait.percentile_ms(0.99),
                    "e2e_p50_ms": st.hist_e2e.percentile_ms(0.50),
                    "e2e_p99_ms": st.hist_e2e.percentile_ms(0.99),
                }
                if st.cfg.slo_ms > 0:
                    pools[name]["slo"] = {
                        "slo_ms": st.cfg.slo_ms,
                        "ok": st.slo_ok,
                        "breaches": st.slo_breaches,
                        "burn_rate": st.burn_rate(),
                    }
                qids[name] = list(st.recent_qids)
            out = {"draining": self._draining,
                   "running": self._running_total,
                   "hbm_inflight": self._hbm_total,
                   "pools": pools}
        if live_obs is not None:
            for name, ids in qids.items():
                try:
                    f = live_obs.recent_findings(ids,
                                                 limit=findings_limit)
                except Exception:
                    f = []
                if f:
                    out["pools"][name]["slo_findings"] = f
        return out

    def metrics_samples(self) -> list:
        """Scrape-time pull for the metrics registry (obs/export.py):
        per-pool counters, depth gauges, SLO burn counters, and the
        three latency histograms under a {pool} label. Pure host reads
        under the scheduler lock."""
        out = []
        with self._cond:
            out.append(("gauge", "serve.running", (),
                        float(self._running_total)))
            out.append(("gauge", "serve.hbm_inflight", (),
                        float(self._hbm_total)))
            for name, st in self._pools.items():
                lbl = (("pool", name),)
                out.extend([
                    ("gauge", "serve.pool.running", lbl,
                     float(st.running)),
                    ("gauge", "serve.pool.queued", lbl,
                     float(len(st.queue))),
                    ("counter", "serve.pool.admitted", lbl, st.granted),
                    ("counter", "serve.pool.completed", lbl,
                     st.completed),
                    ("counter", "serve.pool.rejected_timeout", lbl,
                     st.rejected_timeout),
                    ("counter", "serve.pool.rejected_full", lbl,
                     st.rejected_full),
                    ("histogram", "serve.pool.wait_ms", lbl,
                     st.hist_wait.snapshot()),
                    ("histogram", "serve.pool.exec_ms", lbl,
                     st.hist_exec.snapshot()),
                    ("histogram", "serve.pool.e2e_ms", lbl,
                     st.hist_e2e.snapshot()),
                ])
                if st.cfg.slo_ms > 0:
                    out.append(("counter", "serve.pool.slo_breaches",
                                lbl, st.slo_breaches))
                    out.append(("counter", "serve.pool.slo_ok", lbl,
                                st.slo_ok))
        return out

    def contended_grants(self) -> dict:
        """Per-pool slot grants made while at least two pools had queued
        demand — the weighted-fairness evidence: for uniform queries the
        contended-grant ratio IS the throughput share under contention
        (2:1 weights ⇒ 2:1 grants, by the stride pick)."""
        with self._cond:
            log = list(self.grant_log)
        out: dict = {}
        for name, waiters in log:
            if len(waiters) >= 2:
                out[name] = out.get(name, 0) + 1
        return out

    def fairness_ratio(self) -> float | None:
        """max/min of weight-normalized contended-grant shares across
        pools that saw contention (1.0 = perfectly proportional); None
        when fewer than two pools ever contended."""
        grants = self.contended_grants()
        if len(grants) < 2:
            return None
        with self._cond:
            shares = [grants[n] / max(self._pools[n].cfg.weight, 1e-9)
                      for n in grants]
        lo = min(shares)
        return round(max(shares) / lo, 3) if lo > 0 else None

    def balanced(self) -> bool:
        """True when every reservation has been returned — the
        drain-gate invariant (no leaked slots, no leaked HBM)."""
        with self._cond:
            return (self._running_total == 0 and self._hbm_total == 0
                    and all(st.running == 0 and st.hbm_inflight == 0
                            and not st.queue
                            for st in self._pools.values()))


