"""Multi-tenant query serving: fair-scheduler pools, admission queues,
session isolation (ROADMAP direction 1 — "from engine to service").

Layers over the existing session/scheduler/obs/persist-cache stack:
per-connection cloned sessions (api/session.TpuSession.newSession)
share the process KernelCache, warehouse catalog and persistent caches
while keeping SET/temp views connection-local; weighted fair-scheduler
pools (pools.FairScheduler) queue and admit queries with plan-time HBM
reservations; QueryService (service.py) ties both to SQL execution and
graceful drain; loadgen.run_serve_load drives the measurable proof.
The SQL endpoint (connect/sql_endpoint.py) is the wire surface.
"""

from .pools import FairScheduler, PoolConfig, pool_configs
from .service import QueryService

__all__ = ["FairScheduler", "PoolConfig", "QueryService", "pool_configs"]
