"""SQL scripting: BEGIN … END compound statements with control flow.

Role of the reference's SQL scripting interpreter
(sql/core/.../scripting/SqlScriptingExecution.scala +
SqlScriptingInterpreter — SQL/PSM): a script is a BEGIN…END block of
';'-separated statements with DECLARE/SET variables (shared with the
session-variable machinery in plan/commands.py), IF/ELSEIF/ELSE,
WHILE…DO, REPEAT…UNTIL, nested BEGIN blocks, and LEAVE.

Structure: a quote/paren/CASE-aware word scanner first NORMALIZES the
script — inserting statement breaks after every control header (THEN,
DO, ELSE, BEGIN, REPEAT) and before every terminator (END*, ELSEIF,
ELSE, UNTIL) — so each resulting fragment is exactly one header, one
terminator, or one plain statement. A recursive-descent parser then
builds a small AST, and the executor walks it, running leaf statements
through session.sql (every statement form the engine supports works
unchanged inside a script). CASE…WHEN…THEN…ELSE…END expressions inside
statements are left intact: the scanner tracks CASE nesting and
ignores control words inside it.

The script's result is the LAST executed query's result, returned as a
materialized DataFrame (it is not re-executed when the caller collects
it). Variables DECLAREd inside a block are dropped when the block
exits (scoped, SqlScriptingContextManager role).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional


def is_script(text: str) -> bool:
    return bool(re.match(r"\s*BEGIN\b", text, re.I))


# ---------------------------------------------------------------------------
# Normalization: one fragment per header/terminator/statement
# ---------------------------------------------------------------------------

_WORD = re.compile(r"[A-Za-z_][A-Za-z_0-9]*")
# headers end a fragment AFTER the word; terminators break BEFORE it
_BREAK_AFTER = {"THEN", "DO", "ELSE", "BEGIN", "REPEAT"}
_BREAK_BEFORE = {"END", "ELSEIF", "ELIF", "ELSE", "UNTIL"}


def _normalize(body: str) -> list[str]:
    """Split into fragments at top-level ';' AND around control words,
    skipping quotes, parens, and CASE…END expressions."""
    frags: list[str] = []
    buf: list[str] = []
    i, n = 0, len(body)
    depth = 0
    case_depth = 0

    def flush():
        s = "".join(buf).strip()
        if s:
            frags.append(s)
        buf.clear()

    while i < n:
        ch = body[i]
        if ch in ("'", '"'):
            q = ch
            j = i + 1
            while j < n and body[j] != q:
                j += 2 if body[j] == "\\" else 1
            buf.append(body[i:j + 1])
            i = j + 1
            continue
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == ";" and depth == 0 and case_depth == 0:
            flush()
            i += 1
            continue
        m = _WORD.match(body, i) if ch.isalpha() or ch == "_" else None
        if m and depth == 0:
            w = m.group(0).upper()
            if w == "CASE":
                case_depth += 1
            elif case_depth > 0:
                if w == "END":
                    case_depth -= 1
            else:
                if w in _BREAK_BEFORE:
                    flush()
                if w == "END":
                    # grab the qualifier (IF/WHILE/REPEAT) if present
                    j = m.end()
                    while j < n and body[j].isspace():
                        j += 1
                    m2 = _WORD.match(body, j)
                    if m2 and m2.group(0).upper() in \
                            ("IF", "WHILE", "REPEAT"):
                        frags.append("END " + m2.group(0).upper())
                        i = m2.end()
                    else:
                        frags.append("END")
                        i = m.end()
                    continue
                buf.append(m.group(0))
                if w in _BREAK_AFTER:
                    flush()
                i = m.end()
                continue
            buf.append(m.group(0))
            i = m.end()
            continue
        buf.append(ch)
        i += 1
    flush()
    return frags


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

@dataclass
class _Sql:
    text: str


@dataclass
class _Leave:
    pass


@dataclass
class _If:
    branches: list  # [(cond_sql, [stmts])]
    orelse: list = field(default_factory=list)


@dataclass
class _While:
    cond: str
    body: list


@dataclass
class _Repeat:
    body: list
    until: str


@dataclass
class _Block:
    body: list


class _Parser:
    def __init__(self, frags: list[str]):
        self.frags = frags
        self.i = 0

    def peek(self) -> Optional[str]:
        return self.frags[self.i] if self.i < len(self.frags) else None

    def next(self) -> str:  # noqa: A003
        f = self.frags[self.i]
        self.i += 1
        return f

    def parse_block(self, stops: tuple) -> list:
        out = []
        while True:
            f = self.peek()
            if f is None:
                raise ValueError(f"script block not terminated "
                                 f"(expected one of {stops})")
            up = f.upper()
            if any(up == s or up.startswith(s + " ") for s in stops):
                return out
            out.append(self.parse_statement())

    def parse_statement(self):
        f = self.next()
        up = f.upper()
        head = up.split(None, 1)[0] if up else ""
        if head == "IF":
            cond = re.sub(r"^\s*IF\b", "", f, flags=re.I)
            cond = re.sub(r"\bTHEN\s*$", "", cond, flags=re.I)
            branches = [(cond, self.parse_block(
                ("ELSEIF", "ELIF", "ELSE", "END IF")))]
            orelse = []
            while True:
                t = self.next()
                tu = t.upper()
                if tu.startswith(("ELSEIF", "ELIF")):
                    c = re.sub(r"^\s*\w+\b", "", t)
                    c = re.sub(r"\bTHEN\s*$", "", c, flags=re.I)
                    branches.append((c, self.parse_block(
                        ("ELSEIF", "ELIF", "ELSE", "END IF"))))
                elif tu == "ELSE":
                    orelse = self.parse_block(("END IF",))
                elif tu == "END IF":
                    return _If(branches, orelse)
                else:
                    raise ValueError(f"unexpected {t!r} in IF")
        if head == "WHILE":
            cond = re.sub(r"^\s*WHILE\b", "", f, flags=re.I)
            cond = re.sub(r"\bDO\s*$", "", cond, flags=re.I)
            body = self.parse_block(("END WHILE",))
            self.next()  # END WHILE
            return _While(cond, body)
        if up == "REPEAT":
            body = self.parse_block(("UNTIL",))
            until = re.sub(r"^\s*UNTIL\b", "", self.next(), flags=re.I)
            if (self.peek() or "").upper() != "END REPEAT":
                raise ValueError("UNTIL must be followed by END REPEAT")
            self.next()
            return _Repeat(body, until)
        if up == "BEGIN":
            body = self.parse_block(("END",))
            self.next()  # END
            return _Block(body)
        if head == "LEAVE":
            return _Leave()
        return _Sql(f)


# ---------------------------------------------------------------------------
# Execution
# ---------------------------------------------------------------------------

class _LeaveSignal(Exception):
    pass


class ScriptInterpreter:
    def __init__(self, session):
        self.session = session
        self.last_table = None

    def execute(self, text: str):
        m = re.match(r"\s*BEGIN\b(.*)\bEND\s*;?\s*$", text, re.I | re.S)
        if not m:
            raise ValueError("script must be BEGIN ... END")
        frags = _normalize(m.group(1))
        parser = _Parser(frags)
        body = []
        while parser.peek() is not None:
            body.append(parser.parse_statement())
        try:
            self._run_block(body)
        except _LeaveSignal:
            pass
        if self.last_table is None:
            return None
        return self.session.createDataFrame(self.last_table)

    def _run_block(self, body):
        # (name, previous Literal or None): a DECLARE that shadows an
        # outer variable restores the outer value at block exit
        # (SqlScriptingContextManager scoping)
        declared: list[tuple] = []
        try:
            for stmt in body:
                self._run(stmt, declared)
        finally:
            varstore = self.session.catalog_.variables
            for name, prev in reversed(declared):
                if prev is None:
                    varstore.pop(name.lower(), None)
                else:
                    varstore[name.lower()] = prev

    def _run(self, stmt, declared):
        if isinstance(stmt, _Sql):
            m = re.match(
                r"\s*DECLARE\s+(?:OR\s+REPLACE\s+)?"
                r"(?:VARIABLE\s+|VAR\s+)?([A-Za-z_]\w*)",
                stmt.text, re.I)
            if m:
                name = m.group(1)
                varstore = self.session.catalog_.variables
                prev = varstore.pop(name.lower(), None)
                declared.append((name, prev))
            result = self.session.sql(stmt.text)
            if hasattr(result, "toArrow"):
                # materialize once; the script returns this table so the
                # caller's collect doesn't re-execute the statement
                self.last_table = result.toArrow()
        elif isinstance(stmt, _Leave):
            raise _LeaveSignal()
        elif isinstance(stmt, _Block):
            self._run_block(stmt.body)
        elif isinstance(stmt, _If):
            for cond, body in stmt.branches:
                if self._truthy(cond):
                    self._run_block(body)
                    return
            self._run_block(stmt.orelse)
        elif isinstance(stmt, _While):
            guard = 0
            try:
                while self._truthy(stmt.cond):
                    guard += 1
                    if guard > 10_000:
                        raise RuntimeError(
                            "WHILE exceeded 10000 iterations")
                    self._run_block(stmt.body)
            except _LeaveSignal:
                pass
        elif isinstance(stmt, _Repeat):
            guard = 0
            try:
                while True:
                    guard += 1
                    if guard > 10_000:
                        raise RuntimeError(
                            "REPEAT exceeded 10000 iterations")
                    self._run_block(stmt.body)
                    if self._truthy(stmt.until):
                        break
            except _LeaveSignal:
                pass

    def _truthy(self, cond: str) -> bool:
        table = self.session.sql(f"SELECT ({cond}) AS c").toArrow()
        return bool(table.column(0)[0].as_py())


def execute_script(session, text: str):
    """Run a BEGIN…END script; returns the last statement's result as a
    materialized DataFrame (or None)."""
    return ScriptInterpreter(session).execute(text)
