"""SQL lexer.

Role of the reference's ANTLR SqlBaseLexer.g4 (sql/api/src/main/antlr4/...),
hand-rolled: the token stream feeds the recursive-descent/Pratt parser in
sql/parser.py.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..errors import ParseException

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "like", "rlike", "between",
    "is", "null", "true", "false", "case", "when", "then", "else", "end",
    "cast", "join", "inner", "left", "right", "full", "outer", "cross",
    "semi", "anti", "on", "using", "union", "all", "distinct", "with",
    "asc", "desc", "nulls", "first", "last", "exists", "interval", "date",
    "timestamp", "values", "create", "table", "view", "temporary", "replace",
    "drop", "insert", "into", "describe", "show", "tables", "explain",
    "escape", "div", "over", "partition", "rows", "range", "unbounded",
    "preceding", "following", "current", "row", "intersect", "minus",
    "rollup", "cube", "grouping", "except",
    "update", "delete", "merge", "matched", "set",
}


@dataclass
class Token:
    kind: str   # kw | ident | num | str | op | eof
    value: str
    pos: int

    def __repr__(self):
        return f"{self.kind}:{self.value}"


_TWO_CHAR_OPS = ("<=>", "<<", ">>", "<=", ">=", "<>", "!=", "==", "||", "->")


def tokenize(text: str) -> list[Token]:
    toks: list[Token] = []
    i = 0
    n = len(text)
    while i < n:
        c = text[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and i + 1 < n and text[i + 1] == "-":
            while i < n and text[i] != "\n":
                i += 1
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "*":
            j = text.find("*/", i + 2)
            i = n if j < 0 else j + 2
            continue
        start = i
        if c == "0" and i + 1 < n and text[i + 1] in "xX" \
                and i + 2 < n and (text[i + 2].isdigit()
                                   or text[i + 2] in "abcdefABCDEF"):
            i += 2
            while i < n and (text[i].isdigit() or text[i] in "abcdefABCDEF"):
                i += 1
            toks.append(Token("num", text[start:i], start))
            continue
        if c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            i += 1
            isfloat = c == "."
            while i < n and (text[i].isdigit() or text[i] in ".eE" or
                             (text[i] in "+-" and text[i - 1] in "eE")):
                if text[i] in ".eE":
                    isfloat = True
                i += 1
            # type suffixes: L/l (long), D/d (double), S/s, BD
            if i < n and text[i] in "LlDdSs":
                i += 1
            toks.append(Token("num", text[start:i], start))
            continue
        if c.isalpha() or c == "_":
            i += 1
            while i < n and (text[i].isalnum() or text[i] == "_"):
                i += 1
            word = text[start:i]
            kind = "kw" if word.lower() in KEYWORDS else "ident"
            toks.append(Token(kind, word, start))
            continue
        if c == "`" or c == '"':
            q = c
            i += 1
            buf = []
            while i < n and text[i] != q:
                buf.append(text[i])
                i += 1
            if i >= n:
                raise ParseException(f"unterminated identifier at {start}")
            i += 1
            toks.append(Token("ident", "".join(buf), start))
            continue
        if c == "'":
            i += 1
            buf = []
            while i < n:
                if text[i] == "'" and i + 1 < n and text[i + 1] == "'":
                    buf.append("'")
                    i += 2
                    continue
                if text[i] == "'":
                    break
                if text[i] == "\\" and i + 1 < n:
                    esc = text[i + 1]
                    buf.append({"n": "\n", "t": "\t", "\\": "\\", "'": "'"}
                               .get(esc, "\\" + esc))
                    i += 2
                    continue
                buf.append(text[i])
                i += 1
            if i >= n:
                raise ParseException(f"unterminated string at {start}")
            i += 1
            toks.append(Token("str", "".join(buf), start))
            continue
        for op in _TWO_CHAR_OPS:
            if text.startswith(op, i):
                toks.append(Token("op", op, start))
                i += len(op)
                break
        else:
            if c in "+-*/%(),.=<>!|&^~[]:;":
                toks.append(Token("op", c, start))
                i += 1
            else:
                raise ParseException(f"unexpected character {c!r} at {start}")
    toks.append(Token("eof", "", n))
    return toks
