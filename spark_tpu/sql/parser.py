"""SQL parser: text → unresolved LogicalPlan.

Role of the reference's AstBuilder over the ANTLR grammar
(sqlcat/parser/AstBuilder.scala, 8077 LoC; grammar sql/api/src/main/antlr4/
SqlBaseParser.g4). Hand-rolled recursive descent + Pratt expression parsing
covering the analytic-SQL core: SELECT/FROM/JOIN (all types, ON/USING)/
WHERE/GROUP BY (incl. ordinals)/HAVING/ORDER BY/LIMIT/OFFSET, UNION [ALL],
WITH CTEs, subqueries in FROM, CASE/CAST/IN/LIKE/BETWEEN/IS NULL, date
literals, and a data-type grammar.
"""

from __future__ import annotations

import datetime
import decimal as _decimal

from ..errors import ParseException
from ..plan import logical as L
from ..expr import expressions as E
from ..types import (
    BooleanType, DataType, DateType, DecimalType, DoubleType, FloatType,
    IntegerType, LongType, ShortType, StringType, TimestampType, boolean,
    date, float32, float64, int8, int16, int32, int64, string, timestamp,
)
from .lexer import Token, tokenize


def parse_sql(text: str) -> L.LogicalPlan:
    p = Parser(tokenize(text))
    plan = p.parse_statement()
    p.expect_eof()
    return plan


def parse_expression(text: str) -> E.Expression:
    p = Parser(tokenize(text))
    e = p.parse_named_expression()
    p.expect_eof()
    return e


def parse_data_type(text: str) -> DataType:
    p = Parser(tokenize(text))
    t = p.parse_type()
    p.expect_eof()
    return t


class Parser:
    def __init__(self, tokens: list[Token]):
        self.toks = tokens
        self.i = 0

    # --- token helpers ----------------------------------------------------
    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:  # noqa: A003
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.value.lower() in words

    def eat_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.next()
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.eat_kw(word):
            raise ParseException(
                f"expected {word.upper()} near {self.peek().value!r}")

    def eat_word(self, word: str) -> bool:
        """Consume a statement word that is not a reserved keyword
        (ANALYZE/COMPUTE/STATISTICS… lex as plain identifiers)."""
        t = self.peek()
        if t.kind in ("kw", "ident") and t.value.lower() == word:
            self.next()
            return True
        return False

    def expect_word(self, word: str) -> None:
        if not self.eat_word(word):
            raise ParseException(
                f"expected {word.upper()} near {self.peek().value!r}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def eat_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.eat_op(op):
            raise ParseException(
                f"expected {op!r} near {self.peek().value!r} "
                f"(pos {self.peek().pos})")

    def expect_eof(self) -> None:
        t = self.peek()
        if t.kind != "eof" and not (t.kind == "op" and t.value == ";"):
            raise ParseException(f"unexpected trailing input {t.value!r}")

    def ident(self) -> str:
        t = self.peek()
        if t.kind in ("ident", "kw"):
            self.next()
            return t.value
        raise ParseException(f"expected identifier near {t.value!r}")

    # --- statements -------------------------------------------------------
    def parse_statement(self):
        from ..plan import commands as C

        if self.at_kw("with", "select", "values") or self.at_op("("):
            return self.parse_query()
        if self.eat_kw("create"):
            replace = False
            if self.eat_kw("or"):
                self.expect_kw("replace")
                replace = True
            while self.peek().value.lower() in ("global", "temporary", "temp"):
                self.next()
            materialize = False
            if self.eat_kw("view"):
                pass
            elif self.eat_kw("table"):
                materialize = True
            else:
                raise ParseException("expected VIEW or TABLE")
            name = self._qualified_name()
            self.expect_kw("as")
            q = self.parse_query()
            return C.CreateViewCommand(name, q, replace=replace or True,
                                       materialize=materialize)
        if self.eat_kw("drop"):
            self.eat_word("temporary")
            if self.peek().value.lower() in ("variable", "var"):
                self.next()
                if_exists = False
                if self.eat_word("if"):
                    self.expect_word("exists")
                    if_exists = True
                return C.DropVariableCommand(self.ident(), if_exists)
            if not (self.eat_kw("view") or self.eat_kw("table")):
                raise ParseException("expected VIEW or TABLE")
            if_exists = False
            if self.peek().value.lower() == "if":
                self.next()
                self.expect_kw("exists")
                if_exists = True
            return C.DropRelationCommand(self._qualified_name(), if_exists)
        if self.eat_kw("insert"):
            overwrite = False
            if self.peek().value.lower() == "overwrite":
                self.next()
                overwrite = True
                self.eat_kw("table")
            else:
                self.expect_kw("into")
                self.eat_kw("table")
            name = self._qualified_name()
            q = self.parse_query()
            return C.InsertIntoCommand(name, q, overwrite)
        if self.eat_kw("update"):
            name = self._qualified_name()
            self.expect_kw("set")
            assigns = [self._parse_assignment()]
            while self.eat_op(","):
                assigns.append(self._parse_assignment())
            cond = self.parse_expr() if self.eat_kw("where") else None
            return C.UpdateCommand(name, assigns, cond)
        if self.eat_kw("delete"):
            self.expect_kw("from")
            name = self._qualified_name()
            cond = self.parse_expr() if self.eat_kw("where") else None
            return C.DeleteCommand(name, cond)
        if self.eat_kw("merge"):
            return self._parse_merge()
        if self.eat_kw("show"):
            if self.eat_word("functions"):
                pattern = None
                if self.eat_kw("like"):
                    t = self.next()
                    if t.kind != "str":
                        raise ParseException(
                            "SHOW FUNCTIONS LIKE expects a string "
                            f"literal, got {t.value!r}")
                    pattern = str(t.value)
                return C.ShowFunctionsCommand(pattern)
            self.expect_kw("tables")
            return C.ShowTablesCommand()
        if self.eat_kw("describe"):
            self.eat_kw("table")
            return C.DescribeCommand(self._qualified_name())
        if self.eat_kw("explain"):
            mode = self.peek().value.lower()
            analyze = mode == "analyze"
            extended = mode in ("extended", "formatted")
            if analyze or extended:
                self.next()
            return C.ExplainCommand(self.parse_query(), extended, analyze)
        if self.peek().value.lower() == "declare":
            self.next()
            replace = False
            if self.eat_word("or"):
                self.expect_word("replace")
                replace = True
            self.eat_word("variable") or self.eat_word("var")
            name = self.ident()
            dtype = None
            if self.peek().kind in ("ident", "kw") and \
                    self.peek().value.lower() != "default":
                dtype = self.parse_type()
            default = None
            if self.eat_word("default") or self.eat_op("="):
                default = self.parse_expr()
            return C.DeclareVariableCommand(name, dtype, default,
                                            replace=replace)
        if self.peek().value.lower() == "analyze":
            self.next()
            self.expect_word("table")
            name = self._qualified_name()
            self.expect_word("compute")
            self.expect_word("statistics")
            columns = None
            if self.eat_word("for"):
                if self.eat_word("all"):
                    self.expect_word("columns")
                else:
                    self.expect_word("columns")
                    columns = [self.ident()]
                    while self.eat_op(","):
                        columns.append(self.ident())
            return C.AnalyzeTableCommand(name, columns)
        if self.peek().value.lower() == "cache":
            self.next()
            self.expect_kw("table")
            return C.CacheTableCommand(self._qualified_name())
        if self.peek().value.lower() == "uncache":
            self.next()
            self.expect_kw("table")
            return C.CacheTableCommand(self._qualified_name(), uncache=True)
        if self.peek().value.lower() == "set":
            self.next()
            if self.peek().kind == "eof":
                return C.SetCommand(None, None)
            if self.peek().value.lower() in ("variable", "var"):
                self.next()
                name = self.ident()
                self.expect_op("=")
                return C.SetVariableCommand(name, self.parse_expr())
            key = self._conf_key()
            value = None
            if self.eat_op("="):
                parts = []
                while self.peek().kind != "eof" and not self.at_op(";"):
                    parts.append(self.next().value)
                value = " ".join(parts)
            return C.SetCommand(key, value)
        raise ParseException(
            f"unsupported statement near {self.peek().value!r}")

    def _parse_assignment(self):
        parts = [self.ident()]
        while self.eat_op("."):
            parts.append(self.ident())
        self.expect_op("=")
        return (parts[-1], self.parse_expr())

    def _parse_merge(self):
        from ..plan import commands as C

        self.expect_kw("into")
        name = self._qualified_name()
        talias = self._maybe_alias() or name.split(".")[-1]
        target = L.SubqueryAlias(talias,
                                 L.UnresolvedRelation(name.split(".")))
        self.expect_kw("using")
        source = self.parse_relation_primary()
        self.expect_kw("on")
        cond = self.parse_expr()
        matched, not_matched = [], []
        while self.eat_kw("when"):
            neg = self.eat_kw("not")
            self.expect_kw("matched")
            extra = self.parse_expr() if self.eat_kw("and") else None
            self.expect_kw("then")
            if neg:
                self.expect_kw("insert")
                if self.at_op("*"):
                    self.next()
                    not_matched.append(C.MergeClause(
                        "insert", extra, insert_star=True))
                else:
                    self.expect_op("(")
                    cols = [self.ident()]
                    while self.eat_op(","):
                        cols.append(self.ident())
                    self.expect_op(")")
                    self.expect_kw("values")
                    self.expect_op("(")
                    vals = [self.parse_expr()]
                    while self.eat_op(","):
                        vals.append(self.parse_expr())
                    self.expect_op(")")
                    not_matched.append(C.MergeClause(
                        "insert", extra, insert_cols=cols,
                        insert_vals=vals))
            elif self.eat_kw("delete"):
                matched.append(C.MergeClause("delete", extra))
            else:
                self.expect_kw("update")
                self.expect_kw("set")
                assigns = [self._parse_assignment()]
                while self.eat_op(","):
                    assigns.append(self._parse_assignment())
                matched.append(C.MergeClause("update", extra,
                                             assignments=assigns))
        return C.MergeCommand(name, target, source, cond, matched,
                              not_matched)

    def _qualified_name(self) -> str:
        parts = [self.ident()]
        while self.eat_op("."):
            parts.append(self.ident())
        return ".".join(parts)

    def _conf_key(self) -> str:
        parts = [self.next().value]
        while self.at_op("."):
            self.next()
            parts.append(self.next().value)
        return ".".join(parts)

    def parse_query(self) -> L.LogicalPlan:
        depth = getattr(self, "_query_depth", 0)
        self._query_depth = depth + 1
        try:
            defs: list[tuple[str, L.LogicalPlan]] = []
            if self.eat_kw("with"):
                while True:
                    name = self.ident()
                    self.expect_kw("as") if self.at_kw("as") else None
                    self.expect_op("(")
                    defs.append((name, self.parse_query()))
                    self.expect_op(")")
                    if not self.eat_op(","):
                        break
            plan = self.parse_set_expr()
            plan = self._order_limit(plan)
            if defs:
                plan = _apply_ctes(plan, defs, top_level=(depth == 0))
            return plan
        finally:
            self._query_depth = depth

    def parse_set_expr(self) -> L.LogicalPlan:
        left = self.parse_term_query()
        while self.at_kw("union", "intersect", "minus", "except"):
            op = self.next().value.lower()
            distinct = True
            if self.eat_kw("all"):
                distinct = False
            else:
                self.eat_kw("distinct")
            right = self.parse_term_query()
            if op == "union":
                left = L.Union([left, right])
                if distinct:
                    left = L.Distinct(left)
            elif op == "intersect":
                left = L.Intersect(left, right)
            else:  # except / minus
                left = L.Except(left, right)
        return left

    def parse_term_query(self) -> L.LogicalPlan:
        if self.eat_op("("):
            q = self.parse_query()
            self.expect_op(")")
            return q
        if self.at_kw("values"):
            return self.parse_values()
        return self.parse_select()

    def parse_values(self) -> L.LogicalPlan:
        self.expect_kw("values")
        rows = []
        while True:
            self.expect_op("(")
            row = [self.parse_expr()]
            while self.eat_op(","):
                row.append(self.parse_expr())
            self.expect_op(")")
            rows.append(row)
            if not self.eat_op(","):
                break
        import pyarrow as pa

        from ..plan.optimizer import const_value

        ncols = len(rows[0])
        cols = {}
        for c in range(ncols):
            vals = []
            for r in rows:
                ok, v = const_value(r[c])
                if not ok:
                    raise ParseException("VALUES entries must be literals")
                vals.append(v)
            cols[f"col{c + 1}"] = vals
        table = pa.table(cols)
        from ..types import from_arrow_type

        attrs = [E.AttributeReference(f.name, from_arrow_type(f.type), True)
                 for f in table.schema]
        return L.LocalRelation(attrs, table)

    def parse_select(self) -> L.LogicalPlan:
        self.expect_kw("select")
        distinct = False
        if self.eat_kw("distinct"):
            distinct = True
        else:
            self.eat_kw("all")
        select_list = [self.parse_named_expression()]
        while self.eat_op(","):
            select_list.append(self.parse_named_expression())

        plan: L.LogicalPlan
        if self.eat_kw("from"):
            plan = self.parse_relation()
            while self.eat_op(","):
                right = self.parse_relation()
                plan = L.Join(plan, right, "cross", None)
        else:
            plan = L.OneRowRelation()

        if self.eat_kw("where"):
            plan = L.Filter(self.parse_expr(), plan)

        group_exprs = None
        grouping_sets: list[list[int]] | None = None
        if self.at_kw("group"):
            self.next()
            self.expect_kw("by")
            if self.at_kw("rollup", "cube"):
                kind = self.next().value.lower()
                self.expect_op("(")
                group_exprs = [self.parse_expr()]
                while self.eat_op(","):
                    group_exprs.append(self.parse_expr())
                self.expect_op(")")
                n = len(group_exprs)
                if kind == "rollup":
                    grouping_sets = [list(range(n - i)) for i in range(n + 1)]
                else:  # cube: all subsets
                    import itertools as _it

                    grouping_sets = [list(c) for k in range(n, -1, -1)
                                     for c in _it.combinations(range(n), k)]
            elif self.at_kw("grouping"):
                self.next()
                if self.peek().value.lower() != "sets":
                    raise ParseException("expected SETS after GROUPING")
                self.next()
                self.expect_op("(")
                group_exprs = []
                grouping_sets = []
                index: dict[str, int] = {}
                while True:
                    self.expect_op("(")
                    one: list[int] = []
                    if not self.at_op(")"):
                        while True:
                            e = self.parse_expr()
                            key = e.simple_string()
                            if key not in index:
                                index[key] = len(group_exprs)
                                group_exprs.append(e)
                            one.append(index[key])
                            if not self.eat_op(","):
                                break
                    self.expect_op(")")
                    grouping_sets.append(one)
                    if not self.eat_op(","):
                        break
                self.expect_op(")")
            else:
                group_exprs = [self.parse_expr()]
                while self.eat_op(","):
                    group_exprs.append(self.parse_expr())

        having = None
        if self.eat_kw("having"):
            having = self.parse_expr()

        # WINDOW w AS (spec) [, w2 AS (spec)]* — substitute named specs
        # into `fn() OVER w` placeholders (reference: namedWindow in
        # SqlBaseParser.g4 + Analyzer WindowsSubstitution)
        if self.peek().kind == "ident" and \
                self.peek().value.lower() == "window":
            self.next()
            specs: dict[str, tuple] = {}
            while True:
                wname = self.ident().lower()
                self.expect_kw("as")
                specs[wname] = self._parse_window_spec()
                if not self.eat_op(","):
                    break
            from ..expr.window import UnresolvedWindowExpression as _UW

            def _sub(e):
                if isinstance(e, _UW) and e.ref_name is not None:
                    spec = specs.get(e.ref_name.lower())
                    if spec is None:
                        raise ParseException(
                            f"undefined window: {e.ref_name}")
                    p, o, fr = spec
                    return _UW(e.function, p, o, fr)
                return e

            select_list = [e.transform_up(_sub) for e in select_list]

        has_agg = any(_contains_agg(e) for e in select_list)
        if group_exprs is not None or has_agg or having is not None:
            groups = group_exprs or []
            # GROUP BY ordinals
            resolved_groups = []
            for g in groups:
                if isinstance(g, E.Literal) and isinstance(g.value, int):
                    idx = g.value - 1
                    if not (0 <= idx < len(select_list)):
                        raise ParseException(f"GROUP BY position {g.value}")
                    tgt = select_list[idx]
                    resolved_groups.append(
                        tgt.child if isinstance(tgt, E.Alias) else tgt)
                else:
                    resolved_groups.append(g)
            if grouping_sets is not None:
                plan = L.GroupingSets(grouping_sets, resolved_groups,
                                      list(select_list), plan)
            else:
                plan = L.Aggregate(resolved_groups, list(select_list), plan)
            if having is not None:
                plan = L.Filter(having, plan)
        else:
            plan = L.Project(list(select_list), plan)

        if distinct:
            plan = L.Distinct(plan)
        return plan

    def _order_limit(self, plan: L.LogicalPlan) -> L.LogicalPlan:
        if self.at_kw("order"):
            self.next()
            self.expect_kw("by")
            orders = [self.parse_sort_item(plan)]
            while self.eat_op(","):
                orders.append(self.parse_sort_item(plan))
            plan = L.Sort(orders, True, plan)
        if self.eat_kw("limit"):
            t = self.next()
            if t.kind != "num":
                raise ParseException("LIMIT expects a number")
            plan = L.Limit(int(t.value.rstrip("LlDdSs")), plan)
        if self.eat_kw("offset"):
            t = self.next()
            plan = L.Offset(int(t.value.rstrip("LlDdSs")), plan)
        return plan

    def parse_sort_item(self, plan) -> E.SortOrder:
        e = self.parse_expr()
        # ORDER BY ordinal
        if isinstance(e, E.Literal) and isinstance(e.value, int) and \
                isinstance(plan, (L.Project, L.Aggregate)):
            lst = plan.project_list if isinstance(plan, L.Project) \
                else plan.aggregate_exprs
            idx = e.value - 1
            if 0 <= idx < len(lst):
                tgt = lst[idx]
                if isinstance(tgt, E.Alias):
                    e = E.UnresolvedAttribute([tgt.name])
                elif isinstance(tgt, E.AttributeReference):
                    e = tgt
                elif isinstance(tgt, E.UnresolvedAttribute):
                    e = tgt
        asc = True
        if self.eat_kw("desc"):
            asc = False
        else:
            self.eat_kw("asc")
        nulls_first = None
        if self.eat_kw("nulls"):
            if self.eat_kw("first"):
                nulls_first = True
            else:
                self.expect_kw("last")
                nulls_first = False
        return E.SortOrder(e, asc, nulls_first)

    # --- relations --------------------------------------------------------
    def parse_relation(self) -> L.LogicalPlan:
        left = self.parse_relation_primary()
        while True:
            jt = self._join_type()
            if jt is None:
                return left
            right = self.parse_relation_primary()
            cond = None
            using = None
            if self.eat_kw("on"):
                cond = self.parse_expr()
            elif self.eat_kw("using"):
                self.expect_op("(")
                using = [self.ident()]
                while self.eat_op(","):
                    using.append(self.ident())
                self.expect_op(")")
            if using is not None:
                left = L.UsingJoin(left, right, jt, using)
            else:
                left = L.Join(left, right, jt, cond)

    def _join_type(self) -> str | None:
        if self.eat_kw("cross"):
            self.expect_kw("join")
            return "cross"
        if self.at_kw("join"):
            self.next()
            return "inner"
        if self.eat_kw("inner"):
            self.expect_kw("join")
            return "inner"
        for side in ("left", "right", "full"):
            if self.at_kw(side):
                self.next()
                if side == "left" and self.eat_kw("semi"):
                    self.expect_kw("join")
                    return "left_semi"
                if side == "left" and self.eat_kw("anti"):
                    self.expect_kw("join")
                    return "left_anti"
                self.eat_kw("outer")
                self.expect_kw("join")
                return {"left": "left_outer", "right": "right_outer",
                        "full": "full_outer"}[side]
        return None

    def parse_relation_primary(self) -> L.LogicalPlan:
        if self.eat_op("("):
            sub = self.parse_query()
            self.expect_op(")")
            alias = self._maybe_alias()
            if alias:
                return L.SubqueryAlias(alias, sub)
            return sub
        parts = [self.ident()]
        while self.eat_op("."):
            parts.append(self.ident())
        plan: L.LogicalPlan = L.UnresolvedRelation(parts)
        if self.peek().value.lower() == "tablesample":
            self.next()
            self.expect_op("(")
            t = self.next()
            if t.kind != "num":
                raise ParseException("TABLESAMPLE expects a number")
            amount = float(t.value.rstrip("LlDdSs"))
            unit = self.ident().lower()
            self.expect_op(")")
            if unit == "percent":
                plan = L.Sample(amount / 100.0, 42, plan)
            elif unit == "rows":
                plan = L.Limit(int(amount), plan)
            else:
                raise ParseException(f"TABLESAMPLE unit {unit}")
        alias = self._maybe_alias()
        if alias:
            return L.SubqueryAlias(alias, plan)
        return plan

    # soft keywords that begin a clause and therefore can't be a bare
    # relation alias (WINDOW w AS ..., LATERAL VIEW, PIVOT ...)
    _NON_ALIAS_IDENTS = frozenset(("window", "lateral", "pivot", "unpivot"))

    def _maybe_alias(self) -> str | None:
        if self.eat_kw("as"):
            return self.ident()
        t = self.peek()
        if t.kind == "ident" and t.value.lower() not in self._NON_ALIAS_IDENTS:
            self.next()
            return t.value
        return None

    # --- expressions ------------------------------------------------------
    def parse_named_expression(self) -> E.Expression:
        if self.at_op("*"):
            self.next()
            return E.UnresolvedStar()
        # qualified star: t.*
        if self.peek().kind in ("ident",) and self.peek(1).value == "." and \
                self.peek(2).value == "*":
            target = self.ident()
            self.next()  # .
            self.next()  # *
            return E.UnresolvedStar(target)
        e = self.parse_expr()
        if self.eat_kw("as"):
            return E.Alias(e, self.ident())
        t = self.peek()
        if t.kind == "ident":
            self.next()
            return E.Alias(e, t.value)
        return e

    def parse_expr(self) -> E.Expression:
        return self.parse_or()

    def parse_or(self) -> E.Expression:
        left = self.parse_and()
        while self.eat_kw("or"):
            left = E.Or(left, self.parse_and())
        return left

    def parse_and(self) -> E.Expression:
        left = self.parse_not()
        while self.eat_kw("and"):
            left = E.And(left, self.parse_not())
        return left

    def parse_not(self) -> E.Expression:
        if self.eat_kw("not"):
            return E.Not(self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> E.Expression:
        left = self.parse_bitwise_or()
        while True:
            if self.at_op("=", "==", "<>", "!=", "<", "<=", ">", ">=", "<=>"):
                op = self.next().value
                right = self.parse_bitwise_or()
                cls = {"=": E.EqualTo, "==": E.EqualTo, "<>": E.NotEqualTo,
                       "!=": E.NotEqualTo, "<": E.LessThan,
                       "<=": E.LessThanOrEqual, ">": E.GreaterThan,
                       ">=": E.GreaterThanOrEqual, "<=>": E.EqualNullSafe}[op]
                left = cls(left, right)
                continue
            if self.at_kw("is"):
                self.next()
                neg = self.eat_kw("not")
                self.expect_kw("null")
                left = E.IsNotNull(left) if neg else E.IsNull(left)
                continue
            neg = False
            save = self.i
            if self.eat_kw("not"):
                neg = True
            if self.eat_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    from ..plan.subquery import InSubquery

                    sub = self.parse_query()
                    self.expect_op(")")
                    left = InSubquery(left, sub)
                    if neg:
                        left = E.Not(left)
                    continue
                items = [self.parse_expr()]
                while self.eat_op(","):
                    items.append(self.parse_expr())
                self.expect_op(")")
                left = E.In(left, items)
                if neg:
                    left = E.Not(left)
                continue
            if self.eat_kw("like"):
                pat = self.next()
                if pat.kind != "str":
                    raise ParseException("LIKE expects a string literal")
                left = E.Like(left, pat.value)
                if neg:
                    left = E.Not(left)
                continue
            if self.eat_kw("rlike"):
                pat = self.next()
                left = E.RLike(left, pat.value)
                if neg:
                    left = E.Not(left)
                continue
            if self.eat_kw("between"):
                lo = self.parse_additive()
                self.expect_kw("and")
                hi = self.parse_additive()
                left = E.And(E.GreaterThanOrEqual(left, lo),
                             E.LessThanOrEqual(left, hi))
                if neg:
                    left = E.Not(left)
                continue
            if neg:
                self.i = save
            break
        return left

    def parse_bitwise_or(self) -> E.Expression:
        left = self.parse_bitwise_xor()
        while self.at_op("|"):
            self.next()
            left = E.BitwiseOr(left, self.parse_bitwise_xor())
        return left

    def parse_bitwise_xor(self) -> E.Expression:
        left = self.parse_bitwise_and()
        while self.at_op("^"):
            self.next()
            left = E.BitwiseXor(left, self.parse_bitwise_and())
        return left

    def parse_bitwise_and(self) -> E.Expression:
        left = self.parse_shift()
        while self.at_op("&"):
            self.next()
            left = E.BitwiseAnd(left, self.parse_shift())
        return left

    def parse_shift(self) -> E.Expression:
        left = self.parse_additive()
        while self.at_op("<<", ">>"):
            op = self.next().value
            right = self.parse_additive()
            left = E.ShiftLeft(left, right) if op == "<<" \
                else E.ShiftRight(left, right)
        return left

    def parse_additive(self) -> E.Expression:
        left = self.parse_multiplicative()
        while self.at_op("+", "-") or self.at_op("||"):
            op = self.next().value
            right = self.parse_multiplicative()
            if op == "+":
                left = E.Add(left, right)
            elif op == "-":
                left = E.Subtract(left, right)
            else:
                left = E.Concat([left, right])
        return left

    def parse_multiplicative(self) -> E.Expression:
        left = self.parse_unary()
        while self.at_op("*", "/", "%") or self.at_kw("div"):
            if self.eat_kw("div"):
                right = self.parse_unary()
                left = E.Cast(E.Divide(left, right), int64)
                continue
            op = self.next().value
            right = self.parse_unary()
            cls = {"*": E.Multiply, "/": E.Divide, "%": E.Remainder}[op]
            left = cls(left, right)
        return left

    def parse_unary(self) -> E.Expression:
        if self.eat_op("-"):
            e = self.parse_unary()
            if isinstance(e, E.Literal) and isinstance(e.value, (int, float)):
                return E.Literal(-e.value)
            return E.UnaryMinus(e)
        if self.eat_op("+"):
            return self.parse_unary()
        if self.eat_op("~"):
            return E.BitwiseNot(self.parse_unary())
        e = self.parse_primary()
        # subscript: col[key] → element_at (map value / array element)
        while self.eat_op("["):
            key = self.parse_expr()
            self.expect_op("]")
            e = E.UnresolvedFunction("element_at", [e, key], False)
        return e

    def parse_primary(self) -> E.Expression:
        t = self.peek()
        if t.kind == "num":
            self.next()
            return _num_literal(t.value)
        if t.kind == "str":
            self.next()
            return E.Literal(t.value)
        if self.at_kw("true"):
            self.next()
            return E.Literal(True)
        if self.at_kw("false"):
            self.next()
            return E.Literal(False)
        if self.at_kw("null"):
            self.next()
            return E.Literal(None)
        if self.at_kw("date"):
            save = self.i
            self.next()
            if self.peek().kind == "str":
                s = self.next().value
                return E.Literal(datetime.date.fromisoformat(s.strip()[:10]))
            self.i = save
        if self.at_kw("timestamp"):
            save = self.i
            self.next()
            if self.peek().kind == "str":
                s = self.next().value
                return E.Literal(_parse_ts_literal(s))
            self.i = save
        if self.at_kw("interval"):
            return self.parse_interval()
        if self.at_kw("case"):
            return self.parse_case()
        if self.at_kw("cast"):
            self.next()
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("as")
            to = self.parse_type()
            self.expect_op(")")
            return E.Cast(e, to)
        if t.kind == "ident" and t.value.lower() == "try_cast" and \
                self.peek(1).value == "(":
            self.next()
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("as")
            to = self.parse_type()
            self.expect_op(")")
            return E.Cast(e, to, ansi=False)  # try_cast: NULL on failure
        if self.at_kw("exists") and self.peek(1).value == "(" and \
                (self.peek(2).value == "(" or
                 (self.peek(2).kind == "kw" and
                  self.peek(2).value.lower() in ("select", "with",
                                                 "values"))):
            from ..plan.subquery import Exists

            self.next()
            self.expect_op("(")
            sub = self.parse_query()
            self.expect_op(")")
            return Exists(sub)
        if self.eat_op("("):
            if self.at_kw("select", "with"):
                from ..plan.subquery import ScalarSubquery

                sub = self.parse_query()
                self.expect_op(")")
                return ScalarSubquery(sub)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind in ("ident", "kw"):
            # function call or column reference
            name = self.ident()
            if name.lower() == "extract" and self.at_op("("):
                return self.parse_extract()
            if self.at_op("("):
                f = self.parse_function(name)
                # postfix struct-field access on a function result:
                # named_struct(...).a.b (complexTypeExtractors.scala)
                while self.at_op(".") and \
                        self.peek(1).kind in ("ident", "kw"):
                    self.next()
                    f = E.GetStructField(f, self.ident())
                return f
            parts = [name]
            while self.at_op(".") and self.peek(1).kind in ("ident", "kw"):
                self.next()
                parts.append(self.ident())
            return E.UnresolvedAttribute(parts)
        raise ParseException(f"unexpected token {t.value!r} at {t.pos}")

    def parse_function(self, name: str) -> E.Expression:
        self.expect_op("(")
        distinct = False
        args: list[E.Expression] = []
        if self.at_op("*"):
            self.next()
            args = [E.UnresolvedStar()]
        elif not self.at_op(")"):
            if self.eat_kw("distinct"):
                distinct = True
            if name.lower() == "position":
                # position(substr IN str) — parse below predicate level so
                # the IN is ours, not an IN-list; order matches position(s, c)
                args.append(self.parse_bitwise_or())
                if self.eat_kw("in"):
                    args.append(self.parse_expr())
                while self.eat_op(","):
                    args.append(self.parse_expr())
            else:
                args.append(self.parse_lambda_or_expr())
                if (name.lower() == "overlay"
                        and self.peek().value.lower() == "placing"):
                    # overlay(str PLACING repl FROM pos [FOR len]) — argument
                    # order matches overlay(str, repl, pos[, len])
                    self.next()
                    args.append(self.parse_expr())
                    self.expect_kw("from")
                    args.append(self.parse_expr())
                    if self.peek().value.lower() == "for":
                        self.next()
                        args.append(self.parse_expr())
                else:
                    while self.eat_op(","):
                        args.append(self.parse_lambda_or_expr())
        self.expect_op(")")
        if self.at_kw("over"):
            return self.parse_over(E.UnresolvedFunction(name, args, distinct))
        return E.UnresolvedFunction(name, args, distinct)

    def parse_lambda_or_expr(self) -> E.Expression:
        """A function argument: `x -> body`, `(x, y) -> body`, or a
        plain expression (higher-order function lambdas,
        sqlbase grammar lambda rule)."""
        from ..expr.higher_order import LambdaFunction, mark_lambda_params

        t = self.peek()
        if t.kind in ("ident", "kw") and self.peek(1).value == "->":
            name = self.ident()
            self.next()     # ->
            body = self.parse_expr()
            return LambdaFunction([name], mark_lambda_params(body, [name]))
        if t.value == "(":
            save = self.i
            self.next()
            names: list[str] = []
            ok = True
            while True:
                tt = self.peek()
                if tt.kind in ("ident", "kw") and \
                        tt.value.lower() not in ("select", "with"):
                    names.append(self.ident())
                else:
                    ok = False
                    break
                if self.eat_op(","):
                    continue
                break
            if ok and names and self.at_op(")") and \
                    self.peek(1).value == "->":
                self.next()     # )
                self.next()     # ->
                body = self.parse_expr()
                return LambdaFunction(names,
                                      mark_lambda_params(body, names))
            self.i = save
        return self.parse_expr()

    def parse_over(self, func: E.Expression) -> E.Expression:
        from ..expr.window import WindowExpression

        self.expect_kw("over")
        if not self.at_op("("):
            from ..expr.window import UnresolvedWindowExpression

            # OVER w — named window, spec substituted from the WINDOW clause
            return UnresolvedWindowExpression(func, [], [], None,
                                              ref_name=self.ident())
        partition, orders, frame = self._parse_window_spec()
        from ..expr.window import UnresolvedWindowExpression

        return UnresolvedWindowExpression(func, partition, orders, frame)

    def _parse_window_spec(self):
        self.expect_op("(")
        partition: list[E.Expression] = []
        orders: list[E.SortOrder] = []
        if self.eat_kw("partition"):
            self.expect_kw("by")
            partition.append(self.parse_expr())
            while self.eat_op(","):
                partition.append(self.parse_expr())
        if self.eat_kw("order"):
            self.expect_kw("by")
            orders.append(self.parse_sort_item(None))
            while self.eat_op(","):
                orders.append(self.parse_sort_item(None))
        frame = None
        if self.at_kw("rows", "range"):
            ftype = self.next().value.lower()
            if self.eat_kw("between"):
                lo = self._parse_frame_bound(is_lower=True)
                self.expect_kw("and")
                hi = self._parse_frame_bound(is_lower=False)
            else:
                lo = self._parse_frame_bound(is_lower=True)
                hi = 0  # CURRENT ROW
            if ftype == "range":
                if (lo, hi) == (None, 0):
                    frame = None  # the default frame
                elif (lo, hi) == (None, None):
                    frame = ("rows", None, None)  # whole partition
                else:
                    frame = ("vrange", lo, hi)  # value offsets
            else:
                frame = ("rows", lo, hi)
        self.expect_op(")")
        return partition, orders, frame

    def _parse_frame_bound(self, is_lower: bool):
        """Returns a row offset: None = unbounded, 0 = current row,
        -n preceding, +n following."""
        if self.eat_kw("unbounded"):
            if not (self.eat_kw("preceding") or self.eat_kw("following")):
                raise ParseException("bad frame bound")
            return None
        if self.eat_kw("current"):
            self.expect_kw("row")
            return 0
        t = self.next()
        if t.kind != "num":
            raise ParseException("bad frame bound")
        n = int(t.value.rstrip("LlDdSs"))
        if self.eat_kw("preceding"):
            return -n
        if self.eat_kw("following"):
            return n
        raise ParseException("bad frame bound")

    def parse_interval(self) -> E.Expression:
        """INTERVAL [-]n unit [n unit ...], with quoted or bare numbers."""
        self.expect_kw("interval")
        months = days = micros = 0
        saw = False
        while True:
            sign = 1
            # only claim a '-' that introduces another signed component;
            # `interval '2' day - interval '1' day` must leave the minus
            # for the enclosing subtraction
            if self.at_op("-") and self.peek(1).kind in ("num", "str"):
                self.next()
                sign = -1
            t = self.peek()
            if t.kind == "num":
                self.next()
                n = sign * int(float(t.value.rstrip("LlDdSs")))
            elif t.kind == "str":
                self.next()
                n = sign * int(float(t.value))
            else:
                break
            unit = self.ident().lower().rstrip("s")
            if unit == "year":
                months += 12 * n
            elif unit == "month":
                months += n
            elif unit == "week":
                days += 7 * n
            elif unit == "day":
                days += n
            elif unit == "hour":
                micros += n * 3_600_000_000
            elif unit == "minute":
                micros += n * 60_000_000
            elif unit == "second":
                micros += n * 1_000_000
            else:
                raise ParseException(f"unknown interval unit {unit}")
            saw = True
        if not saw:
            raise ParseException("empty INTERVAL literal")
        return E.IntervalLiteral(months, days, micros)

    def parse_extract(self) -> E.Expression:
        self.expect_op("(")
        field = self.ident().lower()
        self.expect_kw("from")
        src = self.parse_expr()
        self.expect_op(")")
        mapping = {
            "year": E.Year, "month": E.Month, "day": E.DayOfMonth,
            "dayofmonth": E.DayOfMonth, "quarter": E.Quarter,
            "week": E.WeekOfYear, "doy": E.DayOfYear, "dow": E.DayOfWeek,
            "hour": E.Hour, "minute": E.Minute, "second": E.Second,
        }
        cls = mapping.get(field)
        if cls is None:
            raise ParseException(f"EXTRACT field {field} not supported")
        return cls(src)

    def parse_case(self) -> E.Expression:
        self.expect_kw("case")
        base = None
        if not self.at_kw("when"):
            base = self.parse_expr()
        branches = []
        while self.eat_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            val = self.parse_expr()
            if base is not None:
                cond = E.EqualTo(base, cond)
            branches.append((cond, val))
        els = None
        if self.eat_kw("else"):
            els = self.parse_expr()
        self.expect_kw("end")
        return E.CaseWhen(branches, els)

    # --- types ------------------------------------------------------------
    def parse_type(self) -> DataType:
        name = self.ident().lower()
        if name in ("int", "integer"):
            return int32
        if name in ("bigint", "long"):
            return int64
        if name in ("smallint", "short"):
            return int16
        if name in ("tinyint", "byte"):
            return int8
        if name in ("float", "real"):
            return float32
        if name == "double":
            return float64
        if name in ("string", "text"):
            return string
        if name in ("varchar", "char"):
            if self.eat_op("("):
                self.next()
                self.expect_op(")")
            return string
        if name in ("bool", "boolean"):
            return boolean
        if name == "date":
            return date
        if name == "timestamp":
            return timestamp
        if name in ("decimal", "numeric", "dec"):
            p, s = 10, 0
            if self.eat_op("("):
                p = int(self.next().value)
                if self.eat_op(","):
                    s = int(self.next().value)
                self.expect_op(")")
            return DecimalType(min(p, DecimalType.MAX_PRECISION), s)
        raise ParseException(f"unknown type {name}")


def _num_literal(text: str) -> E.Literal:
    if text[:2].lower() == "0x":
        v = int(text, 16)
        return E.Literal(v) if -(2 ** 31) <= v < 2 ** 31 \
            else E.Literal(v, int64)
    suffix = ""
    if text and text[-1] in "LlDdSs":
        suffix = text[-1].lower()
        text = text[:-1]
    if "." in text or "e" in text.lower() or suffix == "d":
        return E.Literal(float(text))
    v = int(text)
    if suffix == "l" or not (-(2 ** 31) <= v < 2 ** 31):
        return E.Literal(v, int64)
    return E.Literal(v)


def _parse_ts_literal(s: str) -> datetime.datetime:
    s = s.strip().replace("T", " ")
    for fmt in ("%Y-%m-%d %H:%M:%S.%f", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d"):
        try:
            return datetime.datetime.strptime(s, fmt)
        except ValueError:
            continue
    raise ParseException(f"bad timestamp literal {s!r}")


_AGG_NAMES = frozenset((
    "sum", "count", "min", "max", "avg", "mean", "first", "any_value",
    "stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop",
    "collect_set", "collect_list", "array_agg", "first_value", "median",
    "percentile",
    "percentile_approx", "corr", "covar_samp", "covar_pop", "skewness",
    "kurtosis", "approx_count_distinct"))


def _contains_agg(e: E.Expression) -> bool:
    from ..expr.window import UnresolvedWindowExpression

    if isinstance(e, UnresolvedWindowExpression):
        return False  # window aggregates are not grouping aggregates
    if isinstance(e, E.AggregateFunction):
        return True
    if isinstance(e, E.UnresolvedFunction) and e.fname.lower() in _AGG_NAMES:
        return True
    return any(_contains_agg(c) for c in e.children)


def _refresh_alias_ids(plan: L.LogicalPlan) -> L.LogicalPlan:
    """Fresh expr_ids for every Alias in a parse-time subtree. CTE bodies
    splice into multiple call sites; shared alias ids would collide once
    resolved (references are still by name pre-resolution, so only the ids
    need refreshing — the analyzer's DeduplicateRelations handles relation
    ids)."""

    def fresh(e: E.Expression) -> E.Expression:
        if isinstance(e, E.Alias):
            return E.Alias(e.child, e.name)  # new expr_id
        return e

    def go(node: L.LogicalPlan) -> L.LogicalPlan:
        node = node.map_children(go)
        return node.map_expressions(lambda ex: ex.transform_up(fresh))

    return go(plan)


def _count_cte_refs(plan: L.LogicalPlan, name: str) -> int:
    """Occurrences of UnresolvedRelation(name) in a plan, including
    inside subquery-expression plans (the same scope _substitute_ctes
    rewrites)."""
    from ..plan.subquery import SubqueryExpression

    count = 0

    def visit_plan(p: L.LogicalPlan) -> None:
        nonlocal count
        for node in p.iter_nodes():
            if isinstance(node, L.UnresolvedRelation) and \
                    node.name.lower() == name:
                count += 1
            node.map_expressions(lambda ex: ex.transform_up(visit_expr))

    def visit_expr(ex):
        if isinstance(ex, SubqueryExpression):
            visit_plan(ex.plan)
        return ex

    visit_plan(plan)
    return count


def _cte_expensive(plan: L.LogicalPlan) -> bool:
    """Worth materializing: joins (each instantiation re-plans and
    re-compiles the join pipeline) or an aggregate over a join."""
    joins = sum(1 for n in plan.iter_nodes() if isinstance(n, L.Join))
    aggs = sum(1 for n in plan.iter_nodes() if isinstance(n, L.Aggregate))
    return joins >= 2 or (joins >= 1 and aggs >= 1)


def _apply_ctes(plan: L.LogicalPlan, defs: list,
                top_level: bool) -> L.LogicalPlan:
    """Inline single-use / cheap CTEs; convert multiply-instantiated
    expensive ones into WithCTE materializations (top-level queries
    only — a mid-tree WithCTE has no execution point)."""
    import uuid as _uuid

    # effective instantiation count, later definitions first: a CTE
    # referenced from an inlined CTE body is instantiated once per
    # instantiation of THAT body; a materialized body runs once
    eff: dict[str, int] = {}
    mat: dict[str, bool] = {}
    for i in range(len(defs) - 1, -1, -1):
        name, body = defs[i]
        key = name.lower()
        cnt = _count_cte_refs(plan, key)
        for j in range(i + 1, len(defs)):
            jname, jbody = defs[j]
            jkey = jname.lower()
            mult = 1 if mat.get(jkey) else eff.get(jkey, 0)
            cnt += _count_cte_refs(jbody, key) * mult
        eff[key] = cnt
        mat[key] = bool(top_level and cnt >= 2 and _cte_expensive(body))

    ctes: dict[str, L.LogicalPlan] = {}
    materializations: list[tuple[str, L.LogicalPlan]] = []
    for name, body in defs:
        key = name.lower()
        body = _substitute_ctes(body, ctes)  # earlier CTEs visible
        if mat[key]:
            uniq = f"__cte_mat_{key}_{_uuid.uuid4().hex[:8]}"
            materializations.append((uniq, body))
            ctes[key] = L.SubqueryAlias(name, L.UnresolvedRelation([uniq]))
        else:
            ctes[key] = L.SubqueryAlias(name, body)
    plan = _substitute_ctes(plan, ctes)
    if materializations:
        plan = L.WithCTE(materializations, plan)
    return plan


def _substitute_ctes(plan: L.LogicalPlan,
                     ctes: dict[str, L.LogicalPlan]) -> L.LogicalPlan:
    from ..plan.subquery import SubqueryExpression

    def fix_expr(ex):
        # CTEs are visible inside subquery expressions too (reference:
        # CTESubstitution runs over subquery plans) — q1-style
        # `WITH ctr AS (...) ... WHERE x > (SELECT avg(..) FROM ctr)`
        if isinstance(ex, SubqueryExpression):
            return ex.copy(plan=_substitute_ctes(ex.plan, ctes))
        return ex

    def rule(node):
        if isinstance(node, L.UnresolvedRelation):
            hit = ctes.get(node.name.lower())
            if hit is not None:
                return _refresh_alias_ids(hit)
        return node.map_expressions(lambda e: e.transform_up(fix_expr))

    return plan.transform_up(rule)
