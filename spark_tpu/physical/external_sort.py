"""External (memory-bounded) sort: range-bucket multi-pass.

Role of the reference's UnsafeExternalSorter + SortExec spill path
(corej/util/collection/unsafe/sort/UnsafeExternalSorter.java,
sqlx/SortExec.scala) — redesigned for the TPU memory model. Disk is not
the scarce resource here, HBM is: instead of run-merge (k-way merges are
control-flow-hostile on a systolic machine), the partition is
range-bucketed by the leading sort key — the same device kernel as the
range exchange (ops/partition.range_partition) — into host buffers, and
each bucket (which fits the device budget) is sorted independently with
the full multi-key kernel. Equal leading keys always share a bucket
(searchsorted), so bucket order × in-bucket order = total order, and no
merge pass exists at all.

Null leading keys route to the first/last bucket per nulls_first, NaNs
follow the same IEEE placement the in-tile kernel uses, and a bucket that
still exceeds the budget (pathological leading-key skew) is sorted whole
with a metrics flag rather than failing.
"""

from __future__ import annotations

import numpy as np

from ..columnar.batch import ColumnarBatch, bucket_capacity
from ..exec.shuffle import _OutBuffer, _pull_sorted, _slice_into
from ..types import StringType

_SAMPLE_PER_BATCH = 4096
_MAX_BUCKETS = 1 << 10


def _jnp():
    import jax.numpy as jnp

    return jnp


def _batch_numeric_samples(b: ColumnarBatch, kpos: int) -> np.ndarray:
    """Leading-sort-key samples for one batch, memoized per device-array
    identity (utils/device_memo.memo_device_scalars): repeated external
    sorts over device-cached batches pull samples to host once, not once
    per batch per pass. Treat the returned array as immutable."""
    from ..utils.device_memo import memo_device_scalars

    col = b.columns[kpos]

    def compute():
        mask = np.asarray(b.row_mask)
        keys = np.asarray(col.sort_keys())[mask]
        if col.validity is not None:
            keys = keys[np.asarray(col.validity)[mask]]
        if keys.dtype.kind == "f":
            keys = keys[~np.isnan(keys)]
        return keys[:_SAMPLE_PER_BATCH]

    return memo_device_scalars(("extsort_sample", kpos),
                                (col.data, col.validity, b.row_mask),
                                compute)


def _sample_numeric_bounds(part, kpos: int, num_buckets: int):
    """Quantile bounds in the sort-key domain from per-batch samples."""
    samples = [_batch_numeric_samples(b, kpos) for b in part]
    allv = np.concatenate(samples) if samples else np.zeros(0)
    if allv.size == 0:
        return None
    s = np.sort(allv)
    qs = (np.arange(1, num_buckets) * len(s)) // num_buckets
    return np.unique(s[qs])


def _batch_string_samples(b: ColumnarBatch, kpos: int) -> tuple:
    """Live non-null string samples for one batch, memoized like the
    numeric path (selection_indices syncs the mask otherwise)."""
    from ..utils.device_memo import memo_device_scalars

    col = b.columns[kpos]

    def compute():
        sel = b.selection_indices()[:_SAMPLE_PER_BATCH]
        vals = col.to_numpy(sel)
        return tuple(v for v in vals if v is not None)

    return memo_device_scalars(("extsort_sample_str", kpos),
                                (col.data, col.validity, b.row_mask),
                                compute)


def _sample_string_bounds(part, kpos: int, num_buckets: int):
    samples: list = []
    for b in part:
        samples.extend(_batch_string_samples(b, kpos))
    if not samples:
        return None
    s = sorted(samples)
    qs = (np.arange(1, num_buckets) * len(s)) // num_buckets
    return sorted(set(s[q] for q in qs))


def external_sort(part, orders, schema, child_output, ctx,
                  budget_rows: int, sort_single):
    """Sort one partition whose total capacity exceeds ``budget_rows``.

    Returns an ordered list of sorted ColumnarBatches (bucket order).
    ``sort_single(list_of_batches) -> ColumnarBatch`` is the in-budget
    single-tile sort (SortExec's kernel)."""
    import jax

    from ..ops.partition import _group_by_pid
    from .compile import GLOBAL_KERNEL_CACHE

    jnp = _jnp()
    total_cap = sum(b.capacity for b in part)
    num_buckets = min(_MAX_BUCKETS,
                      2 * max(2, -(-total_cap // max(budget_rows, 1))))
    first = orders[0]
    kpos = next(i for i, a in enumerate(child_output)
                if a.expr_id == first.child.expr_id)
    string_key = isinstance(schema.fields[kpos].dataType, StringType)

    bounds = (_sample_string_bounds(part, kpos, num_buckets) if string_key
              else _sample_numeric_bounds(part, kpos, num_buckets))
    if bounds is None or len(bounds) == 0:
        # all-null / empty leading key: one bucket == plain sort
        return [sort_single(part)]
    B = len(bounds) + 1
    null_pid = 0 if first.nulls_first else B - 1
    descending = not first.ascending

    bufs = [_OutBuffer(schema, spill_bytes=ctx.memory.spill_bytes,
                       spill_dir=ctx.memory.spill_dir, metrics=ctx.metrics)
            for _ in range(B)]
    for batch in part:
        col = batch.columns[kpos]
        cap = batch.capacity
        has_valid = col.validity is not None
        if string_key:
            sd_vals = np.array(list(col.dictionary.values)
                               if col.dictionary else [], dtype=object)
            lut = np.searchsorted(np.array(bounds, dtype=object), sd_vals,
                                  side="right").astype(np.int32)
            if descending:
                lut = (B - 1) - lut
            if len(lut) == 0:
                lut = np.zeros(1, np.int32)
            lut_d = jnp.asarray(lut)
            kkey = ("extsort_pid_str", cap, B, has_valid, null_pid)

            def build_str():
                def kernel(lut_d, codes, valid, mask):
                    pids = jnp.take(lut_d,
                                    jnp.clip(codes, 0, lut_d.shape[0] - 1))
                    if has_valid:
                        pids = jnp.where(valid, pids, null_pid)
                    return _group_by_pid(pids, mask, B)

                return jax.jit(kernel)

            kernel = GLOBAL_KERNEL_CACHE.get_or_build(kkey, build_str)
            pr = kernel(lut_d, col.data,
                        col.validity if has_valid else jnp.zeros(0, bool),
                        batch.row_mask)
        else:
            keys = col.sort_keys()
            kkey = ("extsort_pid", cap, B, str(keys.dtype), has_valid,
                    null_pid, descending)

            def build_num():
                def kernel(bounds_d, keys, valid, mask):
                    pids = jnp.searchsorted(
                        bounds_d, keys, side="right").astype(jnp.int32)
                    if descending:
                        pids = (B - 1) - pids
                    if has_valid:
                        pids = jnp.where(valid, pids, null_pid)
                    return _group_by_pid(pids, mask, B)

                return jax.jit(kernel)

            kernel = GLOBAL_KERNEL_CACHE.get_or_build(kkey, build_num)
            pr = kernel(jnp.asarray(bounds), keys,
                        col.validity if has_valid else jnp.zeros(0, bool),
                        batch.row_mask)
        gathered, counts = _pull_sorted(batch, pr.perm, pr.counts)
        _slice_into(bufs, gathered, counts)

    ctx.memory.count("sort.external.passes")
    tile = bucket_capacity(max(budget_rows, 1))
    out = []
    for buf in bufs:
        if buf.rows == 0:
            continue
        if buf.rows > budget_rows:
            ctx.memory.count("sort.external.oversizedBucket")
        out.append(sort_single(buf.build(tile)))
    if not out:
        out.append(ColumnarBatch.empty(schema))
    return out
