"""Kernel compilation: expression trees → cached jitted batch functions.

Role of the reference's WholeStageCodegen + CodeGenerator
(sqlx/WholeStageCodegenExec.scala:673 doCodeGen; sqlcat/.../codegen/
CodeGenerator.scala:1557 Janino compile + cache). Here the "generated code"
is a traced JAX function per (expression structure, input signature,
capacity, aux signature); XLA performs the operator fusion the reference
hand-rolls with produce/consume. The cache is keyed STRUCTURALLY (attribute
ids normalized to input positions) so repeated queries reuse compiled
kernels across plan instances.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Sequence

import numpy as np

from ..columnar.batch import Column, ColumnarBatch
from ..expr.eval import HostCtx, TraceCtx, Val
from ..obs.metrics import (
    batch_cost_scope,
    record_kernel_compile as _obs_compile,
    record_kernel_disk_hit as _obs_disk_hit,
    record_kernel_launch as _obs_launch,
    record_kernel_miss as _obs_miss,
)
from ..expr.expressions import (
    Alias, AttributeReference, Expression, Literal, SortOrder,
)
from ..types import ArrayType, DataType, StringType, StructField, StructType
from ..utils import faults as _faults

__all__ = ["canonical_key", "KernelCache", "ExprPipeline", "bind_inputs",
            "broadcast_to_cap", "trace_pipeline", "pipeline_host_pass",
            "pipeline_signature", "pipeline_columns"]


# ---------------------------------------------------------------------------
# Structural canonicalization
# ---------------------------------------------------------------------------

def canonical_key(e: Expression, id_to_pos: dict[int, int]) -> tuple:
    """Hashable structural key with attribute ids replaced by input positions
    (so two queries with identical shapes share kernels)."""
    if isinstance(e, AttributeReference):
        return ("attr", id_to_pos.get(e.expr_id, -1), str(e.dtype))
    if isinstance(e, Alias):
        return ("alias", canonical_key(e.child, id_to_pos))
    if isinstance(e, Literal):
        return ("lit", e.value if not isinstance(e.value, (list, dict)) else str(e.value),
                str(e.dtype))
    if isinstance(e, SortOrder):
        return ("sort", canonical_key(e.child, id_to_pos), e.ascending,
                e.nulls_first)
    data = []
    for k, v in sorted(e.__dict__.items()):
        if k in e.child_fields or k.startswith("_") or isinstance(v, Expression):
            continue
        if isinstance(v, (list, tuple)) and any(isinstance(x, Expression) for x in v):
            continue
        if isinstance(v, DataType):
            v = str(v)
        try:
            hash(v)
        except TypeError:
            v = str(v)
        data.append((k, v))
    return (type(e).__name__, tuple(data),
            tuple(canonical_key(c, id_to_pos) for c in e.children
                  if isinstance(c, Expression)))


# ---------------------------------------------------------------------------
# Kernel cache
# ---------------------------------------------------------------------------

def _tree_nbytes(x, depth: int = 0) -> int:
    """Sum .nbytes over array leaves of a (nested) argument structure —
    shape/dtype metadata only, never touches device data."""
    if depth > 4:
        return 0
    nb = getattr(x, "nbytes", None)
    if nb is not None and not isinstance(x, (bytes, str)):
        return int(nb)
    if isinstance(x, (list, tuple)):
        return sum(_tree_nbytes(i, depth + 1) for i in x)
    if isinstance(x, dict):
        return sum(_tree_nbytes(v, depth + 1) for v in x.values())
    return 0


def _capture_kernel_cost(f, args, kwargs) -> dict | None:
    """Per-launch cost of one compiled kernel, captured once at first
    invocation: XLA's HLO cost analysis via the LOWERING (tracing only —
    no second backend compile; jax.stages.Lowered.cost_analysis) with a
    metadata fallback (argument bytes) when lowering is unavailable.
    Gated by spark.tpu.metrics.kernelCost. With
    spark.tpu.metrics.kernelMemory additionally on, the lowering is
    also COMPILED once to read memory_analysis() temp (scratch) bytes —
    the per-dispatch HBM the engine-tile ledger cannot see; that AOT
    compile is not shared with the dispatch path, hence the separate
    opt-in."""
    from ..obs.resources import kernel_cost_enabled, kernel_memory_enabled

    if not kernel_cost_enabled():
        return None
    cost = {"flops": 0.0, "bytes": float(_tree_nbytes(args)
                                         + _tree_nbytes(kwargs)),
            "source": "metadata"}
    lower = getattr(f, "lower", None)
    if lower is not None:
        try:
            lowered = lower(*args, **kwargs)
            ca = lowered.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            flops = float(ca.get("flops", 0.0) or 0.0)
            ba = float(ca.get("bytes accessed", 0.0) or 0.0)
            if ba > 0.0:
                cost = {"flops": flops, "bytes": ba, "source": "xla"}
            elif flops > 0.0:
                cost["flops"] = flops
            if kernel_memory_enabled():
                try:
                    ma = lowered.compile().memory_analysis()
                    tb = getattr(ma, "temp_size_in_bytes", None)
                    if tb is not None:
                        cost["temp_bytes"] = int(tb)
                except Exception:
                    pass  # memory capture must never fail a dispatch
        except Exception:
            pass  # cost capture must never fail a dispatch
    return cost


class KernelCache:
    """Process-global LRU of jitted kernels.

    Besides hit/miss bookkeeping the cache counts kernel LAUNCHES — every
    invocation of a cached kernel is one device dispatch, so the counters
    are the ground truth for "one dispatch per batch per stage" regression
    tests (the reference's analog is WholeStageCodegen's generated-class
    instantiation count). `launches_by_kind` buckets by the cache key's
    leading tag ("pipeline", "fused_agg", "gagg", ...). `compile_ms`
    accumulates builder time plus each kernel's first invocation (XLA
    compiles lazily on first call).

    Resource accounting (obs/resources.py): the first invocation also
    captures the kernel's per-launch cost (XLA cost_analysis flops /
    bytes accessed via the lowering), after which every launch adds it
    to the process counters (`flops_total`, `bytes_total`), the per-kind
    cost table (`cost_by_kind`), and the executing operator's record —
    launch attribution multiplied out to FLOPs and bytes."""

    def __init__(self, max_size: int = 1024):
        self._cache: "collections.OrderedDict[tuple, Any]" = collections.OrderedDict()
        self.max_size = max_size
        self.hits = 0
        self.misses = 0
        self.launches = 0
        self.compile_ms = 0.0
        self.launches_by_kind: "collections.Counter" = collections.Counter()
        # engine compiles (misses) whose XLA backend compile was served
        # from the persistent disk cache (exec/persist_cache.py): a warm
        # restart re-traces and re-jits every kernel (misses count them)
        # but the expensive XLA compile hits disk — distinct counters so
        # the obs layer tells disk-served compiles from true cold ones
        self.disk_hit_compiles = 0
        self.flops_total = 0.0      # cumulative captured flops dispatched
        self.bytes_total = 0.0      # cumulative captured bytes accessed
        # kind -> {"flops","bytes","kernels","launches"} aggregate of the
        # captured per-launch costs (the resource gate's cost table)
        self.cost_by_kind: dict = {}
        # scheduler stages run in threads; OrderedDict mutation is not
        # thread-safe (builder() itself runs unlocked — duplicate builds of
        # the same key are benign, a torn dict is not)
        self._lock = threading.Lock()

    def _wrap(self, key: tuple, f):
        if not callable(f):
            return f
        kind = key[0] if isinstance(key, tuple) and key else "?"
        state = {"first": True, "cost": None, "capturing": False}

        def launch(*args, **kwargs):
            if _faults.ENABLED:
                # chaos seam: an injected dispatch fault stands in for
                # an XLA runtime error the pre-flight could not predict
                # (RESOURCE_EXHAUSTED at launch). Raised BEFORE counting
                # — a launch that never dispatched must not count.
                # Idle cost: one module-bool read per launch.
                _faults.maybe_fail("kernel.dispatch", detail=str(kind))
            with self._lock:
                self.launches += 1
                self.launches_by_kind[kind] += 1
                first = state["first"]
                state["first"] = False
                # one capturer at a time; retried while unset (a capture
                # under kernelCost=off yields None, so flipping it on
                # later still costs this kernel), concurrent launches
                # during the capture window just skip cost accounting
                cost = state["cost"]
                capture = cost is None and not state["capturing"]
                if capture:
                    state["capturing"] = True
                elif cost is not None:
                    # steady state: cost accounting rides the same
                    # critical section as the launch counters
                    self.flops_total += cost["flops"]
                    self.bytes_total += cost["bytes"]
                    ent = self.cost_by_kind.get(kind)
                    if ent is not None:
                        ent["flops"] += cost["flops"]
                        ent["bytes"] += cost["bytes"]
                        ent["launches"] += 1
            if capture:
                # BEFORE the dispatch so even the first launch
                # attributes cost (host-side trace/lower only — no
                # kernel launch, no device sync)
                cost = _capture_kernel_cost(f, args, kwargs)
                with self._lock:
                    state["cost"] = cost
                    state["capturing"] = False
                    if cost is not None:
                        ent = self.cost_by_kind.setdefault(
                            kind, {"flops": 0.0, "bytes": 0.0,
                                   "kernels": 0, "launches": 0})
                        ent["kernels"] += 1
                        ent["flops"] += cost["flops"]
                        ent["bytes"] += cost["bytes"]
                        ent["launches"] += 1
                        tb = cost.get("temp_bytes")
                        if tb:
                            # scratch is per-dispatch, not cumulative —
                            # the kind's entry keeps the worst kernel
                            ent["temp_bytes"] = max(
                                ent.get("temp_bytes", 0), tb)
                        self.flops_total += cost["flops"]
                        self.bytes_total += cost["bytes"]
            # per-operator attribution (obs/metrics contextvar scope):
            # host bookkeeping only — no dispatch, no sync
            _obs_launch(kind, cost)
            if first:
                import time as _time

                # persistent compile cache (exec/persist_cache.py): the
                # disk-traffic counter delta across the first invocation
                # classifies THIS kernel's XLA compile as disk-served vs
                # true cold. Module-int reads — no overhead when the
                # cache is off (both counters stay 0). Concurrent first
                # invocations on other threads can in principle blur one
                # delta; the counters are process telemetry, not a gate
                # on correctness.
                from ..exec import persist_cache as _pc

                d0 = _pc.DISK_HITS
                t0 = _time.perf_counter()
                out = f(*args, **kwargs)
                dt = (_time.perf_counter() - t0) * 1000
                disk_hit = _pc.DISK_HITS > d0
                with self._lock:
                    self.compile_ms += dt
                    if disk_hit:
                        self.disk_hit_compiles += 1
                if disk_hit:
                    _obs_disk_hit(kind)
                _obs_compile(kind, dt)
                return out
            return f(*args, **kwargs)

        launch._kernel = f
        return launch

    def get_or_build(self, key: tuple, builder: Callable[[], Any]):
        with self._lock:
            f = self._cache.get(key)
            if f is not None:
                self.hits += 1
                self._cache.move_to_end(key)
                return f
            self.misses += 1
        # per-query ledger: one engine compile attributed to the query
        # whose dispatch built this kernel (obs/metrics.py)
        _obs_miss(key[0] if isinstance(key, tuple) and key else "?")
        if _faults.ENABLED:
            # chaos seam: a compile-time failure (trace/lower bug, XLA
            # compiler fault) — fired on the MISS path only, cached
            # kernels never re-compile
            _faults.maybe_fail(
                "kernel.compile",
                detail=str(key[0]) if isinstance(key, tuple) and key
                else "?")
        import time as _time

        t0 = _time.perf_counter()
        f = self._wrap(key, builder())
        dt = (_time.perf_counter() - t0) * 1000
        with self._lock:
            self.compile_ms += dt
            f = self._cache.setdefault(key, f)
            while len(self._cache) > self.max_size:
                self._cache.popitem(last=False)
        _obs_compile(key[0] if isinstance(key, tuple) and key else "?", dt)
        return f

    def counters(self) -> dict:
        """Snapshot for metrics/listener plumbing. Deliberately does NOT
        splat persist_cache.disk_counters() in: the compile.disk_* keys
        already ride the session metrics as per-query deltas (worker
        traffic folded in by the cluster scheduler), and process-absolute
        values under the same names would clobber them in the
        querySucceeded payload — one fact, one metric family. Callers
        that want the raw process-global XLA disk traffic read
        persist_cache.disk_counters() directly (bench, gates)."""
        with self._lock:
            return {
                "kernel_cache.hits": self.hits,
                "kernel_cache.misses": self.misses,
                "kernel_cache.launches": self.launches,
                "kernel_cache.compile_ms": round(self.compile_ms, 3),
                "kernel_cache.disk_hit_compiles": self.disk_hit_compiles,
                "kernel_cache.flops": round(self.flops_total, 1),
                "kernel_cache.bytes_accessed": round(self.bytes_total, 1),
            }


GLOBAL_KERNEL_CACHE = KernelCache()

# the singleton's counter lock is process-global state worth watching:
# every par_map lane and serve session bumps launch tallies through it
from ..utils import lockwatch as _lockwatch  # noqa: E402

_lockwatch.register("physical.compile.KernelCache._lock",
                    GLOBAL_KERNEL_CACHE, "_lock")


# ---------------------------------------------------------------------------
# Input binding
# ---------------------------------------------------------------------------

def bind_inputs(input_attrs: Sequence[AttributeReference]) -> dict[int, int]:
    return {a.expr_id: i for i, a in enumerate(input_attrs)}


def _host_inputs(batch: ColumnarBatch,
                 input_attrs: Sequence[AttributeReference]) -> dict[int, Val]:
    out = {}
    for a, col in zip(input_attrs, batch.columns):
        out[a.expr_id] = Val(a.dtype, None,
                             True if col.validity is not None else None,
                             col.dictionary)
    return out


def broadcast_to_cap(x, cap: int):
    import jax.numpy as jnp

    if x is None:
        return None
    x = jnp.asarray(x)
    if x.ndim == 0:
        return jnp.broadcast_to(x, (cap,))
    return x


def pipeline_host_pass(input_attrs: Sequence[AttributeReference],
                       filters: Sequence[Expression],
                       outputs: Sequence[Expression],
                       batch: ColumnarBatch):
    """Per-batch host shadow pass for a (possibly fused) pipeline kernel:
    harvests aux lookup tables and output metadata (dtype/validity
    presence/dictionaries) without touching row data. Returns
    (hctx, host_outs, aux device arrays)."""
    import jax.numpy as jnp

    hctx = HostCtx(_host_inputs(batch, input_attrs))
    for f in filters:
        hctx.eval(f)
    host_outs = [hctx.eval(o) for o in outputs]
    aux = [jnp.asarray(a) for a in hctx.aux_arrays]
    return hctx, host_outs, aux


def pipeline_signature(batch: ColumnarBatch) -> tuple:
    """Input dtype/validity signature — part of every fused kernel key."""
    return tuple((str(c.data.dtype), c.validity is not None)
                 for c in batch.columns)


def pipeline_columns(fields, host_outs, out_datas, out_valids) -> list:
    """Rebuild output Columns from a pipeline kernel's results, attaching
    each dict-encoded column's host dictionary."""
    from ..types import dict_encoded

    cols = []
    for f, hv, d, v in zip(fields, host_outs, out_datas, out_valids):
        sdict = hv.sdict if dict_encoded(f.dataType) else None
        cols.append(Column(f.dataType, d, v, sdict))
    return cols


def trace_pipeline(input_attrs: Sequence[AttributeReference],
                   filters: Sequence[Expression],
                   outputs: Sequence[Expression],
                   datas, valids, row_mask, aux, cap: int):
    """Trace the filter+project pipeline body inside a jitted kernel.

    Shared consume-side prelude: ExprPipeline wraps it alone; fused-stage
    kernels (physical/fusion.py) run it and feed the projected columns
    straight into their terminal operator's consume code — the produce/
    consume splice of the reference's WholeStageCodegen, done by tracing.
    Returns (out_datas, out_valids, out_mask) broadcast to capacity."""
    inputs = {}
    for a, d, v in zip(input_attrs, datas, valids):
        inputs[a.expr_id] = Val(a.dtype, d, v, None)
    tctx = TraceCtx(inputs, aux, cap, row_mask)
    mask = row_mask
    for f in filters:
        fv = tctx.eval(f)
        pd = fv.data
        if fv.validity is not None:
            pd = pd & fv.validity
        mask = mask & broadcast_to_cap(pd, cap)
    out_datas = []
    out_valids = []
    for o in outputs:
        ov = tctx.eval(o)
        out_datas.append(broadcast_to_cap(ov.data, cap))
        out_valids.append(broadcast_to_cap(ov.validity, cap))
    return out_datas, out_valids, mask


# ---------------------------------------------------------------------------
# ExprPipeline: N filters + M output expressions in one kernel
# ---------------------------------------------------------------------------

class ExprPipeline:
    """Compiles `filters` (conjunctive predicates) and `outputs` (named
    expressions) over a fixed input attribute list into one jitted kernel.

    Per batch: a host pass harvests dictionaries/aux tables and output
    metadata, then the cached kernel runs on device."""

    def __init__(self, input_attrs: Sequence[AttributeReference],
                 filters: Sequence[Expression],
                 outputs: Sequence[Expression],
                 out_schema: StructType):
        self.input_attrs = list(input_attrs)
        self.filters = list(filters)
        self.outputs = list(outputs)
        self.out_schema = out_schema
        self.id_to_pos = bind_inputs(self.input_attrs)
        self._struct_key = (
            tuple(canonical_key(f, self.id_to_pos) for f in self.filters),
            tuple(canonical_key(o, self.id_to_pos) for o in self.outputs),
        )

    def run(self, batch: ColumnarBatch) -> ColumnarBatch:
        cap = batch.capacity
        hctx, host_outs, aux = pipeline_host_pass(
            self.input_attrs, self.filters, self.outputs, batch)
        key = ("pipeline", self._struct_key, cap, pipeline_signature(batch),
               hctx.signature())

        kernel = GLOBAL_KERNEL_CACHE.get_or_build(
            key, lambda: self._build_kernel(cap))

        datas = [c.data for c in batch.columns]
        valids = [c.validity for c in batch.columns]
        with batch_cost_scope(batch):
            out_datas, out_valids, new_mask = kernel(datas, valids,
                                                     batch.row_mask, aux)
        cols = pipeline_columns(self.out_schema.fields, host_outs, out_datas,
                                out_valids)
        cols = self._propagate_runs(batch, cols)
        return ColumnarBatch(self.out_schema, cols, new_mask, num_rows=None)

    def _propagate_runs(self, batch: ColumnarBatch, cols: list) -> list:
        """Pass-through outputs inherit the input column's ingest RunInfo:
        the kernel emits a FRESH array, but a pure attribute reference
        carries the same values row-for-row and mask-only filters never
        reorder rows, so sortedness metadata harvested at ingest still
        describes the output plane — the sorted-run (ragg) aggregate
        stays reachable on filter/project→agg chains, not just direct
        scan→agg (compressed execution; plan_lint mirrors via
        _Batch.ingest pass-through sets)."""
        from dataclasses import replace as _replace

        from ..expr.expressions import Alias as _Alias

        any_runs = any(c.runs is not None for c in batch.columns)
        if not any_runs:
            return cols
        in_pos = {a.expr_id: i for i, a in enumerate(self.input_attrs)}
        out = []
        for o, col in zip(self.outputs, cols):
            target = o.child if isinstance(o, _Alias) else o
            if isinstance(target, AttributeReference):
                i = in_pos.get(target.expr_id)
                if i is not None and batch.columns[i].runs is not None:
                    col = _replace(col, runs=batch.columns[i].runs)
            out.append(col)
        return out

    def _build_kernel(self, cap: int):
        import jax

        input_attrs = self.input_attrs
        filters = self.filters
        outputs = self.outputs

        def kernel(datas, valids, row_mask, aux):
            return trace_pipeline(input_attrs, filters, outputs,
                                  datas, valids, row_mask, aux, cap)

        return jax.jit(kernel)
