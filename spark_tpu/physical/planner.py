"""Physical planner: LogicalPlan → PhysicalPlan.

Role of the reference's SparkPlanner/SparkStrategies (sqlx/
SparkStrategies.scala — join selection, aggregate planning via
sqlx/aggregate/AggUtils.scala) plus EnsureRequirements
(sqlx/exchange/EnsureRequirements.scala:51 — inserts exchanges where a
child's partitioning doesn't satisfy the parent's required distribution).

Planner contracts established here (and relied on by operators):
  * exchange/join/sort/grouping keys are always bound to attributes —
    complex keys get pre-projected via ComputeExec;
  * aggregates are always planned as partial→(exchange)→final with a
    finishing ComputeExec evaluating result expressions over buffers;
  * right outer joins are flipped to left joins over swapped children.
"""

from __future__ import annotations

from typing import Sequence

from ..config import AUTO_BROADCAST_THRESHOLD, SHUFFLE_PARTITIONS, SQLConf
from ..errors import UnsupportedOperationError
from ..plan import logical as L
from ..plan.optimizer import join_conjuncts, split_conjuncts
from ..expr.expressions import (
    AggregateFunction, Alias, AttributeReference, EqualTo, Expression,
    Literal, SortOrder,
)
from ..types import DataType, StringType, DecimalType
from .aggregates import AggSpec, lower_aggregate_function
from .exchange import BroadcastExchangeExec, ShuffleExchangeExec
from .operators import (
    CoalescePartitionsExec, ComputeExec, HashAggregateExec, HashJoinExec,
    LimitExec, LocalTableScanExec, NestedLoopJoinExec, PhysicalPlan, RangeExec,
    ScanExec, SortExec, UnionExec,
)
from .partitioning import (
    AllTuples, BroadcastDistribution, ClusteredDistribution, Distribution,
    HashPartitioning, OrderedDistribution, RangePartitioning, SinglePartition,
    UnspecifiedDistribution,
)


def _row_width(attrs: Sequence[AttributeReference]) -> int:
    w = 0
    for a in attrs:
        w += max(int(a.dtype.device_dtype.itemsize), 4)
    return max(w, 8)


class Planner:
    def __init__(self, conf: SQLConf, cluster: bool = False):
        self.conf = conf
        self.cluster = cluster

    # ------------------------------------------------------------------
    def plan(self, plan: L.LogicalPlan) -> PhysicalPlan:
        from ..config import COMPILE_TIER, FUSION_ENABLED
        from .fusion import collapse_computes, fuse_stages

        p = self._convert(plan)
        p = self._ensure_requirements(p)
        # whole-stage fusion after stage boundaries exist (the
        # CollapseCodegenStages slot); off = operator-at-a-time oracle.
        # Adjacent-ComputeExec collapsing is an invariant, not a mode.
        p = collapse_computes(p)
        tier_pref = str(self.conf.get(COMPILE_TIER)).lower()
        if self.conf.get(FUSION_ENABLED) and tier_pref != "operator":
            p = fuse_stages(p, self.conf)
        self._inject_dpp(p)
        from .exchange import annotate_exchange_stat_cols

        # after fusion (exchanges may have absorbed their pipeline —
        # stat positions index the FUSED output): restrict map-side
        # shuffle stat accumulation to plan-reachable dense candidates
        annotate_exchange_stat_cols(p)
        # compile-tier cost model (physical/whole_query.py): collapse a
        # slice-resident plan into ONE jitted program, or stash the
        # fallback decision for explain("analysis")
        from .whole_query import apply_compile_tier

        p = apply_compile_tier(p, self.conf, cluster=self.cluster)
        return p

    # ------------------------------------------------------------------
    def _inject_dpp(self, plan: PhysicalPlan) -> None:
        """Mark probe-side scans whose hive-partition column is a join key
        so the join executes its build side first and prunes whole splits
        (reference: sqlx/dynamicpruning/PartitionPruning.scala; here the
        materialized build side replaces the duplicated filter subquery)."""
        if not self.conf.get("spark.sql.dynamicPartitionPruning.enabled",
                             True):
            return

        from .exchange import BroadcastExchangeExec as _BX, \
            ShuffleExchangeExec as _SX
        from .operators import CoalescePartitionsExec as _CP, \
            ComputeExec as _CE, UnionExec as _UN

        def scans_under(n, acc):
            """Pruning-safe descent only: an output row of these operators
            carries its source row's partition column unchanged, so dropping
            non-matching scan rows cannot change surviving rows (reference:
            PartitionPruning's Project/Filter/Join/Union restriction).
            Limit/Window/Sort/Sample/Aggregate stop the walk — pruning
            beneath them would change which rows they keep."""
            if isinstance(n, ScanExec):
                acc.append(n)
                return
            if isinstance(n, (_CE, _UN, _SX, _BX, _CP)):
                for c in n.children:
                    scans_under(c, acc)
            elif isinstance(n, HashJoinExec):
                scans_under(n.left, acc)

        def walk(n):
            for c in n.children:
                walk(c)
            if isinstance(n, HashJoinExec) \
                    and n.join_type in ("inner", "left_semi"):
                acc: list = []
                scans_under(n.left, acc)
                for scan in acc:
                    pk = getattr(scan.source, "_part_keys", None)
                    if not pk or not hasattr(scan.source,
                                             "split_partition_value"):
                        continue
                    by_id = {a.expr_id: a.name for a in scan.attrs}
                    for ki, lk in enumerate(n.left_keys):
                        if by_id.get(lk.expr_id) in pk:
                            n.dpp_targets.append((scan, ki))

        walk(plan)

    # ------------------------------------------------------------------
    def _convert(self, node: L.LogicalPlan) -> PhysicalPlan:
        if isinstance(node, L.LogicalRelation):
            return ScanExec(node.source, list(node.attrs), node.name)
        if isinstance(node, L.LocalRelation):
            return LocalTableScanExec(list(node.attrs), node.table)
        if isinstance(node, L.OneRowRelation):
            import pyarrow as pa

            return LocalTableScanExec(
                [], pa.table({"__one": pa.array([1], pa.int32())}).select([]))
        if isinstance(node, L.RangeRelation):
            return RangeExec(node.start, node.end, node.step,
                             node.num_partitions, node.attr)
        if isinstance(node, L.Project):
            child = self._convert(node.child)
            return self._fuse_compute([], node.project_list, child)
        if isinstance(node, L.Filter):
            conjuncts = split_conjuncts(node.condition)
            inner = node.child
            while isinstance(inner, L.SubqueryAlias):
                inner = inner.child
            from ..io.sources import SupportsPushDownFilters

            if isinstance(inner, L.LogicalRelation) \
                    and isinstance(inner.source, SupportsPushDownFilters) \
                    and self.conf.get("spark.tpu.datasource.filterPushdown",
                                      True):
                # DSv2 pushdown negotiation: translatable conjuncts go
                # to the source; it returns the residual it could NOT
                # apply (V2ScanRelationPushDown role) — the engine keeps
                # residuals + untranslatable conjuncts
                mapped = [(c, d) for c, d in
                          _source_predicates_mapped(conjuncts, inner.attrs)]
                if mapped:
                    src2, residual = inner.source.push_filters(
                        [d for _, d in mapped])
                    consumed = {id(c) for c, d in mapped
                                if d not in residual}
                    kept = [c for c in conjuncts if id(c) not in consumed]
                    child = ScanExec(src2, list(inner.attrs), inner.name)
                    return self._fuse_compute(
                        kept, [a for a in node.child.output], child)
            if isinstance(inner, L.LogicalRelation) \
                    and hasattr(inner.source, "pruned") \
                    and self.conf.get("spark.sql.parquet.filterPushdown",
                                      True):
                preds = _source_predicates(conjuncts, inner.attrs)
                if preds:
                    # split/row-group pruning by stats (reference:
                    # ParquetFileFormat row-group filter + partition
                    # pruning); the filter stays — pruning is conservative
                    child = ScanExec(inner.source.pruned(preds),
                                     list(inner.attrs), inner.name)
                    return self._fuse_compute(
                        conjuncts, [a for a in node.child.output], child)
            child = self._convert(node.child)
            return self._fuse_compute(conjuncts,
                                      [a for a in node.child.output], child)
        if isinstance(node, L.Aggregate):
            return self._plan_aggregate(node)
        if isinstance(node, L.Sort):
            return self._plan_sort(node)
        if isinstance(node, (L.Limit, L.Offset)):
            return self._plan_limit(node)
        if isinstance(node, L.Join):
            return self._plan_join(node)
        if isinstance(node, L.Union):
            children = [self._convert(c) for c in node.children_plans]
            return UnionExec(children, list(node.output))
        if isinstance(node, L.SubqueryAlias):
            return self._convert(node.child)
        if isinstance(node, L.EventTimeWatermark):
            return self._convert(node.child)  # batch: transparent marker
        if isinstance(node, L.Repartition):
            child = self._convert(node.child)
            n = node.num_partitions or self.conf.shuffle_partitions
            if not node.shuffle:
                return CoalescePartitionsExec(n, child)
            if node.partition_exprs:
                keys, child = self._bind_keys(
                    [e for e in node.partition_exprs], child, "__repart")
                return ShuffleExchangeExec(HashPartitioning(keys, n), child)
            from .partitioning import UnknownPartitioning

            return ShuffleExchangeExec(UnknownPartitioning(n), child)
        if isinstance(node, L.Distinct):
            # optimizer normally rewrites; safety net
            out = node.child.output
            return self._plan_aggregate(
                L.Aggregate(list(out), list(out), node.child))
        if isinstance(node, L.Window):
            return self._plan_window(node)
        if isinstance(node, L.Sample):
            from .operators import SampleExec

            return SampleExec(node.fraction, node.seed,
                              self._convert(node.child))
        if isinstance(node, L.PythonEval):
            from .python_eval import PythonEvalExec

            return PythonEvalExec(node.udf_aliases,
                                  self._convert(node.child))
        if isinstance(node, L.Generate):
            from .generate import GenerateExec

            return GenerateExec(node.generator, node.element_attr,
                                self._convert(node.child))
        from ..streaming.stateful_map import StatefulMapGroups

        if isinstance(node, StatefulMapGroups):
            from .python_eval import StatefulMapExec

            return StatefulMapExec(node, self._convert(node.child))
        raise UnsupportedOperationError(
            f"no physical plan for {type(node).__name__}")

    # ------------------------------------------------------------------
    def _plan_window(self, node: L.Window) -> PhysicalPlan:
        from ..expr.window import WindowExpression
        from .window import WindowExec

        child = self._convert(node.child)
        pkeys, child = self._bind_keys(list(node.partition_spec), child,
                                       "__wpart")
        okeys, child = self._bind_keys([o.child for o in node.order_spec],
                                       child, "__word")
        orders = [SortOrder(k, o.ascending, o.nulls_first)
                  for k, o in zip(okeys, node.order_spec)]

        arg_exprs = []
        for al in node.window_exprs:
            f = al.child.function
            if getattr(f, "child", None) is not None:
                arg_exprs.append(f.child)
        arg_attrs, child = self._bind_keys(arg_exprs, child, "__warg")
        arg_map = dict(zip((id(e) for e in arg_exprs), arg_attrs))

        new_wexprs = []
        for al in node.window_exprs:
            w = al.child
            f = w.function
            if getattr(f, "child", None) is not None:
                f = f.copy(child=arg_map[id(f.child)])
            nw = WindowExpression(f, list(pkeys), list(orders), w.frame)
            new_wexprs.append(Alias(nw, al.name, al.expr_id))

        wexec = WindowExec(new_wexprs, pkeys, orders, child)
        want = list(node.output)
        if [a.expr_id for a in wexec.output] != [a.expr_id for a in want]:
            return ComputeExec([], want, wexec)
        return wexec

    # ------------------------------------------------------------------
    def _fuse_compute(self, filters: list[Expression],
                      outputs: list[Expression],
                      child: PhysicalPlan) -> PhysicalPlan:
        """Fuse into an existing ComputeExec child when safe (the
        CollapseCodegenStages analog; substitution shared with the
        FuseStages collapse pass in physical/fusion.py)."""
        if isinstance(child, ComputeExec):
            from .fusion import merge_into_compute

            return merge_into_compute(filters, outputs, child)
        return ComputeExec(filters, outputs, child)

    # ------------------------------------------------------------------
    def _bind_keys(self, exprs: list[Expression], child: PhysicalPlan,
                   prefix: str) -> tuple[list[AttributeReference], PhysicalPlan]:
        """Ensure exprs are attributes of child output; project complex ones."""
        child_ids = {a.expr_id for a in child.output}
        keys: list[AttributeReference] = []
        extra: list[Alias] = []
        for i, e in enumerate(exprs):
            if isinstance(e, AttributeReference) and e.expr_id in child_ids:
                keys.append(e)
            elif isinstance(e, Alias):
                extra.append(e)
                keys.append(e.to_attribute())
            else:
                al = Alias(e, f"{prefix}_{i}")
                extra.append(al)
                keys.append(al.to_attribute())
        if extra:
            outputs = list(child.output) + list(extra)
            child = self._fuse_compute([], outputs, child)
        return keys, child

    # ------------------------------------------------------------------
    def _plan_aggregate(self, node: L.Aggregate) -> PhysicalPlan:
        pushed = self._try_push_aggregate(node)
        if pushed is not None:
            return pushed
        child = self._convert(node.child)

        # 1. bind grouping keys to attributes
        group_keys, child = self._bind_keys(list(node.grouping_exprs), child,
                                            "__group")
        return self._plan_aggregate_bound(node, child, group_keys)

    def _fully_pushed_filter_scan(self, plan):
        """If `plan` is (aliased) Filter over an (aliased) pushdown-
        capable relation and EVERY conjunct translates with empty
        residual, return (relation_node, pushed_source); else None.
        Shared by the aggregate and limit composition paths."""
        from ..io.sources import SupportsPushDownFilters

        node = plan
        while isinstance(node, L.SubqueryAlias):
            node = node.child
        if not isinstance(node, L.Filter):
            return None
        if not self.conf.get("spark.tpu.datasource.filterPushdown", True):
            return None
        inner = node.child
        while isinstance(inner, L.SubqueryAlias):
            inner = inner.child
        if not isinstance(inner, L.LogicalRelation) or \
                not isinstance(inner.source, SupportsPushDownFilters):
            return None
        conjs = split_conjuncts(node.condition)
        mapped = _source_predicates_mapped(conjs, inner.attrs)
        if len(mapped) != len(conjs):
            return None
        src2, residual = inner.source.push_filters(
            [d for _, d in mapped])
        if residual:
            return None
        return inner, src2

    def _try_push_aggregate(self, node: L.Aggregate):
        """DSv2 aggregation pushdown (SupportsPushDownAggregates role):
        Aggregate over a bare scan whose groupings are plain columns and
        whose aggregates are count/sum/min/max/avg over plain columns
        executes ENTIRELY in the source; the node is replaced by a scan
        of the aggregated result."""
        from ..expr.expressions import (
            Alias, Average, Count, Max, Min, Sum,
        )
        from ..io.sources import SupportsPushDownAggregation

        inner = node.child
        while isinstance(inner, L.SubqueryAlias):
            inner = inner.child
        filter_src = None
        if isinstance(inner, L.Filter):
            # aggregate over a FULLY-pushable filter composes remotely:
            # WHERE ... GROUP BY ...
            pushed = self._fully_pushed_filter_scan(inner)
            if pushed is not None:
                inner, filter_src = pushed
        if not isinstance(inner, L.LogicalRelation) or \
                not isinstance(inner.source, SupportsPushDownAggregation) \
                or not self.conf.get("spark.tpu.datasource.aggPushdown",
                                     True):
            return None
        names = {a.expr_id: a.name for a in inner.attrs}
        if not all(isinstance(g, AttributeReference)
                   and g.expr_id in names
                   for g in node.grouping_exprs):
            return None
        fn_of = {Count: "count", Sum: "sum", Min: "min", Max: "max",
                 Average: "avg"}
        groupings = [names[g.expr_id] for g in node.grouping_exprs]
        aggs, out_attrs = [], []
        for e in node.aggregate_exprs:
            if isinstance(e, AttributeReference) and \
                    any(e.expr_id == g.expr_id
                        for g in node.grouping_exprs):
                out_attrs.append(e)
                continue
            if not (isinstance(e, Alias) and
                    type(e.child) in fn_of):
                return None
            f = e.child
            if getattr(f, "distinct", False):
                return None
            if f.child is None:
                col = None
            elif isinstance(f.child, AttributeReference) and \
                    f.child.expr_id in names:
                col = names[f.child.expr_id]
            else:
                return None
            aggs.append((fn_of[type(f)], col, e.name))
            out_attrs.append(e.to_attribute())
        if not aggs:
            return None
        base = filter_src if filter_src is not None else inner.source
        src2 = base.push_aggregation(groupings, aggs)
        if src2 is None:
            return None
        return ScanExec(src2, out_attrs, f"{inner.name}:agg")

    def _plan_aggregate_bound(self, node: L.Aggregate, child,
                              group_keys) -> PhysicalPlan:
        group_map: list[tuple[Expression, AttributeReference]] = list(
            zip(node.grouping_exprs, group_keys))

        # 2. collect distinct aggregate functions across output exprs
        funcs: list[AggregateFunction] = []

        def collect(e: Expression):
            for n in e.iter_nodes():
                if isinstance(n, AggregateFunction):
                    if not any(n.semantic_equals(f) for f in funcs):
                        funcs.append(n)

        for e in node.aggregate_exprs:
            collect(e)

        # 3. bind aggregate inputs to attributes
        arg_exprs = []
        for f in funcs:
            if f.child is not None:
                arg_exprs.append(f.child)
        arg_attrs, child = self._bind_keys(arg_exprs, child, "__aggarg")
        arg_map = dict(zip((id(e) for e in arg_exprs), arg_attrs))

        specs: list[AggSpec] = []
        func_to_spec: list[tuple[AggregateFunction, AggSpec]] = []
        for i, f in enumerate(funcs):
            bound_child = arg_map[id(f.child)] if f.child is not None else None
            bound = f.copy(child=bound_child) if f.child is not None else f
            spec = lower_aggregate_function(bound, f"__agg{i}", None or
                                            _fresh_id())
            specs.append(spec)
            func_to_spec.append((f, spec))

        if any(not s.mergeable for s in specs) and \
                child.output_partitioning().num_partitions != 1:
            # non-mergeable aggregates (percentile/median): gather first,
            # aggregate once (no partial/final split)
            child = ShuffleExchangeExec(SinglePartition(), child)
        partial = HashAggregateExec(group_keys, specs, "partial", child)
        if child.output_partitioning().num_partitions == 1:
            # single upstream partition: the partial pass is already
            # complete — skip the merge stage (reference: AggUtils plans
            # one-pass aggregation when no shuffle is needed)
            final: PhysicalPlan = partial
        else:
            final = HashAggregateExec(group_keys, specs, "final", partial)

        # 4. finishing projection: replace agg funcs with spec result exprs,
        #    grouping exprs with grouping attrs
        outputs: list[Expression] = []
        for e in node.aggregate_exprs:
            outputs.append(self._finish_expr(e, func_to_spec, group_map))
        return ComputeExec([], outputs, final)

    def _finish_expr(self, e: Expression, func_to_spec, group_map):
        def replace(x: Expression) -> Expression:
            for g, attr in group_map:
                gc = g.child if isinstance(g, Alias) else g
                if x.semantic_equals(g) or x.semantic_equals(gc):
                    return attr
            for f, spec in func_to_spec:
                if x.semantic_equals(f):
                    return spec.result_alias.child
            return x

        if isinstance(e, Alias):
            return Alias(e.child.transform_down(replace), e.name, e.expr_id)
        if isinstance(e, AttributeReference):
            # grouping attr passthrough
            for g, attr in group_map:
                if e.semantic_equals(g):
                    return e if e.expr_id == attr.expr_id else Alias(
                        attr, e.name, e.expr_id)
            return e
        return Alias(e.transform_down(replace), _auto_name(e))

    # ------------------------------------------------------------------
    def _plan_sort(self, node: L.Sort) -> PhysicalPlan:
        child = self._convert(node.child)
        key_exprs = [o.child for o in node.orders]
        keys, child = self._bind_keys(key_exprs, child, "__sort")
        orders = [SortOrder(k, o.ascending, o.nulls_first)
                  for k, o in zip(keys, node.orders)]
        sort = SortExec(orders, child)
        sort.is_global = node.is_global
        # drop helper columns if we added any
        if len(child.output) != len(node.output):
            return ComputeExec([], list(node.output), sort)
        return sort

    # ------------------------------------------------------------------
    def _plan_limit(self, node) -> PhysicalPlan:
        if isinstance(node, L.Offset):
            child = self._convert(node.child)
            return LimitExec(1 << 62, child, offset=node.n, is_global=True)
        inner = node.child
        offset = 0
        if isinstance(inner, L.Offset):
            offset = inner.n
            inner = inner.child
        # TopK: ORDER BY + LIMIT → per-partition sort+limit, gather, final
        # sort+limit (reference: TakeOrderedAndProjectExec) — avoids the
        # full range-partitioned global sort
        if isinstance(inner, L.Sort) and inner.is_global and all(
                isinstance(o.child, AttributeReference)
                for o in inner.orders):
            child = self._convert(inner.child)
            child_ids = {a.expr_id for a in child.output}
            if all(o.child.expr_id in child_ids for o in inner.orders):
                orders = [SortOrder(o.child, o.ascending, o.nulls_first)
                          for o in inner.orders]
                local = LimitExec(node.n + offset,
                                  SortExec(orders, child))
                gathered = ShuffleExchangeExec(SinglePartition(), local)
                return LimitExec(node.n, SortExec(orders, gathered),
                                 offset=offset, is_global=True)
        # DSv2 limit pushdown (SupportsPushDownLimit role): the source
        # applies the per-partition limit remotely; the engine's limit
        # stays above it as the global cut
        scan_like = inner
        pushed_filters = None
        while isinstance(scan_like, L.SubqueryAlias):
            scan_like = scan_like.child
        if isinstance(scan_like, L.Filter):
            # LIMIT over a FULLY-pushable filter composes remotely:
            # WHERE ... LIMIT n (V2ScanRelationPushDown pushes filters
            # before limits for exactly this reason)
            pushed = self._fully_pushed_filter_scan(scan_like)
            if pushed is not None:
                scan_like, pushed_filters = pushed
        if isinstance(scan_like, L.LogicalRelation):
            from ..io.sources import SupportsPushDownLimit

            base_src = pushed_filters or scan_like.source
            if isinstance(base_src, SupportsPushDownLimit):
                pushed = base_src.push_limit(node.n + offset)
                if pushed is not None:
                    child = ScanExec(pushed, list(scan_like.attrs),
                                     scan_like.name)
                    local = LimitExec(node.n + offset, child,
                                      is_global=False)
                    return LimitExec(node.n, local, offset=offset,
                                     is_global=True)
        child = self._convert(inner)
        local = LimitExec(node.n + offset, child, is_global=False)
        return LimitExec(node.n, local, offset=offset, is_global=True)

    # ------------------------------------------------------------------
    def _plan_join(self, node: L.Join) -> PhysicalPlan:
        jt = node.join_type
        left_l, right_l = node.left, node.right

        # flip right joins: build side is always right, probe left
        flipped = False
        if jt == "right_outer":
            left_l, right_l = right_l, left_l
            jt = "left_outer"
            flipped = True

        left = self._convert(left_l)
        right = self._convert(right_l)

        # split condition into equi keys and residual
        equi: list[tuple[Expression, Expression]] = []
        residual: list[Expression] = []
        if node.condition is not None:
            lids = {a.expr_id for a in left_l.output}
            rids = {a.expr_id for a in right_l.output}
            for c in split_conjuncts(node.condition):
                if isinstance(c, EqualTo):
                    lr, rr = c.left.references(), c.right.references()
                    if lr and rr and lr <= lids and rr <= rids:
                        equi.append((c.left, c.right))
                        continue
                    if lr and rr and lr <= rids and rr <= lids:
                        equi.append((c.right, c.left))
                        continue
                residual.append(c)

        if not equi:
            if jt in ("inner", "cross"):
                nl = NestedLoopJoinExec(
                    join_conjuncts(residual) if residual else None,
                    "cross" if jt == "cross" and not residual else "inner",
                    left, right)
                return self._maybe_reorder(nl, node, flipped)
            if jt in ("left_semi", "left_anti", "left_outer"):
                # e.g. null-aware NOT IN: "eq OR eq IS NULL" is not an
                # equi conjunct; any-match semantics need the pair fold,
                # not a hash probe
                nl = NestedLoopJoinExec(
                    join_conjuncts(residual) if residual else None,
                    jt, left, right)
                return self._maybe_reorder(nl, node, flipped)
            raise UnsupportedOperationError(
                f"non-equi {jt} join not supported yet")

        if residual and jt in ("left_semi", "left_anti", "left_outer"):
            # a residual on top of a semi/anti/outer hash join is NOT a
            # post-filter — match-existence must be decided over the full
            # condition before null extension
            nl = NestedLoopJoinExec(node.condition, jt, left, right)
            return self._maybe_reorder(nl, node, flipped)

        if residual and jt not in ("inner",):
            raise UnsupportedOperationError(
                f"{jt} join with non-equi residual not supported yet")

        lkeys, left = self._bind_keys([lk for lk, _ in equi], left, "__jkl")
        rkeys, right = self._bind_keys([rk for _, rk in equi], right, "__jkr")

        broadcast = self._can_broadcast(right_l, jt)
        join = HashJoinExec(lkeys, rkeys, jt, left, right,
                            is_broadcast=broadcast)

        out: PhysicalPlan = join
        if residual:
            out = self._fuse_compute(residual, list(join.output), join)
        # drop helper key columns
        want = self._expected_join_output(node, flipped)
        if [a.expr_id for a in out.output] != [a.expr_id for a in want]:
            out = self._fuse_compute([], want, out) if not isinstance(out, ComputeExec) \
                else ComputeExec(out.filters, want, out.child)
        return out

    def _expected_join_output(self, node: L.Join, flipped: bool):
        return list(node.output)

    def _maybe_reorder(self, plan: PhysicalPlan, node: L.Join, flipped: bool):
        want = list(node.output)
        if [a.expr_id for a in plan.output] != [a.expr_id for a in want]:
            return ComputeExec([], want, plan)
        return plan

    # join types where a replicated RIGHT build side is sound: every probe
    # partition may see the full build relation. full_outer is NOT here —
    # unmatched build rows would be emitted once per probe partition
    # (reference: JoinSelection canBroadcastBySize + canBuildBroadcastRight).
    # AQE demotion (physical/adaptive.py replan_stages) reuses this set.
    _BROADCAST_RIGHT_TYPES = frozenset(
        ("inner", "cross", "left_outer", "left_semi", "left_anti"))

    def _can_broadcast(self, right_logical: L.LogicalPlan, jt: str) -> bool:
        if jt not in self._BROADCAST_RIGHT_TYPES:
            return False
        rows = right_logical.stats_rows()
        if rows is None:
            return False
        width = _row_width(right_logical.output)
        return rows * width <= int(self.conf.get(AUTO_BROADCAST_THRESHOLD))

    # ------------------------------------------------------------------
    # EnsureRequirements
    # ------------------------------------------------------------------
    def _ensure_requirements(self, plan: PhysicalPlan) -> PhysicalPlan:
        plan = plan.map_children(
            lambda c: self._ensure_requirements(c))

        reqs = plan.required_child_distribution()
        children = plan.children
        if not children:
            return plan
        n_shuffle = self.conf.shuffle_partitions

        new_children = list(children)
        changed = False

        if isinstance(plan, HashJoinExec) and not plan.is_broadcast:
            l, r = children
            lp, rp = l.output_partitioning(), r.output_partitioning()
            lreq, rreq = reqs
            ok = (lp.satisfies(lreq) and rp.satisfies(rreq)
                  and lp.num_partitions == rp.num_partitions)
            if not ok:
                new_children[0] = ShuffleExchangeExec(
                    HashPartitioning(list(plan.left_keys), n_shuffle), l)
                new_children[1] = ShuffleExchangeExec(
                    HashPartitioning(list(plan.right_keys), n_shuffle), r)
                changed = True
        else:
            for i, (child, req) in enumerate(zip(children, reqs)):
                p = child.output_partitioning()
                if p.satisfies(req):
                    continue
                changed = True
                if isinstance(req, BroadcastDistribution):
                    new_children[i] = BroadcastExchangeExec(child)
                elif isinstance(req, AllTuples):
                    new_children[i] = ShuffleExchangeExec(SinglePartition(),
                                                          child)
                elif isinstance(req, ClusteredDistribution):
                    keys = [e for e in req.exprs
                            if isinstance(e, AttributeReference)]
                    new_children[i] = ShuffleExchangeExec(
                        HashPartitioning(keys, n_shuffle), child)
                elif isinstance(req, OrderedDistribution):
                    new_children[i] = ShuffleExchangeExec(
                        RangePartitioning(req.orders, n_shuffle), child)
                else:
                    continue
        # global sort needs range partitioning
        if isinstance(plan, SortExec) and getattr(plan, "is_global", False):
            child = new_children[0]
            p = child.output_partitioning()
            od = OrderedDistribution(plan.orders)
            if not p.satisfies(od) and p.num_partitions > 1:
                new_children[0] = ShuffleExchangeExec(
                    RangePartitioning(plan.orders, n_shuffle), child)
                changed = True
        if changed:
            return plan.with_new_children(new_children)
        return plan


def _source_predicates_mapped(conjuncts, attrs) -> list:
    """Like _source_predicates but keeps the (conjunct, descriptor)
    pairing so pushdown can tell which engine predicates a source
    consumed (DataSourceStrategy.translateFilter + selectFilters)."""
    out = []
    for c in conjuncts:
        descs = _source_predicates([c], attrs)
        if len(descs) == 1:
            out.append((c, descs[0]))
    return out


def _source_predicates(conjuncts, attrs) -> list:
    """Extract (col, op, value) predicates a DataSource can prune with:
    attr-vs-literal comparisons and IN over literals (reference:
    DataSourceStrategy.translateFilter)."""
    from ..expr.expressions import (
        EqualTo, GreaterThan, GreaterThanOrEqual, In, LessThan,
        LessThanOrEqual, Literal,
    )

    names = {a.expr_id: a.name for a in attrs}
    flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}
    ops = {EqualTo: "=", LessThan: "<", LessThanOrEqual: "<=",
           GreaterThan: ">", GreaterThanOrEqual: ">="}
    preds = []
    for c in conjuncts:
        op = ops.get(type(c))
        if op is not None:
            l, r = c.left, c.right
            if isinstance(r, AttributeReference) and isinstance(l, Literal):
                l, r, op = r, l, flip[op]
            if isinstance(l, AttributeReference) and isinstance(r, Literal) \
                    and r.value is not None and l.expr_id in names:
                preds.append((names[l.expr_id], op, r.value))
        elif isinstance(c, In) and isinstance(c.child, AttributeReference) \
                and c.child.expr_id in names \
                and all(isinstance(i, Literal) for i in c.items):
            vals = [i.value for i in c.items if i.value is not None]
            if vals:
                preds.append((names[c.child.expr_id], "in", vals))
    return preds


_id_box = [None]


def _fresh_id() -> int:
    from ..plan.tree import next_id

    return next_id()


def _auto_name(e: Expression) -> str:
    return e.simple_string()[:40]
