"""PythonEvalExec: vectorized host UDF evaluation.

Role of the reference's ArrowEvalPythonExec + PythonRunner worker protocol
(sqlx/python/ArrowEvalPythonExec.scala; SURVEY.md §3.4). No process boundary
here: device pipelines evaluate argument expressions, live rows transfer to
the host once, the UDF runs vectorized over numpy arrays, and results come
back as new device columns (strings re-enter via dictionary encoding).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..columnar.batch import Column, ColumnarBatch, StringDict
from ..exec.context import ExecContext
from ..expr.expressions import Alias
from ..types import StringType, StructField, StructType
from .compile import ExprPipeline
from .operators import PhysicalPlan, attrs_schema


class PythonEvalExec(PhysicalPlan):
    child_fields = ("child",)

    def __init__(self, udf_aliases: Sequence[Alias], child: PhysicalPlan):
        self.udf_aliases = list(udf_aliases)
        self.child = child
        self._arg_pipelines = None

    @property
    def output(self):
        return self.child.output + [a.to_attribute()
                                    for a in self.udf_aliases]

    def output_partitioning(self):
        return self.child.output_partitioning()

    def _pipelines(self):
        if self._arg_pipelines is None:
            self._arg_pipelines = []
            # each UDF's args may reference EARLIER UDF outputs (nested
            # UDFs extract bottom-up — e.g. transform(array(...), f)):
            # grow the visible input attrs as aliases accumulate
            inputs = list(self.child.output)
            for al in self.udf_aliases:
                udf = al.child
                arg_aliases = [Alias(a, f"__a{i}")
                               for i, a in enumerate(udf.args)]
                schema = StructType([
                    StructField(x.name, x.child.dtype, True)
                    for x in arg_aliases])
                self._arg_pipelines.append(ExprPipeline(
                    list(inputs), [], arg_aliases, schema))
                inputs.append(al.to_attribute())
        return self._arg_pipelines

    def execute(self, ctx: ExecContext):
        parts = self.child.execute(ctx)
        return [[self._eval_batch(b, ctx) for b in p] for p in parts]

    def _eval_batch(self, batch: ColumnarBatch, ctx) -> ColumnarBatch:
        import jax.numpy as jnp

        cap = batch.capacity
        mask = np.asarray(batch.row_mask)
        sel = np.nonzero(mask)[0]
        new_cols = list(batch.columns)
        cur_attrs = list(self.child.output)
        cur = batch
        for al, pipe in zip(self.udf_aliases, self._pipelines()):
            udf = al.child
            arg_batch = pipe.run(cur)
            with ctx.metrics.time("python_udf"):
                result = self._dict_domain_call(udf, arg_batch, sel, ctx)
                if result is None:
                    args = [c.to_numpy(sel) for c in arg_batch.columns]
                    result = self._call(udf, args, len(sel))
            col = self._to_column(udf.return_type, result, sel, cap)
            new_cols.append(col)
            cur_attrs.append(al.to_attribute())
            cur = ColumnarBatch(attrs_schema(cur_attrs), new_cols,
                                batch.row_mask, batch._num_rows)
        schema = attrs_schema(self.output)
        return ColumnarBatch(schema, new_cols, batch.row_mask,
                             batch._num_rows)

    def _dict_domain_call(self, udf, arg_batch: ColumnarBatch,
                          sel: np.ndarray, ctx):
        """Dictionary-domain evaluation lane (compressed execution): a
        deterministic UDF over a single dictionary-encoded string column
        evaluates once per DISTINCT dictionary value and maps over codes
        — O(|dictionary|) Python calls instead of O(rows), and the string
        values never materialize per row. This is how non-host-evaluable
        predicates (a UDF filter the expression layer can't turn into a
        dictionary lut itself) still pay per-distinct, not per-row.
        Returns the per-row result array, or None when the lane does not
        apply (the per-row path runs). Gated by spark.tpu.encoding.enabled
        so the decoded oracle keeps per-row behavior for differential
        testing."""
        from ..columnar.encoding import encoding_enabled

        if not encoding_enabled(ctx.conf):
            return None
        if not getattr(udf, "deterministic", True):
            return None
        if len(arg_batch.columns) != 1:
            return None
        c = arg_batch.columns[0]
        if not isinstance(c.dtype, StringType) or c.dictionary is None:
            return None
        values = c.dictionary.values
        if not values or len(values) >= max(len(sel), 1):
            return None  # domain not smaller than the rows: no win
        # the UDF lane's ONE intended pull: codes cross to host once per
        # batch (the per-row path pulls the decoded VALUES instead)
        codes = np.clip(np.asarray(c.data)[sel],  # tpulint: ignore[host-sync]
                        0, len(values) - 1)
        vm = None
        if c.validity is not None:
            vm = np.asarray(c.validity)[sel]  # tpulint: ignore[host-sync]
        # evaluate over the LIVE distinct codes only — the runtime
        # dictionary still covers values that exist solely in rows an
        # upstream filter dropped, and a partial UDF guarded by that
        # filter must never see them (per-row semantics)
        live_codes = np.unique(codes if vm is None else codes[vm])
        if live_codes.size:
            dvals = np.empty(live_codes.size, dtype=object)
            dvals[:] = [str(values[cd]) for cd in live_codes]
            per_value = np.asarray(  # tpulint: ignore[host-sync]
                self._call(udf, [dvals], live_codes.size))
            pos = np.clip(np.searchsorted(live_codes, codes), 0,
                          live_codes.size - 1)
            out = per_value[pos]
        else:
            out = np.empty(len(sel), dtype=object)
        if vm is not None and not vm.all():
            # the null lane evaluates once too (per-row semantics:
            # invalid rows hand the UDF a None)
            null_res = self._call(
                udf, [np.array([None], dtype=object)], 1)
            out = np.asarray(out, dtype=object).copy()  # tpulint: ignore[host-sync]
            out[~vm] = null_res[0] if len(null_res) else None
        ctx.metrics.add("udf.dict_domain_evals")
        ctx.metrics.add("udf.dict_domain_rows_saved",
                        len(sel) - live_codes.size)
        return out

    def _call(self, udf, args: list[np.ndarray], n: int):
        if n == 0:
            return np.zeros(0)
        if udf.vectorized:
            try:
                out = udf.fn(*args)
                out = np.asarray(out)
                if out.shape[:1] == (n,):
                    return out
            except Exception:
                pass
        # row-at-a-time fallback (the reference's non-arrow UDF path)
        return np.array([udf.fn(*[a[i] for a in args]) for i in range(n)],
                        dtype=object)

    def _to_column(self, dt, result, sel: np.ndarray, cap: int) -> Column:
        import jax.numpy as jnp

        result = np.asarray(result)
        nulls = np.array([v is None for v in result]) \
            if result.dtype == object else np.zeros(len(result), bool)
        from ..types import ArrayType, MapType, StructType

        if isinstance(dt, (ArrayType, MapType, StructType)):
            # nested result: dictionary-encode by canonical value.
            # np.asarray may have made equal-length list results 2-D —
            # iterate element-wise, never rely on the array's own rows
            from ..columnar.batch import encode_values

            rows = [None if (v is None) else
                    (list(v) if isinstance(v, np.ndarray) else v)
                    for v in (result.tolist()
                              if result.ndim > 1 else result)]
            values, codes = encode_values(rows)
            nulls = np.array([v is None for v in rows], bool)
            data = np.zeros(cap, np.int32)
            data[sel] = codes
            validity = np.zeros(cap, bool)
            validity[sel] = ~nulls
            empty = [] if isinstance(dt, ArrayType) else {}
            return Column(dt, jnp.asarray(data), jnp.asarray(validity),
                          StringDict(values or [empty]))
        if isinstance(dt, StringType):
            values: list[str] = []
            index: dict[str, int] = {}
            codes = np.zeros(len(result), np.int32)
            for i, v in enumerate(result):
                if v is None:
                    continue
                s = str(v)
                j = index.get(s)
                if j is None:
                    j = len(values)
                    values.append(s)
                    index[s] = j
                codes[i] = j
            data = np.zeros(cap, np.int32)
            data[sel] = codes
            validity = np.zeros(cap, bool)
            validity[sel] = ~nulls
            return Column(dt, jnp.asarray(data), jnp.asarray(validity),
                          StringDict(values or [""]))
        dd = dt.device_dtype
        clean = np.asarray(
            [0 if v is None else v for v in result]
            if result.dtype == object else result)
        data = np.zeros(cap, dd)
        data[sel] = clean.astype(dd)[: len(sel)]
        validity = None
        if nulls.any():
            vm = np.zeros(cap, bool)
            vm[sel] = ~nulls
            validity = jnp.asarray(vm)
        return Column(dt, jnp.asarray(data), validity, None)

    def simple_string(self):
        names = ", ".join(a.child.fname for a in self.udf_aliases)
        return f"PythonEval[{names}]"


class StatefulMapExec(PhysicalPlan):
    """Batch-mode applyInPandasWithState: one pass, empty initial state
    (streaming/query.py drives the incremental version)."""

    child_fields = ("child",)

    def __init__(self, node, child: PhysicalPlan):
        self.node = node
        self.child = child

    @property
    def output(self):
        return self.node.out_attrs

    def execute(self, ctx: ExecContext):
        import pyarrow as pa

        from ..columnar.arrow import record_batch_to_columnar
        from ..streaming.stateful_map import run_stateful_map
        from ..types import to_arrow_type

        parts = self.child.execute(ctx)
        tabs = [b.to_arrow() for p in parts for b in p]
        if tabs:
            child_table = pa.concat_tables(tabs,
                                           promote_options="permissive")
        else:
            child_table = pa.schema(
                [(a.name, to_arrow_type(a.dtype))
                 for a in self.child.output]).empty_table()
        out_schema = pa.schema([(a.name, to_arrow_type(a.dtype))
                                for a in self.node.out_attrs])
        out, _state = run_stateful_map(self.node, child_table, None,
                                       out_schema)
        schema = attrs_schema(self.output)
        return [[record_batch_to_columnar(out, schema)]]
