"""Whole-stage kernel fusion: one XLA dispatch per batch per stage.

Role of the reference's WholeStageCodegen (sqlx/WholeStageCodegenExec.scala:673
doCodeGen + CollapseCodegenStages): Spark splices produce/consume Java code so
a stage's operators run as one loop; here the splice is a TRACE — the
filter/project pipeline body (physical/compile.trace_pipeline) is traced
inside the terminal operator's kernel (partial hash aggregate, hash-join
probe, limit mask) and `jax.jit` compiles the whole stage consume as ONE
program per (structure, input signature, capacity), cached in the
structurally-keyed GLOBAL_KERNEL_CACHE. XLA then performs the operator
fusion the reference hand-rolls.

`FuseStages` runs after stage-boundary insertion (exchanges are already
placed), so each rewrite stays inside one exchange-free chain:

  * ComputeExec(ComputeExec)              -> one ComputeExec (CollapseProject
    /CollapseCodegenStages analog; the substitution is shared with the
    planner's construction-time fusion)
  * HashAggregateExec[partial](ComputeExec) -> FusedAggregateExec
  * LimitExec(ComputeExec)                -> FusedLimitExec
  * HashJoinExec(left=ComputeExec)        -> probe pipeline spliced into the
    probe kernel (operators.HashJoinExec._fused_probe)

The unfused operator-at-a-time path stays intact behind
spark.tpu.fusion.enabled=false as the differential-testing oracle.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..config import (
    ENCODING_ENABLED, FUSION_DENSE_KEYS, FUSION_EXCHANGE, FUSION_MIN_ROWS,
    SQLConf,
)
from ..expr.expressions import Alias, AttributeReference, Expression
from ..types import (
    BooleanType, DateType, IntegralType, StringType, dict_encoded,
)
from ..columnar.batch import Column, ColumnarBatch, bucket_capacity
from ..obs.metrics import batch_cost_scope
from .aggregates import FUSABLE_OPS
from .compile import (
    GLOBAL_KERNEL_CACHE, bind_inputs, canonical_key, pipeline_columns,
    pipeline_host_pass, pipeline_signature, trace_pipeline,
)
from .operators import (
    ComputeExec, HashAggregateExec, HashJoinExec, LimitExec, PhysicalPlan,
    _SchemaOnly, attrs_schema, dense_range_stats,
)

__all__ = ["FusedAggregateExec", "FusedLimitExec", "ExchangeFusion",
           "fuse_stages", "collapse_computes", "merge_into_compute"]


def _jnp():
    import jax.numpy as jnp

    return jnp


# ---------------------------------------------------------------------------
# ComputeExec collapsing (shared with the planner's construction-time path)
# ---------------------------------------------------------------------------

def merge_into_compute(filters: Sequence[Expression],
                       outputs: Sequence[Expression],
                       child: ComputeExec) -> ComputeExec:
    """Fuse a filter/project layer into an existing ComputeExec child by
    substituting the child's output expressions (the CollapseCodegenStages
    analog; all expressions are deterministic and XLA CSEs duplicated
    subtrees, so inlining is always sound)."""
    from ..plan.optimizer import substitute_attrs

    m: dict[int, Expression] = {}
    for e in child.outputs:
        if isinstance(e, Alias):
            m[e.expr_id] = e.child
        elif isinstance(e, AttributeReference):
            m[e.expr_id] = e
    new_filters = [substitute_attrs(f, m) for f in filters]
    new_outputs: list[Expression] = []
    for o in outputs:
        if isinstance(o, Alias):
            new_outputs.append(
                Alias(substitute_attrs(o.child, m), o.name, o.expr_id))
            continue
        sub = m.get(o.expr_id)
        if sub is None or (isinstance(sub, AttributeReference)
                           and sub.expr_id == o.expr_id):
            new_outputs.append(o)
        else:
            new_outputs.append(Alias(sub, o.name, o.expr_id))
    return ComputeExec(child.filters + new_filters, new_outputs, child.child)


def collapse_computes(plan: PhysicalPlan) -> PhysicalPlan:
    """Collapse adjacent ComputeExec nodes anywhere in the physical tree —
    a ComputeExec over a ComputeExec would launch two kernels per batch."""

    def rule(node):
        if isinstance(node, ComputeExec) and isinstance(node.child,
                                                        ComputeExec):
            return merge_into_compute(node.filters, node.outputs, node.child)
        return node

    return plan.transform_up(rule)


# ---------------------------------------------------------------------------
# Shared fused-kernel plumbing
# ---------------------------------------------------------------------------

def _pipe_attrs(outputs: Sequence[Expression]) -> list[AttributeReference]:
    return [o.to_attribute() if isinstance(o, Alias) else o for o in outputs]


def _compute_nontrivial(c: ComputeExec) -> bool:
    """A pure column reorder/prune launches no kernel — nothing to fuse."""
    return bool(c.filters) or any(not isinstance(o, AttributeReference)
                                  for o in c.outputs)


# ---------------------------------------------------------------------------
# FusedAggregateExec
# ---------------------------------------------------------------------------

class FusedAggregateExec(HashAggregateExec):
    """Partial hash aggregate with its feeding filter/project pipeline
    traced into the aggregation kernel: per input batch, ONE jitted program
    filters, projects, and partially aggregates (dense-range scatter,
    sorted-segment, or whole-tile reduce). Per-batch partials then merge
    with the associative final-mode ops — dispatches across batches and
    partitions pipeline asynchronously with no host sync in between (the
    dense-range range decision is memoized per column identity)."""

    child_fields = ("child",)

    def __init__(self, grouping, specs, filters, outputs, child):
        super().__init__(grouping, specs, "partial", child)
        self.filters = list(filters)
        self.pipe_outputs = list(outputs)
        self.pipe_attrs = _pipe_attrs(self.pipe_outputs)
        self._unfused_cache = None
        id_to_pos = bind_inputs(child.output)
        self._struct_key = (
            tuple(canonical_key(f, id_to_pos) for f in self.filters),
            tuple(canonical_key(o, id_to_pos) for o in self.pipe_outputs),
        )

    def graph_name(self) -> str:
        # the plan graph groups by operator role (the reference renders the
        # aggregate node inside its WholeStageCodegen cluster)
        return "HashAggregateExec"

    def fused_members(self) -> list:
        """The FuseStages mapping, inverted: constituent operators whose
        work rides this node's single dispatch per batch (obs/ EXPLAIN
        ANALYZE re-attributes the fused launch to these)."""
        from ..obs.metrics import pipeline_member_names

        return pipeline_member_names(self.filters, self.pipe_outputs) + [
            "HashAggregate[partial](keys=[%s])"
            % ", ".join(a.name for a in self.grouping)]

    def execute(self, ctx) -> list:
        parts = self.child.execute(ctx)
        return ctx.par_map(
            lambda part: [self._fused_partition(part, ctx)], parts)

    # ------------------------------------------------------------------
    def _unfused(self):
        """Operator-at-a-time fallback for partitions under
        spark.tpu.fusion.minRows: the shared (structure-agnostic) agg
        kernels beat a fresh per-structure fused compile on small inputs."""
        if self._unfused_cache is None:
            from .compile import ExprPipeline

            pipe = ExprPipeline(self.child.output, self.filters,
                                self.pipe_outputs,
                                attrs_schema(self.pipe_attrs))
            inner = HashAggregateExec(self.grouping, self.specs, "partial",
                                      _SchemaOnly(self.pipe_attrs))
            self._unfused_cache = (pipe, inner)
        return self._unfused_cache

    def _fused_partition(self, part, ctx) -> ColumnarBatch:
        if not part:
            part = [ColumnarBatch.empty(attrs_schema(self.child.output))]
        if sum(b.capacity for b in part) < int(ctx.conf.get(FUSION_MIN_ROWS)):
            pipe, inner = self._unfused()
            return inner._aggregate_partition(
                [pipe.run(b) for b in part], ctx)
        partials = [self._fused_batch(b, ctx) for b in part]
        if len(partials) == 1:
            return partials[0]
        merger = HashAggregateExec(self.grouping, self.specs, "final",
                                   _SchemaOnly(self.output))
        return merger._aggregate_partition(partials, ctx)

    def _fused_batch(self, batch: ColumnarBatch, ctx) -> ColumnarBatch:
        import jax

        from ..columnar.batch import EMPTY_DICT

        jnp = _jnp()
        cap = batch.capacity
        input_attrs = self.child.output
        filters, outputs = self.filters, self.pipe_outputs
        hctx, host_outs, aux = pipeline_host_pass(input_attrs, filters,
                                                  outputs, batch)
        opos = {a.expr_id: i for i, a in enumerate(self.pipe_attrs)}
        vals = self._plan_values()
        ops = tuple(op for op, _, _ in vals)
        val_idx = tuple(opos[attr.expr_id] if attr is not None else -1
                        for _, attr, _ in vals)
        key_idx = tuple(opos[g.expr_id] for g in self.grouping)
        out_schema = attrs_schema(self.output)
        # string MIN/MAX reduces in RANK space inside the trace: the
        # rank lut (codes→lexicographic rank) and its inverse (winning
        # rank→code) ride as kernel aux inputs, so the whole aggregate
        # stays in the single fused dispatch (no unfused fallback)
        smm_idx = tuple(bi for bi, (op, attr, _p) in enumerate(vals)
                        if op in ("min", "max") and attr is not None
                        and dict_encoded(attr.dtype))
        smm_dicts = [host_outs[val_idx[bi]].sdict or EMPTY_DICT
                     for bi in smm_idx]
        rank_luts = [sd.device_ranks() for sd in smm_dicts]
        inv_luts = [sd.device_rank_to_code() for sd in smm_dicts]
        base_key = (self._struct_key, ops, val_idx, key_idx, cap,
                    smm_idx, tuple(int(r.shape[0]) for r in rank_luts),
                    pipeline_signature(batch), hctx.signature())
        datas = [c.data for c in batch.columns]
        valids = [c.validity for c in batch.columns]
        smm_pos = {bi: j for j, bi in enumerate(smm_idx)}

        def pipe_vals(out_datas, out_valids, mask, rluts):
            vd = []
            for bi, i in enumerate(val_idx):
                d = out_datas[i] if i >= 0 else mask
                if bi in smm_pos:
                    r = rluts[smm_pos[bi]]
                    d = jnp.take(r, jnp.clip(d.astype(jnp.int32), 0,
                                             r.shape[0] - 1))
                vd.append(d)
            vv = [out_valids[i] if i >= 0 else None for i in val_idx]
            return vd, vv

        def rank_to_code(bufs, iluts):
            """Map winning ranks of string min/max buffers back to codes
            (inside the trace; masked/empty groups clip harmlessly — their
            validity is already False)."""
            out = []
            for bi, (bd, bv) in enumerate(bufs):
                if bi in smm_pos:
                    inv = iluts[smm_pos[bi]]
                    bd = jnp.take(inv, jnp.clip(bd.astype(jnp.int32), 0,
                                                inv.shape[0] - 1))
                out.append((bd, bv))
            return out

        # ---- ungrouped -------------------------------------------------
        if not self.grouping:
            out_cap = 8

            def build_ungrouped():
                from ..ops import grouping as G

                def kernel(datas, valids, row_mask, aux, rluts, iluts):
                    out_datas, out_valids, mask = trace_pipeline(
                        input_attrs, filters, outputs, datas, valids,
                        row_mask, aux, cap)
                    vd, vv = pipe_vals(out_datas, out_valids, mask, rluts)
                    outs = G.apply_global_ops(ops, vd, vv, mask)
                    outs = rank_to_code(outs, iluts)
                    bufs_d, bufs_v = [], []
                    for d, v in outs:
                        bufs_d.append(jnp.zeros((out_cap,), dtype=d.dtype)
                                      .at[0].set(d))
                        bufs_v.append(None if v is None else
                                      jnp.zeros((out_cap,), dtype=bool)
                                      .at[0].set(v))
                    m = jnp.zeros((out_cap,), dtype=bool).at[0].set(True)
                    return bufs_d, bufs_v, m

                return jax.jit(kernel)

            kernel = GLOBAL_KERNEL_CACHE.get_or_build(
                ("fused_agg", "u") + base_key, build_ungrouped)
            with batch_cost_scope(batch):
                bufs_d, bufs_v, m = kernel(datas, valids, batch.row_mask,
                                           aux, rank_luts, inv_luts)
            cols = self._fused_cols(
                list(zip(bufs_d, bufs_v)), out_schema.fields, host_outs,
                val_idx, 0)
            return ColumnarBatch(out_schema, cols, m, num_rows=1)

        # ---- grouped: dense-range direct scatter -----------------------
        # dictionary-encoded single keys are ALWAYS dense candidates: the
        # int32 code domain is [0, len(dict)) with the span known from
        # the host pass's output dictionary — no range probe, no sync
        # (compressed execution; the dictionary decodes the output keys)
        dense = None
        key_dict = None
        if len(key_idx) == 1 and ctx.conf.get(FUSION_DENSE_KEYS) \
                and isinstance(self.pipe_attrs[key_idx[0]].dtype,
                               StringType):
            from ..columnar.encoding import encoding_enabled

            if encoding_enabled(ctx.conf):
                from ..columnar.batch import EMPTY_DICT as _ED

                sdk = host_outs[key_idx[0]].sdict or _ED
                if len(sdk) + 1 <= min(4 * cap, 1 << 23):
                    key_dict = sdk
                    dense = (0, bucket_capacity(len(sdk) + 1),
                             host_outs[key_idx[0]].validity is not None)
                    ctx.metrics.add("agg.dict_code_fast_path")
        if dense is None:
            dense = self._dense_decision(batch, key_idx, ctx)
        if dense is not None:
            kmin, out_cap, has_kv = dense
            kpos = key_idx[0]
            kf = out_schema.fields[0]
            kdt = kf.dataType.device_dtype

            def build_dense():
                from jax import lax

                from ..ops import grouping as G

                def kernel(datas, valids, row_mask, aux, kmin_s, rluts,
                           iluts):
                    out_datas, out_valids, mask = trace_pipeline(
                        input_attrs, filters, outputs, datas, valids,
                        row_mask, aux, cap)
                    key = out_datas[kpos].astype(jnp.int64)
                    kvalid = out_valids[kpos]
                    seg = (key - kmin_s).astype(jnp.int32)
                    if kvalid is not None:
                        seg = jnp.where(kvalid, seg, out_cap - 1)
                    seg = jnp.where(mask, seg, out_cap - 1)
                    present = jax.ops.segment_sum(
                        jnp.where(mask, 1, 0), seg, num_segments=out_cap)
                    if kvalid is not None:
                        null_rows = jnp.sum(
                            (mask & ~kvalid).astype(jnp.int64))
                    else:
                        null_rows = jnp.int64(0)
                    vd, vv = pipe_vals(out_datas, out_valids, mask, rluts)
                    bufs = G.apply_dense_ops(seg, out_cap, cap, ops, vd, vv,
                                             mask)
                    bufs = rank_to_code(bufs, iluts)
                    out_keys = (kmin_s +
                                lax.iota(jnp.int64, out_cap)).astype(kdt)
                    out_mask = (present > 0).at[out_cap - 1].set(
                        null_rows > 0)
                    key_validity = jnp.ones(out_cap, dtype=bool) \
                        .at[out_cap - 1].set(False)
                    return out_keys, key_validity, bufs, out_mask

                return jax.jit(kernel)

            kernel = GLOBAL_KERNEL_CACHE.get_or_build(
                ("fused_agg", "d", out_cap) + base_key, build_dense)
            with batch_cost_scope(batch):
                out_keys, key_validity, bufs, out_mask = kernel(
                    datas, valids, batch.row_mask, aux, jnp.int64(kmin),
                    rank_luts, inv_luts)
            ctx.metrics.add("agg.dense_fast_path")
            cols = [Column(kf.dataType, out_keys,
                           key_validity if has_kv else None, key_dict)]
            cols += self._fused_cols(bufs, out_schema.fields[1:], host_outs,
                                     val_idx, 0)
            return ColumnarBatch(out_schema, cols, out_mask, num_rows=None)

        # ---- grouped: sorted-segment -----------------------------------
        key_bool = tuple(isinstance(self.pipe_attrs[i].dtype, BooleanType)
                         for i in key_idx)

        def build_grouped():
            from ..ops import grouping as G

            def kernel(datas, valids, row_mask, aux, rluts, iluts):
                out_datas, out_valids, mask = trace_pipeline(
                    input_attrs, filters, outputs, datas, valids, row_mask,
                    aux, cap)
                key_eqs = []
                for i, is_bool in zip(key_idx, key_bool):
                    kd = out_datas[i]
                    if is_bool:
                        kd = kd.astype(jnp.int32)
                    key_eqs.append(kd)
                key_valids = [out_valids[i] for i in key_idx]
                layout = G.group_rows(key_eqs, key_valids, mask)
                out_keys = [
                    G.scatter_group_keys(layout, out_datas[i], out_valids[i])
                    for i in key_idx]
                vd, vv = pipe_vals(out_datas, out_valids, mask, rluts)
                bufs = G.apply_group_ops(layout, ops, vd, vv)
                bufs = rank_to_code(bufs, iluts)
                out_mask = G.group_output_mask(layout)
                return out_keys, bufs, out_mask

            return jax.jit(kernel)

        kernel = GLOBAL_KERNEL_CACHE.get_or_build(
            ("fused_agg", "g") + base_key, build_grouped)
        with batch_cost_scope(batch):
            out_keys, bufs, out_mask = kernel(datas, valids,
                                              batch.row_mask, aux,
                                              rank_luts, inv_luts)
        cols = []
        nk = len(key_idx)
        for (kd, kv), ki, f in zip(out_keys, key_idx,
                                   out_schema.fields[:nk]):
            sdict = host_outs[ki].sdict if dict_encoded(f.dataType) else None
            cols.append(Column(f.dataType, kd, kv, sdict))
        cols += self._fused_cols(bufs, out_schema.fields[nk:], host_outs,
                                 val_idx, nk)
        return ColumnarBatch(out_schema, cols, out_mask, num_rows=None)

    def _fused_cols(self, bufs, fields, host_outs, val_idx, key_count):
        """Finish buffer columns (dtype casts) and re-attach dictionaries of
        dict-encoded passthrough buffers (e.g. first(string): codes travel,
        the batch's dictionary decodes them)."""
        cols = []
        for bi, ((bd, bv), f) in enumerate(zip(bufs, fields)):
            col = self._finish_buffer(bi, bd, bv, f, {})
            if dict_encoded(f.dataType) and col.dictionary is None:
                vi = val_idx[bi]
                if vi >= 0 and host_outs[vi].sdict is not None:
                    col = Column(f.dataType, col.data, col.validity,
                                 host_outs[vi].sdict)
            cols.append(col)
        return cols

    def _dense_decision(self, batch: ColumnarBatch, key_idx, ctx):
        """(kmin, out_cap, key_has_validity) when the single grouping key is
        a pass-through integral input column whose value range (memoized per
        column identity — the satellite fix for the per-batch two-scalar
        host sync) fits a capacity bucket. The range is measured under the
        PRE-filter row mask: a superset of the post-filter range, so the
        dense table stays sound, merely (rarely) wider."""
        if len(key_idx) != 1:
            return None
        if not ctx.conf.get(FUSION_DENSE_KEYS):
            return None
        kexpr = self.pipe_outputs[key_idx[0]]
        if not isinstance(kexpr, AttributeReference):
            return None
        in_pos = None
        for i, a in enumerate(self.child.output):
            if a.expr_id == kexpr.expr_id:
                in_pos = i
                break
        if in_pos is None:
            return None
        kc = batch.columns[in_pos]
        if not isinstance(kc.dtype, (IntegralType, DateType)):
            return None
        cap = batch.capacity
        kmin, kmax, any_live = dense_range_stats(kc, batch.row_mask, cap)
        if not any_live:
            return None
        span = kmax - kmin + 1
        if span + 1 > min(4 * cap, 1 << 23):
            return None  # sparse keys — sort path handles it
        return kmin, bucket_capacity(span + 1), kc.validity is not None

    def simple_string(self):
        g = ", ".join(a.name for a in self.grouping)
        fns = ", ".join(type(s.func).__name__ for s in self.specs)
        f = " AND ".join(x.simple_string() for x in self.filters)
        s = f"FusedHashAggregate[partial](keys=[{g}], fns=[{fns}])"
        if f:
            s += f" WHERE {f}"
        return s


# ---------------------------------------------------------------------------
# FusedLimitExec
# ---------------------------------------------------------------------------

class FusedLimitExec(LimitExec):
    """Limit with its feeding filter/project pipeline traced into the limit
    kernel: one program per partition computes the pipeline, ranks live rows
    (cumsum), and masks past-limit rows."""

    child_fields = ("child",)

    def __init__(self, n, filters, outputs, child, offset: int = 0,
                 is_global: bool = False):
        super().__init__(n, child, offset=offset, is_global=is_global)
        self.filters = list(filters)
        self.pipe_outputs = list(outputs)
        self.pipe_attrs = _pipe_attrs(self.pipe_outputs)
        self._unfused_cache = None
        id_to_pos = bind_inputs(child.output)
        self._struct_key = (
            tuple(canonical_key(f, id_to_pos) for f in self.filters),
            tuple(canonical_key(o, id_to_pos) for o in self.pipe_outputs),
        )

    @property
    def output(self):
        return self.pipe_attrs

    def graph_name(self) -> str:
        return "LimitExec"

    def fused_members(self) -> list:
        """FuseStages mapping for obs/ dispatch re-attribution."""
        from ..obs.metrics import pipeline_member_names

        return pipeline_member_names(self.filters, self.pipe_outputs) + [
            f"Limit[n={self.n}]"]

    def execute(self, ctx) -> list:
        parts = self.child.execute(ctx)
        return ctx.par_map(lambda part: self._fused_partition(part, ctx),
                           parts)

    def _unfused(self):
        """Operator-at-a-time fallback under spark.tpu.fusion.minRows."""
        if self._unfused_cache is None:
            from .compile import ExprPipeline

            pipe = ExprPipeline(self.child.output, self.filters,
                                self.pipe_outputs,
                                attrs_schema(self.pipe_attrs))
            inner = LimitExec(self.n, _SchemaOnly(self.pipe_attrs),
                              offset=self.offset, is_global=self.is_global)
            self._unfused_cache = (pipe, inner)
        return self._unfused_cache

    def _fused_partition(self, part, ctx) -> list:
        import jax

        from ..columnar.ops import concat_batches

        jnp = _jnp()
        if not part:
            return []
        if sum(b.capacity for b in part) < \
                int(ctx.conf.get(FUSION_MIN_ROWS)):  # tpulint: ignore[host-sync]
            pipe, inner = self._unfused()
            return inner._limit_partition([pipe.run(b) for b in part], ctx)
        batch = concat_batches(part, attrs_schema(self.child.output))
        cap = batch.capacity
        input_attrs = self.child.output
        filters, outputs = self.filters, self.pipe_outputs
        hctx, host_outs, aux = pipeline_host_pass(input_attrs, filters,
                                                  outputs, batch)
        key = ("fused_limit", self._struct_key, cap, self.n, self.offset,
               pipeline_signature(batch), hctx.signature())

        def build():
            def kernel(datas, valids, row_mask, aux):
                out_datas, out_valids, mask = trace_pipeline(
                    input_attrs, filters, outputs, datas, valids, row_mask,
                    aux, cap)
                rank = jnp.cumsum(mask.astype(jnp.int64))
                keep = mask & (rank > self.offset) & \
                    (rank <= self.offset + self.n)
                return out_datas, out_valids, keep

            return jax.jit(kernel)

        kernel = GLOBAL_KERNEL_CACHE.get_or_build(key, build)
        with batch_cost_scope(batch):
            out_datas, out_valids, keep = kernel(
                [c.data for c in batch.columns],
                [c.validity for c in batch.columns], batch.row_mask, aux)
        schema = attrs_schema(self.output)
        cols = pipeline_columns(schema.fields, host_outs, out_datas,
                                out_valids)
        limited = ColumnarBatch(schema, cols, keep, num_rows=None)
        if not self.is_global and self.n * 4 <= cap:
            from ..columnar.ops import compact_batch

            limited = compact_batch(limited)
        return [limited]

    def simple_string(self):
        o = ", ".join(x.simple_string() for x in self.pipe_outputs)
        f = " AND ".join(x.simple_string() for x in self.filters)
        s = f"FusedLimit[n={self.n}]({o})"
        if f:
            s += f" WHERE {f}"
        return s


# ---------------------------------------------------------------------------
# ExchangeFusion: shuffle writes consume straight from the fused stage
# ---------------------------------------------------------------------------

class ExchangeFusion:
    """The map side of a shuffle exchange fused with its producing
    pipeline: per input batch, ONE jitted program filters, projects,
    computes the partition id of every live row (hash / range /
    round-robin), groups rows by pid with `lax.sort`, and gathers the
    pipeline OUTPUT columns into pid order — the shuffle write
    (exec/shuffle.shuffle_fused) slices the grouped host columns straight
    into the reduce buffers. No intermediate materialized batch and no
    separate partition-id dispatch: <=1 XLA dispatch per map batch (the
    round-robin running offset stays an int32 kernel argument, so the
    cache key is position-independent)."""

    def __init__(self, filters: Sequence[Expression],
                 outputs: Sequence[Expression], input_attrs):
        self.filters = list(filters)
        self.pipe_outputs = list(outputs)
        self.pipe_attrs = _pipe_attrs(self.pipe_outputs)
        self.input_attrs = list(input_attrs)
        self._pipe_cache = None
        id_to_pos = bind_inputs(self.input_attrs)
        self._struct_key = (
            tuple(canonical_key(f, id_to_pos) for f in self.filters),
            tuple(canonical_key(o, id_to_pos) for o in self.pipe_outputs),
        )
        # partitioning binding (set by bind_*): mode + operands
        self._mode = None
        self._num_out = None
        self._key_idx = ()
        self._seed = 42
        self._descending = False
        self._bounds_host = None
        self._bounds_dev = None
        self._range_pos = None
        # runtime join filter (physical/adaptive): build-side key domain
        # pruning probe rows inside the SAME fused kernel — the domain is
        # an aux operand (range bounds / per-batch dict-code LUT), never
        # a separate dispatch. rf_pruned accumulates the pruned-row count
        # that rides the counts transfer (no extra sync).
        self._rf = None
        self._rf_dev = None
        self.rf_pruned = 0

    # -- partitioning binding (one ExchangeFusion serves one execute) ------
    def bind_hash(self, key_positions, num_out: int, seed: int = 42):
        self._mode, self._num_out = "h", num_out
        self._key_idx, self._seed = tuple(key_positions), seed
        return self

    def bind_rr(self, num_out: int):
        self._mode, self._num_out = "rr", num_out
        return self

    def bind_runtime_filter(self, rf: dict):
        """Arm the runtime join filter. The cache key grows an element
        ONLY when armed, so filter-off runs keep byte-identical kernel
        keys (the launch-delta identity the obs gate proves); the range
        bounds stay kernel operands, so different domains reuse one
        compiled kernel."""
        import jax.numpy as jnp

        self._rf = dict(rf)
        if rf["kind"] == "range":
            self._rf_dev = jnp.asarray(  # tpulint: ignore[host-sync]
                np.asarray(  # tpulint: ignore[host-sync] host bounds
                    [rf["lo"], rf["hi"]], dtype=np.int64))
        return self

    def bind_range(self, key_position: int, bounds, descending: bool,
                   num_out: int):
        import jax.numpy as jnp

        self._mode, self._num_out = "rg", num_out
        self._range_pos = key_position
        self._descending = descending
        self._bounds_host = bounds
        # host sample bounds → device, once per exchange execute
        self._bounds_dev = jnp.asarray(np.asarray(bounds))  # tpulint: ignore[host-sync]
        return self

    # -- unfused fallback (spark.tpu.fusion.minRows gate) ------------------
    def _pipeline(self):
        if self._pipe_cache is None:
            from .compile import ExprPipeline

            self._pipe_cache = ExprPipeline(
                self.input_attrs, self.filters, self.pipe_outputs,
                attrs_schema(self.pipe_attrs))
        return self._pipe_cache

    def run_pipeline(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Materialize the pipeline only (mesh fallback + size gate)."""
        return self._pipeline().run(batch)

    def partition_unfused(self, batch: ColumnarBatch, start: int):
        """Shared operator-at-a-time kernels for undersized partitions:
        one pipeline dispatch + one shuffle-kind dispatch per batch."""
        from ..exec import shuffle as S

        b = self.run_pipeline(batch)
        if self._rf is not None:
            b = self._apply_rf_unfused(b)
        if self._mode == "h":
            return S.hash_partition_batch(b, self._key_idx, self._num_out,
                                          self._seed)
        if self._mode == "rr":
            return S.rr_partition_batch(b, self._num_out, start)
        return S.range_partition_batch(b, self._range_pos,
                                       self._bounds_host, self._descending,
                                       self._num_out, string_key=False)

    def _apply_rf_unfused(self, b: ColumnarBatch) -> ColumnarBatch:
        """Runtime join filter on the size-gated unfused path: one tiny
        mask-update dispatch (the fused path folds it into the map kernel
        instead). The pruned count rides the partition-kernel counts this
        path already materializes, except here we pull the scalar beside
        them — the path syncs per batch regardless."""
        b, drop = runtime_filter_batch(self._rf, self._rf_dev, b,
                                       self._rf["out_pos"])
        self.rf_pruned += drop
        return b

    # -- the fused kernel --------------------------------------------------
    def partition_batch(self, batch: ColumnarBatch, start: int):
        """One dispatch: (grouped host columns, per-partition counts)."""
        import jax

        jnp = _jnp()
        cap = batch.capacity
        num_out = self._num_out
        input_attrs = self.input_attrs
        filters, outputs = self.filters, self.pipe_outputs
        hctx, host_outs, aux = pipeline_host_pass(input_attrs, filters,
                                                  outputs, batch)
        key_idx = self._key_idx
        key_bool = tuple(isinstance(self.pipe_attrs[i].dtype, BooleanType)
                         for i in key_idx)
        # string partition keys: eq_keys computes inside the trace via
        # padded dictionary-hash aux luts (compressed execution — the
        # fused map dispatch ships codes, never decoded values)
        from ..columnar.batch import EMPTY_DICT as _ED

        dict_pos = {i: j for j, i in enumerate(
            i for i in key_idx
            if isinstance(self.pipe_attrs[i].dtype, StringType))}
        kluts = [(host_outs[i].sdict or _ED).device_hash_lut()
                 for i in dict_pos]
        mode, seed, descending = self._mode, self._seed, self._descending
        rpos = self._range_pos
        # runtime join filter operands (bind_runtime_filter): range
        # bounds ride as a device scalar pair; dict domains become a
        # per-batch bool LUT over the batch's OWN code space (host set
        # membership over StringDict values — no decode, no sync)
        rf = self._rf
        rf_kind = None if rf is None else rf["kind"]
        rf_pos = None if rf is None else rf["out_pos"]
        rf_arg = self._rf_dev
        if rf_kind == "dict":
            sd = host_outs[rf_pos].sdict
            if sd is None:
                rf_kind = rf_pos = rf_arg = None  # undecodable: unfiltered
            else:
                dom = rf["domain"]
                lut = np.fromiter((v in dom for v in sd.values),
                                  dtype=bool, count=len(sd.values))
                if lut.size == 0:
                    lut = np.zeros(1, dtype=bool)
                rf_arg = jnp.asarray(lut)
        key = ("fused_shuffle", mode, self._struct_key, cap, num_out,
               key_idx, seed, descending, rpos,
               None if self._bounds_dev is None
               else (str(self._bounds_dev.dtype), len(self._bounds_host)),
               pipeline_signature(batch), hctx.signature(),
               tuple(sorted(dict_pos)),
               tuple(int(l.shape[0])  # tpulint: ignore[host-sync]
                     for l in kluts))
        if rf_kind is not None:
            # appended ONLY when armed: filter-off cache keys stay
            # byte-identical (zero launch-delta with the layer enabled
            # on a filter-free plan)
            key = key + (("rf", rf_kind, rf_pos,
                          None if rf_kind != "dict"
                          # static shape, not a device scalar
                          else int(rf_arg.shape[0])),)  # tpulint: ignore[host-sync]

        def build():
            from ..ops.hashing import hash_columns, partition_ids
            from ..ops.partition import _group_by_pid

            def kernel(datas, valids, row_mask, aux, start_s, bounds,
                       kluts, rf_op):
                out_datas, out_valids, mask = trace_pipeline(
                    input_attrs, filters, outputs, datas, valids, row_mask,
                    aux, cap)
                rf_drop = None
                if rf_kind is not None:
                    kd = out_datas[rf_pos]
                    kv = out_valids[rf_pos]
                    if rf_kind == "range":
                        k64 = kd.astype(jnp.int64)
                        ok = (k64 >= rf_op[0]) & (k64 <= rf_op[1])
                    else:
                        codes = jnp.clip(kd.astype(jnp.int32), 0,
                                         rf_op.shape[0] - 1)
                        ok = jnp.take(rf_op, codes)
                    if kv is not None:
                        # null keys never match but never mis-route:
                        # keep them (conservative) — the join drops them
                        ok = ok | ~kv
                    rf_drop = jnp.sum(mask & ~ok)
                    mask = mask & ok
                if mode == "h":
                    eqs = []
                    for i, is_bool in zip(key_idx, key_bool):
                        kd = out_datas[i]
                        if is_bool:
                            kd = kd.astype(jnp.int32)
                        if i in dict_pos:
                            lut = kluts[dict_pos[i]]
                            kd = jnp.take(lut, jnp.clip(
                                kd.astype(jnp.int32), 0,
                                lut.shape[0] - 1))
                        eqs.append(kd)
                    kvs = [out_valids[i] for i in key_idx]
                    pids = partition_ids(
                        hash_columns(eqs, kvs, seed=seed), num_out)
                elif mode == "rr":
                    live_rank = jnp.cumsum(mask.astype(jnp.int32)) - 1
                    pids = ((live_rank + start_s) % num_out) \
                        .astype(jnp.int32)
                else:  # range over sampled bounds (numeric key domain)
                    keys64 = out_datas[rpos].astype(bounds.dtype)
                    pids = jnp.searchsorted(bounds, keys64, side="right") \
                        .astype(jnp.int32)
                    if descending:
                        pids = (num_out - 1) - pids
                pr = _group_by_pid(pids, mask, num_out)
                g_datas = [jnp.take(d, pr.perm) for d in out_datas]
                g_valids = [None if v is None else jnp.take(v, pr.perm)
                            for v in out_valids]
                counts = pr.counts
                if rf_drop is not None:
                    # the pruned-row count rides the counts transfer —
                    # one appended lane, not a second sync
                    counts = jnp.concatenate(
                        [counts, rf_drop.astype(counts.dtype)[None]])
                return g_datas, g_valids, counts

            return jax.jit(kernel)

        kernel = GLOBAL_KERNEL_CACHE.get_or_build(key, build)
        with batch_cost_scope(batch):
            g_datas, g_valids, counts = kernel(
                [c.data for c in batch.columns],
                [c.validity for c in batch.columns], batch.row_mask, aux,
                np.int32(start % num_out), self._bounds_dev, kluts,
                rf_arg if rf_kind is not None else None)
        fields = attrs_schema(self.pipe_attrs).fields
        gathered = []
        for i, f in enumerate(fields):
            sdict = host_outs[i].sdict if dict_encoded(f.dataType) else None
            # the shuffle write's ONE intended sync point: map output
            # lands in host buffers for IPC/reduce-buffer slicing
            gathered.append((
                np.asarray(g_datas[i]),  # tpulint: ignore[host-sync]
                None if g_valids[i] is None
                else np.asarray(g_valids[i]),  # tpulint: ignore[host-sync]
                sdict))
        counts = np.asarray(counts)  # tpulint: ignore[host-sync]
        if rf_kind is not None:
            # counts is already host-side numpy here — no extra sync
            self.rf_pruned += int(counts[-1])  # tpulint: ignore[host-sync]
            counts = counts[:-1]
        return gathered, counts


# ---------------------------------------------------------------------------
# FuseStages planner rule
# ---------------------------------------------------------------------------

def runtime_filter_batch(rf: dict, rf_dev, b: ColumnarBatch,
                         pos: int) -> tuple:
    """One mask-update dispatch applying a runtime join filter to a
    batch's key column `pos` (the shared kernel behind the size-gated
    unfused path AND the mesh pre-pass, where the filter cannot ride a
    fused map kernel). Null keys are kept conservatively — the join
    drops them. Returns (filtered batch, pruned-row count)."""
    import jax

    jnp = _jnp()
    col = b.columns[pos]
    if rf["kind"] == "dict":
        sd = col.dictionary
        if sd is None:
            return b, 0    # undecodable codes: pass through unfiltered
        dom = rf["domain"]
        lut = np.fromiter((v in dom for v in sd.values), dtype=bool,
                          count=len(sd.values))
        if lut.size == 0:
            lut = np.zeros(1, dtype=bool)
        op = jnp.asarray(lut)
    elif rf_dev is not None:
        op = rf_dev
    else:
        op = jnp.asarray(  # tpulint: ignore[host-sync]
            np.asarray(  # tpulint: ignore[host-sync] host bounds
                [rf["lo"], rf["hi"]], dtype=np.int64))
    kind = rf["kind"]
    key = ("rf_mask", kind, str(col.data.dtype),
           col.validity is not None, b.capacity,
           # static shape, not a device scalar
           None if kind != "dict" else int(op.shape[0]))  # tpulint: ignore[host-sync]

    def build():
        def kernel(kd, kv, mask, opnd):
            if kind == "range":
                k64 = kd.astype(jnp.int64)
                ok = (k64 >= opnd[0]) & (k64 <= opnd[1])
            else:
                codes = jnp.clip(kd.astype(jnp.int32), 0,
                                 opnd.shape[0] - 1)
                ok = jnp.take(opnd, codes)
            if kv is not None:
                ok = ok | ~kv
            new_mask = mask & ok
            return new_mask, jnp.sum(mask & ~ok)

        return jax.jit(kernel)

    kernel = GLOBAL_KERNEL_CACHE.get_or_build(key, build)
    new_mask, drop = kernel(col.data, col.validity, b.row_mask, op)
    return (ColumnarBatch(b.schema, b.columns, new_mask),
            int(drop))  # tpulint: ignore[host-sync]


def _aggregate_fusable(agg: HashAggregateExec, compute: ComputeExec) -> bool:
    if not _compute_nontrivial(compute):
        return False
    if not all(s.mergeable for s in agg.specs):
        return False
    out_ids = {a.expr_id for a in compute.output}
    if any(g.expr_id not in out_ids for g in agg.grouping):
        return False
    for op, attr, _param in agg._plan_values():
        if op not in FUSABLE_OPS:
            return False
        if attr is not None and attr.expr_id not in out_ids:
            return False
        # string min/max fuses too: the reduce runs in rank space with
        # the rank + inverse-rank luts as kernel aux inputs
    return True


def _exchange_fusable(exch, compute: ComputeExec, conf: SQLConf) -> bool:
    from .partitioning import (
        HashPartitioning, RangePartitioning, UnknownPartitioning,
    )

    if not conf.get(FUSION_EXCHANGE):
        return False
    if not _compute_nontrivial(compute):
        return False
    p = exch.partitioning
    out_by_id = {a.expr_id: a for a in compute.output}
    if isinstance(p, HashPartitioning):
        for e in p.exprs:
            if not isinstance(e, AttributeReference):
                return False
            a = out_by_id.get(e.expr_id)
            if a is None:
                return False
            if isinstance(a.dtype, StringType):
                # string eq-keys compute inside the trace via padded
                # dictionary-hash aux luts (compressed execution)
                if not conf.get(ENCODING_ENABLED):
                    return False
            elif dict_encoded(a.dtype):
                # nested types: raw codes are not a cross-dictionary
                # equality domain — unfused path handles them
                return False
        return True
    if isinstance(p, UnknownPartitioning):
        return True  # round-robin: no keys; offset is a kernel argument
    if isinstance(p, RangePartitioning):
        if len(p.orders) != 1:
            return False
        oc = p.orders[0].child
        if not isinstance(oc, AttributeReference):
            return False
        a = out_by_id.get(oc.expr_id)
        if a is None or isinstance(a.dtype, StringType) \
                or dict_encoded(a.dtype):
            # string pids ride a host rank→pid lut per dictionary
            return False
        # computed sort keys fuse too: bounds sample the POST-pipeline
        # key column (physical/exchange._range_shuffle materializes the
        # pipeline for the sampled batches only)
        return True
    return False  # SinglePartition gathers without kernels


def _probe_fusable(join: HashJoinExec, compute: ComputeExec,
                   conf: SQLConf) -> bool:
    if not _compute_nontrivial(compute):
        return False
    out_by_id = {a.expr_id: a for a in compute.output}
    for k in join.left_keys:
        a = out_by_id.get(k.expr_id)
        if a is None:
            return False
        if isinstance(a.dtype, StringType):
            # string probe keys fuse: eq_keys (codes → value hashes)
            # computes inside the probe kernel via the padded
            # dictionary-hash lut aux input (compressed execution)
            if not conf.get(ENCODING_ENABLED):
                return False
        elif dict_encoded(a.dtype):
            # nested types: codes are not a cross-dictionary eq domain
            return False
    return True


def fuse_stages(plan: PhysicalPlan, conf: SQLConf) -> PhysicalPlan:
    """Collapse each maximal exchange-free chain of fusable operators into
    whole-stage fused operators (run by the planner after EnsureRequirements
    — the CollapseCodegenStages slot in the reference's preparation rules)."""
    plan = collapse_computes(plan)

    def rule(node):
        if isinstance(node, HashAggregateExec) \
                and not isinstance(node, FusedAggregateExec) \
                and node.mode == "partial" \
                and isinstance(node.child, ComputeExec) \
                and _aggregate_fusable(node, node.child):
            c = node.child
            return FusedAggregateExec(node.grouping, node.specs, c.filters,
                                      c.outputs, c.child)
        if isinstance(node, LimitExec) \
                and not isinstance(node, FusedLimitExec) \
                and isinstance(node.child, ComputeExec) \
                and _compute_nontrivial(node.child):
            c = node.child
            return FusedLimitExec(node.n, c.filters, c.outputs, c.child,
                                  offset=node.offset,
                                  is_global=node.is_global)
        if isinstance(node, HashJoinExec) and node.probe_fusion is None \
                and isinstance(node.left, ComputeExec) \
                and _probe_fusable(node, node.left, conf):
            c = node.left
            node.probe_fusion = (list(c.filters), list(c.outputs))
            node.probe_attrs = list(c.output)
            node.left = c.child
            node._probe_pipe_cache = None
            return node
        from .exchange import ShuffleExchangeExec

        if isinstance(node, ShuffleExchangeExec) \
                and node.pipe_fusion is None \
                and isinstance(node.child, ComputeExec) \
                and _exchange_fusable(node, node.child, conf):
            # the exchange terminal consumes straight from the fused
            # stage: the partition-id kernel traces into the pipeline
            # program (ExchangeFusion) and shuffle writes read its
            # pid-grouped output — no materialized intermediate batch
            c = node.child
            node.pipe_fusion = (list(c.filters), list(c.outputs))
            node.pipe_attrs = list(c.output)
            node.child = c.child
            return node
        return node

    return plan.transform_up(rule)
