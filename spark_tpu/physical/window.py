"""WindowExec.

Role of the reference's sqlx/window/WindowExec.scala — but frame evaluation
is the sort/segment kernel in ops/window.py (no row-at-a-time frame
iterators), and results scatter back to the original row order so the
operator is order-preserving like the reference's."""

from __future__ import annotations

from typing import Sequence

from ..columnar.batch import Column, ColumnarBatch
from ..columnar.ops import concat_batches
from ..errors import UnsupportedOperationError
from ..exec.context import ExecContext
from ..expr.expressions import (
    AggregateFunction, Alias, AttributeReference, Average, Count, Literal,
    Max, Min, SortOrder, Sum,
)
from ..expr.window import (
    CumeDist, DenseRank, FirstValue, Lag, LastValue, Lead, NthValue, NTile,
    PercentRank, Rank, RowNumber, WindowExpression,
)
from ..types import DecimalType, StringType, float64, int32, int64
from .compile import GLOBAL_KERNEL_CACHE
from .operators import PhysicalPlan, attrs_schema
from .partitioning import AllTuples, ClusteredDistribution, UnspecifiedDistribution


def _jnp():
    import jax.numpy as jnp

    return jnp


class WindowExec(PhysicalPlan):
    """window_exprs: Alias(WindowExpression) whose function args, partition
    keys, and order keys are bound to child attributes by the planner."""

    child_fields = ("child",)

    def __init__(self, window_exprs: Sequence[Alias],
                 partition_keys: Sequence[AttributeReference],
                 order_keys: Sequence[SortOrder], child: PhysicalPlan):
        self.window_exprs = list(window_exprs)
        self.partition_keys = list(partition_keys)
        self.order_keys = list(order_keys)
        self.child = child

    @property
    def output(self):
        return self.child.output + [a.to_attribute() for a in self.window_exprs]

    def required_child_distribution(self):
        if not self.partition_keys:
            return [AllTuples()]
        return [ClusteredDistribution(list(self.partition_keys))]

    def output_partitioning(self):
        return self.child.output_partitioning()

    def _plans(self):
        """(kind, params) per window expr — static kernel config."""
        out = []
        has_order = bool(self.order_keys)
        for al in self.window_exprs:
            w: WindowExpression = al.child
            f = w.function
            if isinstance(f, RowNumber):
                out.append(("row_number", None, None))
            elif isinstance(f, Rank):
                out.append(("rank", None, None))
            elif isinstance(f, DenseRank):
                out.append(("dense_rank", None, None))
            elif isinstance(f, PercentRank):
                out.append(("percent_rank", None, None))
            elif isinstance(f, CumeDist):
                out.append(("cume_dist", None, None))
            elif isinstance(f, NTile):
                out.append(("ntile", f.n, None))
            elif isinstance(f, (Lag, Lead)):
                off = f.offset if isinstance(f, Lag) else -f.offset
                out.append(("shift", off, f.child))
            elif isinstance(f, (NthValue, FirstValue)):
                # default frame = running-to-current-peers; explicit
                # UNBOUNDED..UNBOUNDED = whole partition; anything else
                # is unsupported rather than silently wrong
                frame = w.frame
                if frame is None:
                    scope = "peers"
                elif (frame[1], frame[2]) == (None, None):
                    scope = "partition"
                else:
                    raise UnsupportedOperationError(
                        f"{type(f).__name__} over a bounded frame is "
                        "not supported yet")
                if isinstance(f, NthValue):
                    out.append(("nth_value", (f.n, scope), f.child))
                elif isinstance(f, LastValue):  # FirstValue subclass
                    out.append(("last_value", scope, f.child))
                else:
                    out.append(("first_value", scope, f.child))
            elif isinstance(f, (Sum, Count, Min, Max, Average)):
                kind = {Sum: "sum", Count: "count", Min: "min", Max: "max",
                        Average: "avg"}[type(f)]
                frame = w.frame
                if frame is not None:
                    ftype, lo, hi = frame
                    if (lo, hi) == (None, None):
                        out.append((f"agg_unbounded_{kind}", None, f.child))
                    elif kind not in ("sum", "count", "avg", "min", "max"):
                        raise UnsupportedOperationError(
                            f"{kind} over a bounded frame is not "
                            "supported yet")
                    elif ftype == "vrange":
                        if len(self.order_keys) != 1:
                            raise UnsupportedOperationError(
                                "RANGE value frames need exactly one "
                                "ORDER BY key")
                        out.append((f"agg_vrange_{kind}", (lo, hi), f.child))
                    else:
                        out.append((f"agg_rows_{kind}", (lo, hi), f.child))
                else:
                    mode = "running" if has_order else "unbounded"
                    out.append((f"agg_{mode}_{kind}", None, f.child))
            else:
                raise UnsupportedOperationError(
                    f"window function {type(f).__name__}")
        return out

    def execute(self, ctx: ExecContext):
        from .adaptive import coalesce_after_exchange

        parts = self.child.execute(ctx)
        parts = coalesce_after_exchange(self.child, parts, ctx,
                                        self.child.output)
        return [[self._run_partition(p)] if p else [] for p in parts]

    def _run_partition(self, part) -> ColumnarBatch:
        import jax

        from ..ops import window as W
        from ..ops.sorting import SortKeySpec

        jnp = _jnp()
        batch = concat_batches(part, attrs_schema(self.child.output))
        pos = {a.expr_id: i for i, a in enumerate(self.child.output)}
        cap = batch.capacity

        pcols = [batch.columns[pos[k.expr_id]] for k in self.partition_keys]
        ocols = [batch.columns[pos[o.child.expr_id]] for o in self.order_keys]
        ospecs = [SortKeySpec(o.ascending, o.nulls_first)
                  for o in self.order_keys]

        plans = self._plans()
        vcols = []
        for kind, param, arg in plans:
            if arg is not None:
                vcols.append(batch.columns[pos[arg.expr_id]])
            elif kind.endswith("_count"):
                # count(*) over a window: count frame rows — an all-valid
                # ones column makes the count kernels row-counting
                vcols.append("ones")
            else:
                vcols.append(None)

        # value-RANGE frames: band the single integral order key per
        # partition (host syncs min/max; band is baked into the kernel)
        kmin = band = 0
        if any(k.startswith("agg_vrange_") for k, _, _ in plans):
            import jax
            from ..types import DateType, IntegralType

            oc = ocols[0]
            if not isinstance(oc.dtype, (IntegralType, DateType)) or \
                    oc.validity is not None:
                raise UnsupportedOperationError(
                    "RANGE value frames need a non-null integral/date "
                    "ORDER BY key")
            if not ospecs[0].ascending:
                raise UnsupportedOperationError(
                    "RANGE value frames need an ascending ORDER BY")
            jnp2 = _jnp()
            k64 = oc.data.astype(jnp2.int64)
            big = jnp2.iinfo(jnp2.int64).max
            small = jnp2.iinfo(jnp2.int64).min
            kmin = int(jnp2.min(jnp2.where(batch.row_mask, k64, big)))
            kmax = int(jnp2.max(jnp2.where(batch.row_mask, k64, small)))
            max_off = max(abs(p[0] or 0) if p else 0 for _, p, _ in plans
                          if p) + max(abs(p[1] or 0) if p else 0
                                      for _, p, _ in plans if p) + 1
            span = max(kmax - kmin + 1 + 2 * max_off, 8)
            band = 1
            while band < span:
                band <<= 1
            if cap * band >= (1 << 62):
                raise UnsupportedOperationError(
                    "RANGE frame key span too large to band")

        key = ("window", cap, kmin, band,
               tuple((str(c.eq_keys().dtype), c.validity is not None)
                     for c in pcols),
               tuple((str(c.sort_keys().dtype), c.validity is not None,
                      s.ascending, s.nulls_first)
                     for c, s in zip(ocols, ospecs)),
               tuple((k, p, "ones" if isinstance(v, str) else
                      None if v is None else
                      (str(v.data.dtype), v.validity is not None))
                     for (k, p, _), v in zip(plans, vcols)))

        def build():
            def kernel(pkeys, pvalids, okeys, ovalids, vdatas, vvalids,
                       row_mask):
                lo = W.build_layout(pkeys, pvalids, okeys, ovalids, ospecs,
                                    row_mask)
                outs = []
                for (kind, param, _), vd, vv in zip(plans, vdatas, vvalids):
                    if kind == "row_number":
                        sv, svalid = W.w_row_number(lo), None
                    elif kind == "rank":
                        sv, svalid = W.w_rank(lo), None
                    elif kind == "dense_rank":
                        sv, svalid = W.w_dense_rank(lo), None
                    elif kind == "percent_rank":
                        sv, svalid = W.w_percent_rank(lo), None
                    elif kind == "cume_dist":
                        sv, svalid = W.w_cume_dist(lo), None
                    elif kind == "ntile":
                        sv, svalid = W.w_ntile(lo, param), None
                    elif kind == "shift":
                        sv, svalid = W.w_shift(lo, vd, vv, param)
                    elif kind == "first_value":
                        sv, svalid = W.w_first_value(lo, vd, vv)
                    elif kind == "last_value":
                        sv, svalid = W.w_last_value(lo, vd, vv,
                                                    whole=param ==
                                                    "partition")
                    elif kind == "nth_value":
                        sv, svalid = W.w_nth_value(
                            lo, vd, vv, param[0],
                            whole=param[1] == "partition")
                    elif kind.startswith("agg_vrange_"):
                        sv, svalid = W.w_agg_value_range(
                            lo, okeys[0], vd, vv, kind.split("_")[-1],
                            param[0], param[1], kmin, band)
                    elif kind.startswith("agg_rows_"):
                        sv, svalid = W.w_agg_rows(lo, vd, vv,
                                                  kind.split("_")[-1],
                                                  param[0], param[1])
                    elif kind.startswith("agg_running_"):
                        sv, svalid = W.w_agg_running(lo, vd, vv,
                                                     kind.split("_")[-1])
                    elif kind.startswith("agg_unbounded_"):
                        sv, svalid = W.w_agg_unbounded(lo, vd, vv,
                                                       kind.split("_")[-1])
                    else:
                        raise ValueError(kind)
                    outs.append(W.scatter_back(lo, sv, svalid))
                return outs

            return jax.jit(kernel)

        kernel = GLOBAL_KERNEL_CACHE.get_or_build(key, build)
        ones = jnp.ones((cap,), jnp.int32)
        outs = kernel([c.eq_keys() for c in pcols],
                      [c.validity for c in pcols],
                      [c.sort_keys() for c in ocols],
                      [c.validity for c in ocols],
                      [ones if isinstance(v, str) else
                       None if v is None else v.data for v in vcols],
                      [None if v is None or isinstance(v, str)
                       else v.validity for v in vcols],
                      batch.row_mask)

        schema = attrs_schema(self.output)
        new_cols = list(batch.columns)
        for (d, v), al in zip(outs, self.window_exprs):
            dt = al.child.dtype
            fn = al.child.function
            if isinstance(dt, DecimalType) and isinstance(fn, Average) \
                    and isinstance(getattr(fn.child, "dtype", None),
                                   DecimalType):
                # the kernel's avg is sum/count in the INPUT scale; the
                # result decimal carries a wider scale (reference:
                # Average resultType = DecimalType(p+4, s+4)); round
                # half-to-even like the cast path, don't truncate
                d = jnp.rint(d * (10.0 ** (dt.scale - fn.child.dtype.scale)))
            want = dt.device_dtype
            if str(d.dtype) != str(want):
                d = d.astype(want)
            sdict = None
            if isinstance(dt, StringType):
                # shift over strings keeps the source dictionary
                arg = al.child.function.child
                sdict = batch.columns[pos[arg.expr_id]].dictionary
            new_cols.append(Column(dt, d, v, sdict))
        return ColumnarBatch(schema, new_cols, batch.row_mask,
                             batch._num_rows)

    def simple_string(self):
        fns = ", ".join(a.child.function.sql_name()
                        for a in self.window_exprs)
        return f"Window[{fns}]"
