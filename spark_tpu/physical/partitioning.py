"""Physical partitioning/distribution model.

Role of the reference's Distribution/Partitioning contract
(sqlcat/plans/physical/partitioning.scala:39 Distribution, :318
HashPartitioning, :720 RangePartitioning) consumed by EnsureRequirements
(sqlx/exchange/EnsureRequirements.scala:51).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

from ..expr.expressions import AttributeReference, Expression, SortOrder


# --- distributions (requirements) ------------------------------------------

class Distribution:
    pass


@dataclass(frozen=True)
class UnspecifiedDistribution(Distribution):
    pass


@dataclass(frozen=True)
class AllTuples(Distribution):
    """Everything in a single partition."""


class ClusteredDistribution(Distribution):
    def __init__(self, exprs: Sequence[Expression]):
        self.exprs = list(exprs)


class OrderedDistribution(Distribution):
    def __init__(self, orders: Sequence[SortOrder]):
        self.orders = list(orders)


@dataclass(frozen=True)
class BroadcastDistribution(Distribution):
    pass


# --- partitionings (what an operator produces) ------------------------------

class Partitioning:
    num_partitions: int = 1

    def satisfies(self, d: Distribution) -> bool:
        if isinstance(d, UnspecifiedDistribution):
            return True
        if isinstance(d, AllTuples):
            return self.num_partitions == 1
        return False


@dataclass
class UnknownPartitioning(Partitioning):
    num_partitions: int = 1


@dataclass
class SinglePartition(Partitioning):
    num_partitions: int = 1

    def satisfies(self, d: Distribution) -> bool:
        if isinstance(d, BroadcastDistribution):
            return False
        return True  # one partition satisfies any non-broadcast distribution


class HashPartitioning(Partitioning):
    def __init__(self, exprs: Sequence[Expression], num_partitions: int):
        self.exprs = list(exprs)
        self.num_partitions = num_partitions

    def satisfies(self, d: Distribution) -> bool:
        if isinstance(d, UnspecifiedDistribution):
            return True
        if isinstance(d, ClusteredDistribution):
            # our hash exprs must be a subset of the required clustering:
            # equal rows then land in the same partition
            return all(any(h.semantic_equals(c) for c in d.exprs)
                       for h in self.exprs) and len(self.exprs) > 0
        return False


class RangePartitioning(Partitioning):
    def __init__(self, orders: Sequence[SortOrder], num_partitions: int):
        self.orders = list(orders)
        self.num_partitions = num_partitions

    def satisfies(self, d: Distribution) -> bool:
        if isinstance(d, UnspecifiedDistribution):
            return True
        if isinstance(d, OrderedDistribution):
            if len(d.orders) > len(self.orders):
                return False
            return all(
                o.child.semantic_equals(m.child) and o.ascending == m.ascending
                for o, m in zip(d.orders, self.orders))
        if isinstance(d, ClusteredDistribution):
            return all(any(o.child.semantic_equals(c) for c in d.exprs)
                       for o in self.orders)
        return False


@dataclass
class BroadcastPartitioning(Partitioning):
    num_partitions: int = 1

    def satisfies(self, d: Distribution) -> bool:
        return isinstance(d, (BroadcastDistribution, UnspecifiedDistribution))
