"""Aggregate lowering: logical aggregate functions → buffer ops + final exprs.

Role of the reference's AggUtils/DeclarativeAggregate contract
(sqlx/aggregate/AggUtils.scala; sqlcat/expressions/aggregate/interfaces.scala:
initialValues/updateExpressions/mergeExpressions/evaluateExpression). Each
function lowers to primitive buffer ops the group kernel understands
(sum/count/min/max/first/sumsq); merge ops are the partial ops' associative
counterparts, so the same kernel serves map-side partial and reduce-side
final aggregation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..errors import UnsupportedOperationError
from ..expr.expressions import (
    AggregateFunction, Alias, AttributeReference, Average, Cast, Count,
    CollectSet, Divide, Expression, First, GreaterThan, If, Literal, Max, Min,
    Multiply, Sqrt, StddevPop, StddevSamp, Subtract, Sum, VariancePop,
    VarianceSamp, cast_if,
)
from ..types import (
    DataType, DecimalType, FractionalType, IntegralType, StringType,
    float64, int64,
)

# primitive ops the kernel implements
PARTIAL_TO_MERGE = {
    "sum": "sum", "count": "sum", "countstar": "sum",
    "min": "min", "max": "max", "first": "first", "sumsq": "sum",
    # bitwise reduces are associative — partials merge with themselves
    "bitand": "bitand", "bitor": "bitor", "bitxor": "bitxor",
}

# ops the whole-stage fused aggregation kernels implement
# (physical/fusion.py): everything associative the segment/scatter reduces
# handle inside one traced program. percentile/collect stay unfused — they
# need host-side list building or a gather-first plan.
FUSABLE_OPS = frozenset(PARTIAL_TO_MERGE)


def _buffer_dtype(op: str, in_dtype: DataType | None) -> DataType:
    if op in ("count", "countstar"):
        return int64
    if op in ("bitand", "bitor", "bitxor"):
        return int64
    if op == "sumsq":
        return float64
    if op == "sum":
        assert in_dtype is not None
        if isinstance(in_dtype, DecimalType):
            return DecimalType(DecimalType.MAX_PRECISION, in_dtype.scale)
        if isinstance(in_dtype, IntegralType):
            return int64
        return float64
    return in_dtype  # min/max/first preserve type


@dataclass
class AggSpec:
    """One aggregate function lowered to buffer columns + a finishing expr."""

    func: AggregateFunction
    input_expr: Expression | None          # argument (None for count(*))
    ops: list[str]                         # primitive op per buffer column
    buffer_attrs: list[AttributeReference]  # schema of partial output
    result_alias: Alias                    # final output (over buffer attrs)
    mergeable: bool = True                 # False → gather-then-one-pass
    param: float | None = None             # e.g. percentile q


def lower_aggregate_function(func: AggregateFunction, out_name: str,
                             out_id: int) -> AggSpec:
    child = func.child

    def battr(i: int, op: str) -> AttributeReference:
        dt = _buffer_dtype(op, child.dtype if child is not None else None)
        nullable = op not in ("count", "countstar")
        return AttributeReference(f"{out_name}#buf{i}", dt, nullable)

    if isinstance(func, Sum):
        b = battr(0, "sum")
        return AggSpec(func, child, ["sum"], [b],
                       Alias(cast_if(b, func.dtype), out_name, out_id))
    if isinstance(func, Count):
        if func.distinct:
            raise UnsupportedOperationError(
                "count(distinct) must be rewritten before lowering")
        op = "count" if child is not None else "countstar"
        b = battr(0, op)
        return AggSpec(func, child, [op], [b],
                       Alias(b, out_name, out_id))
    if isinstance(func, (Min, Max)):
        op = "min" if isinstance(func, Min) else "max"
        b = battr(0, op)
        return AggSpec(func, child, [op], [b], Alias(b, out_name, out_id))
    if isinstance(func, Average):
        bs = battr(0, "sum")
        bc = battr(1, "count")
        result = Divide(bs, bc)
        return AggSpec(func, child, ["sum", "count"], [bs, bc],
                       Alias(cast_if(result, func.dtype), out_name, out_id))
    from ..expr.expressions import BitAndAgg

    if isinstance(func, BitAndAgg):
        op = "bit" + func.kind
        b = battr(0, op)
        return AggSpec(func, child, [op], [b],
                       Alias(cast_if(b, func.dtype), out_name, out_id))
    if isinstance(func, First):
        b = battr(0, "first")
        return AggSpec(func, child, ["first"], [b], Alias(b, out_name, out_id))
    from ..expr.expressions import Percentile

    if isinstance(func, Percentile):
        b = AttributeReference(f"{out_name}#buf0", func.dtype, True)
        return AggSpec(func, child, ["percentile"], [b],
                       Alias(b, out_name, out_id), mergeable=False,
                       param=func.q)
    from ..expr.expressions import CollectList

    if isinstance(func, (CollectList, CollectSet)):
        b = AttributeReference(f"{out_name}#buf0", func.dtype, False)
        return AggSpec(func, child, ["collect"], [b],
                       Alias(b, out_name, out_id), mergeable=False,
                       param=1.0 if isinstance(func, CollectSet) else 0.0)
    if isinstance(func, (StddevSamp, StddevPop, VarianceSamp, VariancePop)):
        bs = battr(0, "sum")
        bq = battr(1, "sumsq")
        bc = battr(2, "count")
        n = cast_if(bc, float64)
        mean_sq = Divide(Multiply(cast_if(bs, float64), cast_if(bs, float64)), n)
        ddof = func.ddof
        denom = Subtract(n, Literal(float(ddof))) if ddof else n
        var = Divide(Subtract(bq, mean_sq), denom)
        var = If(GreaterThan(bc, Literal(ddof)), var, Literal(None, float64))
        result: Expression = var
        if isinstance(func, (StddevSamp, StddevPop)):
            result = Sqrt(var)
        return AggSpec(func, child, ["sum", "sumsq", "count"], [bs, bq, bc],
                       Alias(result, out_name, out_id))
    raise UnsupportedOperationError(
        f"aggregate {type(func).__name__} not supported yet")
