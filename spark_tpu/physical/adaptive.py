"""Adaptive execution: runtime partition coalescing.

Role of the reference's AQE partition coalescing
(sqlx/adaptive/CoalesceShufflePartitions.scala + AQEShuffleReadExec:41,
driven by MapOutputStatistics). Our exchanges execute eagerly and report
per-reducer row counts, so blocking consumers coalesce undersized adjacent
reducer outputs before processing — hash clustering and range ordering are
preserved because only ADJACENT partitions merge. Joins coordinate one
merge plan across both sides (the reference does the same via shared
partition specs). Skew splitting (OptimizeSkewedJoin.scala:57) lives in
split_skewed_join_inputs below.
"""

from __future__ import annotations

from typing import Sequence

from ..config import (
    ADAPTIVE_ENABLED, ADVISORY_PARTITION_BYTES, COALESCE_PARTITIONS_ENABLED,
)
from ..exec.context import ExecContext


def _partition_rows(part) -> int:
    return sum(b.num_rows() for b in part)


def _row_width(schema_attrs) -> int:
    w = 0
    for a in schema_attrs:
        w += max(int(a.dtype.device_dtype.itemsize), 4)
    return max(w, 8)


def plan_merge_groups(sizes: Sequence[int], advisory_rows: int) -> list[list[int]]:
    """Group consecutive partition indices so each group reaches the
    advisory size (last group may be small)."""
    groups: list[list[int]] = []
    cur: list[int] = []
    acc = 0
    for i, s in enumerate(sizes):
        cur.append(i)
        acc += s
        if acc >= advisory_rows:
            groups.append(cur)
            cur = []
            acc = 0
    if cur:
        groups.append(cur)
    return groups


def apply_merge_groups(parts: list, groups: list[list[int]]) -> list:
    return [[b for i in g for b in parts[i]] for g in groups]


def aqe_replanning_enabled(ctx: ExecContext) -> bool:
    return bool(ctx.conf.get(ADAPTIVE_ENABLED))


def replan_stages(stages, done: set, ctx: ExecContext) -> None:
    """Re-optimize not-yet-run stages with observed parent-stage sizes
    (role of AdaptiveSparkPlanExec.reOptimize, sqlx/adaptive/
    AdaptiveSparkPlanExec.scala:301): a shuffled hash join whose
    materialized build side is under the broadcast threshold demotes to a
    broadcast join; if the probe-side shuffle hasn't run yet it is elided
    (its pre-shuffle subtree inlines into the consumer — the reference's
    local-shuffle-read + SMJ→BHJ demotion rolled into one)."""
    from ..config import AUTO_BROADCAST_THRESHOLD
    from ..exec.scheduler import _StageOutput
    from .exchange import BroadcastExchangeExec, ShuffleExchangeExec
    from .operators import HashJoinExec

    threshold = int(ctx.conf.get(AUTO_BROADCAST_THRESHOLD))
    if threshold < 0:
        return

    from .planner import Planner

    broadcastable = Planner._BROADCAST_RIGHT_TYPES

    def _elide_safe(root, join) -> bool:
        """The probe shuffle may be skipped only if no operator between
        the stage root and the join relies on the join's output
        partitioning (role of the reference's ValidateRequirements after
        re-optimization): an ancestor whose required distribution the
        planner satisfied WITHOUT inserting an exchange would silently
        merge wrong after the elision."""
        from .partitioning import UnspecifiedDistribution

        def walk(node) -> bool | None:
            # returns True if join found below and path is safe, None if
            # join not in this subtree
            if node is join:
                return True
            for i, c in enumerate(node.children):
                sub = walk(c)
                if sub is None:
                    continue
                if not sub:
                    return False
                reqs = node.required_child_distribution()
                req = reqs[i] if i < len(reqs) else None
                if req is not None and \
                        not isinstance(req, UnspecifiedDistribution):
                    return False
                return True
            return None

        return walk(root) is True

    for st in stages:
        if st.stage_id in done:
            continue

        def rw(node, _root=st.root):
            if not (isinstance(node, HashJoinExec)
                    and not node.is_broadcast):
                return node
            if node.join_type not in broadcastable:
                return node
            r = node.right
            if not (isinstance(r, _StageOutput)
                    and r.stage.stage_id in done
                    and r.stage.result is not None):
                return node
            rows = sum(b.num_rows() for p in r.stage.result for b in p)
            if rows * _row_width(r.output) > threshold:
                return node
            new_right = BroadcastExchangeExec(r)
            new_left = node.left
            if isinstance(new_left, _StageOutput) \
                    and new_left.stage.stage_id not in done \
                    and isinstance(new_left.stage.root,
                                   ShuffleExchangeExec) \
                    and _elide_safe(_root, node):
                # probe-side shuffle not run and no longer required
                new_left = new_left.stage.root.child
                ctx.metrics.add("aqe.probe_shuffles_elided")
            ctx.metrics.add("aqe.broadcast_demotions")
            return node.copy(left=new_left, right=new_right,
                             is_broadcast=True)

        new_root = st.root.transform_up(rw)
        if new_root is not st.root:
            st.root = new_root


def install_runtime_filters(stages, done: set, ctx: ExecContext) -> None:
    """Sideways information passing (role of the reference's
    DynamicPruning / Presto dynamic filtering): when a hash-join build
    side has materialized, harvest its key domain HOST-SIDE from state
    the engine already synced — per-reducer map-side column stats
    (exec/shuffle._OutBuffer), the seeded dense-range memo, or the
    StringDict code domains of the materialized batches — and stash it
    on the not-yet-run probe-side shuffle exchange. The exchange prunes
    probe rows before they are shuffled: whole batches drop when their
    seeded range misses the domain, and under ExchangeFusion the
    row-level filter rides the existing fused map kernel as aux operands
    (zero extra dispatches, zero extra syncs — the obs gate proves the
    launch-delta identity)."""
    from ..config import ADAPTIVE_RUNTIME_FILTER

    if not ctx.conf.get(ADAPTIVE_RUNTIME_FILTER):
        return
    from ..exec.scheduler import _StageOutput
    from .exchange import ShuffleExchangeExec
    from .operators import HashJoinExec
    from .partitioning import HashPartitioning

    for st in stages:
        if st.stage_id in done:
            continue
        for node in st.root.iter_nodes():
            if not isinstance(node, HashJoinExec):
                continue
            # pruned probe rows must be provably output-irrelevant: only
            # join types whose output is a subset of MATCHING probe rows
            if node.join_type not in ("inner", "left_semi"):
                continue
            if len(node.left_keys) != 1 or len(node.right_keys) != 1:
                continue
            left = node.left
            if not (isinstance(left, _StageOutput)
                    and left.stage.stage_id not in done
                    and isinstance(left.stage.root, ShuffleExchangeExec)):
                continue
            probe = left.stage.root
            if getattr(probe, "runtime_filter", None) is not None:
                continue
            if not isinstance(probe.partitioning, HashPartitioning):
                continue
            filt = _harvest_build_domain(node, done)
            if filt is None:
                continue
            kid = node.left_keys[0].expr_id
            out_pos = next((i for i, a in enumerate(probe.output)
                            if a.expr_id == kid), None)
            if out_pos is None:
                continue
            filt["out_pos"] = out_pos
            # pre-pipeline position enables whole-batch skip via the
            # seeded memo; a computed key (None) still row-prunes fused
            filt["child_pos"] = next(
                (i for i, a in enumerate(probe.child.output)
                 if a.expr_id == kid), None)
            probe.runtime_filter = filt
            ctx.metrics.add("adaptive.runtime_filters_installed")
            tracer = getattr(ctx, "tracer", None)
            if tracer is not None:
                with tracer.span("adaptive.runtime_filter",
                                 cat="adaptive",
                                 args={"kind": filt["kind"],
                                       "stage": st.stage_id}):
                    pass


def _harvest_build_domain(join, done: set):
    """The materialized build side's key domain, from already-synced
    state only (NO kernel launches, NO device reads): returns
    {"kind": "range", "lo", "hi"} for integral/date keys,
    {"kind": "dict", "domain": frozenset} for dict-encoded string keys,
    or None when no free domain is available (never guess)."""
    from ..exec.scheduler import _StageOutput
    from ..types import DateType, IntegralType, StringType
    from ..utils.device_memo import peek_dense_range
    from .exchange import BroadcastExchangeExec, ShuffleExchangeExec

    key = join.right_keys[0]
    integral = isinstance(key.dtype, (IntegralType, DateType))
    stringy = isinstance(key.dtype, StringType)
    if not (integral or stringy):
        return None
    r = join.right
    # see through an AQE-demoted broadcast over a done shuffle stage:
    # the shuffle's map-side stats survive the demotion
    if isinstance(r, BroadcastExchangeExec):
        r = r.child
    if not (isinstance(r, _StageOutput) and r.stage.stage_id in done
            and r.stage.result is not None):
        return None
    result = r.stage.result
    rows = sum(b.num_rows() for p in result for b in p)
    if rows == 0:
        # empty build: inner/semi output is empty — prune everything
        return {"kind": "range", "lo": 1, "hi": 0} if integral \
            else {"kind": "dict", "domain": frozenset()}
    kpos = next((i for i, a in enumerate(r.output)
                 if a.expr_id == key.expr_id), None)
    if kpos is None:
        return None
    if stringy:
        # dict-code domain: the union of the batches' StringDict values
        # is a sound superset of the live key set, readable host-side
        domain: set = set()
        for part in result:
            for b in part:
                d = b.columns[kpos].dictionary
                if d is None:
                    return None
                domain.update(d.values)
        return {"kind": "dict", "domain": frozenset(domain)}
    root = r.stage.root
    if isinstance(root, ShuffleExchangeExec) and root.last_col_stats:
        # map-side accumulated per-reducer (or mesh-union) stats
        lo = hi = None
        for per_col in root.last_col_stats.values():
            st = per_col.get(kpos)
            if st is None:
                return None
            cmin, cmax, any_valid = st
            if not any_valid:
                continue
            lo = cmin if lo is None else min(lo, cmin)
            hi = cmax if hi is None else max(hi, cmax)
        if lo is None:
            return {"kind": "range", "lo": 1, "hi": 0}
        return {"kind": "range", "lo": int(lo), "hi": int(hi)}
    # broadcast/gather builds: the dense-range memo, if (and only if)
    # ingest already seeded it for these exact arrays
    lo = hi = None
    for part in result:
        for b in part:
            hit = peek_dense_range(b.columns[kpos], b.row_mask)
            if hit is None:
                return None
            kmin, kmax, any_live = hit
            if not any_live:
                continue
            lo = kmin if lo is None else min(lo, kmin)
            hi = kmax if hi is None else max(hi, kmax)
    if lo is None:
        return {"kind": "range", "lo": 1, "hi": 0}
    return {"kind": "range", "lo": int(lo), "hi": int(hi)}


def _inline_remaining(root, done: set):
    """Re-inline the NOT-yet-run parent stages into one plan tree: their
    _StageOutput leaves become the stage roots themselves (the unrun
    exchanges return to the tree, where the whole-tier lowering turns
    them into in-program gathers), while DONE stages stay as
    materialized leaves the program builder ingests directly."""
    from ..exec.scheduler import _StageOutput

    def rw(node):
        if isinstance(node, _StageOutput) \
                and node.stage.stage_id not in done:
            return _inline_remaining(node.stage.root, done)
        return node

    return root.transform_up(rw)


def maybe_readmit(result_stage, done: set, ctx: ExecContext) -> None:
    """Stage-boundary re-admission: after a stage materializes, feed the
    now-known output sizes back through the compile-tier chooser for the
    REMAINING plan. A remainder the chooser admits to the whole tier
    collapses into ONE program (materialized stages become ingested
    leaves; unrun exchanges become in-program gathers) instead of
    continuing stage-at-a-time — the runtime counterpart of
    apply_compile_tier's plan-time decision."""
    from ..config import ADAPTIVE_READMISSION

    if not ctx.conf.get(ADAPTIVE_READMISSION):
        return
    from .whole_query import WholeQueryExec, choose_tier

    if result_stage.stage_id in done:
        return
    if isinstance(result_stage.root, WholeQueryExec):
        return
    inlined = _inline_remaining(result_stage.root, done)
    dec = choose_tier(inlined, ctx.conf)
    if dec.tier != "whole":
        return
    dec.details["readmitted"] = True
    result_stage.root = WholeQueryExec(inlined, dec)
    ctx.readmission_decision = dec
    ctx.metrics.add("adaptive.readmissions")
    tracer = getattr(ctx, "tracer", None)
    if tracer is not None:
        with tracer.span("adaptive.readmission", cat="adaptive",
                         args={"tier": dec.tier, "reason": dec.reason}):
            pass


def _effective_child(plan_child):
    """See through scheduler stage boundaries (exec/scheduler.py
    _StageOutput) to the exchange that produced the partitions."""
    from ..exec.scheduler import _StageOutput

    if isinstance(plan_child, _StageOutput):
        return plan_child.stage.root
    return plan_child


def _is_shuffle_output(plan_child) -> bool:
    """An exchange, or a cluster-mode Fetch leaf standing in for one
    (exec/cluster_sql.FetchExec, `is_shuffle_read`): the reduce side of a
    cluster shuffle must coalesce exactly like the local path — adjacent
    merges preserve hash clustering either way, and the plan analyzer
    models ONE coalescing behavior for both modes."""
    from .exchange import ShuffleExchangeExec

    return isinstance(plan_child, ShuffleExchangeExec) or \
        getattr(plan_child, "is_shuffle_read", False)


def coalesce_after_exchange(plan_child, parts: list, ctx: ExecContext,
                            output_attrs) -> list:
    """Coalesce a single exchange's output for a blocking consumer."""
    plan_child = _effective_child(plan_child)
    if not _is_shuffle_output(plan_child):
        return parts
    if not (ctx.conf.get(ADAPTIVE_ENABLED)
            and ctx.conf.get(COALESCE_PARTITIONS_ENABLED)):
        return parts
    if len(parts) <= 1:
        return parts
    advisory = int(ctx.conf.get(ADVISORY_PARTITION_BYTES)) // \
        _row_width(output_attrs)
    sizes = [_partition_rows(p) for p in parts]
    if sum(sizes) == 0:
        return [[b for p in parts for b in p]]
    groups = plan_merge_groups(sizes, advisory)
    if len(groups) == len(parts):
        return parts
    ctx.metrics.add("aqe.partitions_coalesced", len(parts) - len(groups))
    return apply_merge_groups(parts, groups)


def coalesce_join_inputs(left_child, right_child, left_parts: list,
                         right_parts: list, ctx: ExecContext,
                         left_attrs, right_attrs):
    """Coordinated coalescing for co-partitioned join inputs."""
    left_child = _effective_child(left_child)
    right_child = _effective_child(right_child)
    if not (_is_shuffle_output(left_child)
            and _is_shuffle_output(right_child)):
        return left_parts, right_parts
    if not (ctx.conf.get(ADAPTIVE_ENABLED)
            and ctx.conf.get(COALESCE_PARTITIONS_ENABLED)):
        return left_parts, right_parts
    if len(left_parts) != len(right_parts) or len(left_parts) <= 1:
        return left_parts, right_parts
    advisory = int(ctx.conf.get(ADVISORY_PARTITION_BYTES)) // max(
        _row_width(left_attrs), _row_width(right_attrs))
    sizes = [max(_partition_rows(l), _partition_rows(r))
             for l, r in zip(left_parts, right_parts)]
    groups = plan_merge_groups(sizes, advisory)
    if len(groups) == len(left_parts):
        return left_parts, right_parts
    ctx.metrics.add("aqe.partitions_coalesced",
                    len(left_parts) - len(groups))
    return (apply_merge_groups(left_parts, groups),
            apply_merge_groups(right_parts, groups))


def split_skewed_join_inputs(left_parts: list, right_parts: list,
                             ctx: ExecContext, join_type: str,
                             skew_factor: float = 4.0):
    """Split skewed PROBE-side partitions, duplicating the build side
    (reference: OptimizeSkewedJoin.scala:57 — same idea at batch
    granularity: probe rows may be split freely for inner/left joins since
    every probe row still sees the full matching build partition)."""
    from ..config import SKEW_JOIN_ENABLED

    if not ctx.conf.get(SKEW_JOIN_ENABLED):
        return left_parts, right_parts
    if join_type not in ("inner", "left_outer", "left_semi", "left_anti"):
        return left_parts, right_parts
    sizes = [_partition_rows(p) for p in left_parts]
    nonzero = sorted(s for s in sizes if s) or [0]
    median = nonzero[len(nonzero) // 2]
    if median == 0:
        return left_parts, right_parts
    threshold = max(median * skew_factor, 1)
    out_l, out_r = [], []
    split_any = False
    for lp, rp, s in zip(left_parts, right_parts, sizes):
        if s > threshold and len(lp) > 1:
            k = min(len(lp), max(2, int(s // threshold) + 1))
            per = -(-len(lp) // k)
            for start in range(0, len(lp), per):
                out_l.append(lp[start:start + per])
                out_r.append(rp)
                split_any = True
        else:
            out_l.append(lp)
            out_r.append(rp)
    if split_any:
        ctx.metrics.add("aqe.skew_splits", len(out_l) - len(left_parts))
    return out_l, out_r
