"""Adaptive execution: runtime partition coalescing.

Role of the reference's AQE partition coalescing
(sqlx/adaptive/CoalesceShufflePartitions.scala + AQEShuffleReadExec:41,
driven by MapOutputStatistics). Our exchanges execute eagerly and report
per-reducer row counts, so blocking consumers coalesce undersized adjacent
reducer outputs before processing — hash clustering and range ordering are
preserved because only ADJACENT partitions merge. Joins coordinate one
merge plan across both sides (the reference does the same via shared
partition specs). Skew splitting (OptimizeSkewedJoin.scala:57) lives in
split_skewed_join_inputs below.
"""

from __future__ import annotations

from typing import Sequence

from ..config import (
    ADAPTIVE_ENABLED, ADVISORY_PARTITION_BYTES, COALESCE_PARTITIONS_ENABLED,
)
from ..exec.context import ExecContext


def _partition_rows(part) -> int:
    return sum(b.num_rows() for b in part)


def _row_width(schema_attrs) -> int:
    w = 0
    for a in schema_attrs:
        w += max(int(a.dtype.device_dtype.itemsize), 4)
    return max(w, 8)


def plan_merge_groups(sizes: Sequence[int], advisory_rows: int) -> list[list[int]]:
    """Group consecutive partition indices so each group reaches the
    advisory size (last group may be small)."""
    groups: list[list[int]] = []
    cur: list[int] = []
    acc = 0
    for i, s in enumerate(sizes):
        cur.append(i)
        acc += s
        if acc >= advisory_rows:
            groups.append(cur)
            cur = []
            acc = 0
    if cur:
        groups.append(cur)
    return groups


def apply_merge_groups(parts: list, groups: list[list[int]]) -> list:
    return [[b for i in g for b in parts[i]] for g in groups]


def aqe_replanning_enabled(ctx: ExecContext) -> bool:
    return bool(ctx.conf.get(ADAPTIVE_ENABLED))


def replan_stages(stages, done: set, ctx: ExecContext) -> None:
    """Re-optimize not-yet-run stages with observed parent-stage sizes
    (role of AdaptiveSparkPlanExec.reOptimize, sqlx/adaptive/
    AdaptiveSparkPlanExec.scala:301): a shuffled hash join whose
    materialized build side is under the broadcast threshold demotes to a
    broadcast join; if the probe-side shuffle hasn't run yet it is elided
    (its pre-shuffle subtree inlines into the consumer — the reference's
    local-shuffle-read + SMJ→BHJ demotion rolled into one)."""
    from ..config import AUTO_BROADCAST_THRESHOLD
    from ..exec.scheduler import _StageOutput
    from .exchange import BroadcastExchangeExec, ShuffleExchangeExec
    from .operators import HashJoinExec

    threshold = int(ctx.conf.get(AUTO_BROADCAST_THRESHOLD))
    if threshold < 0:
        return

    from .planner import Planner

    broadcastable = Planner._BROADCAST_RIGHT_TYPES

    def _elide_safe(root, join) -> bool:
        """The probe shuffle may be skipped only if no operator between
        the stage root and the join relies on the join's output
        partitioning (role of the reference's ValidateRequirements after
        re-optimization): an ancestor whose required distribution the
        planner satisfied WITHOUT inserting an exchange would silently
        merge wrong after the elision."""
        from .partitioning import UnspecifiedDistribution

        def walk(node) -> bool | None:
            # returns True if join found below and path is safe, None if
            # join not in this subtree
            if node is join:
                return True
            for i, c in enumerate(node.children):
                sub = walk(c)
                if sub is None:
                    continue
                if not sub:
                    return False
                reqs = node.required_child_distribution()
                req = reqs[i] if i < len(reqs) else None
                if req is not None and \
                        not isinstance(req, UnspecifiedDistribution):
                    return False
                return True
            return None

        return walk(root) is True

    for st in stages:
        if st.stage_id in done:
            continue

        def rw(node, _root=st.root):
            if not (isinstance(node, HashJoinExec)
                    and not node.is_broadcast):
                return node
            if node.join_type not in broadcastable:
                return node
            r = node.right
            if not (isinstance(r, _StageOutput)
                    and r.stage.stage_id in done
                    and r.stage.result is not None):
                return node
            rows = sum(b.num_rows() for p in r.stage.result for b in p)
            if rows * _row_width(r.output) > threshold:
                return node
            new_right = BroadcastExchangeExec(r)
            new_left = node.left
            if isinstance(new_left, _StageOutput) \
                    and new_left.stage.stage_id not in done \
                    and isinstance(new_left.stage.root,
                                   ShuffleExchangeExec) \
                    and _elide_safe(_root, node):
                # probe-side shuffle not run and no longer required
                new_left = new_left.stage.root.child
                ctx.metrics.add("aqe.probe_shuffles_elided")
            ctx.metrics.add("aqe.broadcast_demotions")
            return node.copy(left=new_left, right=new_right,
                             is_broadcast=True)

        new_root = st.root.transform_up(rw)
        if new_root is not st.root:
            st.root = new_root


def _effective_child(plan_child):
    """See through scheduler stage boundaries (exec/scheduler.py
    _StageOutput) to the exchange that produced the partitions."""
    from ..exec.scheduler import _StageOutput

    if isinstance(plan_child, _StageOutput):
        return plan_child.stage.root
    return plan_child


def _is_shuffle_output(plan_child) -> bool:
    """An exchange, or a cluster-mode Fetch leaf standing in for one
    (exec/cluster_sql.FetchExec, `is_shuffle_read`): the reduce side of a
    cluster shuffle must coalesce exactly like the local path — adjacent
    merges preserve hash clustering either way, and the plan analyzer
    models ONE coalescing behavior for both modes."""
    from .exchange import ShuffleExchangeExec

    return isinstance(plan_child, ShuffleExchangeExec) or \
        getattr(plan_child, "is_shuffle_read", False)


def coalesce_after_exchange(plan_child, parts: list, ctx: ExecContext,
                            output_attrs) -> list:
    """Coalesce a single exchange's output for a blocking consumer."""
    plan_child = _effective_child(plan_child)
    if not _is_shuffle_output(plan_child):
        return parts
    if not (ctx.conf.get(ADAPTIVE_ENABLED)
            and ctx.conf.get(COALESCE_PARTITIONS_ENABLED)):
        return parts
    if len(parts) <= 1:
        return parts
    advisory = int(ctx.conf.get(ADVISORY_PARTITION_BYTES)) // \
        _row_width(output_attrs)
    sizes = [_partition_rows(p) for p in parts]
    if sum(sizes) == 0:
        return [[b for p in parts for b in p]]
    groups = plan_merge_groups(sizes, advisory)
    if len(groups) == len(parts):
        return parts
    ctx.metrics.add("aqe.partitions_coalesced", len(parts) - len(groups))
    return apply_merge_groups(parts, groups)


def coalesce_join_inputs(left_child, right_child, left_parts: list,
                         right_parts: list, ctx: ExecContext,
                         left_attrs, right_attrs):
    """Coordinated coalescing for co-partitioned join inputs."""
    left_child = _effective_child(left_child)
    right_child = _effective_child(right_child)
    if not (_is_shuffle_output(left_child)
            and _is_shuffle_output(right_child)):
        return left_parts, right_parts
    if not (ctx.conf.get(ADAPTIVE_ENABLED)
            and ctx.conf.get(COALESCE_PARTITIONS_ENABLED)):
        return left_parts, right_parts
    if len(left_parts) != len(right_parts) or len(left_parts) <= 1:
        return left_parts, right_parts
    advisory = int(ctx.conf.get(ADVISORY_PARTITION_BYTES)) // max(
        _row_width(left_attrs), _row_width(right_attrs))
    sizes = [max(_partition_rows(l), _partition_rows(r))
             for l, r in zip(left_parts, right_parts)]
    groups = plan_merge_groups(sizes, advisory)
    if len(groups) == len(left_parts):
        return left_parts, right_parts
    ctx.metrics.add("aqe.partitions_coalesced",
                    len(left_parts) - len(groups))
    return (apply_merge_groups(left_parts, groups),
            apply_merge_groups(right_parts, groups))


def split_skewed_join_inputs(left_parts: list, right_parts: list,
                             ctx: ExecContext, join_type: str,
                             skew_factor: float = 4.0):
    """Split skewed PROBE-side partitions, duplicating the build side
    (reference: OptimizeSkewedJoin.scala:57 — same idea at batch
    granularity: probe rows may be split freely for inner/left joins since
    every probe row still sees the full matching build partition)."""
    from ..config import SKEW_JOIN_ENABLED

    if not ctx.conf.get(SKEW_JOIN_ENABLED):
        return left_parts, right_parts
    if join_type not in ("inner", "left_outer", "left_semi", "left_anti"):
        return left_parts, right_parts
    sizes = [_partition_rows(p) for p in left_parts]
    nonzero = sorted(s for s in sizes if s) or [0]
    median = nonzero[len(nonzero) // 2]
    if median == 0:
        return left_parts, right_parts
    threshold = max(median * skew_factor, 1)
    out_l, out_r = [], []
    split_any = False
    for lp, rp, s in zip(left_parts, right_parts, sizes):
        if s > threshold and len(lp) > 1:
            k = min(len(lp), max(2, int(s // threshold) + 1))
            per = -(-len(lp) // k)
            for start in range(0, len(lp), per):
                out_l.append(lp[start:start + per])
                out_r.append(rp)
                split_any = True
        else:
            out_l.append(lp)
            out_r.append(rp)
    if split_any:
        ctx.metrics.add("aqe.skew_splits", len(out_l) - len(left_parts))
    return out_l, out_r
