"""Mesh whole-query compilation: the ENTIRE sharded plan as ONE
shard_map program per execution step.

The single-device whole tier (physical/whole_query.py) collapses a plan
into one jitted program but runs it on one device — exchanges become
in-program gathers and the full working set must fit one HBM. This tier
keeps the plan's PARALLELISM: leaf planes stage row-sharded over the
device mesh (the same [P * rows_per_shard] base layout the per-stage
mesh path persists for quota retries), hash exchanges lower to
`lax.all_to_all` on the sharded planes (parallel/mesh_fusion's
_exchange_tail — identical bucketing, quota and overflow contract), and
every reduce-side consumer (partial/final aggregate, join build+probe,
sort/limit) folds in BEHIND the collective, on the sharded layouts.
Filter/project pipelines ride trace_pipeline unchanged: inside
shard_map, a per-shard [cap] plane is indistinguishable from a
single-device one.

Retry contract — ONE verdict per dispatch: every per-join `needed`
scalar, per-exchange psum'd overflow count and dense-probe guard ride
the single dispatch's outputs; the host checks them ONCE and re-enters
the loop with bumped join caps / doubled quotas / the dense variant
disabled — all verdicts of a round applied together, so a round is
never wasted re-discovering one of them. Quota/capacity retries restage
NOTHING: the first retry stages the leaf planes once as UNDONATED base
planes and every later round (including gang retries after a runtime
fault, once _planes_alive proves them resident) reuses them.

Admission is decided at plan time (whole_query.choose_tier +
supported_mesh_whole): leaf stats known, per-shard HBM estimate within
budget, one power-of-two hash-partition count with enough devices.
Inadmissible plans fall back tier-by-tier with the reason stashed on
the decision. The plan analyzer mirrors the whole loop
(analysis/plan_lint._mesh_whole): {mesh_whole: attempts} predicts the
dispatch count exactly, fusion on and off.
"""

from __future__ import annotations

from ..columnar.batch import Column, ColumnarBatch, bucket_capacity
from ..errors import ExecutionError
from ..types import BooleanType, dict_encoded
from .compile import GLOBAL_KERNEL_CACHE
from .operators import attrs_schema
from .whole_query import (
    _MAX_PROGRAM_RETRIES, _Collect, _Lowered, _MCol, _ProgramBuilder,
    WholeQueryExec, _jnp, _record_spans, is_runtime_fault,
)

__all__ = ["MeshWholeQueryExec"]

# one fresh attempt after a runtime gang fault, same budget as the
# per-stage mesh gang loop (parallel/mesh_exchange.py)
_MAX_GANG_RETRIES = 1


def _np():
    import numpy as np

    return np


# ---------------------------------------------------------------------------
# the sharded program builder
# ---------------------------------------------------------------------------

class _MeshProgramBuilder(_ProgramBuilder):
    """_ProgramBuilder over a device mesh.

    Every intermediate flow carries a FORM: ("shard", part_ids) — planes
    are per-shard [cap] views of a row-sharded [P*cap] array, hash-
    partitioned by the attribute ids in part_ids (() = arbitrary row
    split) — or ("rep",) — every shard holds the identical full array.
    The form decides each operator's lowering: a final aggregate over
    flows co-partitioned on its grouping keys folds in per shard behind
    the collective; anything needing global view gathers first
    (all_gather, tiled) and proceeds exactly like the single-device
    builder. The inherited lowering helpers (_lower_pipe, _lower_agg,
    _join_tail, _lower_sort, ...) are reused verbatim — inside shard_map
    they see ordinary [cap] arrays."""

    def __init__(self, ctx, join_caps, spans_seed=None, dense_off=None,
                 *, mesh, axis, num_shards, quotas, mesh_seed,
                 leaf_cache, use_base, gang):
        super().__init__(ctx, join_caps, spans_seed=spans_seed,
                         dense_off=dense_off)
        self.mesh = mesh
        self.axis = axis
        self.P = num_shards
        self._quotas = quotas          # xid -> live quota (doubles on retry)
        self._mesh_seed = mesh_seed    # warm-start manifest mesh quotas
        self.leaf_cache = leaf_cache   # id(leaf) -> host staging record
        self.use_base = use_base       # True after the first retry round
        self.gang = gang               # True on a gang-fault rebuild
        self._roles: list[str] = []    # per arg: "don" | "rows" | "rep"
        self._forms: dict[int, tuple] = {}
        self._partial_merged: set[int] = set()
        self._x_seq = 0
        self.x_ids: list[int] = []     # all_to_all exchanges, emit order
        self.quota_keys: dict[int, str] = {}   # xid -> manifest slot
        self.staged: list = []         # this attempt's donated planes
        from ..parallel.mesh_fusion import MeshSpecLayout

        self._layout = MeshSpecLayout(axis)
        self._rep_sharding = self._layout.replicated_sharding(mesh)
        self._row_sharding = self._layout.row_sharding(mesh)

    # -- argument plumbing -------------------------------------------------
    def arg(self, arr) -> int:
        """Replicated program input (aux tables, luts, rank tables):
        committed to the mesh so jit never guesses a placement."""
        import jax

        self.args.append(jax.device_put(arr, self._rep_sharding))
        self._roles.append("rep")
        return len(self.args) - 1

    def shard_arg(self, arr, donated: bool) -> int:
        self.args.append(arr)
        self._roles.append("don" if donated else "rows")
        return len(self.args) - 1

    def split_args(self):
        """(donated, kept) argument lists, program-call order."""
        don = [a for a, r in zip(self.args, self._roles) if r == "don"]
        keep = [a for a, r in zip(self.args, self._roles) if r != "don"]
        return don, keep

    def arg_slots(self):
        """Per merged arg position: (bucket, index-in-bucket), bucket 0 =
        donated, 1 = kept — local_fn reassembles the flat args list the
        emit closures index."""
        slots = []
        nd = nk = 0
        for r in self._roles:
            if r == "don":
                slots.append((0, nd))
                nd += 1
            else:
                slots.append((1, nk))
                nk += 1
        return slots

    def spec_lists(self):
        rows, rep = self._layout.rows(), self._layout.replicated()
        don = [rows for r in self._roles if r == "don"]
        keep = [rows if r == "rows" else rep
                for r in self._roles if r != "don"]
        return don, keep

    # -- form bookkeeping --------------------------------------------------
    def _form(self, low: _Lowered) -> tuple:
        return self._forms[id(low)]

    def _set_form(self, low: _Lowered, form: tuple) -> None:
        self._forms[id(low)] = form

    def _to_rep(self, low: _Lowered) -> _Lowered:
        """Gather a sharded flow to the replicated form (tiled
        all_gather per plane): cap multiplies by P, row order is
        shard-major — the same concatenation order the host gather of
        the per-stage path produces."""
        if self._form(low)[0] != "shard":
            return low
        axis = self.axis
        cap = low.cap * self.P
        self.key.append(("torep",))

        def emit(args, needed, _low=low):
            from jax import lax

            d, v, m = _low.emit(args, needed)

            def g(x):
                return None if x is None else lax.all_gather(
                    x, axis, axis=0, tiled=True)

            return [g(x) for x in d], [g(x) for x in v], g(m)

        out = _Lowered(list(low.metas), cap, emit)
        self._set_form(out, ("rep",))
        return out

    # -- dispatch ----------------------------------------------------------
    def lower(self, node) -> _Lowered:
        from . import operators as O
        from .exchange import BroadcastExchangeExec, ShuffleExchangeExec
        from .fusion import FusedAggregateExec, FusedLimitExec

        if isinstance(node, (O.LocalTableScanExec, O.RangeExec,
                             O.ScanExec)):
            return self._lower_mesh_leaf(node)
        if isinstance(node, FusedAggregateExec):
            self._register_merge(node)
            low = self.lower(node.child)
            f = self._form(low)
            low2 = self._lower_pipe(node.filters, node.pipe_outputs,
                                    node.child.output, node.pipe_attrs,
                                    low)
            self._set_form(low2, f)
            self._member(node)
            return self._lower_mesh_agg(node, node.pipe_attrs, low2)
        if isinstance(node, O.HashAggregateExec):
            self._register_merge(node)
            low = self.lower(node.child)
            self._member(node)
            return self._lower_mesh_agg(node, node.child.output, low)
        if isinstance(node, FusedLimitExec):
            low = self._to_rep(self.lower(node.child))
            low = self._lower_pipe(node.filters, node.pipe_outputs,
                                   node.child.output, node.pipe_attrs,
                                   low)
            self._member(node)
            out = self._lower_limit(node, low)
            self._set_form(out, ("rep",))
            return out
        if isinstance(node, O.LimitExec):
            low = self._to_rep(self.lower(node.child))
            self._member(node)
            out = self._lower_limit(node, low)
            self._set_form(out, ("rep",))
            return out
        if isinstance(node, O.SortExec):
            # a global sort needs the global row set; the gathered flow
            # then rides the inherited single-device lowering
            low = self._to_rep(self.lower(node.child))
            self._member(node)
            out = self._lower_sort(node, low)
            self._set_form(out, ("rep",))
            return out
        if isinstance(node, O.HashJoinExec):
            self._member(node)
            return self._lower_mesh_join(node)
        if isinstance(node, O.ComputeExec):
            low = self.lower(node.child)
            f = self._form(low)
            self._member(node)
            from ..expr.expressions import Alias

            attrs = [o.to_attribute() if isinstance(o, Alias) else o
                     for o in node.outputs]
            out = self._lower_pipe(node.filters, node.outputs,
                                   node.child.output, attrs, low)
            self._set_form(out, f)
            return out
        if isinstance(node, ShuffleExchangeExec):
            return self._lower_mesh_exchange(node)
        if isinstance(node, BroadcastExchangeExec):
            low = self._to_rep(self.lower(node.child))
            self.members.append("BroadcastExchange -> replicated gather")
            return low
        if isinstance(node, O.CoalescePartitionsExec):
            return self.lower(node.child)
        if isinstance(node, O.UnionExec):
            lows = [self._to_rep(self.lower(c))
                    for c in node.children_plans]
            self._member(node)
            out = self._lower_union(node, lows)
            self._set_form(out, ("rep",))
            return out
        raise ExecutionError(            # admission guarantees this
            f"mesh-whole lowering missing for {type(node).__name__}")

    # -- leaves ------------------------------------------------------------
    def _stage_leaf_host(self, node) -> dict:
        """Host staging, ONCE per execute (cached across retry rounds):
        execute the leaf, flatten its batches to [total_cap] planes with
        strings recoded against a merged global dictionary (codes must
        be comparable across shards after the collective — the mesh
        encoding carry-over), and fix the row split."""
        np = _np()
        parts = node.execute(self.ctx)
        batches = [b for p in parts for b in p]
        schema = attrs_schema(node.output)
        from ..columnar.batch import EMPTY_DICT
        from ..parallel.mesh_exchange import _stage_payloads

        staged = _stage_payloads(batches, schema)
        if staged is None:
            # empty leaf: one all-dead slot per shard keeps every plane
            # shape valid without a special empty program variant
            datas = [np.zeros(1, np.dtype(f.dataType.device_dtype))
                     for f in schema.fields]
            valids = [None] * len(schema.fields)
            mask = np.zeros(1, bool)
            dicts = [EMPTY_DICT if dict_encoded(f.dataType) else None
                     for f in schema.fields]
            total_cap = 1
        else:
            datas, valids, mask, dicts, total_cap = staged
        rps = max(-(-total_cap // self.P), 1)
        return {"datas": datas, "valids": valids, "mask": mask,
                "dicts": dicts, "total_cap": total_cap, "rps": rps,
                "fields": schema.fields, "base": None,
                "base_ledger": None}

    def _leaf_planes(self, rec):
        """Device planes for one leaf, honoring the retry/donation
        contract: attempt 0 stages donated planes (released in-place by
        the dispatch); the first retry stages UNDONATED base planes once
        and every later round — quota/capacity retries AND gang retries
        — reuses them after _planes_alive proves them resident, so
        retries never re-cross the host."""
        import jax

        from ..parallel.mesh_exchange import _pad_base, _planes_alive
        from ..parallel.mesh_fusion import StagedBuffers

        P, rps = self.P, rec["rps"]

        def put(a):
            return None if a is None else jax.device_put(
                _pad_base(a, P, rps), self._row_sharding)

        if not self.use_base:
            datas = [put(a) for a in rec["datas"]]
            valids = [put(v) for v in rec["valids"]]
            mask = put(rec["mask"])
            self.staged.extend(
                [x for x in datas + valids + [mask] if x is not None])
            return datas, valids, mask
        base = rec["base"]
        if base is not None and _planes_alive(
                list(base[0]) + [v for v in base[1]] + [base[2]]):
            if self.gang:
                self.ctx.metrics.add("whole_query.mesh_gang_base_reused")
            else:
                self.ctx.metrics.add(
                    "whole_query.mesh_retry_restage_saved")
            return base
        if base is not None and rec["base_ledger"] is not None:
            rec["base_ledger"].release_all()
        datas = [put(a) for a in rec["datas"]]
        valids = [put(v) for v in rec["valids"]]
        mask = put(rec["mask"])
        rec["base"] = (datas, valids, mask)
        rec["base_ledger"] = StagedBuffers(
            [x for x in datas + valids + [mask] if x is not None])
        return datas, valids, mask

    def _lower_mesh_leaf(self, node) -> _Lowered:
        nid = id(node)
        rec = self.leaf_cache.get(nid)
        if rec is None:
            rec = self.leaf_cache[nid] = self._stage_leaf_host(node)
        self._member(node)
        datas, valids, mask = self._leaf_planes(rec)
        donated = not self.use_base
        arg_of = []
        metas = []
        for i, f in enumerate(rec["fields"]):
            di = self.shard_arg(datas[i], donated)
            vi = None if valids[i] is None \
                else self.shard_arg(valids[i], donated)
            arg_of.append((di, vi))
            metas.append(_MCol(f.dataType, valids[i] is not None,
                               rec["dicts"][i]))
        mi = self.shard_arg(mask, donated)
        rps = rec["rps"]
        self.key.append(("mleaf", rps, tuple(
            (str(d.dtype), v is not None)
            for d, v in zip(datas, valids))))

        def emit(args, needed):
            ds = [args[di] for di, _vi in arg_of]
            vs = [None if vi is None else args[vi] for _di, vi in arg_of]
            return ds, vs, args[mi]

        out = _Lowered(metas, rps, emit)
        self._set_form(out, ("shard", ()))
        return out

    # -- exchanges ---------------------------------------------------------
    def _exchange_keys(self, node, low: _Lowered):
        """(positions, luts, bools) of the hash-partitioning keys in the
        exchange's output flow. Dict-encoded keys hash through their
        merged dictionary's hash lut — the same lanes the host partition
        split and the per-stage mesh path hash, so row placement is
        bit-identical across all three (and the analyzer's host mirror)."""
        pos = {a.expr_id: i for i, a in enumerate(node.output)}
        kidx = tuple(pos[e.expr_id] for e in node.partitioning.exprs)
        luts = [self._eq_lut(low.metas[i]) for i in kidx]
        bools = tuple(isinstance(low.metas[i].dtype, BooleanType)
                      for i in kidx)
        return kidx, luts, bools

    def _quota_for(self, xid: int, node, in_cap: int, kidx) -> int:
        """This exchange's live quota: geometry default, raised by the
        warm-start manifest seed on first use, doubled by the retry loop
        (persisted in self._quotas across rebuilds)."""
        from ..exec.persist_cache import mesh_quota_key
        from ..parallel.mesh_fusion import mesh_stage_geometry

        sig = "|".join(str(a.dtype) for a in node.output)
        mkey = mesh_quota_key("w", self.P, in_cap,
                              f"x{xid}:k{kidx}:s{sig}")
        self.quota_keys[xid] = mkey
        q = self._quotas.get(xid)
        if q is None:
            q = mesh_stage_geometry(self.P * in_cap, self.P)[2]
            seed = (self._mesh_seed or {}).get(mkey)
            if seed and int(seed) > q:
                q = int(seed)
                self.ctx.metrics.add("cache.mesh_quota_seeded")
            self._quotas[xid] = q
        return q

    def _lower_mesh_exchange(self, node) -> _Lowered:
        from .partitioning import HashPartitioning

        low = self.lower(node.child)
        form = self._form(low)
        if node.pipe_fusion is not None:
            filters, outputs = node.pipe_fusion
            low2 = self._lower_pipe(filters, outputs, node.child.output,
                                    node.pipe_attrs, low)
            self._set_form(low2, form)
            low = low2
        p = node.partitioning
        if isinstance(p, HashPartitioning):
            key_ids = tuple(e.expr_id for e in p.exprs)
            if form[0] == "shard":
                return self._exchange_all_to_all(node, low, key_ids)
            return self._exchange_local_filter(node, low, key_ids)
        # range/single/round-robin: the downstream consumer re-groups,
        # re-sorts or reduces globally anyway — gather to the replicated
        # flow (the single-device whole tier's in-program gather)
        self.members.append(
            f"Exchange[{type(p).__name__}] -> in-program gather")
        self.key.append(("xgather",))
        return self._to_rep(low)

    def _exchange_all_to_all(self, node, low: _Lowered,
                             key_ids) -> _Lowered:
        """Hash exchange on a sharded flow: the collective. Same
        _exchange_tail leg as the per-stage mesh path — bucket live rows
        by destination into [P, quota] blocks, all_to_all every plane,
        psum the overflow — but the received planes stay IN the program
        for the reduce-side consumer instead of crossing to host."""
        jnp = _jnp()
        xid = self._x_seq
        self._x_seq += 1
        kidx, luts, bools = self._exchange_keys(node, low)
        quota = self._quota_for(xid, node, low.cap, kidx)
        P, axis = self.P, self.axis
        out_cap = P * quota
        self.key.append(("mxchg", xid, quota, kidx, bools,
                         tuple(x[1] for x in luts)))
        self.members.append(
            "Exchange[HashPartitioning] -> in-program all_to_all")
        self.x_ids.append(xid)

        def emit(args, needed, _low=low):
            from ..ops.hashing import hash_columns, partition_ids
            from ..parallel.mesh_fusion import _exchange_tail

            d, v, m = _low.emit(args, needed)
            eqs, kvs = [], []
            for j, i in enumerate(kidx):
                kd = d[i]
                if luts[j][0] is not None:
                    lut = args[luts[j][0]]
                    kd = jnp.take(lut, jnp.clip(kd.astype(jnp.int32), 0,
                                                lut.shape[0] - 1))
                elif bools[j]:
                    kd = kd.astype(jnp.int32)
                eqs.append(kd)
                kvs.append(v[i])
            pids = partition_ids(hash_columns(eqs, kvs), P)
            n = len(d)
            planes = list(d) + list(v)
            outs, new_mask, _cnt, overflow, _st = _exchange_tail(
                planes, pids, m, P, quota, axis)
            needed.overflows.append(overflow)
            return outs[:n], outs[n:], new_mask

        out = _Lowered(list(low.metas), out_cap, emit)
        self._set_form(out, ("shard", key_ids))
        return out

    def _exchange_local_filter(self, node, low: _Lowered,
                               key_ids) -> _Lowered:
        """Hash exchange on a REPLICATED flow: every shard already holds
        all rows — keep the rows whose partition id IS this shard. No
        collective, no quota, no overflow."""
        jnp = _jnp()
        kidx, luts, bools = self._exchange_keys(node, low)
        P, axis = self.P, self.axis
        self.key.append(("mxlocal", kidx, bools,
                         tuple(x[1] for x in luts)))
        self.members.append(
            "Exchange[HashPartitioning] -> in-program pid filter")

        def emit(args, needed, _low=low):
            from jax import lax

            from ..ops.hashing import hash_columns, partition_ids

            d, v, m = _low.emit(args, needed)
            eqs, kvs = [], []
            for j, i in enumerate(kidx):
                kd = d[i]
                if luts[j][0] is not None:
                    lut = args[luts[j][0]]
                    kd = jnp.take(lut, jnp.clip(kd.astype(jnp.int32), 0,
                                                lut.shape[0] - 1))
                elif bools[j]:
                    kd = kd.astype(jnp.int32)
                eqs.append(kd)
                kvs.append(v[i])
            pids = partition_ids(hash_columns(eqs, kvs), P)
            m = m & (pids == lax.axis_index(axis))
            return d, v, m

        out = _Lowered(list(low.metas), low.cap, emit)
        self._set_form(out, ("shard", key_ids))
        return out

    # -- aggregates --------------------------------------------------------
    def _register_merge(self, node) -> None:
        """A final-mode aggregate marks the partial aggregate it merges
        (walking through exchange/coalesce wrappers): ONLY a merged
        partial may lower per-shard — the planner also emits partial-as-
        complete (single-partition collapse), and running THAT per shard
        would return P unmerged states as the answer."""
        from . import operators as O
        from .exchange import ShuffleExchangeExec

        if getattr(node, "mode", "") != "final":
            return
        c = node.child
        while isinstance(c, (ShuffleExchangeExec,
                             O.CoalescePartitionsExec)):
            c = c.child
        if isinstance(c, O.HashAggregateExec) \
                and getattr(c, "mode", "") == "partial":
            self._partial_merged.add(id(c))

    def _lower_mesh_agg(self, node, in_attrs, low: _Lowered) -> _Lowered:
        form = self._form(low)
        if form[0] == "shard":
            part_ids = form[1]
            grouping_ids = set(g.expr_id for g in node.grouping)
            co = bool(part_ids) and set(part_ids) <= grouping_ids
            if getattr(node, "mode", "") == "partial" \
                    and id(node) in self._partial_merged:
                # merged partial: per-shard states are exactly what the
                # downstream final merge expects
                out_part = part_ids if (node.grouping and co) else ()
            elif node.grouping and co:
                # co-partitioned on the grouping keys: every group's
                # rows sit on one shard — the per-shard aggregate IS the
                # global one
                out_part = part_ids
            else:
                low = self._to_rep(low)
                form = ("rep",)
                out_part = None
        out = self._lower_agg(node, in_attrs, low)
        self._set_form(out, ("shard", out_part)
                       if form[0] == "shard" else ("rep",))
        return out

    # -- joins -------------------------------------------------------------
    def _lower_mesh_join(self, node) -> _Lowered:
        probe = self.lower(node.left)
        pform = self._form(probe)
        if node.probe_fusion is not None:
            filters, outputs = node.probe_fusion
            probe2 = self._lower_pipe(filters, outputs,
                                      node.left.output,
                                      node.probe_attrs, probe)
            self._set_form(probe2, pform)
            probe = probe2
        build = self.lower(node.right)
        bform = self._form(build)
        lkeys = tuple(k.expr_id for k in node.left_keys)
        rkeys = tuple(k.expr_id for k in node.right_keys)
        if pform[0] == "shard":
            # per-shard probe. The build side joins in per shard when
            # CO-PARTITIONED (both sides hash-split by the join keys,
            # positionally: equal keys landed on equal shards) or
            # replicated (every shard probes the full table); an
            # arbitrarily-split build gathers first.
            co = (bform[0] == "shard" and len(lkeys) > 0
                  and pform[1] == lkeys and bform[1] == rkeys)
            if bform[0] == "shard" and not co:
                build = self._to_rep(build)
            out = self._join_tail(node, probe, build)
            self._set_form(out, ("shard", pform[1]))
            return out
        if bform[0] == "shard":
            build = self._to_rep(build)
        out = self._join_tail(node, probe, build)
        self._set_form(out, ("rep",))
        return out


# ---------------------------------------------------------------------------
# program compilation
# ---------------------------------------------------------------------------

def _build_mesh_program(b: _MeshProgramBuilder, root: _Lowered):
    """jit(shard_map(local program)). The local function reassembles the
    flat args list from the (donated, kept) buckets, emits the whole
    lowered tree per shard, and centrally reduces every verdict scalar
    (pmax'd join `needed`s and guards, pmin/pmax'd spans; overflows are
    already psum'd) so the host reads ONE value per check after the
    single dispatch. Outputs are replicated (the root is gathered), so
    check_vma=False with P() out_specs is sound by construction."""
    import jax

    from ..parallel import mesh_fusion as MF
    from ..parallel._shard_map_compat import shard_map

    slots = b.arg_slots()
    don_specs, keep_specs = b.spec_lists()
    rep = b._layout.replicated()
    n_args = len(b.args)
    axis = b.axis
    valid_sig = tuple(m.valid for m in root.metas)
    njoin = b._join_seq
    nov = len(b.x_ids)
    nsp = len(b.span_jids)
    ng = len(b.guard_jids)

    def local_fn(don, keep):
        from jax import lax

        args = [None] * n_args
        for pos, (bk, j) in enumerate(slots):
            args[pos] = don[j] if bk == 0 else keep[j]
        needed = _Collect()
        datas, valids, mask = root.emit(args, needed)
        needed_r = tuple(lax.pmax(x, axis) for x in needed)
        ovfs = tuple(needed.overflows)
        spans = tuple((lax.pmin(lo, axis), lax.pmax(hi, axis),
                       lax.pmax(dup, axis))
                      for lo, hi, dup in needed.spans)
        guards = tuple(lax.pmax(g, axis) for g in needed.guards)
        return (datas, valids, mask, needed_r, ovfs, spans, guards)

    out_specs = ([rep] * len(valid_sig),
                 [rep if hv else None for hv in valid_sig],
                 rep,
                 (rep,) * njoin, (rep,) * nov,
                 ((rep, rep, rep),) * nsp, (rep,) * ng)

    def sharded(don, keep):
        f = shard_map(local_fn, mesh=b.mesh,
                      in_specs=(don_specs, keep_specs),
                      out_specs=out_specs, check_vma=False)
        return f(don, keep)

    donate = MF.DONATE_DEFAULT and not b.use_base and len(don_specs) > 0
    return jax.jit(sharded,  # tpulint: ignore[raw-jit]
                   donate_argnums=(0,) if donate else ())


# ---------------------------------------------------------------------------
# the operator
# ---------------------------------------------------------------------------

class MeshWholeQueryExec(WholeQueryExec):
    """WholeQueryExec over the device mesh: ONE shard_map dispatch per
    retry round, exchanges as in-program collectives. Inherits the
    runtime-degradation contract (a runtime fault past the gang-retry
    budget falls back to the stage tier with the reason recorded) and
    the obs surface (fused_members attribution, degraded_inner)."""

    def graph_name(self) -> str:
        return "MeshWholeQueryExec"

    def simple_string(self):
        n = sum(1 for _ in self.plan.iter_nodes())
        P = self.decision.details.get("mesh_devices")
        return (f"WholeQuery[ops={n}, tier=mesh-whole, mesh={P}] "
                f"({self.decision.reason[:60]})")

    def _execute_whole(self, ctx) -> list:
        from contextlib import nullcontext

        from ..config import DEVICE_MESH_AXIS
        from ..parallel.mesh_exchange import _get_mesh
        from ..parallel.mesh_fusion import (
            StagedBuffers, expected_donation_residue,
        )

        P = int(self.decision.details.get("mesh_devices") or 0)
        axis = str(ctx.conf.get(DEVICE_MESH_AXIS))
        mesh = _get_mesh(P, axis)
        tracer = getattr(ctx, "tracer", None)
        span = tracer.span("whole_query.program", cat="operator",
                           args={"tier": "mesh-whole",
                                 "reason": self.decision.reason,
                                 **{k: v for k, v in
                                    self.decision.details.items()
                                    if isinstance(v, (int, float, str))}}) \
            if tracer is not None else nullcontext()
        seed_rec = getattr(ctx, "persist_seed", None) or {}
        join_caps: list[int] = [int(c) for c in
                                (seed_rec.get("join_caps") or ())]
        if join_caps:
            ctx.metrics.add("cache.capacity_seeded")
        spans_seed = seed_rec.get("join_spans") or None
        mesh_seed = seed_rec.get("mesh_quotas") or {}
        dense_off: set[int] = set()
        quotas: dict[int, int] = {}
        leaf_cache: dict[int, dict] = {}
        use_base = False
        gang = False
        gang_left = _MAX_GANG_RETRIES
        rounds = 0
        try:
            with span:
                while rounds < _MAX_PROGRAM_RETRIES:
                    b = _MeshProgramBuilder(
                        ctx, join_caps, spans_seed=spans_seed,
                        dense_off=dense_off, mesh=mesh, axis=axis,
                        num_shards=P, quotas=quotas,
                        mesh_seed=mesh_seed, leaf_cache=leaf_cache,
                        use_base=use_base, gang=gang)
                    gang = False
                    root = b._to_rep(b.lower(self.plan))
                    key = ("mesh_whole", axis, P,
                           "base" if use_base else "don",
                           tuple(b.key))
                    kernel = GLOBAL_KERNEL_CACHE.get_or_build(
                        key, lambda _b=b, _r=root:
                        _build_mesh_program(_b, _r))
                    staged = StagedBuffers(b.staged)
                    don_args, keep_args = b.split_args()
                    try:
                        with expected_donation_residue():
                            (datas, valids, mask, needed, ovfs, spans,
                             guards) = kernel(don_args, keep_args)
                    except Exception as e:
                        staged.release_all()
                        if not is_runtime_fault(e) or gang_left <= 0:
                            raise
                        # gang retry: ONE fresh attempt. Base planes are
                        # undonated by contract — the rebuilt program
                        # reuses them after _planes_alive proves it
                        # (whole_query.mesh_gang_base_reused)
                        gang_left -= 1
                        gang = True
                        use_base = True
                        ctx.metrics.add("whole_query.mesh_gang_retries")
                        continue
                    staged.release_consumed()
                    # the round's ONE verdict: every capacity scalar of
                    # the single dispatch, applied together
                    bumped = False
                    for i, nd in enumerate(needed):
                        n_i = int(nd)  # tpulint: ignore[host-sync]
                        if n_i > join_caps[i]:
                            join_caps[i] = bucket_capacity(n_i)
                            bumped = True
                    for xid, o in zip(b.x_ids, ovfs):
                        if int(o) > 0:  # tpulint: ignore[host-sync]
                            quotas[xid] = quotas[xid] * 2
                            ctx.metrics.add(
                                "mesh_whole.quota_retries")
                            bumped = True
                    for jid, g in zip(b.guard_jids, guards):
                        if int(g):  # tpulint: ignore[host-sync]
                            dense_off.add(jid)
                            ctx.metrics.add(
                                "whole_query.dense_guard_retries")
                            bumped = True
                    if not bumped:
                        if rounds:
                            ctx.metrics.add(
                                "whole_query.capacity_retries", rounds)
                        ctx.metrics.add("whole_query.dispatches",
                                        rounds + 1)
                        ctx.metrics.add("mesh_whole.dispatches",
                                        rounds + 1)
                        if join_caps:
                            ctx.persist_join_caps = list(join_caps)
                        if b.quota_keys:
                            prior = getattr(ctx, "persist_mesh_quotas",
                                            None) or {}
                            ctx.persist_mesh_quotas = {
                                **prior,
                                **{mk: int(quotas[x])  # tpulint: ignore[host-sync]
                                   for x, mk in b.quota_keys.items()}}
                        if b.dense_joins:
                            ctx.metrics.add("whole_query.dense_probe",
                                            len(b.dense_joins))
                        _record_spans(ctx, b, spans, len(join_caps))
                        schema = attrs_schema(self.output)
                        cols = [Column(f.dataType, d, v,
                                       m.sdict
                                       if dict_encoded(f.dataType)
                                       else None)
                                for f, d, v, m in
                                zip(schema.fields, datas, valids,
                                    root.metas)]
                        batch = ColumnarBatch(schema, cols, mask,
                                              num_rows=None)
                        return [[batch]]
                    rounds += 1
                    use_base = True
                raise ExecutionError(
                    "mesh whole-query program exceeded its retry budget "
                    f"({_MAX_PROGRAM_RETRIES}) — report this plan")
        finally:
            for rec in leaf_cache.values():
                bl = rec.get("base_ledger")
                if bl is not None:
                    bl.release_all()
