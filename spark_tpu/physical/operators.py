"""Physical operators.

Role of the reference's SparkPlan hierarchy (sqlx/SparkPlan.scala:343
doExecute / :359 doExecuteColumnar and the exec nodes under sqlx/). Every
operator here is columnar-only (the reference's ColumnarRule path,
sqlx/Columnar.scala:47, made the default): execute() returns a list of
partitions, each a list of device ColumnarBatches. Blocking operators
(aggregate/sort/join-build) concatenate their partition's batches and run one
fused kernel; XLA plays the role of WholeStageCodegen.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from ..columnar.batch import Column, ColumnarBatch, bucket_capacity
from ..columnar.ops import concat_batches, gather_batch
from ..errors import CapacityOverflowError, ExecutionError, UnsupportedOperationError
from ..exec.context import ExecContext
from ..expr.eval import HostCtx, TraceCtx, Val
from ..expr.expressions import (
    Alias, AttributeReference, Expression, SortOrder,
)
from ..plan.tree import TreeNode
from ..types import (
    BooleanType, StringType, StructField, StructType, int64,
)
from .aggregates import PARTIAL_TO_MERGE, AggSpec
from .compile import (
    GLOBAL_KERNEL_CACHE, ExprPipeline, broadcast_to_cap, canonical_key,
)
from .partitioning import (
    AllTuples, BroadcastDistribution, BroadcastPartitioning,
    ClusteredDistribution, Distribution, HashPartitioning, OrderedDistribution,
    Partitioning, RangePartitioning, SinglePartition, UnknownPartitioning,
    UnspecifiedDistribution,
)

Partition = list  # list[ColumnarBatch]


def _jnp():
    import jax.numpy as jnp

    return jnp


def attrs_schema(attrs: Sequence[AttributeReference]) -> StructType:
    return StructType([StructField(a.name, a.dtype, a.nullable) for a in attrs])


class PhysicalPlan(TreeNode):
    """Base physical operator.

    Every subclass's `execute` is wrapped ONCE at class-creation time
    with per-operator instrumentation (role of SQLMetrics,
    sqlx/metric/SQLMetrics.scala: each SparkPlan carries rows/time
    metrics the UI's plan graph renders) plus the observability layer
    (obs/): a tracer span per operator execute, and a kernel-attribution
    scope so KernelCache launches/compile-ms bucket to the dispatching
    node. The wrapper is a no-op unless the ExecContext carries a
    `plan_metrics` dict or an enabled tracer, so bare runs pay two
    attribute lookups. Collection is sync-free: row counts come from
    host-side batch metadata; device masks are parked and resolved once
    per identity at query end (obs.metrics.finalize_plan_metrics)."""

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        fn = cls.__dict__.get("execute")
        if fn is None or getattr(fn, "_sql_metrics_wrapped", False):
            return

        import functools
        import time as _time

        from ..obs import metrics as _OM

        @functools.wraps(fn)
        def traced(self, ctx, *a, _orig=fn, **k):
            rec = getattr(ctx, "plan_metrics", None)
            tracer = getattr(ctx, "tracer", None)
            if rec is None and tracer is None:
                return _orig(self, ctx, *a, **k)
            name = self.graph_name()
            ent = None
            token = None
            if rec is not None:
                key = getattr(self, "_metric_id", None)
                if key is None:
                    key = id(self)
                # locked insert: the heartbeat flush iterates this dict
                # under the attribution lock (export_op_records_partial)
                ent = _OM.get_or_create_op_record(rec, key)
                if getattr(ctx, "kernel_attribution", True):
                    token = _OM.push_op(ent, name)
            sp = tracer.span(name, cat="operator") if tracer is not None \
                else None
            l0 = ent["launch_total"] if ent is not None else 0
            t0 = _time.perf_counter()
            try:
                if sp is not None:
                    sp.__enter__()
                try:
                    out = _orig(self, ctx, *a, **k)
                finally:
                    if sp is not None:
                        if ent is not None:
                            launched = ent["launch_total"] - l0
                            if launched:
                                sp.set_args({"launches": launched})
                        sp.__exit__(None, None, None)
            finally:
                if token is not None:
                    _OM.pop_op(token)
            if ent is not None:
                ent["ms"] += (_time.perf_counter() - t0) * 1000  # inclusive
                ent["calls"] += 1
                try:
                    for p in out:
                        for b in p:
                            _OM.count_batch(rec, ent, b)
                except Exception:
                    pass                    # non-standard result shape
            return out

        traced._sql_metrics_wrapped = True
        cls.execute = traced

    @property
    def output(self) -> list[AttributeReference]:
        raise NotImplementedError

    def graph_name(self) -> str:
        """Operator-role name the plan graph/UI groups by. Whole-stage
        fused operators report the operator they implement (the reference
        renders the member operators inside a WholeStageCodegen cluster)."""
        return type(self).__name__

    def output_partitioning(self) -> Partitioning:
        ch = self.children
        if ch:
            return ch[0].output_partitioning()
        return UnknownPartitioning(1)

    def required_child_distribution(self) -> list[Distribution]:
        return [UnspecifiedDistribution() for _ in self.children]

    def execute(self, ctx: ExecContext) -> list[Partition]:
        raise NotImplementedError

    def schema(self) -> StructType:
        return attrs_schema(self.output)


# ---------------------------------------------------------------------------
# Scans
# ---------------------------------------------------------------------------

class ScanExec(PhysicalPlan):
    """Columnar scan over a DataSource (role of FileSourceScanExec,
    sqlx/DataSourceScanExec.scala:719, vectorized path)."""

    child_fields = ()

    def __init__(self, source, attrs: list[AttributeReference], name: str = ""):
        self.source = source
        self.attrs = attrs
        self.name = name
        # (partition column name, allowed values) installed at runtime by a
        # joining operator before this scan executes — dynamic partition
        # pruning (reference: sqlx/dynamicpruning/PartitionPruning.scala)
        self.runtime_split_filter = None

    @property
    def output(self):
        return self.attrs

    def output_partitioning(self):
        return UnknownPartitioning(self.source.num_partitions())

    def _split_pruned(self, i: int) -> bool:
        """True if split i cannot contain rows passing the runtime filter.
        Partition count stays stable — pruned splits read as empty."""
        if self.runtime_split_filter is None:
            return False
        from ..io.sources import UNKNOWN_PARTITION_VALUE

        col, allowed = self.runtime_split_filter
        pv = self.source.split_partition_value(i, col)
        if pv is UNKNOWN_PARTITION_VALUE:
            return False  # conservative: value not derivable from layout
        return pv is None or pv not in allowed  # null never equals a key

    def execute(self, ctx: ExecContext) -> list[Partition]:
        from ..columnar.arrow import table_to_batches

        cols = [a.name for a in self.attrs]
        cap = ctx.conf.batch_capacity
        cache = getattr(self.source, "_device_cache", None)
        if cache is None and getattr(self.source, "cache_device_batches", False):
            cache = self.source._device_cache = {}
        schema = attrs_schema(self.attrs)
        out: list[Partition] = []
        for i in range(self.source.num_partitions()):
            if self._split_pruned(i):
                ctx.metrics.add("scan.dpp_pruned_splits")
                out.append([ColumnarBatch.empty(schema)])
                continue
            key = (i, tuple(cols), cap)
            bm = getattr(ctx, "block_manager", None)
            # block id covers the FULL cache key: the same partition
            # projected differently is a distinct pinned entry
            bid = f"scan-{id(self.source)}-{i}-{hash(key) & 0xffffffff:x}"
            if cache is not None and key in cache:
                if bm is not None:
                    bm.touch_device(bid)
                out.append(cache[key])
                continue
            table = self.source.read_partition(i, cols)
            batches = list(table_to_batches(table, cap, schema))
            ctx.metrics.add(f"scan.{self.name}.rows", table.num_rows)
            if cache is not None:
                cache[key] = batches
                if bm is not None:
                    # device-tier governance: LRU-unpin over budget
                    nbytes = sum(b.device_nbytes() for b in batches)
                    bm.pin_device(bid, cache, key, nbytes)
            out.append(batches)
        return out

    def simple_string(self):
        return f"Scan[{self.name}]({', '.join(a.name for a in self.attrs)})"


_LOCAL_TABLE_CACHE: "weakref.WeakKeyDictionary" = None


class LocalTableScanExec(PhysicalPlan):
    child_fields = ()

    def __init__(self, attrs: list[AttributeReference], table):
        self.attrs = attrs
        self.table = table  # pyarrow.Table

    @property
    def output(self):
        return self.attrs

    def output_partitioning(self):
        return SinglePartition()

    def execute(self, ctx: ExecContext) -> list[Partition]:
        import weakref

        from ..columnar.arrow import table_to_batches

        global _LOCAL_TABLE_CACHE
        if _LOCAL_TABLE_CACHE is None:
            _LOCAL_TABLE_CACHE = {}

        # pa.Table is unhashable: key by id with a weakref finalizer so the
        # device batches die with the table. id() values recycle after GC
        # (and weakref callbacks can be skipped when the referent dies in a
        # collected cycle), so a hit must prove the entry still belongs to
        # THIS table — a stale entry here once served another test's batches.
        tid = id(self.table)
        entry = _LOCAL_TABLE_CACHE.get(tid)
        if entry is not None:
            ref = entry.get("ref")
            if ref is None or ref() is not self.table:
                entry = None
        if entry is None:
            try:
                ref = weakref.ref(self.table,
                                  lambda _r, t=tid:
                                  _LOCAL_TABLE_CACHE.pop(t, None))
            except TypeError:
                ref = None
            entry = {"ref": ref, "batches": {}}
            _LOCAL_TABLE_CACHE[tid] = entry

        names = tuple(a.name for a in self.attrs)
        key = (names, ctx.conf.batch_capacity)
        hit = entry["batches"].get(key)
        if hit is not None:
            return [hit]
        tbl = self.table.select(list(names)) if self.table.num_columns \
            else self.table
        batches = list(table_to_batches(tbl, ctx.conf.batch_capacity,
                                        attrs_schema(self.attrs)))
        entry["batches"][key] = batches
        return [batches]


class RangeExec(PhysicalPlan):
    child_fields = ()

    def __init__(self, start: int, end: int, step: int, num_partitions: int,
                 attr: AttributeReference):
        self.start = start
        self.end = end
        self.step = step
        self.num_partitions = max(1, num_partitions)
        self.attr = attr

    @property
    def output(self):
        return [self.attr]

    def output_partitioning(self):
        return UnknownPartitioning(self.num_partitions)

    def execute(self, ctx: ExecContext) -> list[Partition]:
        jnp = _jnp()
        total = max(0, -(-(self.end - self.start) // self.step)) if self.step > 0 \
            else max(0, -(-(self.start - self.end) // -self.step))
        per = -(-total // self.num_partitions)
        parts: list[Partition] = []
        schema = attrs_schema([self.attr])
        tile = ctx.conf.batch_capacity
        for p in range(self.num_partitions):
            lo = min(p * per, total)
            hi = min(lo + per, total)
            batches = []
            for s in range(lo, hi, tile):
                e = min(s + tile, hi)
                n = e - s
                cap = bucket_capacity(n)
                idx = jnp.arange(cap, dtype=jnp.int64)
                data = self.start + (s + idx) * self.step
                mask = idx < n
                batches.append(ColumnarBatch(
                    schema, [Column(self.attr.dtype, data, None, None)],
                    mask, num_rows=n))
            if not batches:
                batches = [ColumnarBatch.empty(schema)]
            parts.append(batches)
        return parts


# ---------------------------------------------------------------------------
# Compute (fused filter+project)
# ---------------------------------------------------------------------------

class ComputeExec(PhysicalPlan):
    """Fused conjunctive filters + projections — one XLA kernel per batch
    (the WholeStageCodegen pipeline analog for narrow operators)."""

    child_fields = ("child",)

    def __init__(self, filters: Sequence[Expression],
                 outputs: Sequence[Expression], child: PhysicalPlan):
        self.filters = list(filters)
        self.outputs = list(outputs)  # Alias | AttributeReference
        self.child = child
        self._pipeline: ExprPipeline | None = None

    @property
    def output(self):
        out = []
        for e in self.outputs:
            if isinstance(e, Alias):
                out.append(e.to_attribute())
            else:
                out.append(e)
        return out

    def output_partitioning(self):
        p = self.child.output_partitioning()
        if isinstance(p, (HashPartitioning, RangePartitioning)):
            out_ids = {a.expr_id for a in self.output}
            exprs = p.exprs if isinstance(p, HashPartitioning) else \
                [o.child for o in p.orders]
            for e in exprs:
                if not (e.references() <= out_ids):
                    return UnknownPartitioning(p.num_partitions)
        return p

    def _get_pipeline(self) -> ExprPipeline:
        if self._pipeline is None:
            self._pipeline = ExprPipeline(
                self.child.output, self.filters, self.outputs,
                attrs_schema(self.output))
        return self._pipeline

    def execute(self, ctx: ExecContext) -> list[Partition]:
        parts = self.child.execute(ctx)
        if not self.filters:
            # pure column reorder/prune: share the child's arrays instead of
            # launching an identity kernel — a computed copy would also be
            # re-staged per downstream dispatch on transfer-bound transports
            pos = {a.expr_id: i for i, a in enumerate(self.child.output)}
            if all(isinstance(e, AttributeReference) and e.expr_id in pos
                   for e in self.outputs):
                schema = attrs_schema(self.output)
                idx = [pos[e.expr_id] for e in self.outputs]

                def reorder(b):
                    nb = ColumnarBatch(schema, [b.columns[i] for i in idx],
                                       b.row_mask, num_rows=b._num_rows)
                    # column objects are shared, so id-keyed per-batch
                    # caches (bloom bitsets) stay valid — keep them; the
                    # dense-range memo is identity-keyed and global
                    nb._stats = b._stats
                    return nb

                return [[reorder(b) for b in part] for part in parts]
        pipe = self._get_pipeline()
        return ctx.par_map(lambda part: [pipe.run(b) for b in part], parts)

    def simple_string(self):
        f = " AND ".join(x.simple_string() for x in self.filters)
        o = ", ".join(x.simple_string() for x in self.outputs)
        s = f"Compute[{o}]"
        if f:
            s += f" WHERE {f}"
        return s


# ---------------------------------------------------------------------------
# Aggregation
# ---------------------------------------------------------------------------

def _batch_stats_cache(batch: ColumnarBatch) -> dict:
    if batch._stats is None:
        batch._stats = {}
    return batch._stats


# Process-global memo of host-synced scalars derived from device arrays,
# keyed by the arrays' identities. Unlike the per-batch `_stats` dict this
# survives re-wrapping the same device columns into fresh ColumnarBatches
# (device-cached scans re-executed per query, reorder projections, repeated
# broadcast probes), so the dense-range decision syncs its two scalars ONCE
# per distinct (column, mask) pair instead of once per batch per run —
# per-batch dispatches then pipeline without a host round-trip in between.
# Implementation lives in utils/device_memo (also used by exchange/sort
# sampling and columnar ingest seeding).
from ..utils.device_memo import (
    DENSE_RANGE_KIND, memo_device_scalars as _memo_device_scalars,
)


def dense_range_stats(kc: Column, row_mask, cap: int):
    """(kmin, kmax, any_live) of an integral key column under `row_mask`,
    memoized across batches sharing the same device arrays (the
    physical/operators dense fast-path decision; one kernel + one two-scalar
    host sync per distinct column/mask identity)."""
    import jax

    jnp = _jnp()

    def compute():
        rkey = ("krange3", cap, str(kc.data.dtype), kc.validity is not None)

        def build_range():
            def kr(k, v, m):
                k = k.astype(jnp.int64)  # cast inside (transport cost)
                if v is not None:
                    m = m & v
                big = jnp.iinfo(jnp.int64).max
                small = jnp.iinfo(jnp.int64).min
                return (jnp.min(jnp.where(m, k, big)),
                        jnp.max(jnp.where(m, k, small)),
                        jnp.any(m))
            return jax.jit(kr)

        kmin_d, kmax_d, any_d = GLOBAL_KERNEL_CACHE.get_or_build(
            rkey, build_range)(kc.data, kc.validity, row_mask)
        return (int(kmin_d), int(kmax_d), bool(any_d))

    return _memo_device_scalars(DENSE_RANGE_KIND,
                                (kc.data, kc.validity, row_mask), compute)


def _group_kernel(num_keys: int, ops: tuple[str, ...], cap: int,
                  key_valid_sig: tuple[bool, ...],
                  val_valid_sig: tuple[bool, ...]):
    """Build the jitted grouped-aggregation kernel (SURVEY.md §7 step 2)."""
    import jax

    from ..ops import grouping as G

    def kernel(key_eqs, key_outs, key_valids, val_datas, val_valids, row_mask):
        layout = G.group_rows(key_eqs, key_valids, row_mask)
        out_keys = []
        for ko, kv in zip(key_outs, key_valids):
            out_keys.append(G.scatter_group_keys(layout, ko, kv))
        bufs = G.apply_group_ops(layout, ops, val_datas, val_valids)
        out_mask = G.group_output_mask(layout)
        return out_keys, bufs, out_mask, layout.num_groups

    return jax.jit(kernel)


def _dense_group_kernel(ops: tuple[str, ...], cap: int, out_cap: int,
                        has_key_valid: bool):
    """Dense-range fast path: a single integral key whose value range fits a
    capacity bucket aggregates by DIRECT scatter-add (`segment_sum` keyed by
    `key - min`) — no sort at all. This is the analog of the reference's
    vectorized hashmap fast path (AggregateBenchmark 'vectorized hashmap'
    rows) and the main bench configuration's hot kernel. NULL keys get the
    last slot."""
    import jax
    from jax import lax

    from ..ops import grouping as G

    def kernel(key, key_valid, kmin, val_datas, val_valids, row_mask):
        jnp = _jnp()
        # cast INSIDE the program: an eager host-side astype would make the
        # key a computed array, which some device transports re-stage on
        # every downstream dispatch (axon tunnel: ~50 MB/s per boundary)
        key = key.astype(jnp.int64)
        seg = (key - kmin).astype(jnp.int32)
        if has_key_valid:
            seg = jnp.where(key_valid, seg, out_cap - 1)
        seg = jnp.where(row_mask, seg, out_cap - 1)
        w_all = row_mask

        present = jax.ops.segment_sum(
            jnp.where(row_mask, 1, 0), seg, num_segments=out_cap)
        # rows parked in the null/inactive slot: count actual nulls there
        if has_key_valid:
            null_rows = jnp.sum((row_mask & ~key_valid).astype(jnp.int64))
        else:
            null_rows = jnp.int64(0)

        bufs = G.apply_dense_ops(seg, out_cap, cap, ops, val_datas,
                                 val_valids, w_all)

        out_keys = kmin + lax.iota(jnp.int64, out_cap)
        out_mask = present > 0
        # the parking slot is a real group only for actual null keys
        out_mask = out_mask.at[out_cap - 1].set(null_rows > 0)
        key_validity = jnp.ones(out_cap, dtype=bool).at[out_cap - 1].set(False)
        return out_keys, key_validity, bufs, out_mask

    return jax.jit(kernel)


def _run_group_kernel(ops: tuple[str, ...], cap: int):
    """RLE-aware grouped aggregation: the key column is already sorted
    (ingest RunInfo), so segments come from run boundaries
    (ops/grouping.group_rows_presorted) and `lax.sort` is skipped — the
    reduce visits each run once. Compiled only when sorted-run metadata
    is actually present (encoded-operand cache-key discipline)."""
    import jax

    from ..ops import grouping as G

    def kernel(key, val_datas, val_valids, row_mask):
        layout = G.group_rows_presorted(key, row_mask)
        out_key = G.scatter_group_keys(layout, key, None)
        bufs = G.apply_group_ops(layout, ops, val_datas, val_valids)
        out_mask = G.group_output_mask(layout)
        return out_key, bufs, out_mask, layout.num_groups

    return jax.jit(kernel)


def _ungrouped_kernel(ops: tuple[str, ...], cap: int,
                      val_valid_sig: tuple[bool, ...], out_cap: int = 8):
    import jax

    from ..ops import grouping as G

    def kernel(val_datas, val_valids, row_mask):
        jnp = _jnp()
        outs = G.apply_global_ops(ops, val_datas, val_valids, row_mask)
        # materialize as 1-row arrays of capacity out_cap
        datas = []
        valids = []
        for d, v in outs:
            arr = jnp.zeros((out_cap,), dtype=d.dtype).at[0].set(d)
            datas.append(arr)
            if v is None:
                valids.append(None)
            else:
                varr = jnp.zeros((out_cap,), dtype=bool).at[0].set(v)
                valids.append(varr)
        mask = jnp.zeros((out_cap,), dtype=bool).at[0].set(True)
        return datas, valids, mask

    return jax.jit(kernel)


class HashAggregateExec(PhysicalPlan):
    """Grouped aggregation via the sort/segment kernel (role of
    HashAggregateExec, sqlx/aggregate/HashAggregateExec.scala:50; the
    lax.sort design replaces UnsafeFixedWidthAggregationMap).

    mode 'partial': values come from spec.input_expr attributes.
    mode 'final':   values are the buffer attrs; ops are merge ops.
    Output (both modes): grouping attrs ++ flattened buffer attrs."""

    child_fields = ("child",)

    def __init__(self, grouping: Sequence[AttributeReference],
                 specs: Sequence[AggSpec], mode: str, child: PhysicalPlan):
        assert mode in ("partial", "final")
        self.grouping = list(grouping)
        self.specs = list(specs)
        self.mode = mode
        self.child = child

    @property
    def output(self):
        out = list(self.grouping)
        for s in self.specs:
            out.extend(s.buffer_attrs)
        return out

    def required_child_distribution(self):
        if self.mode == "partial":
            return [UnspecifiedDistribution()]
        if not self.grouping:
            return [AllTuples()]
        return [ClusteredDistribution(list(self.grouping))]

    def output_partitioning(self):
        return self.child.output_partitioning()

    def _plan_values(self):
        """(op, input attr, param) per buffer column."""
        out = []
        for s in self.specs:
            for i, op in enumerate(s.ops):
                if self.mode == "partial":
                    attr = s.input_expr if op != "countstar" else None
                    out.append((op, attr, s.param))
                else:
                    out.append((PARTIAL_TO_MERGE[op], s.buffer_attrs[i],
                                s.param))
        return out

    def execute(self, ctx: ExecContext) -> list[Partition]:
        from .adaptive import coalesce_after_exchange

        parts = self.child.execute(ctx)
        if self.mode == "final":
            parts = coalesce_after_exchange(self.child, parts, ctx,
                                            self.child.output)
        return ctx.par_map(
            lambda part: [self._aggregate_partition(part, ctx)], parts)

    def _aggregate_partition(self, part: Partition, ctx) -> ColumnarBatch:
        """Aggregate one partition. Partitions larger than the blockwise
        threshold fold incrementally — partial-agg each chunk, then agg the
        accumulated partials — bounding HBM like the reference's
        sort-based spill fallback (TungstenAggregationIterator), but with
        associative merges instead of disk (SURVEY.md §7 'Hard parts' (3))."""
        max_rows = int(ctx.conf.get("spark.tpu.agg.blockRows", 1 << 22))
        if len(part) > 1 and sum(b.capacity for b in part) > max_rows \
                and self.grouping and all(s.mergeable for s in self.specs):
            acc: list[ColumnarBatch] = []
            chunk: list[ColumnarBatch] = []
            cap_sum = 0
            for b in part:
                chunk.append(b)
                cap_sum += b.capacity
                if cap_sum >= max_rows:
                    acc.append(self._aggregate_chunk(chunk, ctx))
                    chunk, cap_sum = [], 0
            if chunk:
                acc.append(self._aggregate_chunk(chunk, ctx))
            # merge accumulated partials (buffer schema) with final-mode ops
            merger = HashAggregateExec(self.grouping, self.specs, "final",
                                       _SchemaOnly(self.output))
            return merger._aggregate_chunk(acc, ctx)
        return self._aggregate_chunk(part, ctx)

    def _aggregate_chunk(self, part: Partition, ctx) -> ColumnarBatch:
        jnp = _jnp()
        batch = concat_batches(part, attrs_schema(self.child.output))
        cap = batch.capacity
        pos = {a.expr_id: i for i, a in enumerate(self.child.output)}

        vals = self._plan_values()
        percentiles: dict[int, tuple] = {}  # buffer idx → (column, q)
        collects: dict[int, tuple] = {}     # buffer idx → (column, dedupe)
        main_vals = []
        for bi, (op, attr, param) in enumerate(vals):
            if op == "percentile":
                percentiles[bi] = (batch.columns[pos[attr.expr_id]], param)
                main_vals.append(("first", attr))  # placeholder, overwritten
            elif op == "collect":
                collects[bi] = (batch.columns[pos[attr.expr_id]],
                                param >= 0.5)
                main_vals.append(("first", attr))  # placeholder, overwritten
            else:
                main_vals.append((op, attr))
        ops = tuple(op for op, _ in main_vals)
        val_datas = []
        val_valids = []
        string_minmax: dict[int, Column] = {}  # buffer idx → source column
        for bi, (op, attr) in enumerate(main_vals):
            if attr is None:
                val_datas.append(batch.row_mask)  # dummy
                val_valids.append(None)
                continue
            c = batch.columns[pos[attr.expr_id]]
            if op in ("min", "max") and c.is_string:
                # strings reduce in RANK space (lexicographic); the winning
                # rank maps back to a dictionary code afterwards
                val_datas.append(c.sort_keys())
                string_minmax[bi] = c
            else:
                val_datas.append(c.data)
            val_valids.append(c.validity)

        out_schema = attrs_schema(self.output)

        if not self.grouping:
            key = ("uagg", ops, cap,
                   tuple(v is not None for v in val_valids),
                   tuple(str(d.dtype) for d in val_datas))
            kernel = GLOBAL_KERNEL_CACHE.get_or_build(
                key, lambda: _ungrouped_kernel(
                    ops, cap, tuple(v is not None for v in val_valids)))
            datas, valids, mask = kernel(val_datas, val_valids, batch.row_mask)
            datas, valids = list(datas), list(valids)
            for bi, (pc, q) in percentiles.items():
                datas[bi], valids[bi] = self._ungrouped_percentile(
                    batch, pc, q, datas[bi].shape[0])
            collect_cols = {
                bi: self._ungrouped_collect(batch, vc, dd,
                                            datas[bi].shape[0],
                                            out_schema.fields[bi].dataType)
                for bi, (vc, dd) in collects.items()}
            cols = [self._finish_buffer(bi, d, v, f, string_minmax,
                                        collect_cols)
                    for bi, (f, d, v) in enumerate(
                        zip(out_schema.fields, datas, valids))]
            return ColumnarBatch(out_schema, cols, mask, num_rows=1)

        key_cols = [batch.columns[pos[g.expr_id]] for g in self.grouping]
        key_eqs = [c.eq_keys() for c in key_cols]
        key_outs = [c.data for c in key_cols]
        key_valids = [c.validity for c in key_cols]

        if not percentiles and not collects:
            dense = self._try_dense(batch, key_cols, ops, val_datas,
                                    val_valids, out_schema, ctx,
                                    string_minmax)
            if dense is not None:
                return dense
            rle = self._try_run_sorted(batch, key_cols, ops, val_datas,
                                       val_valids, out_schema, ctx,
                                       string_minmax)
            if rle is not None:
                return rle

        kkey = ("gagg", len(key_cols), ops, cap,
                tuple(v is not None for v in key_valids),
                tuple(v is not None for v in val_valids),
                tuple(str(d.dtype) for d in key_eqs),
                tuple(str(d.dtype) for d in val_datas))
        kernel = GLOBAL_KERNEL_CACHE.get_or_build(
            kkey, lambda: _group_kernel(
                len(key_cols), ops, cap,
                tuple(v is not None for v in key_valids),
                tuple(v is not None for v in val_valids)))
        out_keys, bufs, out_mask, _ng = kernel(
            key_eqs, key_outs, key_valids, val_datas, val_valids, batch.row_mask)

        bufs = list(bufs)
        for bi, (pc, q) in percentiles.items():
            from ..ops.grouping import group_percentile

            pkey = ("gperc", batch.capacity, len(key_cols), float(q),
                    tuple(str(k.dtype) for k in key_eqs),
                    tuple(v is not None for v in key_valids),
                    str(pc.data.dtype), pc.validity is not None)

            def build_p(q=q):
                import jax

                return jax.jit(lambda ke, kv, vd, vv, m:
                               group_percentile(ke, kv, vd, vv, m, q))

            pk = GLOBAL_KERNEL_CACHE.get_or_build(pkey, build_p)
            pvals, phas = pk(key_eqs, key_valids, pc.data, pc.validity,
                             batch.row_mask)
            bufs[bi] = (pvals, phas)
        collect_cols = {
            bi: self._group_collect(
                batch, key_cols, out_keys, out_mask, vc, dd,
                out_schema.fields[len(key_cols) + bi].dataType)
            for bi, (vc, dd) in collects.items()}
        cols = []
        for (kd, kv), kc, f in zip(out_keys, key_cols,
                                   out_schema.fields[: len(key_cols)]):
            cols.append(Column(f.dataType, kd, kv, kc.dictionary))
        for bi, ((bd, bv), f) in enumerate(
                zip(bufs, out_schema.fields[len(key_cols):])):
            cols.append(self._finish_buffer(bi, bd, bv, f, string_minmax,
                                            collect_cols))
        return ColumnarBatch(out_schema, cols, out_mask, num_rows=None)

    def _ungrouped_percentile(self, batch, pc: Column, q: float,
                              out_cap: int):
        import jax

        from ..ops.grouping import masked_percentile

        jnp = _jnp()
        key = ("uperc", batch.capacity, float(q), str(pc.data.dtype),
               pc.validity is not None, out_cap)

        def build(q=q):
            def kernel(vd, vv, m):
                v, has = masked_percentile(vd, m, vv, q)
                arr = jnp.zeros((out_cap,), dtype=v.dtype).at[0].set(v)
                hv = jnp.zeros((out_cap,), dtype=bool).at[0].set(has)
                return arr, hv

            return jax.jit(kernel)

        k = GLOBAL_KERNEL_CACHE.get_or_build(key, build)
        return k(pc.data, pc.validity, batch.row_mask)

    def _ungrouped_collect(self, batch, vc: Column, dedupe: bool,
                           out_cap: int, out_dtype):
        """collect_list/set with no grouping: one list over all valid rows
        (list order = input row order; reference leaves it unspecified)."""
        jnp = _jnp()
        sel = batch.selection_indices()
        vals = [v for v in vc.to_numpy(sel) if v is not None]
        if dedupe:
            vals = list(dict.fromkeys(vals))
        from ..columnar.batch import StringDict

        return Column(out_dtype, jnp.zeros(out_cap, jnp.int32), None,
                      StringDict([vals]))

    def _group_collect(self, batch, key_cols, out_keys, out_mask,
                       vc: Column, dedupe: bool, out_dtype):
        """Grouped collect: the group structure comes from the device
        kernel; lists are built host-side and matched to the kernel's
        group rows by key tuple (same raw key domain on both sides)."""
        jnp = _jnp()

        def key_tuples(cols, selection):
            arrs = []
            for kd, kv in cols:
                d = np.asarray(kd)[selection]
                v = None if kv is None else np.asarray(kv)[selection]
                arrs.append((d, v))
            return [tuple(None if (v is not None and not v[i])
                          else d[i].item() for d, v in arrs)
                    for i in range(len(selection))]

        sel = batch.selection_indices()
        vals = vc.to_numpy(sel)
        groups: dict[tuple, list] = {}
        for kt, v in zip(key_tuples(
                [(c.data, c.validity) for c in key_cols], sel), vals):
            if v is not None:
                groups.setdefault(kt, []).append(v)

        gm = np.asarray(out_mask)
        gsel = np.nonzero(gm)[0]
        codes = np.zeros(gm.shape[0], np.int32)
        values: list[list] = []
        out_tuples = key_tuples(out_keys, gsel)
        for g, kt in zip(gsel, out_tuples):
            lst = groups.get(kt, [])
            if dedupe:
                lst = list(dict.fromkeys(lst))
            codes[g] = len(values)
            values.append(lst)
        from ..columnar.batch import StringDict

        return Column(out_dtype, jnp.asarray(codes), None,
                      StringDict(values or [[]]))

    def _finish_buffer(self, bi, bd, bv, f, string_minmax,
                       collect_cols=None):
        if collect_cols and bi in collect_cols:
            return collect_cols[bi]
        jnp = _jnp()
        if bi in string_minmax:
            from ..columnar.batch import EMPTY_DICT

            c = string_minmax[bi]
            sd = c.dictionary or EMPTY_DICT
            inv = sd.device_rank_to_code()
            codes = jnp.take(inv, jnp.clip(bd.astype(jnp.int32), 0,
                                           inv.shape[0] - 1))
            return Column(f.dataType, codes, bv, sd)
        want = f.dataType.device_dtype
        if str(bd.dtype) != str(want):
            bd = bd.astype(want)
        return Column(f.dataType, bd, bv, None)

    def _try_dense(self, batch: ColumnarBatch, key_cols, ops, val_datas,
                   val_valids, out_schema, ctx, string_minmax):
        """Dense-range fast path dispatch: single integral key whose value
        span fits a capacity bucket (host syncs two scalars to decide),
        OR a single dictionary-encoded string key — its int32 codes ARE a
        dense domain [0, len(dict)) with the span known host-side
        (len(dictionary)), so the decision never launches the range
        probe and the dictionary decodes the output keys (compressed
        execution: the aggregate groups directly on codes)."""
        import jax

        from ..types import DateType, IntegralType

        jnp = _jnp()
        if len(key_cols) != 1:
            return None
        kc = key_cols[0]
        cap = batch.capacity
        key_dict = None
        if kc.is_string:
            from ..columnar.batch import EMPTY_DICT
            from ..columnar.encoding import encoding_enabled

            if not encoding_enabled(ctx.conf):
                return None
            key_dict = kc.dictionary or EMPTY_DICT
            kmin, span = 0, len(key_dict)
            if span + 1 > min(4 * cap, 1 << 23):
                return None  # mega-dictionary — sort path handles it
            ctx.metrics.add("agg.dict_code_fast_path")
        elif isinstance(kc.dtype, (IntegralType, DateType)):
            kmin, kmax, any_live = dense_range_stats(kc, batch.row_mask,
                                                     cap)
            if not any_live:
                return None
            span = kmax - kmin + 1
            if span + 1 > min(4 * cap, 1 << 23):
                return None  # sparse keys — sort path handles it
        else:
            return None

        out_cap = bucket_capacity(span + 1)
        dkey = ("dagg", ops, cap, out_cap, kc.validity is not None,
                str(kc.data.dtype),
                tuple(str(d.dtype) for d in val_datas),
                tuple(v is not None for v in val_valids))
        kernel = GLOBAL_KERNEL_CACHE.get_or_build(
            dkey, lambda: _dense_group_kernel(
                ops, cap, out_cap, kc.validity is not None))
        out_keys, key_validity, bufs, out_mask = kernel(
            kc.data, kc.validity, jnp.int64(kmin), val_datas, val_valids,
            batch.row_mask)
        ctx.metrics.add("agg.dense_fast_path")

        cols = []
        kf = out_schema.fields[0]
        kdata = out_keys.astype(kf.dataType.device_dtype)
        kv = key_validity if kc.validity is not None else None
        cols.append(Column(kf.dataType, kdata, kv, key_dict))
        for bi, ((bd, bv), f) in enumerate(zip(bufs, out_schema.fields[1:])):
            cols.append(self._finish_buffer(bi, bd, bv, f, string_minmax))
        return ColumnarBatch(out_schema, cols, out_mask, num_rows=None)

    def _try_run_sorted(self, batch: ColumnarBatch, key_cols, ops,
                        val_datas, val_valids, out_schema, ctx,
                        string_minmax):
        """RLE fast path: a single integral key whose ingest RunInfo says
        the live rows are already sorted (no validity plane) reduces per
        RUN BOUNDARY — no grouping sort, no dense table. Reached only
        when the dense-range path declined (sparse span), so clustered
        sparse keys (sorted file reads, post-sort streams) keep a
        sort-free aggregate. Metadata-only decision: zero launches."""
        from ..columnar.encoding import encoding_enabled

        if len(key_cols) != 1:
            return None
        kc = key_cols[0]
        runs = getattr(kc, "runs", None)
        if runs is None or not runs.is_sorted or kc.validity is not None:
            return None
        if not encoding_enabled(ctx.conf):
            return None
        cap = batch.capacity
        rkey = ("ragg", ops, cap, str(kc.data.dtype),
                tuple(str(d.dtype) for d in val_datas),
                tuple(v is not None for v in val_valids))
        kernel = GLOBAL_KERNEL_CACHE.get_or_build(
            rkey, lambda: _run_group_kernel(ops, cap))
        (out_key, out_kv), bufs, out_mask, _ng = kernel(
            kc.data, val_datas, val_valids, batch.row_mask)
        ctx.metrics.add("agg.run_sorted_fast_path")
        cols = [Column(out_schema.fields[0].dataType, out_key, None,
                       kc.dictionary)]
        for bi, ((bd, bv), f) in enumerate(zip(bufs, out_schema.fields[1:])):
            cols.append(self._finish_buffer(bi, bd, bv, f, string_minmax))
        return ColumnarBatch(out_schema, cols, out_mask, num_rows=None)

    def simple_string(self):
        g = ", ".join(a.name for a in self.grouping)
        fns = ", ".join(type(s.func).__name__ for s in self.specs)
        return f"HashAggregate[{self.mode}](keys=[{g}], fns=[{fns}])"


# ---------------------------------------------------------------------------
# Sort / Limit
# ---------------------------------------------------------------------------

class _SchemaOnly(PhysicalPlan):
    """Placeholder child carrying only an output schema (blockwise-agg
    merge step)."""

    child_fields = ()

    def __init__(self, attrs):
        self.attrs = list(attrs)

    @property
    def output(self):
        return self.attrs


class SortExec(PhysicalPlan):
    """In-partition sort (role of sqlx/SortExec.scala:39). Orders must be
    over child output attributes (planner pre-projects complex keys)."""

    child_fields = ("child",)

    def __init__(self, orders: Sequence[SortOrder], child: PhysicalPlan):
        self.orders = list(orders)
        self.child = child
        for o in self.orders:
            assert isinstance(o.child, AttributeReference), \
                "planner must bind sort keys to attributes"

    @property
    def output(self):
        return self.child.output

    def required_child_distribution(self):
        return [UnspecifiedDistribution()]

    def execute(self, ctx: ExecContext) -> list[Partition]:
        from .adaptive import coalesce_after_exchange

        parts = self.child.execute(ctx)
        parts = coalesce_after_exchange(self.child, parts, ctx,
                                        self.child.output)
        return [self._sort_partition(p, ctx) if p else [] for p in parts]

    def _sort_partition(self, part: Partition, ctx) -> Partition:
        """Budget dispatch: a partition that fits the device budget sorts
        as one tile; a larger one takes the external range-bucketed
        multi-pass (physical/external_sort.py, the UnsafeExternalSorter
        role)."""
        schema = attrs_schema(self.child.output)
        budget = ctx.memory.tile_rows(schema, amplification=3)
        if sum(b.capacity for b in part) <= budget:
            return [self._sort_single(part)]
        from .external_sort import external_sort

        return external_sort(part, self.orders, schema, self.child.output,
                             ctx, budget, self._sort_single)

    def _sort_single(self, part: Partition) -> ColumnarBatch:
        import jax

        from ..ops.sorting import SortKeySpec, sort_permutation

        jnp = _jnp()
        batch = concat_batches(part, attrs_schema(self.child.output))
        pos = {a.expr_id: i for i, a in enumerate(self.child.output)}
        keys = []
        valids = []
        specs = []
        for o in self.orders:
            c = batch.columns[pos[o.child.expr_id]]
            keys.append(c.sort_keys())
            valids.append(c.validity)
            specs.append(SortKeySpec(o.ascending, o.nulls_first))

        cap = batch.capacity
        skey = ("sort", cap, tuple((s.ascending, s.nulls_first) for s in specs),
                tuple(str(k.dtype) for k in keys),
                tuple(v is not None for v in valids),
                tuple((str(c.data.dtype), c.validity is not None)
                      for c in batch.columns))

        def build():
            def kernel(keys, valids, datas, dvalids, row_mask):
                perm = sort_permutation(keys, valids, specs, row_mask)
                out_d = [jnp.take(d, perm) for d in datas]
                out_v = [None if v is None else jnp.take(v, perm)
                         for v in dvalids]
                return out_d, out_v, jnp.take(row_mask, perm)

            return jax.jit(kernel)

        kernel = GLOBAL_KERNEL_CACHE.get_or_build(skey, build)
        datas = [c.data for c in batch.columns]
        dvalids = [c.validity for c in batch.columns]
        out_d, out_v, out_mask = kernel(keys, valids, datas, dvalids,
                                        batch.row_mask)
        cols = [Column(c.dtype, d, v, c.dictionary)
                for c, d, v in zip(batch.columns, out_d, out_v)]
        return ColumnarBatch(batch.schema, cols, out_mask, batch._num_rows)

    def simple_string(self):
        o = ", ".join(
            f"{x.child.simple_string()} {'ASC' if x.ascending else 'DESC'}"
            for x in self.orders)
        return f"Sort[{o}]"


class LimitExec(PhysicalPlan):
    """Keep first n live rows per partition (LocalLimit); with a single
    child partition this is GlobalLimit (reference: sqlx/limit.scala)."""

    child_fields = ("child",)

    def __init__(self, n: int, child: PhysicalPlan, offset: int = 0,
                 is_global: bool = False):
        self.n = n
        self.offset = offset
        self.is_global = is_global
        self.child = child

    @property
    def output(self):
        return self.child.output

    def required_child_distribution(self):
        return [AllTuples()] if self.is_global else [UnspecifiedDistribution()]

    def execute(self, ctx: ExecContext) -> list[Partition]:
        return [self._limit_partition(part, ctx)
                for part in self.child.execute(ctx)]

    def _limit_partition(self, part: Partition, ctx) -> Partition:
        import jax

        jnp = _jnp()
        if not part:
            return []
        batch = concat_batches(part, attrs_schema(self.output))
        cap = batch.capacity
        key = ("limit", cap, self.n, self.offset)

        def build():
            def kernel(mask):
                rank = jnp.cumsum(mask.astype(jnp.int64))
                keep = mask & (rank > self.offset) & \
                    (rank <= self.offset + self.n)
                return keep

            return jax.jit(kernel)

        kernel = GLOBAL_KERNEL_CACHE.get_or_build(key, build)
        new_mask = kernel(batch.row_mask)
        limited = ColumnarBatch(batch.schema, batch.columns, new_mask,
                                num_rows=None)
        # a local limit leaves ≤ n live rows in a full-capacity tile;
        # compact so the gather exchange and downstream sort touch only
        # the kept rows (the TakeOrderedAndProject shrink)
        if not self.is_global and self.n * 4 <= cap:
            from ..columnar.ops import compact_batch

            limited = compact_batch(limited)
        return [limited]


# ---------------------------------------------------------------------------
# Joins
# ---------------------------------------------------------------------------

class HashJoinExec(PhysicalPlan):
    """Equi-join via the sorted-probe kernel (role of ShuffledHashJoinExec /
    BroadcastHashJoinExec, sqlx/joins/). The right side is the build side;
    the planner flips right-joins into left joins over swapped children."""

    child_fields = ("left", "right")

    def __init__(self, left_keys: Sequence[AttributeReference],
                 right_keys: Sequence[AttributeReference], join_type: str,
                 left: PhysicalPlan, right: PhysicalPlan,
                 is_broadcast: bool = False):
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.join_type = join_type  # inner/left_outer/left_semi/left_anti/full_outer
        self.left = left
        self.right = right
        self.is_broadcast = is_broadcast
        # [(ScanExec, key index)] injected by the planner: probe-side scans
        # whose partition column is a join key — executing the build side
        # first lets those scans skip whole splits (DPP)
        self.dpp_targets: list = []
        # whole-stage fusion splice (physical/fusion.py FuseStages): when a
        # filter/project pipeline fed this join's probe side, its
        # (filters, outputs) trace inside the probe kernel and `left` is the
        # pipeline's child. probe_attrs = the pipeline's output attributes —
        # the join's probe-side schema from the outside.
        self.probe_fusion: tuple | None = None
        self.probe_attrs: list | None = None
        self._probe_pipe_cache: ExprPipeline | None = None

    @property
    def _left_attrs(self) -> list:
        """Probe-side output attributes as consumers see them (after the
        fused pipeline when one is spliced in)."""
        return self.probe_attrs if self.probe_fusion is not None \
            else self.left.output

    def _probe_pipeline(self) -> "ExprPipeline | None":
        if self.probe_fusion is None:
            return None
        if self._probe_pipe_cache is None:
            filters, outputs = self.probe_fusion
            self._probe_pipe_cache = ExprPipeline(
                self.left.output, filters, outputs,
                attrs_schema(self.probe_attrs))
        return self._probe_pipe_cache

    @property
    def output(self):
        if self.join_type in ("left_semi", "left_anti"):
            return self._left_attrs
        ro = self.right.output
        lo = self._left_attrs
        if self.join_type in ("left_outer", "full_outer"):
            ro = [a.with_nullability(True) for a in ro]
        if self.join_type == "full_outer":
            lo = [a.with_nullability(True) for a in lo]
        return lo + ro

    def required_child_distribution(self):
        if self.is_broadcast:
            return [UnspecifiedDistribution(), BroadcastDistribution()]
        return [ClusteredDistribution(list(self.left_keys)),
                ClusteredDistribution(list(self.right_keys))]

    def output_partitioning(self):
        return self.left.output_partitioning()

    def execute(self, ctx: ExecContext) -> list[Partition]:
        from .adaptive import coalesce_join_inputs

        if self.dpp_targets:
            # build first; its distinct keys prune probe-side splits
            right_parts = self.right.execute(ctx)
            self._install_dpp_filters(right_parts, ctx)
            left_parts = self.left.execute(ctx)
        else:
            left_parts = self.left.execute(ctx)
            right_parts = self.right.execute(ctx)
        if self.is_broadcast:
            # broadcast exchange produced one partition; replicate
            bp = right_parts[0]
            right_parts = [bp for _ in left_parts]
        else:
            from .adaptive import split_skewed_join_inputs

            left_parts, right_parts = coalesce_join_inputs(
                self.left, self.right, left_parts, right_parts, ctx,
                self.left.output, self.right.output)
            left_parts, right_parts = split_skewed_join_inputs(
                left_parts, right_parts, ctx, self.join_type)
        if len(left_parts) != len(right_parts):
            raise ExecutionError(
                f"join children partition counts differ: "
                f"{len(left_parts)} vs {len(right_parts)}")
        probe_pipe = self._probe_pipeline()
        if probe_pipe is not None and (
                self.join_type == "full_outer"
                or ctx.conf.get("spark.tpu.join.runtimeFilter", False)
                or ctx.conf.get("spark.tpu.join.runtimeFilter.bloom",
                                False)):
            # paths that read probe key columns outside the probe kernel
            # (anti-join of build vs probe keys, runtime filters):
            # materialize the pipeline up front and join as if unfused
            left_parts = [[probe_pipe.run(b) for b in p]
                          for p in left_parts]
            probe_pipe = None
        rschema = attrs_schema(self.right.output)
        lschema = attrs_schema(self.left.output if probe_pipe is not None
                               else self._left_attrs)
        return ctx.par_map(
            lambda pair: self._join_partition(pair[0], pair[1], lschema,
                                              rschema, ctx,
                                              probe_pipe=probe_pipe),
            list(zip(left_parts, right_parts)))

    def _install_dpp_filters(self, right_parts, ctx) -> None:
        """Distinct build-side key values → runtime split filters on the
        probe scans (reference: PartitionPruning's duplicated build
        subquery; here the materialized build side IS the value source, so
        nothing is executed twice)."""
        from ..config import DPP_BUILD_THRESHOLD

        max_rows = int(ctx.conf.get(DPP_BUILD_THRESHOLD))
        total = sum(b.num_rows() for p in right_parts for b in p)
        rpos = {a.expr_id: i for i, a in enumerate(self.right.output)}
        values_by_key: dict[int, set] = {}
        for scan, key_idx in self.dpp_targets:
            if total > max_rows:
                scan.runtime_split_filter = None
                continue
            values = values_by_key.get(key_idx)
            if values is None:
                ci = rpos[self.right_keys[key_idx].expr_id]
                values = set()
                for part in right_parts:
                    for b in part:
                        arr = b.columns[ci].to_numpy(b.selection_indices())
                        if arr.dtype == object:
                            arr = np.array([v for v in arr if v is not None],
                                           dtype=object)
                        if len(arr):
                            values.update(
                                v.item() if hasattr(v, "item") else v
                                for v in np.unique(arr))
                values_by_key[key_idx] = values
            col_name = scan.attrs[self._dpp_attr_index(scan, key_idx)].name
            scan.runtime_split_filter = (col_name, values)

    def _dpp_attr_index(self, scan, key_idx: int) -> int:
        target = self.left_keys[key_idx].expr_id
        for i, a in enumerate(scan.attrs):
            if a.expr_id == target:
                return i
        raise KeyError(target)

    def _join_partition(self, lp: Partition, rp: Partition, lschema, rschema,
                        ctx, _depth: int = 0, probe_pipe=None) -> Partition:
        import jax

        from ..ops import joining as J

        jnp = _jnp()
        if probe_pipe is not None:
            from ..config import FUSION_MIN_ROWS

            if sum(b.capacity for b in lp) < int(ctx.conf.get(
                    FUSION_MIN_ROWS)):
                # partition too small to amortize a per-structure fused
                # probe compile: run the shared pipeline + probe kernels
                lp = [probe_pipe.run(b) for b in lp]
                lschema = attrs_schema(self._left_attrs)
                probe_pipe = None
        # Grace hash join (memory discipline): a build side over the device
        # budget is hash-fragmented together with its probe side — same key
        # hash, same fragment — and each fragment joins independently
        # (role of the reference's spillable HashedRelation fallback;
        # exec/memory.py is the budget authority). Depth guard: one level —
        # re-hashing with the same function cannot split further.
        if rp and _depth == 0:
            budget = ctx.memory.tile_rows(rschema, amplification=4)
            build_cap = sum(b.capacity for b in rp)
            if build_cap > budget:
                if probe_pipe is not None:
                    # grace fragments by computed key columns: materialize
                    lp = [probe_pipe.run(b) for b in lp]
                    lschema = attrs_schema(self._left_attrs)
                return self._grace_join(lp, rp, lschema, rschema, ctx,
                                        budget, build_cap)
        build = concat_batches(rp, rschema) if rp else ColumnarBatch.empty(rschema)
        # mesh partitions are committed to their device; the build side and
        # every probe batch must share one before a kernel can see both
        # (broadcast batch vs mesh partition, AQE-coalesced neighbours)
        from ..columnar.ops import _device_of, batch_to_device

        bdev = _device_of(build.row_mask)
        if bdev is not None and lp:
            lp = [pb if _device_of(pb.row_mask) in (None, bdev)
                  else batch_to_device(pb, bdev) for pb in lp]
        rpos = {a.expr_id: i for i, a in enumerate(self.right.output)}
        lpos = {a.expr_id: i for i, a in enumerate(self._left_attrs)}
        bkeys = [build.columns[rpos[k.expr_id]] for k in self.right_keys]

        dense = self._try_dense_build(build, bkeys, ctx)
        if dense is not None:
            out_batches = [
                self._dense_probe_batch(pb, build, dense, lpos, ctx,
                                        probe_pipe)
                for pb in (lp or [ColumnarBatch.empty(lschema)])]
            if self.join_type == "full_outer":
                out_batches.append(
                    self._unmatched_build_rows(lp, build, lschema, ctx))
            return out_batches

        bkey_eqs = [c.eq_keys() for c in bkeys]
        bkey_valids = [c.validity for c in bkeys]

        from ..types import DateType, DecimalType, IntegralType

        if self.join_type in ("inner", "left_semi") and len(bkeys) == 1 \
                and isinstance(bkeys[0].dtype,
                               (IntegralType, DateType, DecimalType)) \
                and ctx.conf.get("spark.tpu.join.runtimeFilter", False):
            lp = self._range_filter_probe(lp, build, bkeys, bkey_valids,
                                          lpos, ctx)
        if self.join_type in ("inner", "left_semi") \
                and ctx.conf.get("spark.tpu.join.runtimeFilter.bloom", False):
            lp = self._bloom_filter_probe(lp, build, bkeys, bkey_valids,
                                          lpos, ctx)

        bi_key = ("join_build", build.capacity, len(bkeys),
                  tuple(str(k.dtype) for k in bkey_eqs),
                  tuple(v is not None for v in bkey_valids))

        def build_bi():
            return jax.jit(lambda eqs, valids, mask: J.build_index(eqs, valids, mask))

        bi_kernel = GLOBAL_KERNEL_CACHE.get_or_build(bi_key, build_bi)
        bindex = bi_kernel(bkey_eqs, bkey_valids, build.row_mask)

        out_batches = []
        for pb in (lp or [ColumnarBatch.empty(lschema)]):
            out_batches.append(
                self._probe_batch(pb, build, bindex, bkey_eqs, bkey_valids,
                                  lpos, ctx, probe_pipe))
        if self.join_type == "full_outer":
            out_batches.append(
                self._unmatched_build_rows(lp, build, lschema, ctx))
        return out_batches

    def _range_filter_probe(self, lp, build, bkeys, bkey_valids, lpos, ctx):
        """Runtime min-max join filter (reference: InjectRuntimeFilter /
        bloom pushdown, simplified to a range): probe rows outside the
        build key range can't match an inner/semi join, so they drop
        BEFORE the O(cap log cap) sort-probe; batches that shrink enough
        compact to a smaller capacity bucket. Default OFF: on the 2-core
        CPU VM the filter+sync overhead beats the smaller sort; benchmark
        on a live chip (where lax.sort dominates) before enabling."""
        import jax

        from ..columnar.ops import compact_batch

        jnp = _jnp()
        bc = bkeys[0]
        rkey = ("join_rf_range", build.capacity, str(bc.data.dtype),
                bc.validity is not None)

        def build_range():
            def kr(k, v, m):
                k64 = k.astype(jnp.int64)
                live = m if v is None else (m & v)
                big = jnp.iinfo(jnp.int64).max
                small = jnp.iinfo(jnp.int64).min
                return (jnp.min(jnp.where(live, k64, big)),
                        jnp.max(jnp.where(live, k64, small)))

            return jax.jit(kr)

        kr = GLOBAL_KERNEL_CACHE.get_or_build(rkey, build_range)
        bmin, bmax = kr(bc.data, bc.validity, build.row_mask)

        min_cap = int(ctx.conf.get(
            "spark.tpu.join.runtimeFilter.minCapacity", 1 << 20))
        out = []
        for pb in (lp or []):
            if pb.capacity < min_cap:
                out.append(pb)  # small batch: the sort-probe is cheap
                continue
            pc = pb.columns[lpos[self.left_keys[0].expr_id]]
            fkey = ("join_rf_mask", pb.capacity, str(pc.data.dtype),
                    pc.validity is not None)

            def build_mask():
                def km(k, v, m, lo, hi):
                    k64 = k.astype(jnp.int64)
                    keep = (k64 >= lo) & (k64 <= hi)
                    if v is not None:
                        keep = keep & v
                    nm = m & keep
                    return nm, jnp.sum(nm)

                return jax.jit(km)

            km = GLOBAL_KERNEL_CACHE.get_or_build(fkey, build_mask)
            nm, live = km(pc.data, pc.validity, pb.row_mask, bmin, bmax)
            live = int(live)
            nb = ColumnarBatch(pb.schema, pb.columns, nm, num_rows=live)
            if bucket_capacity(max(live, 1)) <= pb.capacity // 16:
                nb = compact_batch(nb)
                ctx.metrics.add("join.runtime_filter_compactions")
            out.append(nb)
        return out

    def _bloom_filter_probe(self, lp, build, bkeys, bkey_valids, lpos, ctx):
        """Runtime bloom join filter (reference: InjectRuntimeFilter.scala
        bloom branch + BloomFilterImpl): a device bitset of build-key hashes
        drops probe rows that cannot match an inner/semi join before the
        sort-probe. Works for any key arity/type (the hash domain is
        hash_columns), unlike the single-integral-key min-max filter. The
        bitset is two scatter-sets at build + two gathers at probe — all
        inside XLA; k=2 with ≥8 bits/row keeps the false-positive rate
        under ~5%."""
        import jax

        from ..columnar.ops import compact_batch
        from ..ops.hashing import hash_columns, mix64

        jnp = _jnp()
        nbits = min(1 << 24, bucket_capacity(max(build.capacity, 1) * 8))
        bkey_eqs = [c.eq_keys() for c in bkeys]

        bkey2 = ("join_rf_bloom_build", build.capacity, nbits, len(bkeys),
                 tuple(str(k.dtype) for k in bkey_eqs),
                 tuple(v is not None for v in bkey_valids))

        from ..utils.sketch import bloom_position_offsets

        off0, off1 = bloom_position_offsets(2)

        def build_bloom():
            def kb(eqs, valids, mask):
                h = hash_columns(eqs, list(valids))
                p1 = mix64(h + jnp.int64(off0)) & (nbits - 1)
                p2 = mix64(h + jnp.int64(off1)) & (nbits - 1)
                p1 = jnp.where(mask, p1, nbits)
                p2 = jnp.where(mask, p2, nbits)
                bits = jnp.zeros(nbits, dtype=bool)
                bits = bits.at[p1].set(True, mode="drop")
                bits = bits.at[p2].set(True, mode="drop")
                return bits

            return jax.jit(kb)

        # a broadcast join probes the SAME build batch once per partition —
        # memoize the bitset on the batch so the scatter-build runs once
        bstats = _batch_stats_cache(build)
        mkey = ("bloom_bits", nbits,
                tuple(k.expr_id for k in self.right_keys))
        bits = bstats.get(mkey)
        if bits is None:
            bits = GLOBAL_KERNEL_CACHE.get_or_build(bkey2, build_bloom)(
                bkey_eqs, bkey_valids, build.row_mask)
            bstats[mkey] = bits

        out = []
        for pb in (lp or []):
            pkeys = [pb.columns[lpos[k.expr_id]] for k in self.left_keys]
            pkey_eqs = [c.eq_keys() for c in pkeys]
            pkey_valids = [c.validity for c in pkeys]
            fkey = ("join_rf_bloom_probe", pb.capacity, nbits, len(pkeys),
                    tuple(str(k.dtype) for k in pkey_eqs),
                    tuple(v is not None for v in pkey_valids))

            def probe_bloom():
                def kp(bits, eqs, valids, mask):
                    h = hash_columns(eqs, list(valids))
                    keep = jnp.take(bits, mix64(h + jnp.int64(off0))
                                    & (nbits - 1)) \
                        & jnp.take(bits, mix64(h + jnp.int64(off1))
                                   & (nbits - 1))
                    nm = mask & keep
                    return nm, jnp.sum(nm)

                return jax.jit(kp)

            nm, live = GLOBAL_KERNEL_CACHE.get_or_build(fkey, probe_bloom)(
                bits, pkey_eqs, pkey_valids, pb.row_mask)
            before = pb.num_rows()
            live = int(live)
            ctx.metrics.add("join.bloom_filtered_rows", before - live)
            nb = ColumnarBatch(pb.schema, pb.columns, nm, num_rows=live)
            if bucket_capacity(max(live, 1)) <= pb.capacity // 16:
                nb = compact_batch(nb)
                ctx.metrics.add("join.runtime_filter_compactions")
            out.append(nb)
        return out

    def _probe_batch(self, pb: ColumnarBatch, build: ColumnarBatch, bindex,
                     bkey_eqs, bkey_valids, lpos, ctx,
                     probe_pipe=None) -> ColumnarBatch:
        import jax

        from ..ops import joining as J

        jnp = _jnp()
        jt = self.join_type if self.join_type != "full_outer" else "left_outer"
        if probe_pipe is not None:
            pb, r = self._fused_probe(pb, bindex, bkey_eqs, bkey_valids,
                                      ctx, jt)
        else:
            pkeys = [pb.columns[lpos[k.expr_id]] for k in self.left_keys]
            pkey_eqs = [c.eq_keys() for c in pkeys]
            pkey_valids = [c.validity for c in pkeys]

            out_cap = max(pb.capacity, 1 << 10)
            while True:
                key = ("join_probe", jt, pb.capacity, build.capacity, out_cap,
                       len(pkey_eqs), tuple(str(k.dtype) for k in pkey_eqs),
                       tuple(v is not None for v in pkey_valids),
                       tuple(v is not None for v in bkey_valids))

                def build_kernel(oc=out_cap):
                    def kernel(bidx_sorted, bidx_perm, beqs, bvalids, peqs,
                               pvalids, pmask):
                        bi = J.BuildSide(bidx_sorted, bidx_perm)
                        return J.probe_join(bi, beqs, bvalids, peqs, pvalids,
                                            pmask, oc, jt)

                    return jax.jit(kernel)

                kernel = GLOBAL_KERNEL_CACHE.get_or_build(key, build_kernel)
                r = kernel(bindex.sorted_hash, bindex.perm, bkey_eqs,
                           bkey_valids, pkey_eqs, pkey_valids, pb.row_mask)
                needed = int(r.needed)
                if needed <= out_cap:
                    break
                out_cap = bucket_capacity(needed)
                ctx.metrics.add("join.capacity_retry")

        probe_out = gather_batch(pb, r.probe_idx, r.out_mask)
        if self.join_type in ("left_semi", "left_anti"):
            return probe_out
        null_build = ~r.matched
        build_out = gather_batch(build, r.build_idx, r.out_mask,
                                 extra_invalid=null_build)
        schema = attrs_schema(self.output)
        cols = probe_out.columns + build_out.columns
        return ColumnarBatch(schema, cols, r.out_mask, num_rows=None)

    def _fused_probe(self, pb: ColumnarBatch, bindex, bkey_eqs, bkey_valids,
                     ctx, jt):
        """Whole-stage fused probe: the probe-side filter/project pipeline
        traces INSIDE the probe kernel — one dispatch computes the projected
        columns, derives the join keys, and probes the build index (the
        consume splice of the reference's codegen'd
        BroadcastHashJoinExec.doConsume). Returns the COMPUTED probe batch
        plus the probe result; the caller's gathers read the computed
        columns."""
        import jax

        from ..ops import joining as J
        from .compile import (
            pipeline_columns, pipeline_host_pass, pipeline_signature,
            trace_pipeline,
        )
        from ..types import BooleanType

        jnp = _jnp()
        from ..columnar.batch import EMPTY_DICT
        from ..types import StringType

        filters, outputs = self.probe_fusion
        input_attrs = self.left.output
        pipe = self._probe_pipeline()
        cap = pb.capacity
        hctx, host_outs, aux = pipeline_host_pass(input_attrs, filters,
                                                  outputs, pb)
        opos = {a.expr_id: i for i, a in enumerate(self.probe_attrs)}
        kidx = tuple(opos[k.expr_id] for k in self.left_keys)
        key_bool = tuple(isinstance(self.probe_attrs[i].dtype, BooleanType)
                         for i in kidx)
        # string probe keys: padded dictionary-hash luts ride as kernel
        # aux inputs so eq_keys (codes → stable value hashes) computes
        # INSIDE the trace — the former unfused string-probe fallback is
        # retired (compressed execution)
        dict_pos = {i: j for j, i in enumerate(
            i for i in kidx
            if isinstance(self.probe_attrs[i].dtype, StringType))}
        kluts = [(host_outs[i].sdict or EMPTY_DICT).device_hash_lut()
                 for i in dict_pos]
        in_sig = pipeline_signature(pb)

        out_cap = max(cap, 1 << 10)
        while True:
            kkey = ("fused_probe", jt, pipe._struct_key, cap,
                    bindex.perm.shape[0], out_cap, kidx, in_sig,
                    hctx.signature(), tuple(v is not None
                                            for v in bkey_valids),
                    tuple(sorted(dict_pos)),
                    tuple(int(l.shape[0])  # tpulint: ignore[host-sync]
                          for l in kluts))

            def build_kernel(oc=out_cap):
                def kernel(bidx_sorted, bidx_perm, beqs, bvalids, datas,
                           valids, pmask, aux, kluts):
                    out_datas, out_valids, mask = trace_pipeline(
                        input_attrs, filters, outputs, datas, valids, pmask,
                        aux, cap)
                    peqs = []
                    pvalids = []
                    for i, is_bool in zip(kidx, key_bool):
                        kd = out_datas[i]
                        if is_bool:
                            kd = kd.astype(jnp.int32)
                        if i in dict_pos:
                            lut = kluts[dict_pos[i]]
                            kd = jnp.take(lut, jnp.clip(
                                kd.astype(jnp.int32), 0,
                                lut.shape[0] - 1))
                        peqs.append(kd)
                        pvalids.append(out_valids[i])
                    bi = J.BuildSide(bidx_sorted, bidx_perm)
                    r = J.probe_join(bi, beqs, bvalids, peqs, pvalids,
                                     mask, oc, jt)
                    return r, out_datas, out_valids, mask

                return jax.jit(kernel)

            kernel = GLOBAL_KERNEL_CACHE.get_or_build(kkey, build_kernel)
            r, out_datas, out_valids, mask = kernel(
                bindex.sorted_hash, bindex.perm, bkey_eqs, bkey_valids,
                [c.data for c in pb.columns],
                [c.validity for c in pb.columns], pb.row_mask, aux, kluts)
            needed = int(r.needed)
            if needed <= out_cap:
                break
            out_cap = bucket_capacity(needed)
            ctx.metrics.add("join.capacity_retry")

        pschema = attrs_schema(self.probe_attrs)
        cols = pipeline_columns(pschema.fields, host_outs, out_datas,
                                out_valids)
        computed = ColumnarBatch(pschema, cols, mask, num_rows=None)
        return computed, r

    def _grace_join(self, lp: Partition, rp: Partition, lschema, rschema,
                    ctx, budget_rows: int, build_cap: int) -> Partition:
        """Fragment both sides by join-key hash and join fragment-wise.
        Equal keys co-locate, so every join type distributes over the
        fragments (full_outer's unmatched-build emission runs per
        fragment against that fragment's probe rows only)."""
        from ..exec import shuffle as S

        nfrag = -(-build_cap // max(budget_rows, 1))
        nfrag = min(256, 1 << max(1, (nfrag - 1).bit_length()))
        rpos = {a.expr_id: i for i, a in enumerate(self.right.output)}
        lpos = {a.expr_id: i for i, a in enumerate(self._left_attrs)}
        rk = [rpos[k.expr_id] for k in self.right_keys]
        lk = [lpos[k.expr_id] for k in self.left_keys]
        # distinct seed: the inputs are already hash-partitioned on these
        # keys with the exchange's default seed — reusing it would send the
        # whole partition to one fragment (h % nfrag constant)
        r_frags = S.shuffle_hash([rp], rk, nfrag, rschema, ctx,
                                 seed=0x9E3779B9)
        l_frags = S.shuffle_hash([lp], lk, nfrag, lschema, ctx,
                                 seed=0x9E3779B9)
        ctx.memory.count("join.grace.fragments", nfrag)
        out: Partition = []
        for lf, rf in zip(l_frags, r_frags):
            out.extend(self._join_partition(lf, rf, lschema, rschema, ctx,
                                            _depth=1))
        return out

    def _try_dense_build(self, build: ColumnarBatch, bkeys, ctx):
        """Dense unique-key build fast path (TPC-DS dimension tables: dense
        integral primary keys): the 'hash table' is a direct-address row
        index, the probe a single gather — no sort, no searchsorted, no
        expansion (probe output is 1:1). Falls back when keys are multi,
        non-integral, sparse, or duplicated."""
        import jax

        from ..types import DateType, IntegralType

        jnp = _jnp()
        if len(bkeys) != 1:
            return None
        kc = bkeys[0]
        if not isinstance(kc.dtype, (IntegralType, DateType)):
            return None
        cap = build.capacity

        kmin, kmax, any_live = dense_range_stats(kc, build.row_mask, cap)
        if not any_live:
            return None
        span = kmax - kmin + 1
        if span > min(8 * cap, 1 << 23):
            return None

        tcap = bucket_capacity(span)
        tkey = ("djoin_build", cap, tcap, str(kc.data.dtype),
                kc.validity is not None)

        def build_table():
            from jax import lax

            def kt(k, v, rm, kmin_s):
                k = k.astype(jnp.int64)  # cast inside (transport cost)
                m = rm if v is None else (rm & v)
                slot = jnp.where(m, k - kmin_s, tcap)
                rowidx = jnp.full((tcap,), 0, jnp.int32).at[slot].set(
                    lax.iota(jnp.int32, cap), mode="drop")
                cnt = jnp.zeros((tcap,), jnp.int32).at[slot].add(
                    1, mode="drop")
                return rowidx, cnt, jnp.max(cnt)

            return jax.jit(kt)

        rowidx, present, maxc_d = GLOBAL_KERNEL_CACHE.get_or_build(
            tkey, build_table)(kc.data, kc.validity, build.row_mask,
                               jnp.int64(kmin))
        # the duplicate-key verdict is one scalar: memoize it per build
        # column identity so a broadcast build probed from many partitions
        # syncs once, not once per partition
        maxc = _memo_device_scalars(
            ("djoin_maxc", tcap), (kc.data, kc.validity, build.row_mask),
            lambda: int(maxc_d))
        if maxc > 1:
            return None  # duplicate build keys → sorted-probe path
        ctx.metrics.add("join.dense_fast_path")
        return {"rowidx": rowidx, "present": present, "kmin": kmin,
                "tcap": tcap}

    def _dense_probe_batch(self, pb: ColumnarBatch, build: ColumnarBatch,
                           dense, lpos, ctx, probe_pipe=None) -> ColumnarBatch:
        import jax

        jnp = _jnp()
        cap = pb.capacity
        tcap = dense["tcap"]
        jt = self.join_type if self.join_type != "full_outer" else "left_outer"

        def probe_body(k64, pvalid, pmask, rowidx, present, kmin_s):
            k = k64 - kmin_s
            in_range = (k >= 0) & (k < tcap)
            slot = jnp.clip(k, 0, tcap - 1)
            usable = pmask & in_range
            if pvalid is not None:
                usable = usable & pvalid
            matched = usable & (jnp.take(present, slot) > 0)
            bidx = jnp.take(rowidx, slot)
            if jt == "inner":
                out_mask = matched
            elif jt == "left_outer":
                out_mask = pmask
            elif jt == "left_semi":
                out_mask = matched
            else:  # left_anti
                out_mask = pmask & ~matched
            return bidx, matched, out_mask

        if probe_pipe is not None:
            # fused: the probe-side pipeline traces inside the dense-probe
            # kernel; the computed batch comes back with the probe result
            from .compile import (
                pipeline_columns, pipeline_host_pass, pipeline_signature,
                trace_pipeline,
            )

            filters, outputs = self.probe_fusion
            input_attrs = self.left.output
            hctx, host_outs, aux = pipeline_host_pass(input_attrs, filters,
                                                      outputs, pb)
            opos = {a.expr_id: i for i, a in enumerate(self.probe_attrs)}
            ki = opos[self.left_keys[0].expr_id]
            pipe = self._probe_pipeline()
            key = ("fused_djoin_probe", jt, pipe._struct_key, cap, tcap, ki,
                   pipeline_signature(pb), hctx.signature())

            def build_fused():
                def kp(datas, valids, pmask, aux, rowidx, present, kmin_s):
                    out_datas, out_valids, mask = trace_pipeline(
                        input_attrs, filters, outputs, datas, valids, pmask,
                        aux, cap)
                    k64 = out_datas[ki].astype(jnp.int64)
                    bidx, matched, out_mask = probe_body(
                        k64, out_valids[ki], mask, rowidx, present, kmin_s)
                    return bidx, matched, out_mask, out_datas, out_valids

                return jax.jit(kp)

            kernel = GLOBAL_KERNEL_CACHE.get_or_build(key, build_fused)
            bidx, matched, out_mask, out_datas, out_valids = kernel(
                [c.data for c in pb.columns],
                [c.validity for c in pb.columns], pb.row_mask, aux,
                dense["rowidx"], dense["present"], jnp.int64(dense["kmin"]))
            pschema = attrs_schema(self.probe_attrs)
            cols = pipeline_columns(pschema.fields, host_outs, out_datas,
                                    out_valids)
            pb = ColumnarBatch(pschema, cols, out_mask, num_rows=None)
        else:
            kc = pb.columns[lpos[self.left_keys[0].expr_id]]
            key = ("djoin_probe", jt, cap, tcap, str(kc.data.dtype),
                   kc.validity is not None)

            def build_kernel():
                def kp(pkey, pvalid, pmask, rowidx, present, kmin_s):
                    return probe_body(pkey.astype(jnp.int64), pvalid, pmask,
                                      rowidx, present, kmin_s)

                return jax.jit(kp)

            kernel = GLOBAL_KERNEL_CACHE.get_or_build(key, build_kernel)
            bidx, matched, out_mask = kernel(
                kc.data, kc.validity, pb.row_mask, dense["rowidx"],
                dense["present"], jnp.int64(dense["kmin"]))

        if self.join_type in ("left_semi", "left_anti"):
            return ColumnarBatch(pb.schema, pb.columns, out_mask,
                                 num_rows=None)
        build_out = gather_batch(build, bidx, out_mask,
                                 extra_invalid=~matched)
        schema = attrs_schema(self.output)
        cols = pb.columns + build_out.columns
        return ColumnarBatch(schema, cols, out_mask, num_rows=None)

    def _unmatched_build_rows(self, lp: Partition, build: ColumnarBatch,
                              lschema, ctx) -> ColumnarBatch:
        """full_outer extension: anti-join build side against probe keys."""
        import jax

        from ..ops import joining as J

        jnp = _jnp()
        probe_all = concat_batches(lp, lschema) if lp \
            else ColumnarBatch.empty(lschema)
        lpos = {a.expr_id: i for i, a in enumerate(self._left_attrs)}
        pkeys = [probe_all.columns[lpos[k.expr_id]] for k in self.left_keys]
        pkey_eqs = [c.eq_keys() for c in pkeys]
        pkey_valids = [c.validity for c in pkeys]
        rpos = {a.expr_id: i for i, a in enumerate(self.right.output)}
        bkeys = [build.columns[rpos[k.expr_id]] for k in self.right_keys]
        bkey_eqs = [c.eq_keys() for c in bkeys]
        bkey_valids = [c.validity for c in bkeys]

        # swap: probe = build side, build = probe side; left_anti
        pi = J.build_index(pkey_eqs, pkey_valids, probe_all.row_mask)
        out_cap = build.capacity
        r = J.probe_join(pi, pkey_eqs, pkey_valids, bkey_eqs, bkey_valids,
                         build.row_mask, out_cap, "left_anti")
        build_rows = gather_batch(build, r.probe_idx, r.out_mask)
        schema = attrs_schema(self.output)
        nl = len(self._left_attrs)
        from ..columnar.batch import EMPTY_DICT

        jnpmod = _jnp()
        cap = r.out_mask.shape[0]
        left_cols = [
            Column(f.dataType,
                   jnpmod.zeros(cap, dtype=f.dataType.device_dtype),
                   jnpmod.zeros(cap, dtype=bool),
                   EMPTY_DICT if isinstance(f.dataType, StringType) else None)
            for f in schema.fields[:nl]]
        cols = left_cols + build_rows.columns
        return ColumnarBatch(schema, cols, r.out_mask, num_rows=None)

    def fused_members(self) -> list:
        """FuseStages probe-splice mapping for obs/ re-attribution: the
        probe-side pipeline shares this join's probe dispatch."""
        if self.probe_fusion is None:
            return []
        from ..obs.metrics import pipeline_member_names

        filters, outputs = self.probe_fusion
        return pipeline_member_names(filters, outputs) + [
            f"HashJoin[{self.join_type}] probe"]

    def simple_string(self):
        k = ", ".join(f"{l.name}={r.name}"
                      for l, r in zip(self.left_keys, self.right_keys))
        b = "Broadcast" if self.is_broadcast else "Shuffled"
        s = f"{b}HashJoin[{self.join_type}]({k})"
        if self.probe_fusion is not None:
            filters, outputs = self.probe_fusion
            o = ", ".join(x.simple_string() for x in outputs)
            s += f" FUSED-PROBE[{o}]"
            if filters:
                s += " WHERE " + " AND ".join(x.simple_string()
                                              for x in filters)
        return s


class NestedLoopJoinExec(PhysicalPlan):
    """Cartesian product + optional condition (role of
    BroadcastNestedLoopJoinExec / CartesianProductExec). Build side (right)
    is broadcast."""

    child_fields = ("left", "right")

    def __init__(self, condition: Expression | None, join_type: str,
                 left: PhysicalPlan, right: PhysicalPlan):
        if join_type not in ("inner", "cross", "left_semi", "left_anti",
                             "left_outer"):
            raise UnsupportedOperationError(
                f"nested-loop {join_type} join not supported yet")
        self.condition = condition
        self.join_type = join_type
        self.left = left
        self.right = right
        self._cond_pipeline: ExprPipeline | None = None

    @property
    def output(self):
        if self.join_type in ("left_semi", "left_anti"):
            return list(self.left.output)
        return self.left.output + self.right.output

    def required_child_distribution(self):
        return [UnspecifiedDistribution(), BroadcastDistribution()]

    def execute(self, ctx: ExecContext) -> list[Partition]:
        import jax

        from ..ops.joining import cross_join

        jnp = _jnp()
        left_parts = self.left.execute(ctx)
        build = self.right.execute(ctx)[0]
        rschema = attrs_schema(self.right.output)
        lschema = attrs_schema(self.left.output)
        bbatch = concat_batches(build, rschema) if build \
            else ColumnarBatch.empty(rschema)
        nb = bbatch.num_rows()
        pair_attrs = list(self.left.output) + list(self.right.output)
        pair_schema = attrs_schema(pair_attrs)
        semi_anti = self.join_type in ("left_semi", "left_anti")

        cond_pipe = None
        if self.condition is not None:
            cond_pipe = ExprPipeline(pair_attrs, [self.condition],
                                     pair_attrs, pair_schema)

        out = []
        for part in left_parts:
            obatches = []
            for pb in (part or [ColumnarBatch.empty(lschema)]):
                np_rows = pb.num_rows()
                out_cap = bucket_capacity(max(np_rows * max(nb, 1), 1))
                r = cross_join(pb.row_mask, bbatch.row_mask, out_cap)
                if int(r.needed) > out_cap:
                    r = cross_join(pb.row_mask, bbatch.row_mask,
                                   bucket_capacity(int(r.needed)))
                probe_out = gather_batch(pb, r.probe_idx, r.out_mask)
                build_out = gather_batch(bbatch, r.build_idx, r.out_mask)
                joined = ColumnarBatch(pair_schema,
                                       probe_out.columns + build_out.columns,
                                       r.out_mask, num_rows=None)
                if cond_pipe is not None:
                    joined = cond_pipe.run(joined)
                if semi_anti:
                    # fold pair matches back onto probe rows: a probe row
                    # matches iff ANY surviving pair points at it
                    matched = jnp.zeros(pb.capacity, bool) \
                        .at[r.probe_idx].max(joined.row_mask)
                    keep = pb.row_mask & (
                        matched if self.join_type == "left_semi"
                        else ~matched)
                    obatches.append(ColumnarBatch(
                        pb.schema, pb.columns, keep, num_rows=None))
                elif self.join_type == "left_outer":
                    obatches.append(joined)
                    # null-extend unmatched probe rows as a second batch
                    matched = jnp.zeros(pb.capacity, bool) \
                        .at[r.probe_idx].max(joined.row_mask)
                    from ..columnar.batch import EMPTY_DICT
                    from ..types import dict_encoded

                    null_cols = []
                    for f in rschema.fields:
                        null_cols.append(Column(
                            f.dataType,
                            jnp.zeros(pb.capacity, f.dataType.device_dtype),
                            jnp.zeros(pb.capacity, bool),
                            EMPTY_DICT if dict_encoded(f.dataType)
                            else None))
                    obatches.append(ColumnarBatch(
                        pair_schema, list(pb.columns) + null_cols,
                        pb.row_mask & ~matched, num_rows=None))
                else:
                    obatches.append(joined)
            out.append(obatches)
        return out


# ---------------------------------------------------------------------------
# Union / Coalesce
# ---------------------------------------------------------------------------

class SampleExec(PhysicalPlan):
    """Bernoulli sampling via a hash of the row's global position —
    deterministic for a given seed (role of BasicOperators' SampleExec)."""

    child_fields = ("child",)

    def __init__(self, fraction: float, seed: int, child: PhysicalPlan):
        self.fraction = fraction
        self.seed = seed
        self.child = child

    @property
    def output(self):
        return self.child.output

    def execute(self, ctx: ExecContext) -> list[Partition]:
        import jax

        from ..ops.hashing import mix64

        jnp = _jnp()
        threshold = int(self.fraction * (1 << 30))
        out = []
        for pi, part in enumerate(self.child.execute(ctx)):
            obatches = []
            for bi, b in enumerate(part):
                cap = b.capacity
                # the per-(partition,batch) global position base is a
                # KERNEL INPUT, not part of the cache key: one compiled
                # kernel per capacity bucket serves every batch position
                # (keying by (pi, bi) compiled a kernel per batch — the
                # recompile storm plan_lint/ROADMAP flagged)
                key = ("sample", cap, self.seed, threshold)

                def build():
                    def kernel(mask, base):
                        pos = jnp.arange(cap, dtype=jnp.int64) + base
                        h = mix64(pos + self.seed)
                        keep = (h.view(jnp.uint64) >> jnp.uint64(34)) \
                            .astype(jnp.int64) < threshold
                        return mask & keep

                    return jax.jit(kernel)

                kernel = GLOBAL_KERNEL_CACHE.get_or_build(key, build)
                base = jnp.int64((pi << 40) + (bi << 28))
                obatches.append(ColumnarBatch(
                    b.schema, b.columns, kernel(b.row_mask, base),
                    num_rows=None))
            out.append(obatches)
        return out


class UnionExec(PhysicalPlan):
    child_fields = ("children_plans",)

    def __init__(self, children_plans: Sequence[PhysicalPlan],
                 attrs: list[AttributeReference]):
        self.children_plans = list(children_plans)
        self.attrs = attrs

    @property
    def output(self):
        return self.attrs

    def output_partitioning(self):
        n = sum(c.output_partitioning().num_partitions
                for c in self.children_plans)
        return UnknownPartitioning(n)

    def execute(self, ctx: ExecContext) -> list[Partition]:
        out: list[Partition] = []
        schema = attrs_schema(self.attrs)
        for c in self.children_plans:
            for part in c.execute(ctx):
                # rewrap batches under union output schema (names may differ)
                out.append([ColumnarBatch(schema, b.columns, b.row_mask,
                                          b._num_rows) for b in part])
        return out


class CoalescePartitionsExec(PhysicalPlan):
    child_fields = ("child",)

    def __init__(self, num_partitions: int, child: PhysicalPlan):
        self.num_partitions = max(1, num_partitions)
        self.child = child

    @property
    def output(self):
        return self.child.output

    def output_partitioning(self):
        if self.num_partitions == 1:
            return SinglePartition()
        return UnknownPartitioning(self.num_partitions)

    def execute(self, ctx: ExecContext) -> list[Partition]:
        parts = self.child.execute(ctx)
        n = self.num_partitions
        out: list[Partition] = [[] for _ in range(min(n, max(len(parts), 1)))]
        for i, p in enumerate(parts):
            out[i % len(out)].extend(p)
        return out
