"""GenerateExec: row expansion for explode(split(...)).

Role of the reference's GenerateExec (sqlx/GenerateExec.scala). Arrays have
no device representation here (ragged); the expansion plan is computed
host-side, but the expensive part — splitting strings — runs ONCE PER
DICTIONARY ENTRY, not per row; per-row element counts come from a code
gather and the source columns are repeated with a device gather.
"""

from __future__ import annotations

import numpy as np

from ..columnar.arrow import _chunked_to_numpy
from ..columnar.batch import Column, ColumnarBatch, bucket_capacity
from ..columnar.ops import gather_batch
from ..errors import UnsupportedOperationError
from ..exec.context import ExecContext
from ..expr.expressions import AttributeReference, Split
from ..types import StringType
from .operators import PhysicalPlan, attrs_schema


class GenerateExec(PhysicalPlan):
    child_fields = ("child",)

    def __init__(self, generator, element_attr: AttributeReference,
                 child: PhysicalPlan):
        from ..types import ArrayType

        if not (isinstance(generator, Split)
                or (isinstance(generator, AttributeReference)
                    and isinstance(generator.dtype, ArrayType))):
            raise UnsupportedOperationError(
                "explode() supports split(col, delim) or an array column")
        self.generator = generator
        self.element_attr = element_attr
        self.child = child

    @property
    def output(self):
        return self.child.output + [self.element_attr]

    def execute(self, ctx: ExecContext):
        from ..expr.expressions import Literal

        src = self.generator.child if isinstance(self.generator, Split) \
            else self.generator
        if isinstance(src, Literal):
            # explode over a constant: every input row expands by the same
            # literal list (code 0 into a one-entry dictionary)
            cidx = None
        elif isinstance(src, AttributeReference):
            pos = {a.expr_id: i for i, a in enumerate(self.child.output)}
            cidx = pos[src.expr_id]
        else:
            raise UnsupportedOperationError(
                "split() argument must be a column or literal")
        out_schema = attrs_schema(self.output)
        parts = self.child.execute(ctx)
        return [[self._expand(b, cidx, out_schema)
                 for b in p] for p in parts]

    def _expand(self, batch: ColumnarBatch, cidx: int | None,
                out_schema) -> ColumnarBatch:
        import jax.numpy as jnp
        import pyarrow as pa

        from ..expr.expressions import Literal

        if cidx is None:
            src = self.generator.child \
                if isinstance(self.generator, Split) else self.generator
            assert isinstance(src, Literal)
            if src.value is None:
                lists = [[]]  # split(NULL) is NULL; explode(NULL) emits none
            elif isinstance(self.generator, Split):
                lists = self.generator.split_lists([str(src.value)])
            else:
                lists = [list(src.value)]
            col = None
        else:
            col = batch.columns[cidx]
            values = col.dictionary.values if col.dictionary else []
            if isinstance(self.generator, Split):
                if not isinstance(col.dtype, StringType):
                    raise UnsupportedOperationError(
                        "split() needs a string column")
                lists = self.generator.split_lists(values or [""])
            else:  # array column: the dictionary values ARE the lists
                lists = [list(v) for v in values] or [[]]
        counts_per_code = np.array([len(x) for x in lists], np.int64)
        offsets_per_code = np.zeros(len(lists) + 1, np.int64)
        np.cumsum(counts_per_code, out=offsets_per_code[1:])
        flat_elements = np.array(
            [e for lst in lists for e in lst], dtype=object)

        sel = np.nonzero(np.asarray(batch.row_mask))[0]
        if col is None:
            codes = np.zeros(len(sel), np.int64)
        else:
            codes = np.clip(np.asarray(col.data)[sel], 0, len(lists) - 1)
        row_counts = counts_per_code[codes]
        if col is not None and col.validity is not None:
            row_counts = np.where(np.asarray(col.validity)[sel],
                                  row_counts, 0)
        total = int(row_counts.sum())
        out_cap = bucket_capacity(max(total, 1))

        rep_idx = np.repeat(np.arange(len(sel)), row_counts)
        src_rows = np.zeros(out_cap, np.int32)
        src_rows[:total] = sel[rep_idx]
        out_mask = jnp.arange(out_cap) < total
        gathered = gather_batch(batch, jnp.asarray(src_rows), out_mask)

        if total:
            elem_codes = np.concatenate(
                [np.arange(offsets_per_code[c], offsets_per_code[c] + n)
                 for c, n in zip(codes, row_counts)])
            elems = flat_elements[elem_codes]
        else:
            elems = np.zeros(0, object)
        from ..types import to_arrow_type

        edt = self.element_attr.dtype
        data, validity, sd = _chunked_to_numpy(
            pa.array(list(elems), to_arrow_type(edt)), edt)
        pad = np.zeros(out_cap, edt.device_dtype)
        pad[:total] = data
        ev = None
        if validity is not None:
            vm = np.zeros(out_cap, bool)
            vm[:total] = validity
            ev = jnp.asarray(vm)
        elem_col = Column(edt, jnp.asarray(pad), ev, sd)

        return ColumnarBatch(out_schema, list(gathered.columns) + [elem_col],
                             out_mask, num_rows=total)
