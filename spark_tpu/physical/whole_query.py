"""Whole-QUERY compilation: collapse a slice-resident plan into ONE jitted
program (Flare's bet, ROADMAP direction 4).

Whole-stage fusion (PR 1) compiles each exchange-free chain into one
program per batch; exchange map-side fusion (PR 5) extends the program to
the shuffle write; mesh stage fusion (PR 8) makes a shuffle stage one
sharded dispatch. The host shuffle ROUND-TRIPS between stages remain: the
scheduler materializes every stage output, pulls grouped columns to host,
and re-ingests them for the next stage. When plan-time statistics show the
whole query's working set fits device-side, those round-trips are pure
overhead — the same tracing machinery that builds the per-stage programs
can trace EVERY stage into one `jax.jit` program per (plan structure,
input signatures, capacities):

  * exchanges lower to in-program GATHERS — on one device a hash/range/rr
    redistribution moves no data, it only re-partitions rows the next
    operator re-groups/re-sorts anyway, so the lowering concatenates the
    flow and lets the consumer's trace do the grouping;
  * aggregates always take the sorted-segment layout (static shapes: the
    output tile has the input capacity) — the value-dependent dense-range
    scatter stays a per-stage optimization, the whole-query program trades
    it for zero host hops;
  * joins run the sorted-probe kernel in-trace; output-capacity overflow
    comes back as a per-join `needed` scalar checked ONCE after the single
    dispatch (the same capacity-bucket retry contract as the per-batch
    kernels — a retry recompiles with the bumped bucket and re-dispatches
    the whole program);
  * intermediate stage outputs never materialize as ColumnarBatches —
    they are XLA values inside one program, resident in HBM only for the
    program's lifetime.

The `minRows` size gate generalizes into a three-tier cost model
(`spark.tpu.compile.tier` = auto | whole | stage | operator):

  whole     — one jitted program per query step (this module);
  stage     — one program per stage per batch (PR 1/5/8 fusion; the
              per-partition minRows runtime gate keeps routing undersized
              partitions to the shared operator kernels, i.e. the
              stage→operator fallback stays a runtime decision);
  operator  — operator-at-a-time shared kernels (the differential oracle;
              forced globally by the tier, per-partition by the gate).

`auto` picks whole-query only when the plan is structurally lowerable,
every leaf row count is known (LocalTableScan/Range statistics), the
plan actually contains exchange round-trips to eliminate (a single-stage
plan is already one program per batch under stage fusion — collapsing it
would trade the value-dependent dense fast paths for nothing), the
batch volume amortizes the bigger compile (spark.tpu.compile.whole.minRows
scaled by program depth — the compile-cost proxy; the measured per-kernel
compile cost from the KernelCache cost table refines the estimate when
available), and the fully-resident working set passes the
`spark.tpu.memory.budget` admission check. Any failed check falls back
tier-by-tier with the reason recorded on the plan
(`explain("analysis")` surfaces the decision).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, NamedTuple, Optional, Sequence

from ..columnar.batch import (
    EMPTY_DICT, Column, ColumnarBatch, StringDict, bucket_capacity,
    merge_string_dicts,
)
from ..errors import ExecutionError
from ..expr.expressions import Alias, AttributeReference
from ..types import BooleanType, StringType, dict_encoded
from .aggregates import FUSABLE_OPS
from .compile import (
    GLOBAL_KERNEL_CACHE, bind_inputs, canonical_key, pipeline_host_pass,
    trace_pipeline,
)
from .operators import PhysicalPlan, attrs_schema

__all__ = ["WholeQueryExec", "TierDecision", "choose_tier",
           "apply_compile_tier", "supported_whole_query",
           "supported_mesh_whole", "is_runtime_fault"]

_MAX_PROGRAM_RETRIES = 8

# re-export: tier degradation shares the runtime-fault classifier with
# the mesh gang-failure path (utils/faults.py owns it — no deps)
from ..utils.faults import is_runtime_fault  # noqa: E402


def _jnp():
    import jax.numpy as jnp

    return jnp


def _home_batch(b):
    """Re-home one batch's planes onto the default device (no-op for
    arrays already there). Readmitted plans ingest mesh-materialized
    stages whose partitions live one-per-device; a single jitted
    program cannot take args spread across devices."""
    import jax

    from dataclasses import replace

    dev = jax.devices()[0]

    def put(a):
        return None if a is None else jax.device_put(a, dev)

    cols = [replace(c, data=put(c.data), validity=put(c.validity))
            for c in b.columns]
    return ColumnarBatch(b.schema, cols, put(b.row_mask), b._num_rows)


# ---------------------------------------------------------------------------
# tier decision
# ---------------------------------------------------------------------------

@dataclass
class TierDecision:
    """Outcome of the compile-tier cost model, stashed on the plan so
    explain("analysis") and the execution span can surface it."""

    tier: str                 # "mesh-whole" | "whole" | "stage" | "operator"
    reason: str               # human-readable why (incl. fallback cause)
    details: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"tier": self.tier, "reason": self.reason,
                "details": dict(self.details)}


def _scan_table(node):
    """The backing arrow table of an in-memory ScanExec (io/sources
    InMemorySource), or None for external sources — in-memory scans have
    exact plan-time statistics like LocalTableScan."""
    import pyarrow as pa

    t = getattr(getattr(node, "source", None), "table", None)
    return t if isinstance(t, pa.Table) else None


def _external_scan_rows(node) -> Optional[int]:
    """Plan-time row count of an external scan from file-format
    statistics: io/sources.ParquetSource exposes `plan_time_rows()`
    (exact footer row-group counts, no data read). None for formats
    without trustworthy plan-time statistics."""
    fn = getattr(getattr(node, "source", None), "plan_time_rows", None)
    if fn is None:
        return None
    try:
        r = fn()
    except Exception:
        return None
    return None if r is None else int(r)


def _leaf_rows(node) -> Optional[int]:
    from ..exec.scheduler import _StageOutput
    from . import operators as O

    if isinstance(node, O.LocalTableScanExec):
        return int(node.table.num_rows)  # tpulint: ignore[host-sync]
    if isinstance(node, O.ScanExec):
        t = _scan_table(node)
        if t is None:
            return _external_scan_rows(node)
        return int(t.num_rows)  # tpulint: ignore[host-sync]
    if isinstance(node, O.RangeExec):
        step = node.step
        if step > 0:
            return max(0, -(-(node.end - node.start) // step))
        return max(0, -(-(node.start - node.end) // -step))
    if isinstance(node, _StageOutput) and node.stage.result is not None:
        # materialized parent stage (adaptive re-admission): sizes are
        # OBSERVED, not estimated — host-known batch row counts
        return sum(b.num_rows() for p in node.stage.result for b in p)
    return None


def supported_whole_query(plan, conf,
                          history_ok: bool = False) -> tuple[bool, str]:
    """Structural admission: every operator of the plan must have a
    whole-query lowering. Returns (ok, reason-if-not). `history_ok`
    relaxes the external-scan statistics requirement when a recorded
    QueryProfile run supplies observed volumes instead (adaptive
    history re-planning)."""
    from ..config import ADAPTIVE_PARQUET_STATS
    from ..exec.scheduler import _StageOutput
    from . import operators as O
    from .exchange import BroadcastExchangeExec, ShuffleExchangeExec
    from .fusion import FusedAggregateExec, FusedLimitExec  # noqa: F401

    for node in _iter_inner(plan):
        if isinstance(node, (O.LocalTableScanExec, O.RangeExec)):
            continue
        if isinstance(node, _StageOutput):
            if node.stage.result is not None:
                continue   # materialized stage: an ingestable leaf
            return False, (f"stage {node.stage.stage_id} output is not "
                           "materialized")
        if isinstance(node, O.ScanExec):
            if _scan_table(node) is None:
                stats_ok = (bool(  # tpulint: ignore[host-sync] conf flag
                    conf.get(ADAPTIVE_PARQUET_STATS))
                    and _external_scan_rows(node) is not None)
                if not (stats_ok or history_ok):
                    return False, (f"scan [{node.name}] reads an external "
                                   "source (no plan-time statistics)")
            continue
        if isinstance(node, (O.ComputeExec, O.LimitExec, O.SortExec,
                             O.UnionExec, O.CoalescePartitionsExec,
                             BroadcastExchangeExec, ShuffleExchangeExec)):
            continue
        if isinstance(node, O.HashAggregateExec):
            vals = node._plan_values()
            bad = [op for op, _, _ in vals if op not in FUSABLE_OPS]
            if bad:
                return False, (f"aggregate op {bad[0]} needs host-side "
                               "finishing (no in-program lowering)")
            for g in node.grouping:
                if dict_encoded(g.dtype) and not isinstance(g.dtype,
                                                            StringType):
                    return False, (f"grouping key {g.name} is a nested "
                                   "dictionary type (codes are not a "
                                   "canonical group domain)")
            continue
        if isinstance(node, O.HashJoinExec):
            if node.join_type == "full_outer":
                return False, ("full_outer join runs eager host-side "
                               "passes (no in-program lowering)")
            for k in list(node.left_keys) + list(node.right_keys):
                if dict_encoded(k.dtype) and not isinstance(k.dtype,
                                                            StringType):
                    return False, (f"join key {k.name} is a nested "
                                   "dictionary type")
            continue
        return False, (f"operator {type(node).__name__} has no "
                       "whole-query lowering")
    return True, ""


def _iter_inner(plan):
    """Iterate the plan INCLUDING through fused-exchange absorption (the
    plan tree itself; WholeQueryExec is opaque to the stage cutter but
    this walks its inner plan when given one)."""
    inner = plan.plan if isinstance(plan, WholeQueryExec) else plan
    return inner.iter_nodes()


def supported_mesh_whole(plan, conf) -> tuple[bool, str, dict]:
    """Mesh admission on top of supported_whole_query: every hash
    exchange must lower to an in-program `lax.all_to_all` on ONE
    power-of-two mesh axis known at plan time (plain attribute keys, a
    consistent partition count, enough devices), and at least one such
    exchange must exist — without one the single-device whole program
    already eliminates every round-trip and sharding buys nothing.
    Returns (ok, why-not, details)."""
    from ..config import MESH_ENABLED
    from .exchange import ShuffleExchangeExec
    from .partitioning import HashPartitioning

    if not conf.get(MESH_ENABLED):
        return False, "spark.tpu.mesh.enabled=false", {}
    counts: set[int] = set()
    for node in _iter_inner(plan):
        if not isinstance(node, ShuffleExchangeExec):
            continue
        p = node.partitioning
        if not isinstance(p, HashPartitioning):
            continue
        if not all(isinstance(e, AttributeReference) for e in p.exprs):
            return False, ("hash exchange keys are computed expressions "
                           "(no in-program partition-id lowering)"), {}
        for e in p.exprs:
            if dict_encoded(e.dtype) and not isinstance(e.dtype,
                                                        StringType):
                return False, (f"exchange key {e.name} is a nested "
                               "dictionary type"), {}
        counts.add(int(p.num_partitions))  # tpulint: ignore[host-sync]
    if not counts:
        return False, ("no hash exchange to run as an in-program "
                       "collective (the single-device whole tier "
                       "already eliminates the round-trips)"), {}
    if len(counts) > 1:
        return False, (f"mixed hash partition counts {sorted(counts)} "
                       "(one mesh axis per program)"), {}
    P = counts.pop()
    if P < 2 or (P & (P - 1)) != 0:
        return False, (f"partition count {P} is not a power-of-two "
                       "mesh axis"), {}
    import jax

    n_dev = len(jax.devices())
    if n_dev < P:
        return False, f"mesh needs {P} devices, {n_dev} visible", {}
    return True, "", {"mesh_devices": P}


def _estimate_resident_bytes(plan, conf) -> Optional[int]:
    """Cheap upper-bound of the fully-resident program's engine bytes:
    every lowered operator's output tile (capacity x row bytes) plus the
    leaf input planes — all live inside ONE XLA program. Pure host
    arithmetic over plan metadata (no value tracing: the tier chooser
    must stay launch-free and cheap enough to run per query)."""
    from ..exec.memory import schema_row_bytes
    from . import operators as O
    from .exchange import BroadcastExchangeExec, ShuffleExchangeExec
    from .fusion import FusedAggregateExec

    tile = int(conf.get(  # tpulint: ignore[host-sync]
        "spark.tpu.batch.capacity", 1 << 20))
    memo: dict[int, Optional[int]] = {}

    def cap_of(node) -> Optional[int]:
        hit = memo.get(id(node))
        if hit is not None or id(node) in memo:
            return hit
        memo[id(node)] = out = _cap_of(node)
        return out

    def _cap_of(node) -> Optional[int]:
        rows = _leaf_rows(node)
        if rows is not None:
            # tiling mirror: per-tile buckets, then the gathered concat
            total = 0
            n = rows
            while n > 0:
                total += bucket_capacity(min(tile, n))
                n -= tile
            return bucket_capacity(max(total, 1))
        kids = [cap_of(c) for c in node.children]
        if any(k is None for k in kids):
            return None
        if isinstance(node, O.HashAggregateExec) and not node.grouping:
            return 8
        if isinstance(node, O.HashJoinExec):
            return max(kids[0], 1 << 10)
        if isinstance(node, O.UnionExec):
            return bucket_capacity(sum(kids))
        if isinstance(node, (ShuffleExchangeExec, BroadcastExchangeExec,
                             O.CoalescePartitionsExec)):
            return kids[0]
        return kids[0] if kids else None

    total = 0
    for node in _iter_inner(plan):
        cap = cap_of(node)
        if cap is None:
            return None
        try:
            rb = schema_row_bytes(attrs_schema(node.output))
        except Exception:
            rb = 16
        total += cap * rb
        if isinstance(node, FusedAggregateExec):
            # the traced pipeline's projected planes are live too
            total += cap * 16
    return total


def _avg_compile_ms() -> float:
    """Online per-kernel compile-cost estimate from the KernelCache (PR 7
    cost table companion): total builder+first-invocation time over
    compiled kernels. Falls back to a conservative constant cold."""
    kc = GLOBAL_KERNEL_CACHE
    misses = max(kc.misses, 1)
    avg = kc.compile_ms / misses
    return max(avg, 50.0)


def choose_tier(plan, conf, cluster: bool = False,
                observed_rows: Optional[int] = None) -> TierDecision:
    """The three-tier cost model. See module docstring for the rules.
    `observed_rows` substitutes a RECORDED run's total shuffled volume
    (QueryProfile / warm-start manifest) for leaves whose plan-time row
    count is unknown — adaptive history re-planning for recurring
    queries over external sources."""
    from ..config import (
        COMPILE_TIER, FUSION_ENABLED, MEMORY_BUDGET, WHOLE_MIN_ROWS,
    )

    pref = str(conf.get(COMPILE_TIER)).lower()
    if pref == "operator":
        return TierDecision("operator", "forced by spark.tpu.compile.tier")
    if pref == "stage":
        return TierDecision("stage", "forced by spark.tpu.compile.tier")
    forced_mesh = pref == "mesh-whole"
    forced = pref == "whole" or forced_mesh
    base = "forced by spark.tpu.compile.tier" if forced \
        else "cost model (spark.tpu.compile.tier=auto)"
    if not conf.get(FUSION_ENABLED):
        # the whole-query program IS fusion taken to its limit: with
        # fusion disabled the session asked for the operator-at-a-time
        # differential oracle, and collapsing the plan anyway would make
        # the fusion-on/off comparison compare whole vs whole
        return TierDecision(
            "stage", "whole-query fallback: spark.tpu.fusion.enabled="
            "false (operator-at-a-time differential oracle)")
    if cluster:
        return TierDecision(
            "stage", "cluster scheduler: stages place on workers — the "
            "whole-query program needs the data driver-resident")
    if not forced:
        # cheap disqualifier FIRST: the common exchange-free query must
        # not pay the full admission walk at plan time (auto only — the
        # whole tier's win is ELIMINATING stage round-trips; a plan with
        # no exchanges is already one program per batch under stage
        # fusion, and collapsing it would trade the value-dependent
        # dense fast paths for nothing)
        from .exchange import BroadcastExchangeExec, ShuffleExchangeExec

        n_exch = sum(1 for x in _iter_inner(plan)
                     if isinstance(x, (ShuffleExchangeExec,
                                       BroadcastExchangeExec)))
        if n_exch == 0:
            return TierDecision(
                "stage", "whole-query fallback: no exchange round-trips "
                "to eliminate (single-stage plan — stage fusion already "
                "dispatches once per batch)", {"exchanges": 0})
    ok, why = supported_whole_query(plan, conf,
                                    history_ok=observed_rows is not None)
    if not ok:
        return TierDecision("stage", f"whole-query fallback: {why}")
    rows = []
    n_ops = 0
    unknown_leaves = False
    for node in _iter_inner(plan):
        n_ops += 1
        r = _leaf_rows(node)
        if r is not None:
            rows.append(r)
        elif not node.children:
            if observed_rows is None:
                return TierDecision(
                    "stage", "whole-query fallback: leaf statistics "
                    f"unknown ({type(node).__name__} row count untraced)")
            unknown_leaves = True
    volume = sum(rows)
    if unknown_leaves:
        # recorded volume stands in for the untraced leaves
        volume = max(volume, int(observed_rows))
    details = {"volume_rows": volume, "lowered_ops": n_ops,
               "est_compile_ms": round(_avg_compile_ms() * n_ops, 1)}
    if observed_rows is not None:
        details["observed_rows"] = int(observed_rows)
    est = _estimate_resident_bytes(plan, conf)
    if est is not None:
        details["est_resident_bytes"] = est
    budget = int(conf.get(MEMORY_BUDGET))  # tpulint: ignore[host-sync]
    over_budget = budget > 0 and est is not None and est > budget
    if forced_mesh or (pref == "auto" and over_budget):
        # mesh admission: the whole-program win at 1/P the per-device
        # residency. Forced mesh-whole always tries it; auto reaches for
        # it ONLY in the budget gap (the single-device whole program
        # does not fit, but a per-shard slice does) — under budget the
        # single-device program keeps its value-dependent fast paths
        mok, mwhy, mdet = supported_mesh_whole(plan, conf)
        per_shard = None
        if mok:
            P = mdet["mesh_devices"]
            per_shard = None if est is None else -(-est // P)
            if budget > 0 and per_shard is not None \
                    and per_shard > budget:
                mok = False
                mwhy = ("per-shard resident estimate "
                        f"~{per_shard / (1 << 20):.1f} MiB still "
                        "exceeds spark.tpu.memory.budget")
        if mok:
            details.update(mdet)
            if per_shard is not None:
                details["est_resident_bytes_per_shard"] = per_shard
            reason = base if forced_mesh else (
                base + " — fully-resident set exceeds the single-device "
                "budget but fits per-shard across the mesh")
            return TierDecision("mesh-whole", reason, details)
        # tier-by-tier fallback: the reason rides the decision so
        # explain("analysis") shows why the mesh program was refused
        details["mesh_whole_fallback"] = mwhy
    if over_budget:
        return TierDecision(
            "stage", "whole-query fallback: predicted fully-resident "
            f"working set ~{est / (1 << 20):.1f} MiB exceeds "
            f"spark.tpu.memory.budget ({budget / (1 << 20):.1f} MiB)",
            details)
    if not forced:
        floor = int(conf.get(WHOLE_MIN_ROWS))  # tpulint: ignore[host-sync]
        floor *= max(1, -(-n_ops // 8))
        details["volume_floor"] = floor
        if volume < floor:
            return TierDecision(
                "stage", "whole-query fallback: batch volume "
                f"{volume} rows under the compile-amortization floor "
                f"({floor}; spark.tpu.compile.whole.minRows scaled by "
                "program depth)", details)
    if forced_mesh:
        # mesh admission failed but the plan fits one device: fall back
        # ONE tier (mesh-whole -> whole), not all the way to stage
        return TierDecision(
            "whole", "mesh-whole fallback: "
            f"{details.get('mesh_whole_fallback', 'mesh inadmissible')}",
            details)
    return TierDecision("whole", base, details)


def apply_compile_tier(plan, conf, cluster: bool = False):
    """Planner hook: wrap the plan for the whole tier, or stash the
    decision (with its fallback reason) for explain("analysis")."""
    decision = choose_tier(plan, conf, cluster=cluster)
    if decision.tier == "mesh-whole":
        from .mesh_whole import MeshWholeQueryExec

        return MeshWholeQueryExec(plan, decision)
    if decision.tier == "whole":
        return WholeQueryExec(plan, decision)
    try:
        plan._tier_decision = decision
    except Exception:
        pass
    return plan


# ---------------------------------------------------------------------------
# program builder
# ---------------------------------------------------------------------------

class _MCol(NamedTuple):
    """Host-side column metadata threaded through the shadow pass: the
    same (dtype, validity presence, dictionary) triple pipeline_host_pass
    reads off a real batch — intermediate flows never materialize, their
    metadata derives from the producing operator's host pass."""

    dtype: object
    valid: bool
    sdict: Optional[StringDict]


class _MetaColShim:
    """Column-shaped view over _MCol for pipeline_host_pass (which reads
    only `.validity is not None` and `.dictionary`)."""

    __slots__ = ("validity", "dictionary")

    def __init__(self, m: _MCol):
        self.validity = True if m.valid else None
        self.dictionary = m.sdict


class _MetaView:
    __slots__ = ("columns",)

    def __init__(self, metas: Sequence[_MCol]):
        self.columns = [_MetaColShim(m) for m in metas]


class _Lowered(NamedTuple):
    metas: list            # list[_MCol] per output column
    cap: int               # static tile capacity of this flow
    emit: Callable         # emit(args, needed) -> (datas, valids, mask)


class _Collect(list):
    """Emit-time scalar collector. The list body carries per-join
    `needed` capacities (the capacity-retry contract); the side channels
    carry the dense-probe guard verdicts, the observed build-key spans
    (warm-start manifest food), and per-exchange overflow counts (mesh
    tier) that ride the SAME single dispatch — all checked once, on the
    host, after the program returns."""

    __slots__ = ("spans", "guards", "overflows")

    def __init__(self):
        super().__init__()
        self.spans: list = []      # (lo, hi, dup) per span-observed join
        self.guards: list = []     # violation scalar per dense join
        self.overflows: list = []  # psum'd overflow per mesh exchange


class _ProgramBuilder:
    """Lowers an admitted physical plan into one traced program.

    Host pass (per execute): leaf scans execute (launch-free device-cached
    ingest), dictionaries merge, aux luts harvest, and every operator
    contributes a structural key fragment. The traced pass (once per
    program cache key) composes the SAME kernel bodies the per-stage path
    uses — trace_pipeline, ops.grouping, ops.joining, ops.sorting — into
    a single function; XLA fuses across what used to be stage boundaries."""

    def __init__(self, ctx, join_caps: list, spans_seed=None,
                 dense_off=None):
        self.ctx = ctx
        self.args: list = []           # program inputs, in arg-index order
        self.key: list = []            # cache-key fragments
        self.join_caps = join_caps     # per-join output capacities (shared
        # across the retry loop: a bumped bucket re-enters here)
        self._join_seq = 0
        self.members: list[str] = []   # lowered ops, produce->consume order
        # warm-start build-side key spans ([lo, hi, unique] per join id,
        # from the persistent manifest) and the joins whose seeded span
        # the data contradicted this run (guard-verdict retry state)
        self._spans_seed = spans_seed
        self._dense_off = dense_off if dense_off is not None else set()
        self.span_jids: list[int] = []   # joins observing their span —
        # append order matches emit-time needed.spans appends (probe
        # subtree lowers AND emits before build subtree before self)
        self.guard_jids: list[int] = []  # dense joins, = guards order
        self.dense_joins: list[int] = [] # joins on the dense fast path

    # -- plumbing ----------------------------------------------------------
    def arg(self, arr) -> int:
        self.args.append(arr)
        return len(self.args) - 1

    def _member(self, node) -> None:
        s = node.simple_string() if hasattr(node, "simple_string") \
            else type(node).__name__
        self.members.append(s[:100])

    # -- dispatch ----------------------------------------------------------
    def lower(self, node) -> _Lowered:
        from ..exec.scheduler import _StageOutput
        from . import operators as O
        from .exchange import BroadcastExchangeExec, ShuffleExchangeExec
        from .fusion import FusedAggregateExec, FusedLimitExec

        if isinstance(node, (O.LocalTableScanExec, O.RangeExec,
                             O.ScanExec, _StageOutput)):
            # _StageOutput: a materialized parent stage ingests exactly
            # like a scan (adaptive re-admission mid-query)
            return self._lower_leaf(node)
        if isinstance(node, FusedAggregateExec):
            low = self.lower(node.child)
            low = self._lower_pipe(node.filters, node.pipe_outputs,
                                   node.child.output, node.pipe_attrs, low)
            self._member(node)
            return self._lower_agg(node, node.pipe_attrs, low)
        if isinstance(node, O.HashAggregateExec):
            low = self.lower(node.child)
            self._member(node)
            return self._lower_agg(node, node.child.output, low)
        if isinstance(node, FusedLimitExec):
            low = self.lower(node.child)
            low = self._lower_pipe(node.filters, node.pipe_outputs,
                                   node.child.output, node.pipe_attrs, low)
            self._member(node)
            return self._lower_limit(node, low)
        if isinstance(node, O.LimitExec):
            low = self.lower(node.child)
            self._member(node)
            return self._lower_limit(node, low)
        if isinstance(node, O.SortExec):
            low = self.lower(node.child)
            self._member(node)
            return self._lower_sort(node, low)
        if isinstance(node, O.HashJoinExec):
            self._member(node)
            return self._lower_join(node)
        if isinstance(node, O.ComputeExec):
            low = self.lower(node.child)
            self._member(node)
            attrs = [o.to_attribute() if isinstance(o, Alias) else o
                     for o in node.outputs]
            return self._lower_pipe(node.filters, node.outputs,
                                    node.child.output, attrs, low)
        if isinstance(node, ShuffleExchangeExec):
            low = self.lower(node.child)
            if node.pipe_fusion is not None:
                filters, outputs = node.pipe_fusion
                low = self._lower_pipe(filters, outputs, node.child.output,
                                       node.pipe_attrs, low)
            self.members.append(
                f"Exchange[{type(node.partitioning).__name__}] -> "
                "in-program gather")
            self.key.append(("xgather",))
            return low
        if isinstance(node, BroadcastExchangeExec):
            self.members.append("BroadcastExchange -> in-program identity")
            return self.lower(node.child)
        if isinstance(node, O.CoalescePartitionsExec):
            return self.lower(node.child)
        if isinstance(node, O.UnionExec):
            lows = [self.lower(c) for c in node.children_plans]
            self._member(node)
            return self._lower_union(node, lows)
        raise ExecutionError(            # admission guarantees this
            f"whole-query lowering missing for {type(node).__name__}")

    # -- leaves ------------------------------------------------------------
    def _lower_leaf(self, node) -> _Lowered:
        from ..exec.scheduler import _StageOutput

        jnp = _jnp()
        parts = node.execute(self.ctx)
        batches = [b for p in parts for b in p]
        if batches and isinstance(node, _StageOutput):
            # a mesh-materialized stage leaves partition i resident on
            # device i; a jitted program's args must share one device —
            # re-home everything (device_put is a no-op for arrays that
            # already live there, so host-shuffled stages pay nothing)
            batches = [_home_batch(b) for b in batches]
        if not batches:
            # all-empty partitions (e.g. an empty materialized stage):
            # one empty batch keeps the concat/pad lowering uniform
            batches = [ColumnarBatch.empty(attrs_schema(node.output))]
        fields = attrs_schema(node.output).fields
        self._member(node)
        caps = [b.capacity for b in batches]
        cap = bucket_capacity(max(sum(caps), 1))
        ncols = len(fields)

        col_args = []      # per col: list[(data_idx, valid_idx|None)]
        luts = []          # per col: list[lut arg idx]|None
        metas = []
        for i, f in enumerate(fields):
            cols = [b.columns[i] for b in batches]
            merged = None
            lut_idx = None
            if dict_encoded(f.dataType):
                dicts = [c.dictionary or EMPTY_DICT for c in cols]
                if all(d is dicts[0] for d in dicts):
                    merged = dicts[0]
                else:
                    merged, lut_list = merge_string_dicts(dicts)
                    lut_idx = [self.arg(jnp.asarray(lt))
                               for lt in lut_list]
            any_valid = any(c.validity is not None for c in cols)
            entry = []
            for c in cols:
                di = self.arg(c.data)
                vi = self.arg(c.validity) if c.validity is not None \
                    else None
                entry.append((di, vi))
            col_args.append(entry)
            luts.append(lut_idx)
            metas.append(_MCol(f.dataType, any_valid, merged))
        mask_idx = [self.arg(b.row_mask) for b in batches]
        self.key.append((
            "leaf", tuple(caps),
            tuple((str(c.data.dtype), c.validity is not None)
                  for b in batches for c in b.columns),
            tuple(None if li is None else len(li) for li in luts)))

        col_args_f = list(col_args)
        luts_f = list(luts)
        metas_f = list(metas)
        bcaps = list(caps)

        def emit(args, needed):
            def pad(a, fill):
                n = sum(bcaps)
                if n < cap:
                    a = jnp.concatenate(
                        [a, jnp.full(cap - n, fill, dtype=a.dtype)])
                return a

            datas, valids = [], []
            for ci in range(ncols):
                chunks = []
                for bi, (di, _vi) in enumerate(col_args_f[ci]):
                    d = args[di]
                    if luts_f[ci] is not None:
                        lt = args[luts_f[ci][bi]]
                        d = jnp.take(lt, jnp.clip(d, 0, lt.shape[0] - 1))
                    chunks.append(d)
                datas.append(pad(jnp.concatenate(chunks), 0))
                if metas_f[ci].valid:
                    vchunks = []
                    for bi, (_di, vi) in enumerate(col_args_f[ci]):
                        if vi is None:
                            vchunks.append(jnp.ones(bcaps[bi], dtype=bool))
                        else:
                            vchunks.append(args[vi])
                    valids.append(pad(jnp.concatenate(vchunks), False))
                else:
                    valids.append(None)
            mask = pad(jnp.concatenate([args[i] for i in mask_idx]), False)
            return datas, valids, mask

        return _Lowered(metas, cap, emit)

    # -- filter/project pipelines ------------------------------------------
    def _lower_pipe(self, filters, outputs, input_attrs, out_attrs,
                    low: _Lowered) -> _Lowered:
        if not filters and all(isinstance(o, AttributeReference)
                               for o in outputs):
            # pure column selection: reorder the flow, zero trace work
            pos = {a.expr_id: i for i, a in enumerate(input_attrs)}
            sel = [pos[o.expr_id] for o in outputs]
            metas = [low.metas[i] for i in sel]
            self.key.append(("reorder", tuple(sel)))

            def emit(args, needed, _low=low, _sel=tuple(sel)):
                d, v, m = _low.emit(args, needed)
                return [d[i] for i in _sel], [v[i] for i in _sel], m

            return _Lowered(metas, low.cap, emit)
        hctx, host_outs, aux = pipeline_host_pass(
            input_attrs, filters, outputs, _MetaView(low.metas))
        aux_idx = [self.arg(a) for a in aux]
        id_to_pos = bind_inputs(input_attrs)
        self.key.append((
            "pipe",
            tuple(canonical_key(f, id_to_pos) for f in filters),
            tuple(canonical_key(o, id_to_pos) for o in outputs),
            hctx.signature()))
        metas = [_MCol(a.dtype, hv.validity is not None,
                       hv.sdict if dict_encoded(a.dtype) else None)
                 for a, hv in zip(out_attrs, host_outs)]
        cap = low.cap
        in_attrs = list(input_attrs)
        flt = list(filters)
        outs = list(outputs)

        def emit(args, needed, _low=low):
            d, v, m = _low.emit(args, needed)
            aux_arrs = [args[i] for i in aux_idx]
            return trace_pipeline(in_attrs, flt, outs, d, v, m, aux_arrs,
                                  cap)

        return _Lowered(metas, cap, emit)

    # -- aggregation -------------------------------------------------------
    def _lower_agg(self, node, in_attrs, low: _Lowered) -> _Lowered:
        jnp = _jnp()
        pos = {a.expr_id: i for i, a in enumerate(in_attrs)}
        out_fields = attrs_schema(node.output).fields
        vals = node._plan_values()
        ops = tuple(op for op, _, _ in vals)
        val_idx = tuple(pos[attr.expr_id] if attr is not None else -1
                        for _, attr, _ in vals)
        key_idx = tuple(pos[g.expr_id] for g in node.grouping)
        key_bool = tuple(isinstance(in_attrs[i].dtype, BooleanType)
                         for i in key_idx)
        nk = len(key_idx)
        # string MIN/MAX reduces in rank space (same trick as the fused
        # aggregate): rank lut in, winning rank -> code out
        smm = {}
        for bi, (op, attr, _p) in enumerate(vals):
            if op in ("min", "max") and attr is not None \
                    and dict_encoded(attr.dtype):
                sd = low.metas[val_idx[bi]].sdict or EMPTY_DICT
                smm[bi] = (self.arg(sd.device_ranks()),
                           self.arg(sd.device_rank_to_code()),
                           len(sd))
        buf_metas = []
        for bi, (op, attr, _p) in enumerate(vals):
            f = out_fields[nk + bi]
            sdict = None
            if dict_encoded(f.dataType):
                vi = val_idx[bi]
                if vi >= 0:
                    sdict = low.metas[vi].sdict
            buf_metas.append(_MCol(f.dataType,
                                   op not in ("count", "countstar"), sdict))
        self.key.append(("agg", node.mode, ops, key_idx, val_idx,
                         key_bool, tuple((bi, n) for bi, (_r, _i, n)
                                         in sorted(smm.items()))))

        def pipe_vals(d, v, m):
            vd, vv = [], []
            for bi, i in enumerate(val_idx):
                dd = d[i] if i >= 0 else m
                if bi in smm:
                    rank = args_box[0][smm[bi][0]]
                    dd = jnp.take(rank, jnp.clip(dd.astype(jnp.int32), 0,
                                                 rank.shape[0] - 1))
                vd.append(dd)
                vv.append(v[i] if i >= 0 else None)
            return vd, vv

        def rank_back(bufs):
            out = []
            for bi, (bd, bv) in enumerate(bufs):
                if bi in smm:
                    inv = args_box[0][smm[bi][1]]
                    bd = jnp.take(inv, jnp.clip(bd.astype(jnp.int32), 0,
                                                inv.shape[0] - 1))
                out.append((bd, bv))
            return out

        def finish(bufs):
            out = []
            for bi, (bd, bv) in enumerate(bufs):
                if bi in smm:
                    out.append((bd, bv))
                    continue
                want = out_fields[nk + bi].dataType.device_dtype
                if str(bd.dtype) != str(want):
                    bd = bd.astype(want)
                out.append((bd, bv))
            return out

        args_box = [None]  # bound to the live args list inside emit

        if not node.grouping:
            metas = list(buf_metas)

            def emit(args, needed, _low=low):
                from ..ops import grouping as G

                args_box[0] = args
                d, v, m = _low.emit(args, needed)
                vd, vv = pipe_vals(d, v, m)
                outs = G.apply_global_ops(ops, vd, vv, m)
                outs = rank_back(outs)
                outs = finish(outs)
                datas, valids = [], []
                for bd, bv in outs:
                    datas.append(jnp.zeros((8,), dtype=bd.dtype)
                                 .at[0].set(bd))
                    valids.append(None if bv is None else
                                  jnp.zeros((8,), dtype=bool)
                                  .at[0].set(bv))
                mask = jnp.zeros((8,), dtype=bool).at[0].set(True)
                return datas, valids, mask

            return _Lowered(metas, 8, emit)

        key_metas = [_MCol(out_fields[j].dataType, low.metas[i].valid,
                           low.metas[i].sdict)
                     for j, i in enumerate(key_idx)]
        metas = key_metas + buf_metas
        cap = low.cap

        def emit(args, needed, _low=low):
            from ..ops import grouping as G

            args_box[0] = args
            d, v, m = _low.emit(args, needed)
            key_eqs = []
            for i, is_bool in zip(key_idx, key_bool):
                kd = d[i]
                if is_bool:
                    kd = kd.astype(jnp.int32)
                key_eqs.append(kd)
            key_valids = [v[i] for i in key_idx]
            layout = G.group_rows(key_eqs, key_valids, m)
            out_keys = [G.scatter_group_keys(layout, d[i], v[i])
                        for i in key_idx]
            vd, vv = pipe_vals(d, v, m)
            bufs = G.apply_group_ops(layout, ops, vd, vv)
            bufs = finish(rank_back(bufs))
            out_mask = G.group_output_mask(layout)
            datas = [kd for kd, _kv in out_keys] + [bd for bd, _ in bufs]
            valids = [kv for _kd, kv in out_keys] + [bv for _, bv in bufs]
            return datas, valids, out_mask

        return _Lowered(metas, cap, emit)

    # -- limit / sort ------------------------------------------------------
    def _lower_limit(self, node, low: _Lowered) -> _Lowered:
        jnp = _jnp()
        n, offset = node.n, node.offset
        self.key.append(("limit", n, offset))

        def emit(args, needed, _low=low):
            d, v, m = _low.emit(args, needed)
            rank = jnp.cumsum(m.astype(jnp.int64))
            keep = m & (rank > offset) & (rank <= offset + n)
            return d, v, keep

        return _Lowered(low.metas, low.cap, emit)

    def _lower_sort(self, node, low: _Lowered) -> _Lowered:
        jnp = _jnp()
        from ..ops.sorting import SortKeySpec

        pos = {a.expr_id: i for i, a in enumerate(node.child.output)}
        kidx, specs, rank_idx = [], [], []
        for o in node.orders:
            i = pos[o.child.expr_id]
            kidx.append(i)
            specs.append(SortKeySpec(o.ascending, o.nulls_first))
            mc = low.metas[i]
            if dict_encoded(mc.dtype):
                sd = mc.sdict or EMPTY_DICT
                rank_idx.append((self.arg(sd.device_ranks()), len(sd)))
            else:
                rank_idx.append(None)
        self.key.append(("sort", tuple(kidx),
                         tuple((s.ascending, s.nulls_first)
                               for s in specs),
                         tuple(None if r is None else r[1]
                               for r in rank_idx)))
        kidx_t, specs_t, ranks_t = tuple(kidx), list(specs), list(rank_idx)
        is_bool = tuple(isinstance(low.metas[i].dtype, BooleanType)
                        for i in kidx)

        def emit(args, needed, _low=low):
            from ..ops.sorting import sort_permutation

            d, v, m = _low.emit(args, needed)
            keys, kvalids = [], []
            for j, i in enumerate(kidx_t):
                kd = d[i]
                if ranks_t[j] is not None:
                    r = args[ranks_t[j][0]]
                    kd = jnp.take(r, jnp.clip(kd, 0, r.shape[0] - 1))
                elif is_bool[j]:
                    kd = kd.astype(jnp.int32)
                keys.append(kd)
                kvalids.append(v[i])
            perm = sort_permutation(keys, kvalids, specs_t, m)
            out_d = [jnp.take(x, perm) for x in d]
            out_v = [None if x is None else jnp.take(x, perm) for x in v]
            return out_d, out_v, jnp.take(m, perm)

        return _Lowered(low.metas, low.cap, emit)

    # -- joins -------------------------------------------------------------
    def _eq_lut(self, mc: _MCol):
        if isinstance(mc.dtype, StringType) or dict_encoded(mc.dtype):
            sd = mc.sdict or EMPTY_DICT
            lut = sd.device_hash_lut()
            return self.arg(lut), int(lut.shape[0])  # tpulint: ignore[host-sync]
        return None, None

    def _lower_join(self, node) -> _Lowered:
        probe = self.lower(node.left)
        if node.probe_fusion is not None:
            filters, outputs = node.probe_fusion
            probe = self._lower_pipe(filters, outputs, node.left.output,
                                     node.probe_attrs, probe)
        build = self.lower(node.right)
        return self._join_tail(node, probe, build)

    def _dense_eligible(self, node) -> bool:
        """Single plain-integral-key equi-join: the shape whose build
        side CAN have a dense direct-address table (operators.py's
        value-dependent fast path) — whether it DOES is decided by the
        warm-start span seed (_dense_span)."""
        from ..config import FUSION_DENSE_KEYS
        from ..types import DateType, IntegralType

        if len(node.left_keys) != 1 or len(node.right_keys) != 1:
            return False
        if not bool(self.ctx.conf.get(  # tpulint: ignore[host-sync]
                FUSION_DENSE_KEYS)):
            return False
        return all(isinstance(k.dtype, (IntegralType, DateType))
                   for k in (node.left_keys[0], node.right_keys[0]))

    def _dense_span(self, join_id: int, build_cap: int):
        """The seeded [lo, hi] span when the manifest proves the build
        keys of this join were unique and dense enough last run — the
        whole program then compiles the direct-address probe variant
        up front, guarded in-program against data drift."""
        if self._spans_seed is None or join_id in self._dense_off:
            return None
        if join_id >= len(self._spans_seed):
            return None
        sp = self._spans_seed[join_id]
        if not sp or len(sp) < 3 or not int(sp[2]):  # tpulint: ignore[host-sync]
            return None
        lo, hi = int(sp[0]), int(sp[1])  # tpulint: ignore[host-sync]
        span = hi - lo + 1
        # same density bound as the per-stage fast path: the table must
        # stay proportional to the build tile (8x) and bounded absolutely
        if span <= 0 or span > min(8 * build_cap, 1 << 23):
            return None
        return lo, hi

    def _join_tail(self, node, probe: _Lowered,
                   build: _Lowered) -> _Lowered:
        jnp = _jnp()
        jt = node.join_type
        lattrs = node._left_attrs
        rattrs = node.right.output
        lpos = {a.expr_id: i for i, a in enumerate(lattrs)}
        rpos = {a.expr_id: i for i, a in enumerate(rattrs)}
        lk = tuple(lpos[k.expr_id] for k in node.left_keys)
        rk = tuple(rpos[k.expr_id] for k in node.right_keys)
        lk_luts = [self._eq_lut(probe.metas[i]) for i in lk]
        rk_luts = [self._eq_lut(build.metas[i]) for i in rk]
        lk_bool = tuple(isinstance(probe.metas[i].dtype, BooleanType)
                        for i in lk)
        rk_bool = tuple(isinstance(build.metas[i].dtype, BooleanType)
                        for i in rk)
        join_id = self._join_seq
        self._join_seq += 1
        if join_id >= len(self.join_caps):
            self.join_caps.append(max(probe.cap, 1 << 10))
        out_cap = self.join_caps[join_id]
        eligible = self._dense_eligible(node)
        dense = self._dense_span(join_id, build.cap) if eligible else None
        if dense is not None:
            # dense 1:1 probe: one output row per probe row, no
            # expansion buffer — the join cap never binds
            out_cap = probe.cap
            self.dense_joins.append(join_id)
            self.ctx.metrics.add("cache.join_span_seeded")
        if eligible:
            self.span_jids.append(join_id)
        self.key.append(("join", jt, lk, rk, out_cap, lk_bool, rk_bool,
                         tuple(x[1] for x in lk_luts),
                         tuple(x[1] for x in rk_luts),
                         ("dense",) + dense if dense is not None
                         else None, eligible))
        semi_anti = jt in ("left_semi", "left_anti")
        if semi_anti:
            metas = list(probe.metas)
        else:
            metas = list(probe.metas) + [
                _MCol(m.dtype, True, m.sdict) for m in build.metas]
        if dense is not None:
            return self._join_dense(node, probe, build, metas, lk, rk,
                                    dense, semi_anti)

        def eqs_of(d, v, idx, luts, bools, args):
            eqs, valids = [], []
            for j, i in enumerate(idx):
                kd = d[i]
                if luts[j][0] is not None:
                    lut = args[luts[j][0]]
                    kd = jnp.take(lut, jnp.clip(kd.astype(jnp.int32), 0,
                                                lut.shape[0] - 1))
                elif bools[j]:
                    kd = kd.astype(jnp.int32)
                eqs.append(kd)
                valids.append(v[i])
            return eqs, valids

        def emit(args, needed, _probe=probe, _build=build, _oc=out_cap):
            from ..ops import joining as J

            pd, pv, pm = _probe.emit(args, needed)
            bd, bv, bm = _build.emit(args, needed)
            beqs, bvalids = eqs_of(bd, bv, rk, rk_luts, rk_bool, args)
            peqs, pvalids = eqs_of(pd, pv, lk, lk_luts, lk_bool, args)
            bi_ = J.build_index(beqs, bvalids, bm)
            r = J.probe_join(bi_, beqs, bvalids, peqs, pvalids, pm, _oc,
                             jt)
            needed.append(r.needed)
            if eligible:
                # observe the build-key span + uniqueness so the NEXT
                # same-fingerprint run (via the warm-start manifest)
                # compiles the dense direct-address variant directly
                bk = beqs[0].astype(jnp.int64)
                blive = bm if bvalids[0] is None else (bm & bvalids[0])
                big = jnp.int64(1) << 62
                lo_o = jnp.min(jnp.where(blive, bk, big))
                hi_o = jnp.max(jnp.where(blive, bk, -big))
                sk = jnp.sort(jnp.where(blive, bk, big))
                dup = jnp.any((sk[1:] == sk[:-1]) & (sk[:-1] != big)) \
                    if sk.shape[0] > 1 else jnp.asarray(False)
                needed.spans.append((lo_o, hi_o, dup.astype(jnp.int32)))
            if semi_anti:
                datas = [jnp.take(x, r.probe_idx) for x in pd]
                valids = [None if x is None else jnp.take(x, r.probe_idx)
                          for x in pv]
                return datas, valids, r.out_mask
            datas = [jnp.take(x, r.probe_idx) for x in pd]
            valids = [None if x is None else jnp.take(x, r.probe_idx)
                      for x in pv]
            null_build = ~r.matched
            for x, xv in zip(bd, bv):
                datas.append(jnp.take(x, r.build_idx))
                base = jnp.take(xv, r.build_idx) if xv is not None \
                    else jnp.ones(_oc, dtype=bool)
                valids.append(base & ~null_build)
            return datas, valids, r.out_mask

        return _Lowered(metas, out_cap, emit)

    def _join_dense(self, node, probe: _Lowered, build: _Lowered, metas,
                    lk, rk, dense, semi_anti) -> _Lowered:
        """Dense direct-address probe inside the whole program: the same
        scatter/take body as the per-stage fast path (operators.py), but
        compiled up front from the warm-start manifest's build-key span
        instead of a host-synced value inspection. A guard scalar rides
        the dispatch: if the data drifted off the seeded span (or grew a
        duplicate) the host disables dense for this join and re-lowers —
        one extra round, never a wrong result."""
        jnp = _jnp()
        lo, hi = dense
        tcap = bucket_capacity(hi - lo + 1)
        jt = node.join_type
        self.guard_jids.append(self._join_seq - 1)
        pcap, bcap = probe.cap, build.cap
        self.ctx.metrics.add("join.dense_fast_path")

        def emit(args, needed, _probe=probe, _build=build):
            from jax import lax

            pd, pv, pm = _probe.emit(args, needed)
            bd, bv, bm = _build.emit(args, needed)
            bk = bd[rk[0]].astype(jnp.int64)
            bvd = bv[rk[0]]
            blive = bm if bvd is None else (bm & bvd)
            big = jnp.int64(1) << 62
            lo_o = jnp.min(jnp.where(blive, bk, big))
            hi_o = jnp.max(jnp.where(blive, bk, -big))
            # dead/out-of-span rows dump past the table: mode="drop"
            # discards out-of-bounds scatters (same idiom as per-stage)
            slot = jnp.where(blive, bk - lo, tcap)
            rowidx = jnp.full((tcap,), 0, jnp.int32).at[slot].set(
                lax.iota(jnp.int32, bcap), mode="drop")
            present = jnp.zeros((tcap,), jnp.int32).at[slot].add(
                1, mode="drop")
            dup = jnp.max(present) > 1
            guard = (lo_o < lo) | (hi_o > hi) | dup
            needed.guards.append(guard.astype(jnp.int32))
            needed.spans.append((lo_o, hi_o, dup.astype(jnp.int32)))
            needed.append(jnp.zeros((), jnp.int64))  # cap-slot alignment
            pk = pd[lk[0]].astype(jnp.int64) - lo
            in_range = (pk >= 0) & (pk < tcap)
            pslot = jnp.clip(pk, 0, tcap - 1)
            usable = pm & in_range
            pvd = pv[lk[0]]
            if pvd is not None:
                usable = usable & pvd
            matched = usable & (jnp.take(present, pslot) > 0)
            bidx = jnp.take(rowidx, pslot)
            if jt in ("inner", "left_semi"):
                out_mask = matched
            elif jt == "left_outer":
                out_mask = pm
            else:  # left_anti (full_outer never admits to this tier)
                out_mask = pm & ~matched
            if semi_anti:
                return list(pd), list(pv), out_mask
            datas = list(pd)
            valids = list(pv)
            for x, xv in zip(bd, bv):
                datas.append(jnp.take(x, bidx))
                base = jnp.take(xv, bidx) if xv is not None \
                    else jnp.ones(pcap, dtype=bool)
                valids.append(base & matched)
            return datas, valids, out_mask

        return _Lowered(metas, pcap, emit)

    # -- union -------------------------------------------------------------
    def _lower_union(self, node, lows: list) -> _Lowered:
        jnp = _jnp()
        fields = attrs_schema(node.output).fields
        ncols = len(fields)
        cap = bucket_capacity(sum(lw.cap for lw in lows))
        luts = []
        metas = []
        for ci, f in enumerate(fields):
            merged = None
            lut_idx = None
            if dict_encoded(f.dataType):
                dicts = [lw.metas[ci].sdict or EMPTY_DICT for lw in lows]
                if all(d is dicts[0] for d in dicts):
                    merged = dicts[0]
                else:
                    merged, lut_list = merge_string_dicts(dicts)
                    lut_idx = [self.arg(jnp.asarray(lt))
                               for lt in lut_list]
            luts.append(lut_idx)
            metas.append(_MCol(f.dataType,
                               any(lw.metas[ci].valid for lw in lows),
                               merged))
        self.key.append(("union", tuple(lw.cap for lw in lows)))

        def emit(args, needed):
            outs = [lw.emit(args, needed) for lw in lows]

            def pad(a, fill):
                n = sum(lw.cap for lw in lows)
                if n < cap:
                    a = jnp.concatenate(
                        [a, jnp.full(cap - n, fill, dtype=a.dtype)])
                return a

            datas, valids = [], []
            for ci in range(ncols):
                chunks = []
                for li, (d, _v, _m) in enumerate(outs):
                    dd = d[ci]
                    if luts[ci] is not None:
                        lt = args[luts[ci][li]]
                        dd = jnp.take(lt, jnp.clip(dd, 0,
                                                   lt.shape[0] - 1))
                    chunks.append(dd)
                datas.append(pad(jnp.concatenate(chunks), 0))
                if metas[ci].valid:
                    vchunks = []
                    for li, (_d, v, _m) in enumerate(outs):
                        vchunks.append(
                            v[ci] if v[ci] is not None
                            else jnp.ones(lows[li].cap, dtype=bool))
                    valids.append(pad(jnp.concatenate(vchunks), False))
                else:
                    valids.append(None)
            mask = pad(jnp.concatenate([m for _d, _v, m in outs]), False)
            return datas, valids, mask

        return _Lowered(metas, cap, emit)


def _record_spans(ctx, b: _ProgramBuilder, spans, n_joins: int) -> None:
    """Stash the observed build-side key spans on the context (aligned
    by join id with persist_join_caps) so the close-time manifest write
    carries them — the NEXT same-fingerprint run seeds the dense
    direct-address probe variant from them (sp[2]=1 means unique)."""
    if not b.span_jids:
        return
    out: list = [None] * n_joins
    for jid, (lo, hi, dup) in zip(b.span_jids, spans):
        lo_i = int(lo)  # tpulint: ignore[host-sync]
        hi_i = int(hi)  # tpulint: ignore[host-sync]
        if hi_i < lo_i:
            continue  # empty build side: nothing worth seeding
        uniq = 0 if int(dup) else 1  # tpulint: ignore[host-sync]
        out[jid] = [lo_i, hi_i, uniq]
    if any(s is not None for s in out):
        ctx.persist_join_spans = out


# ---------------------------------------------------------------------------
# the operator
# ---------------------------------------------------------------------------

class WholeQueryExec(PhysicalPlan):
    """The whole query as ONE jitted program per step.

    Opaque to the stage cutter (child_fields = ()): the scheduler sees a
    single stage with no exchanges, so there are zero host shuffle
    round-trips by construction. Leaf scans execute normally (device-
    cached, launch-free); everything above them traces into one program
    whose single dispatch the obs layer re-attributes to the member
    operators via fused_members(). Join output-capacity overflow retries
    re-dispatch the whole program with bumped buckets (counted, and
    mirrored by the plan analyzer's whole-query launch model)."""

    child_fields = ()          # the inner plan is NOT a schedulable child

    def __init__(self, plan, decision: TierDecision):
        self.plan = plan
        self.decision = decision
        self._members_cache: list | None = None
        # set when a runtime fault degraded this execution to the stage
        # tier: the obs walkers then render the INNER plan (per-member
        # attribution through the wrapper — PR 11 follow-on (d))
        self._degraded = False

    @property
    def output(self):
        return self.plan.output

    def output_partitioning(self):
        from .partitioning import SinglePartition

        return SinglePartition()

    def graph_name(self) -> str:
        return "WholeQueryExec"

    def degraded_inner(self, always: bool = False):
        """The inner plan for metric/graph rendering: exposed once a
        runtime fault degraded this run to the stage tier (the inner
        operators then executed individually and own real records), or
        unconditionally for metric-ID pre-assignment (`always=True` —
        ids must exist before execution decides whether to degrade).
        obs/metrics.metric_children is the only caller."""
        return self.plan if (always or self._degraded) else None

    def fused_members(self) -> list:
        """Every lowered operator shares this node's single dispatch.
        A degraded run renders the members as REAL child nodes with
        their own records instead (degraded_inner), so the fused view
        empties — the two renderings must not duplicate each other."""
        if self._degraded:
            return []
        if self._members_cache is None:
            self._members_cache = [
                (n.simple_string() if hasattr(n, "simple_string")
                 else type(n).__name__)[:100]
                for n in self.plan.iter_nodes()]
        return self._members_cache

    def simple_string(self):
        n = sum(1 for _ in self.plan.iter_nodes())
        return (f"WholeQuery[ops={n}, tier=whole] "
                f"({self.decision.reason[:60]})")

    def tree_string(self, depth: int = 0) -> str:
        pad = "  " * depth
        head = pad + ("+- " if depth else "") + self.simple_string()
        return head + "\n" + self.plan.tree_string(depth + 1)

    def execute(self, ctx) -> list:
        try:
            return self._execute_whole(ctx)
        except Exception as e:
            if not is_runtime_fault(e):
                raise
            # the program died AT RUNTIME (XLA fault / RESOURCE_EXHAUSTED
            # the MemoryBudgetExceeded pre-flight could not predict, or
            # an injected chaos fault): degrade to the STAGE tier and
            # re-execute the inner plan stage-at-a-time — smaller
            # programs, host round-trips, value-dependent fast paths.
            # The reason lands on the tier decision so explain() and the
            # degrade span show WHY this query did not run whole.
            return self._degrade_to_stage(ctx, e)

    def _degrade_to_stage(self, ctx, cause: Exception) -> list:
        from contextlib import nullcontext

        from ..exec.scheduler import DAGScheduler

        reason = f"{type(cause).__name__}: {str(cause)[:200]}"
        self.decision.details["runtime_degraded"] = reason
        # flip the obs walkers to per-member rendering: the inner
        # operators are about to execute individually, and their records
        # must be comparable to a stage-tier run's (plan graph, EXPLAIN
        # ANALYZE, and the query profile all descend through the wrapper)
        self._degraded = True
        ctx.metrics.add("whole_query.runtime_degraded")
        live = getattr(ctx, "live_obs", None)
        if live is not None:
            live.add_finding(getattr(ctx, "query_id", None), {
                "severity": "warning", "kind": "tier.degraded",
                "msg": "whole-query program failed at runtime — "
                       f"degraded to the stage tier and re-executed "
                       f"({reason})"})
        tracer = getattr(ctx, "tracer", None)
        sp = tracer.span("whole_query.degrade", cat="operator",
                         args={"tier": "stage", "reason": reason}) \
            if tracer is not None else nullcontext()
        with sp:
            # _run (not run): the ENCLOSING scheduler already owns this
            # query's KernelCache delta accounting — wrapping again would
            # double-count the stage tier's launches in kernel.* metrics
            return DAGScheduler(ctx)._run(self.plan)

    def _execute_whole(self, ctx) -> list:
        import jax

        tracer = getattr(ctx, "tracer", None)
        from contextlib import nullcontext

        span = tracer.span("whole_query.program", cat="operator",
                           args={"tier": "whole",
                                 "reason": self.decision.reason,
                                 **{k: v for k, v in
                                    self.decision.details.items()
                                    if isinstance(v, (int, float, str))}}) \
            if tracer is not None else nullcontext()
        # warm-start seeding (exec/persist_cache.py): a prior same-
        # fingerprint run's FINAL join output capacities ride the
        # persistent manifest back onto this process's first attempt, so
        # a restarted server compiles the final program directly (one
        # engine compile, served by the XLA disk cache) instead of
        # replaying the capacity-retry ladder. Absent/short seeds fall
        # back to the normal per-join defaults; an under-sized seed just
        # re-enters the ordinary retry loop.
        seed_rec = getattr(ctx, "persist_seed", None) or {}
        seed = seed_rec.get("join_caps")
        join_caps: list[int] = [int(c) for c in (seed or ())]
        if join_caps:
            ctx.metrics.add("cache.capacity_seeded")
        spans_seed = seed_rec.get("join_spans") or None
        dense_off: set[int] = set()
        with span:
            for attempt in range(_MAX_PROGRAM_RETRIES):
                b = _ProgramBuilder(ctx, join_caps,
                                    spans_seed=spans_seed,
                                    dense_off=dense_off)
                root = b.lower(self.plan)
                key = ("whole_query", tuple(b.key))

                def build(_root=root, _nargs=len(b.args)):
                    def program(args):
                        needed = _Collect()
                        datas, valids, mask = _root.emit(args, needed)
                        return (datas, valids, mask, tuple(needed),
                                tuple(needed.spans),
                                tuple(needed.guards))

                    return jax.jit(program)

                kernel = GLOBAL_KERNEL_CACHE.get_or_build(key, build)
                datas, valids, mask, needed, spans, guards = \
                    kernel(b.args)
                # the program's ONE capacity verdict: join `needed`
                # scalars sync after the single dispatch (the query's
                # last device interaction before collect)
                bumped = False
                for i, nd in enumerate(needed):
                    n_i = int(nd)  # tpulint: ignore[host-sync]
                    if n_i > join_caps[i]:
                        join_caps[i] = bucket_capacity(n_i)
                        bumped = True
                # dense-probe guards: the seeded span no longer covers
                # the build rows (data drifted under the fingerprint) —
                # drop the dense variant for that join and re-lower
                for jid, g in zip(b.guard_jids, guards):
                    if int(g):  # tpulint: ignore[host-sync]
                        dense_off.add(jid)
                        ctx.metrics.add("whole_query.dense_guard_retries")
                        bumped = True
                if not bumped:
                    if attempt:
                        ctx.metrics.add("whole_query.capacity_retries",
                                        attempt)
                    ctx.metrics.add("whole_query.dispatches", attempt + 1)
                    if join_caps:
                        # capacity outcomes for the warm-start manifest
                        # (QueryExecution writes it at query close)
                        ctx.persist_join_caps = list(join_caps)
                    if b.dense_joins:
                        ctx.metrics.add("whole_query.dense_probe",
                                        len(b.dense_joins))
                    _record_spans(ctx, b, spans, len(join_caps))
                    schema = attrs_schema(self.output)
                    cols = [Column(f.dataType, d, v,
                                   m.sdict if dict_encoded(f.dataType)
                                   else None)
                            for f, d, v, m in zip(schema.fields, datas,
                                                  valids, root.metas)]
                    batch = ColumnarBatch(schema, cols, mask,
                                          num_rows=None)
                    return [[batch]]
            raise ExecutionError(
                "whole-query program exceeded its capacity-retry budget "
                f"({_MAX_PROGRAM_RETRIES}) — report this plan")
