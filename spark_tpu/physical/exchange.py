"""Exchange operators.

Role of the reference's ShuffleExchangeExec (sqlx/exchange/
ShuffleExchangeExec.scala:190) and BroadcastExchangeExec (:61
relationFuture + torrent broadcast). Broadcast here is a replicated
concatenated batch (on a mesh: an ICI all-gather — SURVEY.md §2.5).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..columnar.batch import ColumnarBatch
from ..columnar.ops import concat_batches
from ..errors import UnsupportedOperationError
from ..exec import shuffle as S
from ..exec.context import ExecContext
from ..expr.expressions import AttributeReference, SortOrder
from ..types import StringType
from .operators import PhysicalPlan, attrs_schema
from .partitioning import (
    BroadcastPartitioning, HashPartitioning, Partitioning, RangePartitioning,
    SinglePartition, UnknownPartitioning,
)


class ShuffleExchangeExec(PhysicalPlan):
    child_fields = ("child",)

    def __init__(self, partitioning: Partitioning, child: PhysicalPlan):
        self.partitioning = partitioning
        self.child = child
        self.last_stats: dict[int, int] = {}
        # map-side per-reduce-partition integral column stats (satellite
        # of the fused write: seeds the dense-range memo locally and
        # rides the MapStatus payload in cluster mode)
        self.last_col_stats: dict[int, dict] = {}
        # set by FuseStages (physical/fusion.py): (filters, outputs) of
        # the producing pipeline traced into the partition-id kernel
        self.pipe_fusion: tuple | None = None
        self.pipe_attrs: list | None = None
        # output column positions whose min/max the map-side write
        # accumulates (annotate_exchange_stat_cols: only plan-reachable
        # dense candidates); None = every integral column (bare plans)
        self.stat_cols: list | None = None
        # runtime join filter (physical/adaptive.install_runtime_filters):
        # a materialized build side's key domain, applied to map batches
        # before they are shuffled — whole-batch skip via the seeded
        # dense-range memo, row-level pruning inside the fused map kernel
        self.runtime_filter: dict | None = None

    @property
    def output(self):
        if self.pipe_attrs is not None:
            return self.pipe_attrs
        return self.child.output

    def output_partitioning(self):
        return self.partitioning

    def fused_members(self) -> list:
        """FuseStages mapping for obs/ dispatch re-attribution: the
        pipeline members share this exchange's single map-side dispatch
        per batch (the partition-id kernel rides the same program)."""
        if self.pipe_fusion is None:
            return []
        from ..obs.metrics import pipeline_member_names

        filters, outputs = self.pipe_fusion
        return pipeline_member_names(filters, outputs) + [
            f"Exchange[{type(self.partitioning).__name__}] partition-ids"]

    def _fusion(self):
        """Fresh ExchangeFusion per execute (it carries the partitioning
        binding); the jitted kernels live in the global KernelCache, so
        rebuilding the binder costs no compile."""
        from .fusion import ExchangeFusion

        filters, outputs = self.pipe_fusion
        return ExchangeFusion(filters, outputs, self.child.output)

    def execute(self, ctx: ExecContext) -> list:
        parts = self.child.execute(ctx)
        if self.runtime_filter is not None:
            parts = self._runtime_filter_skip(parts, ctx)
        schema = attrs_schema(self.output)
        p = self.partitioning
        # cleared IN PLACE: stage-builder/AQE copies share this node's
        # __dict__ values (TreeNode.copy), so mutating the same dicts
        # keeps runtime stats visible on the pre-copy plan the user
        # inspects (EXPLAIN, tests); rebinding would strand them on the
        # executing copy
        self.last_stats.clear()
        self.last_col_stats.clear()
        fusion = self._fusion() if self.pipe_fusion is not None else None
        with ctx.metrics.time("shuffle"):
            if isinstance(p, SinglePartition):
                with self._span(ctx, "exchange.gather", p):
                    return S.gather_single(parts)
            if isinstance(p, HashPartitioning):
                pos = {a.expr_id: i for i, a in enumerate(self.output)}
                key_positions = []
                for e in p.exprs:
                    assert isinstance(e, AttributeReference), \
                        "exchange keys must be attributes (planner contract)"
                    key_positions.append(pos[e.expr_id])
                from ..parallel import mesh_exchange as ME

                mesh = ME.mesh_for(p.num_partitions, ctx.conf, schema)
                if mesh is not None:
                    # the whole stage — pipeline, partition ids,
                    # all-to-all — is ONE SPMD dispatch per step when the
                    # map side is fused (spark.tpu.fusion.mesh); the
                    # legacy materialize-then-collective composition sits
                    # behind that flag
                    if self.runtime_filter is not None:
                        # the mesh program stages whole host arrays, so
                        # the filter cannot ride it as aux operands —
                        # prune rows per batch BEFORE staging (one tiny
                        # mask dispatch each; fewer live rows also eases
                        # the quota ladder)
                        parts = self._runtime_filter_rows(parts, ctx)
                    with self._span(ctx, "exchange.mesh_all_to_all", p):
                        return ME.mesh_shuffle_hash(
                            parts, key_positions, p.num_partitions, schema,
                            ctx, self.last_stats, mesh,
                            fusion=None if fusion is None else
                            fusion.bind_hash(key_positions,
                                             p.num_partitions),
                            col_stats=self.last_col_stats,
                            stat_cols=self.stat_cols)
                with self._span(ctx, "exchange.hash", p):
                    if fusion is not None:
                        bound = fusion.bind_hash(key_positions,
                                                 p.num_partitions)
                        if self.runtime_filter is not None:
                            # row-level pruning rides the SAME fused map
                            # kernel as aux operands — no extra dispatch
                            bound.bind_runtime_filter(self.runtime_filter)
                        out = S.shuffle_fused(
                            parts, bound,
                            p.num_partitions, schema, ctx, self.last_stats,
                            self.last_col_stats, self.stat_cols)
                        if fusion.rf_pruned:
                            ctx.metrics.add("adaptive.filter_rows_pruned",
                                            fusion.rf_pruned)
                        return out
                    return S.shuffle_hash(parts, key_positions,
                                          p.num_partitions, schema, ctx,
                                          self.last_stats,
                                          col_stats=self.last_col_stats,
                                          stat_cols=self.stat_cols)
            if isinstance(p, RangePartitioning):
                with self._span(ctx, "exchange.range", p):
                    return self._range_shuffle(parts, p, schema, ctx,
                                               fusion)
            if isinstance(p, UnknownPartitioning):
                with self._span(ctx, "exchange.round_robin", p):
                    if fusion is not None:
                        return S.shuffle_fused(
                            parts, fusion.bind_rr(p.num_partitions),
                            p.num_partitions, schema, ctx, self.last_stats,
                            self.last_col_stats, self.stat_cols)
                    return S.shuffle_round_robin(
                        parts, p.num_partitions, schema, ctx,
                        self.last_stats, col_stats=self.last_col_stats,
                        stat_cols=self.stat_cols)
        raise UnsupportedOperationError(f"exchange for {p}")

    def _runtime_filter_skip(self, parts: list, ctx: ExecContext) -> list:
        """Whole-batch pruning against the build-side key domain using
        ONLY already-synced state: the seeded dense-range memo for
        integral keys (peek — a miss never computes) and the host-side
        StringDict code domain for encoded string keys. A batch whose
        key range/domain misses the build domain cannot produce a join
        match and never enters the shuffle. Zero kernels, zero syncs."""
        rf = self.runtime_filter
        cp = rf.get("child_pos")
        if cp is None:
            return parts    # computed key: no pre-pipeline column
        from ..utils.device_memo import peek_dense_range

        kind = rf["kind"]
        kept, skipped = [], 0
        for part in parts:
            keep_part = []
            for b in part:
                drop = False
                col = b.columns[cp]
                if kind == "range":
                    hit = peek_dense_range(col, b.row_mask)
                    if hit is not None:
                        kmin, kmax, any_live = hit
                        drop = (not any_live) or kmax < rf["lo"] \
                            or kmin > rf["hi"]
                else:
                    d = col.dictionary
                    if d is not None:
                        dom = rf["domain"]
                        drop = not any(v in dom for v in d.values)
                if drop:
                    skipped += 1
                else:
                    keep_part.append(b)
            kept.append(keep_part)
        if skipped:
            ctx.metrics.add("adaptive.filter_batches_skipped", skipped)
        return kept

    def _runtime_filter_rows(self, parts: list, ctx: ExecContext) -> list:
        """Row-level pruning ahead of the mesh path: batches are the
        CHILD's output here (any map pipeline runs inside the mesh
        program), so the filter applies at the pre-pipeline key position.
        One shared mask-update kernel per batch (physical/fusion.
        runtime_filter_batch)."""
        from .fusion import runtime_filter_batch

        rf = self.runtime_filter
        cp = rf.get("child_pos")
        if cp is None:
            return parts    # computed key: no pre-pipeline column
        pruned = 0
        out = []
        for part in parts:
            new_part = []
            for b in part:
                nb, drop = runtime_filter_batch(rf, None, b, cp)
                pruned += drop
                new_part.append(nb)
            out.append(new_part)
        if pruned:
            ctx.metrics.add("adaptive.filter_rows_pruned", pruned)
        return out

    @staticmethod
    def _span(ctx, name: str, p):
        """Shuffle-kind span INSIDE the operator span, so the trace
        timeline separates redistribution work from child execution (the
        shuffle write/read lane of the reference's stage timeline)."""
        tracer = getattr(ctx, "tracer", None)
        if tracer is None:
            from contextlib import nullcontext

            return nullcontext()
        return tracer.span(name, cat="exchange",
                           args={"partitions": p.num_partitions})

    def _range_shuffle(self, parts, p: RangePartitioning, schema, ctx,
                       fusion=None):
        order = p.orders[0]
        pos = {a.expr_id: i for i, a in enumerate(self.output)}
        assert isinstance(order.child, AttributeReference)
        kpos = pos[order.child.expr_id]
        if fusion is not None:
            # bounds sample the POST-pipeline key column: the pipeline
            # materializes for ≤3 sampled batches per partition — spread
            # first/middle/last so ordered domains (range scans) are
            # covered end to end — and selective filters no longer skew
            # partition balance; COMPUTED sort keys fuse too (the
            # pre-pipeline input-column sampling was a pre-filter
            # superset — sound but uneven, and it required a
            # pass-through key)
            def picks(part):
                if len(part) <= 3:
                    return list(part)
                return [part[0], part[len(part) // 2], part[-1]]

            sample_parts = [[fusion.run_pipeline(b) for b in picks(part)]
                            for part in parts]
            bounds = _sample_bounds(sample_parts, kpos, schema,
                                    p.num_partitions, all_batches=True)
            if bounds is None or len(bounds) == 0:
                return S.gather_single(
                    [[fusion.run_pipeline(b) for b in part]
                     for part in parts])
            return S.shuffle_fused(
                parts,
                fusion.bind_range(kpos, bounds, not order.ascending,
                                  p.num_partitions),
                p.num_partitions, schema, ctx, self.last_stats,
                self.last_col_stats, self.stat_cols)
        bounds = _sample_bounds(parts, kpos, schema, p.num_partitions)
        if bounds is None or len(bounds) == 0:
            return S.gather_single(parts)
        return S.shuffle_range(parts, kpos, bounds, not order.ascending,
                               p.num_partitions, schema, ctx,
                               self.last_stats,
                               col_stats=self.last_col_stats,
                               stat_cols=self.stat_cols)

    def simple_string(self):
        s = f"Exchange[{type(self.partitioning).__name__}" \
            f"({self.partitioning.num_partitions})]"
        if self.runtime_filter is not None:
            s += f" RUNTIME-FILTER[{self.runtime_filter['kind']}]"
        if self.pipe_fusion is not None:
            filters, outputs = self.pipe_fusion
            o = ", ".join(x.simple_string() for x in outputs)
            s += f" FUSED-MAP[{o}]"
            if filters:
                s += " WHERE " + " AND ".join(x.simple_string()
                                              for x in filters)
        return s


def _batch_key_samples(batch: ColumnarBatch, kpos: int, f,
                       per_part_sample: int) -> tuple:
    """Up to `per_part_sample` live non-null key values of one batch as an
    immutable tuple. The device→host pull is memoized per (data, validity,
    mask) identity (utils/device_memo.memo_device_scalars): repeated
    range exchanges over device-cached scan batches sync once, not once
    per batch per query."""
    from ..utils.device_memo import memo_device_scalars

    col = batch.columns[kpos]

    def compute():
        mask = np.asarray(batch.row_mask)
        if isinstance(f.dataType, StringType):
            vals = col.to_numpy(np.nonzero(mask)[0][:per_part_sample])
            return tuple(v for v in vals if v is not None)
        data = np.asarray(col.data)[mask][:per_part_sample]
        if col.validity is not None:
            vmask = np.asarray(col.validity)[mask][:per_part_sample]
            data = data[vmask[: len(data)]]
        return tuple(data.tolist())

    return memo_device_scalars(
        ("range_sample", kpos, per_part_sample, str(f.dataType)),
        (col.data, col.validity, batch.row_mask), compute)


def _sample_bounds(parts, kpos: int, schema, num_out: int,
                   per_part_sample: int = 4096,
                   all_batches: bool = False):
    """Sample the sort key to derive range bounds (role of the reference's
    RangePartitioner sampling job, core/Partitioner.scala:388).
    `all_batches` samples every batch handed in — the fused exchange
    pre-selects a spread of materialized pipeline outputs instead of
    relying on the first-2 heuristic."""
    f = schema.fields[kpos]
    samples = []
    for part in parts:
        for batch in (part if all_batches else part[:2]):
            samples.extend(_batch_key_samples(batch, kpos, f,
                                              per_part_sample))
    if not samples:
        return None
    if isinstance(f.dataType, StringType):
        s = sorted(set(samples))
    else:
        # host math over already-pulled (memoized) sample tuples
        s = np.unique(np.asarray(samples))  # tpulint: ignore[host-sync]
    if len(s) <= 1:
        return None
    qs = [int(round(i * (len(s) - 1) / num_out))  # tpulint: ignore[host-sync]
          for i in range(1, num_out)]
    if isinstance(f.dataType, StringType):
        bounds = sorted(set(s[q] for q in qs))
    else:
        bounds = np.unique(s[qs])
    return bounds


def dense_stat_candidate_ids(plan: PhysicalPlan) -> set:
    """Expr ids whose value RANGE some downstream dense decision can
    consult: the single integral/date grouping key of a hash aggregate
    (dense-scatter vs sorted-segment, operators._try_dense and
    fusion._dense_decision) and the single integral/date keys of a hash
    join (dense direct-address build, operators._try_dense_build; both
    sides listed — AQE may re-side the build). Pass-through projections
    preserve expr ids, so membership at an exchange's output is exactly
    'a consumer above can read this column's range'. Aliased/computed
    keys produce FRESH device arrays whose identity the memo can never
    hit, so excluding them loses nothing."""
    from ..types import DateType, IntegralType
    from .operators import HashAggregateExec, HashJoinExec

    def single_int(keys) -> bool:
        return len(keys) == 1 and isinstance(
            keys[0].dtype, (IntegralType, DateType))

    out: set = set()
    for node in plan.iter_nodes():
        if isinstance(node, HashAggregateExec):  # FusedAggregate too
            if single_int(node.grouping):
                out.add(node.grouping[0].expr_id)
        if isinstance(node, HashJoinExec):
            for keys in (node.left_keys, node.right_keys):
                if single_int(keys):
                    out.add(keys[0].expr_id)
    return out


def annotate_exchange_stat_cols(plan: PhysicalPlan) -> None:
    """Restrict every shuffle exchange's map-side stat accumulation
    (exec/shuffle._OutBuffer) to plan-reachable dense candidates: the
    historical behavior ran host min/max over EVERY integral column per
    appended slice even when no downstream consumer makes a dense
    decision. Idempotent; runs at plan time (Planner.plan) so the
    annotation rides stage-builder copies (shared __dict__) and
    cloudpickle into cluster map tasks, and the plan analyzer reads the
    SAME annotation for its krange3 launch model."""
    exchanges = [n for n in plan.iter_nodes()
                 if isinstance(n, ShuffleExchangeExec)]
    # planner-annotated plans reach execute() already done (stat_cols
    # defaults to None until annotated) — skip the candidate recompute;
    # any exchange an adaptive rewrite introduced un-annotated re-runs it
    if all(n.stat_cols is not None for n in exchanges):
        return
    cands = dense_stat_candidate_ids(plan)
    for node in exchanges:
        node.stat_cols = [
            i for i, a in enumerate(node.output)
            if a.expr_id in cands]


class BroadcastExchangeExec(PhysicalPlan):
    child_fields = ("child",)

    def __init__(self, child: PhysicalPlan):
        self.child = child

    @property
    def output(self):
        return self.child.output

    def output_partitioning(self):
        return BroadcastPartitioning()

    def execute(self, ctx: ExecContext) -> list:
        parts = self.child.execute(ctx)
        merged = []
        for p in parts:
            merged.extend(p)
        schema = attrs_schema(self.output)
        if not merged:
            return [[ColumnarBatch.empty(schema)]]
        batch = concat_batches(merged, schema)
        ctx.metrics.add("broadcast.rows", batch.num_rows())
        return [[batch]]

    def simple_string(self):
        return "BroadcastExchange"
