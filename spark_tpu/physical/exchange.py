"""Exchange operators.

Role of the reference's ShuffleExchangeExec (sqlx/exchange/
ShuffleExchangeExec.scala:190) and BroadcastExchangeExec (:61
relationFuture + torrent broadcast). Broadcast here is a replicated
concatenated batch (on a mesh: an ICI all-gather — SURVEY.md §2.5).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..columnar.batch import ColumnarBatch
from ..columnar.ops import concat_batches
from ..errors import UnsupportedOperationError
from ..exec import shuffle as S
from ..exec.context import ExecContext
from ..expr.expressions import AttributeReference, SortOrder
from ..types import StringType
from .operators import PhysicalPlan, attrs_schema
from .partitioning import (
    BroadcastPartitioning, HashPartitioning, Partitioning, RangePartitioning,
    SinglePartition, UnknownPartitioning,
)


class ShuffleExchangeExec(PhysicalPlan):
    child_fields = ("child",)

    def __init__(self, partitioning: Partitioning, child: PhysicalPlan):
        self.partitioning = partitioning
        self.child = child
        self.last_stats: dict[int, int] = {}

    @property
    def output(self):
        return self.child.output

    def output_partitioning(self):
        return self.partitioning

    def execute(self, ctx: ExecContext) -> list:
        parts = self.child.execute(ctx)
        schema = attrs_schema(self.output)
        p = self.partitioning
        self.last_stats = {}
        with ctx.metrics.time("shuffle"):
            if isinstance(p, SinglePartition):
                with self._span(ctx, "exchange.gather", p):
                    return S.gather_single(parts)
            if isinstance(p, HashPartitioning):
                pos = {a.expr_id: i for i, a in enumerate(self.output)}
                key_positions = []
                for e in p.exprs:
                    assert isinstance(e, AttributeReference), \
                        "exchange keys must be attributes (planner contract)"
                    key_positions.append(pos[e.expr_id])
                from ..parallel import mesh_exchange as ME

                mesh = ME.mesh_for(p.num_partitions, ctx.conf, schema)
                if mesh is not None:
                    with self._span(ctx, "exchange.mesh_all_to_all", p):
                        return ME.mesh_shuffle_hash(
                            parts, key_positions, p.num_partitions, schema,
                            ctx, self.last_stats, mesh)
                with self._span(ctx, "exchange.hash", p):
                    return S.shuffle_hash(parts, key_positions,
                                          p.num_partitions, schema, ctx,
                                          self.last_stats)
            if isinstance(p, RangePartitioning):
                with self._span(ctx, "exchange.range", p):
                    return self._range_shuffle(parts, p, schema, ctx)
            if isinstance(p, UnknownPartitioning):
                with self._span(ctx, "exchange.round_robin", p):
                    return S.shuffle_round_robin(parts, p.num_partitions,
                                                 schema, ctx,
                                                 self.last_stats)
        raise UnsupportedOperationError(f"exchange for {p}")

    @staticmethod
    def _span(ctx, name: str, p):
        """Shuffle-kind span INSIDE the operator span, so the trace
        timeline separates redistribution work from child execution (the
        shuffle write/read lane of the reference's stage timeline)."""
        tracer = getattr(ctx, "tracer", None)
        if tracer is None:
            from contextlib import nullcontext

            return nullcontext()
        return tracer.span(name, cat="exchange",
                           args={"partitions": p.num_partitions})

    def _range_shuffle(self, parts, p: RangePartitioning, schema, ctx):
        order = p.orders[0]
        pos = {a.expr_id: i for i, a in enumerate(self.output)}
        assert isinstance(order.child, AttributeReference)
        kpos = pos[order.child.expr_id]
        bounds = _sample_bounds(parts, kpos, schema, p.num_partitions)
        if bounds is None or len(bounds) == 0:
            return S.gather_single(parts)
        return S.shuffle_range(parts, kpos, bounds, not order.ascending,
                               p.num_partitions, schema, ctx, self.last_stats)

    def simple_string(self):
        return f"Exchange[{type(self.partitioning).__name__}" \
               f"({self.partitioning.num_partitions})]"


def _batch_key_samples(batch: ColumnarBatch, kpos: int, f,
                       per_part_sample: int) -> tuple:
    """Up to `per_part_sample` live non-null key values of one batch as an
    immutable tuple. The device→host pull is memoized per (data, validity,
    mask) identity (utils/device_memo.memo_device_scalars): repeated
    range exchanges over device-cached scan batches sync once, not once
    per batch per query."""
    from ..utils.device_memo import memo_device_scalars

    col = batch.columns[kpos]

    def compute():
        mask = np.asarray(batch.row_mask)
        if isinstance(f.dataType, StringType):
            vals = col.to_numpy(np.nonzero(mask)[0][:per_part_sample])
            return tuple(v for v in vals if v is not None)
        data = np.asarray(col.data)[mask][:per_part_sample]
        if col.validity is not None:
            vmask = np.asarray(col.validity)[mask][:per_part_sample]
            data = data[vmask[: len(data)]]
        return tuple(data.tolist())

    return memo_device_scalars(
        ("range_sample", kpos, per_part_sample, str(f.dataType)),
        (col.data, col.validity, batch.row_mask), compute)


def _sample_bounds(parts, kpos: int, schema, num_out: int,
                   per_part_sample: int = 4096):
    """Sample the sort key to derive range bounds (role of the reference's
    RangePartitioner sampling job, core/Partitioner.scala:388)."""
    f = schema.fields[kpos]
    samples = []
    for part in parts:
        for batch in part[:2]:
            samples.extend(_batch_key_samples(batch, kpos, f,
                                              per_part_sample))
    if not samples:
        return None
    if isinstance(f.dataType, StringType):
        s = sorted(set(samples))
    else:
        # host math over already-pulled (memoized) sample tuples
        s = np.unique(np.asarray(samples))  # tpulint: ignore[host-sync]
    if len(s) <= 1:
        return None
    qs = [int(round(i * (len(s) - 1) / num_out))  # tpulint: ignore[host-sync]
          for i in range(1, num_out)]
    if isinstance(f.dataType, StringType):
        bounds = sorted(set(s[q] for q in qs))
    else:
        bounds = np.unique(s[qs])
    return bounds


class BroadcastExchangeExec(PhysicalPlan):
    child_fields = ("child",)

    def __init__(self, child: PhysicalPlan):
        self.child = child

    @property
    def output(self):
        return self.child.output

    def output_partitioning(self):
        return BroadcastPartitioning()

    def execute(self, ctx: ExecContext) -> list:
        parts = self.child.execute(ctx)
        merged = []
        for p in parts:
            merged.extend(p)
        schema = attrs_schema(self.output)
        if not merged:
            return [[ColumnarBatch.empty(schema)]]
        batch = concat_batches(merged, schema)
        ctx.metrics.add("broadcast.rows", batch.num_rows())
        return [[batch]]

    def simple_string(self):
        return "BroadcastExchange"
